"""Protobuf wire-format row codec.

The reference decodes Flink rows from the protobuf wire format in Rust
(reference: datafusion-ext-plans/src/flink/pb_deserializer.rs, 2,161 LoC).
This is the same contract re-implemented for the host on-ramp: one message
= one row; field number N = schema column N-1; scalar encodings follow
protobuf proper:

    int/bool/date32/timestamp → varint (two's complement, 64-bit)
    float64                   → fixed64 (LE IEEE-754)
    float32                   → fixed32
    string / decimal-as-string→ length-delimited UTF-8

Unknown field numbers and wire types are skipped (forward compatibility),
missing fields decode as null. The decoder is dependency-free (no protoc
schema needed — the engine schema IS the message schema).
"""

from __future__ import annotations

import struct
from typing import Iterable, Optional

import pyarrow as pa

from auron_tpu.columnar.arrow_bridge import schema_to_arrow
from auron_tpu.columnar.schema import DataType, Schema

_VARINT = 0
_FIXED64 = 1
_LEN = 2
_SGROUP = 3   # deprecated proto2 start-group (skipped)
_EGROUP = 4   # deprecated proto2 end-group
_FIXED32 = 5

#: engine dtype → expected wire type
_WIRE = {
    DataType.BOOL: _VARINT, DataType.INT8: _VARINT, DataType.INT16: _VARINT,
    DataType.INT32: _VARINT, DataType.INT64: _VARINT,
    DataType.DATE32: _VARINT, DataType.TIMESTAMP_US: _VARINT,
    DataType.DECIMAL: _LEN,     # decimal-as-string (documented contract)
    DataType.FLOAT64: _FIXED64, DataType.FLOAT32: _FIXED32,
    DataType.STRING: _LEN,
}


def _read_varint(buf: memoryview, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift >= 70:
            raise ValueError("malformed varint")


def _write_varint(out: bytearray, value: int) -> None:
    value &= (1 << 64) - 1   # two's complement for negatives
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _to_signed64(u: int) -> int:
    return u - (1 << 64) if u >= (1 << 63) else u


def encode_pb_row(row: dict, schema: Schema) -> bytes:
    """One row → one protobuf message (None values are omitted)."""
    out = bytearray()
    for i, f in enumerate(schema):
        v = row.get(f.name)
        if v is None:
            continue
        wt = _WIRE[f.dtype]
        _write_varint(out, ((i + 1) << 3) | wt)
        if wt == _VARINT:
            _write_varint(out, int(v))
        elif wt == _FIXED64:
            out += struct.pack("<d", float(v))
        elif wt == _FIXED32:
            out += struct.pack("<f", float(v))
        else:
            if isinstance(v, str):
                b = v.encode()
            elif isinstance(v, bytes):
                b = v
            else:
                b = str(v).encode()   # Decimal and friends
            _write_varint(out, len(b))
            out += b
    return bytes(out)


def decode_pb_row(msg: bytes, schema: Schema,
                  n_cols: int) -> list[Optional[object]]:
    buf = memoryview(msg)
    vals: list[Optional[object]] = [None] * n_cols
    pos, end = 0, len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        fnum, wt = tag >> 3, tag & 7
        idx = fnum - 1
        known = 0 <= idx < n_cols
        if wt == _VARINT:
            u, pos = _read_varint(buf, pos)
            if known and _WIRE[schema[idx].dtype] == _VARINT:
                vals[idx] = _to_signed64(u)
        elif wt == _FIXED64:
            if known and _WIRE[schema[idx].dtype] == _FIXED64:
                vals[idx] = struct.unpack_from("<d", buf, pos)[0]
            pos += 8
        elif wt == _FIXED32:
            if known and _WIRE[schema[idx].dtype] == _FIXED32:
                vals[idx] = struct.unpack_from("<f", buf, pos)[0]
            pos += 4
        elif wt == _LEN:
            ln, pos = _read_varint(buf, pos)
            if known and _WIRE[schema[idx].dtype] == _LEN:
                vals[idx] = bytes(buf[pos:pos + ln]).decode("utf-8",
                                                            "replace")
            pos += ln
        elif wt == _SGROUP:
            pos = _skip_group(buf, pos)   # deprecated proto2 groups
        elif wt == _EGROUP:
            raise ValueError("unbalanced group end")
        else:
            raise ValueError(f"unsupported wire type {wt}")
    return vals


def _skip_group(buf: memoryview, pos: int) -> int:
    """Consume a (deprecated) proto2 group: everything up to and
    including the matching end-group tag. Iterative depth counter — a
    hostile message of thousands of nested start-groups must produce
    ValueError at worst, never RecursionError."""
    depth = 1
    while depth:
        tag, pos = _read_varint(buf, pos)
        wt = tag & 7
        if wt == _EGROUP:
            depth -= 1
        elif wt == _SGROUP:
            depth += 1
        elif wt == _VARINT:
            _, pos = _read_varint(buf, pos)
        elif wt == _FIXED64:
            pos += 8
        elif wt == _FIXED32:
            pos += 4
        elif wt == _LEN:
            ln, pos = _read_varint(buf, pos)
            pos += ln
        else:
            raise ValueError(f"unsupported wire type {wt}")
    return pos


def decode_pb_rows(messages: Iterable[bytes],
                   schema: Schema) -> pa.RecordBatch:
    """One protobuf message per broker message → RecordBatch."""
    arrow_schema = schema_to_arrow(schema)
    n = len(arrow_schema)
    rows = [decode_pb_row(m, schema, n) for m in messages]
    cols = []
    for i, f in enumerate(arrow_schema):
        col = [r[i] for r in rows]
        if schema[i].dtype == DataType.BOOL:
            col = [None if v is None else bool(v) for v in col]
        elif schema[i].dtype == DataType.DECIMAL:
            from decimal import Decimal, InvalidOperation

            def dec(v):
                try:
                    return None if v is None else Decimal(v)
                except InvalidOperation:
                    return None

            col = [dec(v) for v in col]
        cols.append(pa.array(col, f.type))
    return pa.record_batch(cols, schema=arrow_schema)
