"""Row deserializers: message bytes → Arrow record batches.

The reference decodes Flink rows from JSON and from a protobuf wire format
(reference: datafusion-ext-plans/src/flink/json_deserializer.rs,
pb_deserializer.rs). Here the decoders produce a pyarrow RecordBatch for a
message window, which then rides the standard host→device on-ramp.

``proto_rows`` is a minimal length-prefixed JSON-per-row framing (one
message = many rows) — the structural role of the reference's pb row
format (batched rows in one message) without re-speccing protobuf wire
decode on the host path.
"""

from __future__ import annotations

import json
import struct
from typing import Iterable

import pyarrow as pa

from auron_tpu.columnar.arrow_bridge import schema_to_arrow
from auron_tpu.columnar.schema import Schema


def decode_json_rows(messages: Iterable[bytes], schema: Schema) -> pa.RecordBatch:
    """One JSON object per message; missing keys become nulls."""
    arrow_schema = schema_to_arrow(schema)
    rows = [json.loads(m) for m in messages]
    cols = []
    for f in arrow_schema:
        cols.append(pa.array([r.get(f.name) for r in rows], f.type))
    return pa.record_batch(cols, schema=arrow_schema)


def encode_proto_rows(rows: list[dict]) -> bytes:
    """Frame many rows into one message: u32-le length-prefixed JSON rows."""
    out = bytearray()
    for r in rows:
        payload = json.dumps(r).encode()
        out += struct.pack("<I", len(payload))
        out += payload
    return bytes(out)


def decode_proto_rows(messages: Iterable[bytes], schema: Schema) -> pa.RecordBatch:
    """Inverse of encode_proto_rows, across a window of messages."""
    arrow_schema = schema_to_arrow(schema)
    rows = []
    for m in messages:
        off = 0
        while off < len(m):
            (ln,) = struct.unpack_from("<I", m, off)
            off += 4
            rows.append(json.loads(m[off:off + ln]))
            off += ln
    cols = [pa.array([r.get(f.name) for r in rows], f.type)
            for f in arrow_schema]
    return pa.record_batch(cols, schema=arrow_schema)


from auron_tpu.streaming.pbrows import decode_pb_rows  # noqa: E402

DECODERS = {"json": decode_json_rows, "proto_rows": decode_proto_rows,
            "pb": decode_pb_rows}
