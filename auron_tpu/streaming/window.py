"""Event-time tumbling-window aggregation with watermarks.

The reference's streaming side delegates windowing to Flink and only runs
the per-window compute natively (reference: auron-flink-extension/
FlinkAuronCalcOperator.java buffering + checkpoint flush). Here the engine
owns the streaming semantics too — the BASELINE.md "Flink-style streaming
windowed aggregate" target:

  - events carry an event-time column (TIMESTAMP_US);
  - the watermark is max(event_time) - out-of-orderness bound
    (Flink's BoundedOutOfOrdernessWatermarks);
  - rows are bucketed into tumbling windows of ``window_us``; a window
    FIRES when the watermark passes its end, at which point its buffered
    rows run through the engine's device aggregation (ops/agg.AggOp) and
    the results are emitted with a leading window_start column;
  - rows later than an already-fired window are DROPPED and counted in
    the ``late_rows`` metric (allowed lateness 0 — Flink's default);
  - end-of-stream flushes every unfired window (bounded-run semantics).

Ingest buffering is host-side Arrow (cheap at stream rates); the window
aggregate itself is the same jit-compiled device path batch queries use.
"""

from __future__ import annotations

from typing import Iterator, Optional

import pyarrow as pa
import pyarrow.compute as pc

import jax.numpy as jnp

from auron_tpu.columnar.arrow_bridge import to_arrow, to_device
from auron_tpu.columnar.batch import DeviceBatch, PrimitiveColumn
from auron_tpu.columnar.schema import DataType, Field, Schema
from auron_tpu.exprs import ir
from auron_tpu.ops.base import ExecContext, PhysicalOp, count_output


class StreamingWindowAggOp(PhysicalOp):
    name = "streaming_window_agg"

    def __init__(self, child: PhysicalOp, time_col: int, window_us: int,
                 group_exprs: list[ir.Expr], aggs: list[ir.AggFunction],
                 ooo_bound_us: int = 0,
                 group_names: Optional[list[str]] = None,
                 agg_names: Optional[list[str]] = None):
        assert window_us > 0
        self.child = child
        self.time_col = time_col
        self.window_us = window_us
        self.ooo_bound_us = ooo_bound_us
        self.group_exprs = list(group_exprs)
        self.aggs = list(aggs)
        self.group_names = group_names
        self.agg_names = agg_names
        # schema = window_start ++ the aggregate's output schema
        from auron_tpu.ops.agg import AggOp
        probe = AggOp(child, self.group_exprs, self.aggs, mode="complete",
                      group_names=group_names, agg_names=agg_names)
        self._schema = Schema(
            (Field("window_start", DataType.TIMESTAMP_US, False),)
            + tuple(probe.schema().fields))

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self._schema

    def _make_agg(self, batches):
        from auron_tpu.io.parquet import DeviceBatchScanOp
        from auron_tpu.ops.agg import AggOp
        scan = DeviceBatchScanOp(lambda _p: batches, self.child.schema())
        return AggOp(scan, self.group_exprs, self.aggs, mode="complete",
                     group_names=self.group_names, agg_names=self.agg_names)

    def _fire(self, wstart: int, batches, ctx) -> Iterator[DeviceBatch]:
        agg = self._make_agg(batches)
        for out in agg.execute(0, ExecContext(
                stage_id=ctx.stage_id, partition_id=ctx.partition_id,
                metrics=ctx.metrics, mem_manager=ctx.mem_manager,
                config=ctx.config)):
            cap = out.capacity
            wcol = PrimitiveColumn(jnp.full(cap, wstart, jnp.int64),
                                   jnp.ones(cap, bool))
            yield DeviceBatch((wcol,) + out.columns, out.num_rows)

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)
        late_rows = metrics.counter("late_rows")
        fired_windows = metrics.counter("fired_windows")
        in_schema = self.child.schema()
        win = self.window_us

        def stream():
            import numpy as np
            #: window_start → list of host RecordBatches awaiting fire
            pending: dict[int, list] = {}
            watermark = None     # advances BETWEEN batches (per-batch
            #                      approximation of Flink's per-element wm)

            def fire_window(w: int):
                rbs = pending.pop(w)
                fired_windows.add(1)
                # lazy per-batch upload: the window's rows reach the device
                # one batch at a time as the aggregation consumes them, not
                # all at once outside memmgr control
                batches = (to_device(x)[0] for x in rbs if x.num_rows)
                yield from self._fire(w, batches, ctx)

            for batch in self.child.execute(partition, ctx):
                rb = to_arrow(batch, in_schema)
                if rb.num_rows == 0:
                    continue
                ts = rb.column(self.time_col)
                if ts.null_count:
                    keep = pc.is_valid(ts)
                    dropped = rb.num_rows - pc.sum(
                        keep.cast(pa.int64())).as_py()
                    late_rows.add(dropped)   # null event time = unusable
                    rb = rb.filter(keep)
                    if rb.num_rows == 0:
                        continue
                    ts = rb.column(self.time_col)
                # exact int64 bucketing (float division misassigns rows
                # beyond 2^53 us)
                ts_np = pc.cast(ts, pa.int64()).to_numpy(
                    zero_copy_only=False)
                wstart_np = ts_np - np.mod(ts_np, win)
                wstarts = pa.array(wstart_np, pa.int64())
                for wstart in np.unique(wstart_np).tolist():
                    rows = rb.filter(pc.equal(wstarts, wstart))
                    # Flink lateness: element late iff its window end has
                    # been passed by the watermark — whether or not the
                    # window ever held on-time rows
                    if watermark is not None and wstart + win <= watermark:
                        late_rows.add(rows.num_rows)
                        continue
                    pending.setdefault(wstart, []).append(rows)
                batch_max = int(ts_np.max())
                wm = batch_max - self.ooo_bound_us
                watermark = wm if watermark is None else max(watermark, wm)
                for w in sorted(w for w in pending
                                if w + win <= watermark):
                    yield from fire_window(w)
            # end of (bounded) stream: flush the rest in window order
            for w in sorted(pending):
                yield from fire_window(w)

        return count_output(stream(), metrics, timed=True)

    def __repr__(self):
        return (f"StreamingWindowAggOp[{self.window_us}us, "
                f"{len(self.aggs)} aggs]")
