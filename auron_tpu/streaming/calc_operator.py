"""Streaming calc operator: the host-engine streaming lifecycle.

The reference's Flink integration wraps the native engine in a streaming
operator: ``FlinkAuronCalcOperator`` buffers incoming rows into an Arrow
writer, flushes a batch through the native runtime when the buffer fills
OR a checkpoint barrier arrives, and drains results back as host rows
(reference: auron-flink-extension/.../operator/
FlinkAuronCalcOperator.java:87-267 — open() resolves the converted plan,
processElement() buffers, snapshotState() flushes so no buffered row is
lost across a checkpoint/restore).

``CalcOperator`` is that lifecycle for ANY host streaming engine:

    op = CalcOperator(plan_node, input_schema, adaptor=...)
    op.open()
    for row in source: out.extend(op.process(row))
    state = op.snapshot()          # checkpoint barrier: flush + state
    ...crash...
    op2 = CalcOperator(...); op2.restore(state)

The plan executes per flushed batch through the engine's in-process
runtime with the batch exposed as a memory-scan table — the structural
role of FFIReaderExec feeding the converted Calc program.
"""

from __future__ import annotations

import json
from typing import Callable, Iterator, Optional

import pyarrow as pa

from auron_tpu.columnar.schema import Schema
from auron_tpu.ir import pb

#: rows buffered before an automatic flush (the reference flushes on the
#: Arrow writer's batch boundary)
DEFAULT_BUFFER_ROWS = 4096

#: the well-known catalog name the calc plan reads its buffered rows from
INPUT_TABLE = "__calc_input__"


class CalcOperator:
    """Buffer → flush-through-engine → emit, with checkpoint flush."""

    def __init__(self, plan: pb.PlanNode, input_schema: Schema,
                 buffer_rows: int = DEFAULT_BUFFER_ROWS,
                 on_emit: Optional[Callable] = None):
        self._plan = plan
        self._input_schema = input_schema
        self._buffer_rows = buffer_rows
        self._rows: list[dict] = []
        self._opened = False
        self._emitted_batches = 0
        self._processed_rows = 0
        self.on_emit = on_emit

    # -- lifecycle ----------------------------------------------------------

    def open(self) -> None:
        """Resolve the plan once (the reference resolves the converted
        proto in open()); cheap here — planning happens per flush against
        the buffered table, kernels are cached across flushes."""
        self._opened = True

    def process(self, row: dict) -> list[dict]:
        """Buffer one host row; returns emitted result rows (empty until
        a flush boundary)."""
        assert self._opened, "open() first"
        self._rows.append(row)
        self._processed_rows += 1
        if len(self._rows) >= self._buffer_rows:
            return list(self._flush())
        return []

    def snapshot(self) -> bytes:
        """Checkpoint barrier: FLUSH buffered rows (the reference flushes
        in snapshotState so no element is lost on restore), then return
        the durable operator state. Returns state bytes; emitted rows go
        through ``on_emit`` (set it to capture flush-at-checkpoint
        output)."""
        flushed = list(self._flush())
        if flushed and self.on_emit is None:
            raise RuntimeError(
                "checkpoint flushed rows but no on_emit sink is attached "
                "— results would be lost")
        state = {"processed_rows": self._processed_rows,
                 "emitted_batches": self._emitted_batches}
        return json.dumps(state).encode()

    def restore(self, state: bytes) -> None:
        s = json.loads(state.decode())
        self._processed_rows = int(s["processed_rows"])
        self._emitted_batches = int(s["emitted_batches"])
        self._opened = True

    def close(self) -> list[dict]:
        """End of stream: final flush."""
        return list(self._flush())

    # -- the engine boundary -------------------------------------------------

    def _flush(self) -> Iterator[dict]:
        if not self._rows:
            return
        from auron_tpu.columnar.arrow_bridge import (schema_to_arrow,
                                                     to_arrow)
        from auron_tpu.ir.planner import PlannerContext, plan_from_bytes
        from auron_tpu.runtime.executor import (ExecutionRuntime,
                                                TaskDefinition)
        arrow_schema = schema_to_arrow(self._input_schema)
        tbl = pa.Table.from_pylist(self._rows, schema=arrow_schema)
        self._rows = []
        ctx = PlannerContext(catalog={INPUT_TABLE: tbl})
        op = plan_from_bytes(
            pb.TaskDefinition(
                plan=self._plan,
                task_id=self._emitted_batches).SerializeToString(), ctx)
        rt = ExecutionRuntime(op, TaskDefinition(
            task_id=self._emitted_batches))
        out_schema = op.schema()
        self._emitted_batches += 1
        for batch in rt.batches():
            rb = to_arrow(batch, out_schema)
            for row in rb.to_pylist():
                if self.on_emit is not None:
                    self.on_emit(row)
                yield row
        rt.finalize()
