"""Streaming sources (the Flink-analogue slice of the framework).

The reference's streaming support lives in datafusion-ext-plans/src/flink/:
a native Kafka consumer (kafka_scan_exec.rs), an in-process mock broker for
tests (kafka_mock_scan_exec.rs), and row deserializers (json_deserializer.rs,
pb_deserializer.rs). Here the same roles are: MockBroker (broker.py),
KafkaScanOp (kafka.py), and the json/proto-rows decoders (rows.py).
"""
