"""In-process mock message broker.

The test-time stand-in for a Kafka cluster, mirroring the reference's
kafka_mock_scan_exec (reference: datafusion-ext-plans/src/flink/
kafka_mock_scan_exec.rs): topics are named partitioned logs of byte
messages; consumers poll by (topic, partition, offset). A real-broker
backend would implement the same poll surface — the scan op only sees this
interface.
"""

from __future__ import annotations

import threading
from typing import Optional


class MockBroker:
    """Thread-safe topic → partitioned log of bytes messages."""

    _registry: dict[str, "MockBroker"] = {}
    _registry_lock = threading.Lock()

    @classmethod
    def get(cls, bootstrap: str) -> "MockBroker":
        """Resolve a broker by bootstrap string (creating it on first use),
        so producers (tests / host engine) and the scan op rendezvous by
        name the way Kafka clients do by bootstrap servers."""
        with cls._registry_lock:
            if bootstrap not in cls._registry:
                cls._registry[bootstrap] = cls()
            return cls._registry[bootstrap]

    @classmethod
    def reset(cls, bootstrap: Optional[str] = None) -> None:
        with cls._registry_lock:
            if bootstrap is None:
                cls._registry.clear()
            else:
                cls._registry.pop(bootstrap, None)

    def __init__(self):
        self._lock = threading.Lock()
        self._topics: dict[str, list[list[bytes]]] = {}
        #: consumer-group committed offsets: (group, topic, partition) → off
        self._commits: dict[tuple[str, str, int], int] = {}

    def create_topic(self, topic: str, num_partitions: int = 1) -> None:
        with self._lock:
            self._topics.setdefault(
                topic, [[] for _ in range(num_partitions)])

    def num_partitions(self, topic: str) -> int:
        with self._lock:
            return len(self._topics.get(topic, ()))

    def produce(self, topic: str, message: bytes, partition: int = 0) -> None:
        with self._lock:
            if topic not in self._topics:
                self._topics[topic] = [[]]
            self._topics[topic][partition].append(message)

    def poll(self, topic: str, partition: int, offset: int,
             max_messages: int) -> list[bytes]:
        """Fetch up to max_messages starting at offset (may be empty)."""
        with self._lock:
            log = self._topics.get(topic)
            if log is None or partition >= len(log):
                return []
            return list(log[partition][offset:offset + max_messages])

    def commit(self, group: str, topic: str, partition: int,
               offset: int) -> None:
        """Record a consumer group's next-read offset (Kafka offset-commit
        semantics: the committed offset is the NEXT message to consume)."""
        with self._lock:
            self._commits[(group, topic, partition)] = offset

    def committed(self, group: str, topic: str, partition: int) -> int:
        with self._lock:
            return self._commits.get((group, topic, partition), 0)

    def end_offset(self, topic: str, partition: int) -> int:
        with self._lock:
            log = self._topics.get(topic)
            if log is None or partition >= len(log):
                return 0
            return len(log[partition])
