"""Spark version shims for plan ingestion.

The reference compiles one Scala source tree per Spark version with the
``@sparkver`` whitebox macro enabling/disabling defs per version, plus a
60-method ``Shims`` seam (reference: spark-version-annotation-macros/
sparkver.scala:24-94, spark-extension/.../Shims.scala:64-293). This
engine ingests serialized plan JSON instead of linking against Spark, so
the seam collapses to data: per-version tables of (a) plan wrappers that
are transparent, (b) expression wrappers that are semantically identity
or reduce to casts, and (c) class renames across versions. One converter
source serves Spark 3.0..4.x by consulting the shims for the session's
version.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import total_ordering


@total_ordering
@dataclass(frozen=True)
class SemanticVersion:
    """'3.5.1' style version with comparison (reference: common/
    SemanticVersion.scala)."""

    major: int
    minor: int
    patch: int = 0

    @classmethod
    def parse(cls, s: str) -> "SemanticVersion":
        parts = (s.split("-")[0].split(".") + ["0", "0"])[:3]
        return cls(int(parts[0]), int(parts[1] or 0), int(parts[2] or 0))

    def _key(self):
        return (self.major, self.minor, self.patch)

    def __lt__(self, other):
        return self._key() < other._key()

    def __str__(self):
        return f"{self.major}.{self.minor}.{self.patch}"


class SparkShims:
    """Version-conditioned ingestion tables."""

    def __init__(self, version: str = "3.5.0"):
        #: retained as the gating hook: the ingestion tables below are
        #: deliberately version-TOLERANT supersets (a plan recorded on
        #: Spark 3.0 must ingest under a 3.5 session and vice versa), so
        #: nothing currently branches on it; a genuinely incompatible
        #: future difference gates here with `self.version >= V(x, y)`.
        self.version = SemanticVersion.parse(version)
        self.version_str = version

        #: plan nodes that wrap a single child transparently — both AQE
        #: reader spellings accepted (renamed in 3.2:
        #: CustomShuffleReaderExec → AQEShuffleReadExec)
        self.transparent_plan = {
            "WholeStageCodegenExec", "InputAdapter",
            "AdaptiveSparkPlanExec", "QueryStageExec",
            "ShuffleQueryStageExec", "BroadcastQueryStageExec",
            "ReusedExchangeExec",
            "AQEShuffleReadExec", "CustomShuffleReaderExec",
        }

        #: expression wrappers that evaluate to their child.
        #: PromotePrecision existed through 3.3 (removed in 3.4 —
        #: SPARK-39316); the normalization wrappers are identity for
        #: engine semantics (this engine already canonicalizes NaN/-0.0
        #: in its hash/sort kernels).
        self.identity_exprs = {"KnownFloatingPointNormalized",
                               "KnownNotNull", "PromotePrecision"}

        #: CheckOverflow(child, dtype, nullOnOverflow) reduces to a
        #: decimal cast in this engine when nullOnOverflow is true (the
        #: cast path implements the overflow-to-null contract); present
        #: in all 3.x
        self.overflow_wrappers = {"CheckOverflow", "CheckOverflowInSum"}

    def is_transparent_plan(self, cls: str) -> bool:
        return cls in self.transparent_plan

    def is_identity_expr(self, cls: str) -> bool:
        return cls in self.identity_exprs

    def is_overflow_wrapper(self, cls: str) -> bool:
        return cls in self.overflow_wrappers
