"""Spark physical plan → auron proto converter.

The engine-integration slice (L1): consumes a RECORDED Spark physical
plan in Spark's own ``toJSON`` tree encoding (see spark_plan.py) and
lowers it node-by-node to this engine's protobuf IR, the way the
reference's Scala extension converts live plans (reference:
AuronConverters.scala:209-310 per-class dispatch + tryConvert tagging;
NativeConverters.scala:95-1540 expression translation;
AuronConvertStrategy.scala:41-76 convertible/never-convert tags).

Strategy contract:
- every plan node gets a tag: ``convertible`` or a never-convert reason
  (``ConversionReport.tags``);
- an unconvertible node WITH declared output becomes an explicit fallback
  boundary — a MemoryScanNode on a well-known table name the embedding
  host must populate with that subtree's rows (the ConvertToNativeExec
  boundary of the reference, SURVEY §3.1); its subtree stays unconverted;
- an unconvertible node without declared output poisons its ancestors up
  to the nearest fallback-capable node.

Simplifications vs live Spark JSON (documented, fixture-facing): case
objects (join type, agg mode, build side) may appear either as Spark's
``{"object": "...Inner$"}`` or as plain strings; scan file lists come
from ``metadata.Location``'s ``InMemoryFileIndex[...]`` rendering.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from auron_tpu.integration.spark_plan import SparkNode, parse_plan
from auron_tpu.ir import pb

# ---------------------------------------------------------------------------
# dtype mapping
# ---------------------------------------------------------------------------

_DT = {
    "boolean": pb.DT_BOOL,
    "byte": pb.DT_INT8, "tinyint": pb.DT_INT8,
    "short": pb.DT_INT16, "smallint": pb.DT_INT16,
    "integer": pb.DT_INT32, "int": pb.DT_INT32,
    "long": pb.DT_INT64, "bigint": pb.DT_INT64,
    "float": pb.DT_FLOAT32, "real": pb.DT_FLOAT32,
    "double": pb.DT_FLOAT64,
    "string": pb.DT_STRING, "varchar": pb.DT_STRING,
    "date": pb.DT_DATE32,
    "timestamp": pb.DT_TIMESTAMP_US, "timestamp_ntz": pb.DT_TIMESTAMP_US,
}

_DECIMAL_RE = re.compile(r"decimal\((\d+),\s*(\d+)\)")


def _dtype_to_proto(s: str) -> tuple[int, int, int]:
    """spark dataType string → (DataTypeP, precision, scale)."""
    if s in _DT:
        return _DT[s], 0, 0
    m = _DECIMAL_RE.fullmatch(s)
    if m:
        return pb.DT_DECIMAL, int(m.group(1)), int(m.group(2))
    raise NotImplementedError(f"unsupported Spark dataType {s!r}")


def _object_name(v) -> str:
    """'Inner' from {"object": "...joins.Inner$"} or plain "Inner"."""
    if isinstance(v, dict):
        v = v.get("object", "")
    v = str(v)
    return v.rstrip("$").rsplit(".", 1)[-1]


# ---------------------------------------------------------------------------
# attributes
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Attr:
    name: str
    expr_id: int
    dtype: str     # spark dataType string


def _expr_id(raw: dict) -> int:
    e = raw.get("exprId") or raw.get("resultId") or {}
    return int(e.get("id", -1))


def _attr_of(node: SparkNode) -> Attr:
    return Attr(node.fields.get("name", "?"), _expr_id(node.fields),
                node.fields.get("dataType", "long"))


def _parse_output(node: SparkNode) -> list[Attr]:
    return [_attr_of(t) for t in node.field_trees("output")]


# ---------------------------------------------------------------------------
# expressions
# ---------------------------------------------------------------------------

_BINARY = {
    "Add": "+", "Subtract": "-", "Multiply": "*", "Divide": "/",
    "Remainder": "%", "EqualTo": "==", "GreaterThan": ">",
    "LessThan": "<", "GreaterThanOrEqual": ">=", "LessThanOrEqual": "<=",
    "And": "and", "Or": "or",
}

_STRING_PRED = {"StartsWith": "starts_with", "EndsWith": "ends_with",
                "Contains": "contains"}

_SCALAR_FN = {"Upper": "upper", "Lower": "lower", "Length": "length",
              "Substring": "substring", "Concat": "concat",
              "Coalesce": "coalesce", "Abs": "abs",
              # round-3 surface expansion (exprs/fn_*.py)
              "ConcatWs": "concat_ws", "InitCap": "initcap",
              "StringRepeat": "repeat", "Reverse": "reverse",
              "StringLPad": "lpad", "StringRPad": "rpad",
              "StringInstr": "instr", "StringLocate": "locate",
              "SubstringIndex": "substring_index",
              "StringTranslate": "translate", "Ascii": "ascii", "Chr": "chr",
              "Year": "year", "Month": "month", "DayOfMonth": "day",
              "Quarter": "quarter", "DayOfWeek": "dayofweek",
              "DayOfYear": "dayofyear", "WeekOfYear": "weekofyear",
              "Hour": "hour", "Minute": "minute", "Second": "second",
              "DateAdd": "date_add", "DateSub": "date_sub",
              "DateDiff": "datediff", "DateFormatClass": "date_format",
              "FromUnixTime": "from_unixtime",
              "UnixTimestamp": "unix_timestamp",
              "ToUnixTimestamp": "to_unix_timestamp",
              "TruncDate": "trunc", "TruncTimestamp": "date_trunc",
              "AddMonths": "add_months", "LastDay": "last_day",
              "MonthsBetween": "months_between", "NextDay": "next_day",
              "MakeDate": "make_date",
              "Md5": "md5", "Sha1": "sha1", "Sha2": "sha2", "Crc32": "crc32",
              "Base64": "base64", "UnBase64": "unbase64",
              "Hex": "hex", "Unhex": "unhex",
              "GetJsonObject": "get_json_object",
              "RegExpExtract": "regexp_extract",
              "RegExpReplace": "regexp_replace", "RLike": "rlike",
              "CreateArray": "array", "ArrayContains": "array_contains",
              "ArrayPosition": "array_position", "ElementAt": "element_at",
              "Size": "size", "SortArray": "sort_array",
              "ArrayMax": "array_max", "ArrayMin": "array_min",
              "CreateMap": "map", "MapFromArrays": "map_from_arrays",
              "MapKeys": "map_keys", "MapValues": "map_values",
              "MapContainsKey": "map_contains_key",
              "MapConcat": "map_concat", "GetMapValue": "get_map_value",
              "CreateNamedStruct": "named_struct",
              "Round": "round", "BRound": "bround", "Pow": "pow",
              "Sqrt": "sqrt", "Exp": "exp", "Log": "log",
              "Floor": "floor", "Ceil": "ceil", "Greatest": "greatest",
              "Least": "least", "IsNaN": "isnan", "NaNvl": "nanvl",
              "NullIf": "nullif", "If": "if",
              "StringTrim": "trim", "StringTrimLeft": "ltrim",
              "StringTrimRight": "rtrim", "Murmur3Hash": "hash",
              "XxHash64": "xxhash64"}

_AGG_FN = {"Sum": "sum", "Min": "min", "Max": "max", "Average": "avg",
           "Count": "count", "First": "first",
           "CollectList": "collect_list", "CollectSet": "collect_set"}

#: per-function argument positions whose kernels require a static literal
#: (checked at conversion time so non-literal uses fall back cleanly)
_LITERAL_ARGS = {
    "repeat": (1,), "lpad": (1, 2), "rpad": (1, 2), "instr": (1,),
    "locate": (0,), "substring_index": (1, 2), "translate": (1, 2),
    "space": (0,), "sha2": (1,), "regexp_extract": (1, 2),
    "regexp_replace": (1, 2), "rlike": (1,), "get_json_object": (1,),
    "date_format": (1,), "from_unixtime": (1,), "unix_timestamp": (1,),
    "to_unix_timestamp": (1,), "trunc": (1,), "date_trunc": (0,),
    "next_day": (1,), "sort_array": (1,), "array_repeat": (1,),
}


class ExprConverter:
    def __init__(self, attrs: list[Attr], shims=None, plan_converter=None):
        from auron_tpu.integration.shims import SparkShims
        self.index_of = {a.expr_id: i for i, a in enumerate(attrs)}
        self.attrs = attrs
        self.shims = shims or SparkShims()
        # callback converting an embedded Spark plan (toJSON tree) into a
        # pb.PlanNode — used by ScalarSubquery expressions
        self.plan_converter = plan_converter

    def convert(self, e: SparkNode) -> pb.ExprNode:
        cls = e.simple_name
        # version shims: identity wrappers unwrap; overflow wrappers
        # (CheckOverflow around decimal arith) reduce to a decimal cast
        # whose non-ANSI path IS the null-on-overflow contract
        if self.shims.is_identity_expr(cls):
            return self.convert(e.children[0])
        if self.shims.is_overflow_wrapper(cls):
            if e.fields.get("nullOnOverflow") is False:
                # ANSI mode: Spark RAISES on overflow; the engine's cast
                # nulls — fall back rather than silently diverge
                raise NotImplementedError(
                    "CheckOverflow with nullOnOverflow=false (ANSI)")
            dt, p, s = _dtype_to_proto(str(e.fields.get("dataType", "")))
            return pb.ExprNode(cast=pb.CastE(
                child=self.convert(e.children[0]), dtype=dt, precision=p,
                scale=s))
        if cls == "AttributeReference":
            eid = _expr_id(e.fields)
            if eid not in self.index_of:
                raise NotImplementedError(
                    f"attribute {e.fields.get('name')}#{eid} not found in "
                    f"child output")
            return pb.ExprNode(column=pb.ColumnRefE(
                index=self.index_of[eid],
                name=e.fields.get("name", "")))
        if cls == "Literal":
            return self._literal(e)
        if cls == "Alias":
            return self.convert(e.children[0])
        if cls in _BINARY:
            return pb.ExprNode(binary=pb.BinaryE(
                op=_BINARY[cls], left=self.convert(e.children[0]),
                right=self.convert(e.children[1])))
        if cls == "Not":
            return pb.ExprNode(unary=pb.UnaryE(
                op="not", child=self.convert(e.children[0])))
        if cls == "IsNull":
            return pb.ExprNode(unary=pb.UnaryE(
                op="is_null", child=self.convert(e.children[0])))
        if cls == "IsNotNull":
            return pb.ExprNode(unary=pb.UnaryE(
                op="is_not_null", child=self.convert(e.children[0])))
        if cls in ("Cast", "AnsiCast", "TryCast"):
            dt, p, s = _dtype_to_proto(e.fields["dataType"])
            return pb.ExprNode(cast=pb.CastE(
                child=self.convert(e.children[0]), dtype=dt, precision=p,
                scale=s, try_cast=(cls == "TryCast"),
                ansi=(cls == "AnsiCast")))
        if cls == "In":
            child, *vals = e.children
            lits = []
            for v in vals:
                if v.simple_name != "Literal":
                    raise NotImplementedError("non-literal IN list")
                lits.append(self._literal(v).literal)
            return pb.ExprNode(in_list=pb.InListE(
                child=self.convert(child), values=lits))
        if cls in _STRING_PRED:
            return pb.ExprNode(string_pred=pb.StringPredE(
                kind=_STRING_PRED[cls], child=self.convert(e.children[0]),
                pattern=str(e.children[1].fields.get("value", ""))))
        if cls == "Like":
            return pb.ExprNode(like=pb.LikeE(
                child=self.convert(e.children[0]),
                pattern=str(e.children[1].fields.get("value", ""))))
        if cls in _SCALAR_FN:
            fn = _SCALAR_FN[cls]
            # functions whose kernels need a static (literal) argument must
            # reject non-literal args HERE, at conversion time, so the
            # subtree falls back to the host engine instead of failing the
            # task at kernel-build time
            for idx in _LITERAL_ARGS.get(fn, ()):
                if idx < len(e.children) \
                        and e.children[idx].simple_name != "Literal":
                    raise NotImplementedError(
                        f"{fn}: argument {idx} must be a literal")
            return pb.ExprNode(scalar_function=pb.ScalarFunctionE(
                name=fn,
                args=[self.convert(c) for c in e.children]))
        if cls == "GetStructField":
            # Spark carries the child ordinal as a field, not an argument
            return pb.ExprNode(get_struct_field=pb.GetStructFieldE(
                child=self.convert(e.children[0]),
                ordinal=int(e.fields.get("ordinal", 0))))
        if cls == "ScalarSubquery":
            # uncorrelated scalar subquery: Spark embeds the subquery's
            # physical plan; it executes once and acts as a constant
            # (reference: spark_scalar_subquery_wrapper.rs)
            sub = e.fields.get("plan")
            if sub is None or self.plan_converter is None:
                raise NotImplementedError(
                    "ScalarSubquery without an embedded plan")
            node = self.plan_converter(sub)
            dt, p, s = _dtype_to_proto(str(e.fields.get("dataType", "")))
            sid = 0
            eid = e.fields.get("exprId")
            if isinstance(eid, dict):
                sid = int(eid.get("id", 0))
            return pb.ExprNode(scalar_subquery=pb.ScalarSubqueryE(
                plan=node, dtype=dt, precision=p, scale=s, sid=sid))
        raise NotImplementedError(f"unsupported Spark expression {cls}")

    def _literal(self, e: SparkNode) -> pb.ExprNode:
        dt_s = e.fields.get("dataType", "null")
        raw = e.fields.get("value")
        if raw is None or dt_s == "null":
            dt, p, s = (pb.DT_NULL, 0, 0) if dt_s == "null" \
                else _dtype_to_proto(dt_s)
            return pb.ExprNode(literal=pb.LiteralE(dtype=dt, is_null=True,
                                                   precision=p, scale=s))
        dt, p, s = _dtype_to_proto(dt_s)
        lit = pb.LiteralE(dtype=dt, precision=p, scale=s)
        try:
            if dt in (pb.DT_FLOAT32, pb.DT_FLOAT64):
                lit.f64 = float(raw)
            elif dt == pb.DT_STRING:
                lit.str = str(raw)
            elif dt == pb.DT_BOOL:
                lit.i64 = 1 if str(raw).lower() == "true" else 0
            elif dt == pb.DT_DECIMAL:
                # decimals travel as the scaled unscaled integer
                from decimal import Decimal
                lit.i64 = int(Decimal(str(raw)).scaleb(s))
            else:
                lit.i64 = int(raw)
        except (ValueError, ArithmeticError) as e:
            # surface as never-convert, not a crash of the whole plan
            raise NotImplementedError(
                f"unparseable {dt_s} literal {raw!r}: {e}") from e
        return pb.ExprNode(literal=lit)

    def sort_order(self, e: SparkNode) -> pb.SortOrderP:
        assert e.simple_name == "SortOrder", e.cls
        direction = _object_name(e.fields.get("direction", "Ascending"))
        null_ord = _object_name(e.fields.get("nullOrdering", ""))
        asc = direction == "Ascending"
        nulls_first = (null_ord == "NullsFirst") if null_ord \
            else asc  # spark default: nulls first iff ascending
        return pb.SortOrderP(expr=self.convert(e.children[0]),
                             ascending=asc, nulls_first=nulls_first)


# ---------------------------------------------------------------------------
# plan conversion
# ---------------------------------------------------------------------------

@dataclass
class ConversionReport:
    """convertible / never-convert tagging + fallback boundaries — the
    record the reference keeps in plan tags (convertibleTag,
    neverConvertReasonTag, AuronConvertStrategy.scala:41-47)."""
    tags: list = field(default_factory=list)        # (cls, ok, reason)
    boundaries: list = field(default_factory=list)  # (table, cls, [Attr])

    def tag(self, node: SparkNode, ok: bool, reason: str = ""):
        self.tags.append((node.simple_name, ok, reason))

    @property
    def never_converted(self):
        return [(c, r) for c, ok, r in self.tags if not ok]

    def summary(self) -> str:
        lines = []
        for cls, ok, reason in self.tags:
            lines.append(f"  [{'native' if ok else 'FALLBACK'}] {cls}"
                         + (f" — {reason}" if reason else ""))
        return "\n".join(lines)


@dataclass
class _Converted:
    node: pb.PlanNode
    attrs: list          # output Attrs
    partitions: int = 1  # partition count flowing to parents


_TRANSPARENT = ("WholeStageCodegenExec", "InputAdapter",
                "AQEShuffleReadExec", "CustomShuffleReaderExec",
                "AdaptiveSparkPlanExec", "QueryStageExec",
                "ShuffleQueryStageExec", "BroadcastQueryStageExec")


class SparkPlanConverter:
    """One-shot converter for a recorded plan. ``path_rewrite`` maps the
    recorded file paths into the local filesystem (fixtures record the
    original cluster paths). ``spark_version`` selects the version shims
    (integration/shims.py — the @sparkver seam analogue)."""

    def __init__(self, path_rewrite=None, spark_version: str = "3.5.0"):
        from auron_tpu.integration.shims import SparkShims
        self.path_rewrite = path_rewrite or (lambda p: p)
        self.report = ConversionReport()
        self.shims = SparkShims(spark_version)
        self._fallback_ids = 0

    # -- public entry -------------------------------------------------------

    def convert(self, plan) -> tuple[pb.PlanNode, ConversionReport]:
        root = plan if isinstance(plan, SparkNode) else parse_plan(plan)
        conv = self._convert(root)
        return conv.node, self.report

    def task_bytes(self, plan, partition_id: int = 0) -> bytes:
        node, _ = self.convert(plan)
        return pb.TaskDefinition(plan=node,
                                 partition_id=partition_id).SerializeToString()

    def _convert_subplan(self, plan) -> pb.PlanNode:
        """Convert a plan embedded inside an expression (ScalarSubquery).
        Runs a FRESH converter sharing rewrite/shims: the subquery's
        tags/fallbacks must not pollute this plan's report, and an
        unconvertible subquery falls back as a whole via the raised
        NotImplementedError."""
        sub = SparkPlanConverter(path_rewrite=self.path_rewrite,
                                 spark_version=self.shims.version_str)
        node, report = sub.convert(plan)
        if report.never_converted:
            raise NotImplementedError(
                "unconvertible subquery plan: "
                + "; ".join(r for _c, r in report.never_converted))
        return node

    # -- dispatch with tagging ---------------------------------------------

    def _convert(self, node: SparkNode) -> _Converted:
        cls = node.simple_name
        if cls in _TRANSPARENT or self.shims.is_transparent_plan(cls):
            return self._convert(node.children[0])
        handler = getattr(self, f"_c_{cls}", None)
        try:
            if handler is None:
                raise NotImplementedError(f"no converter for {cls}")
            out = handler(node)
            self.report.tag(node, True)
            return out
        except NotImplementedError as e:
            return self._fallback(node, str(e))

    def _fallback(self, node: SparkNode, reason: str) -> _Converted:
        """ConvertToNative boundary: the host engine executes this subtree
        and feeds rows in via a well-known catalog table."""
        self.report.tag(node, False, reason)
        attrs = _parse_output(node)
        if not attrs:
            raise NotImplementedError(
                f"{node.simple_name} unconvertible ({reason}) and declares "
                "no output to fall back on")
        self._fallback_ids += 1
        table = f"__spark_fallback_{self._fallback_ids}"
        self.report.boundaries.append((table, node.simple_name, attrs))
        return _Converted(
            pb.PlanNode(memory_scan=pb.MemoryScanNode(table_name=table)),
            attrs)

    # -- leaves -------------------------------------------------------------

    _LOCATION_RE = re.compile(r"\[(.*)\]")

    def _scan_files(self, node: SparkNode) -> list[str]:
        meta = node.fields.get("metadata") or {}
        loc = meta.get("Location", "")
        m = self._LOCATION_RE.search(loc)
        if not m:
            raise NotImplementedError(
                f"scan without parseable Location: {loc!r}")
        files = [f.strip() for f in m.group(1).split(",") if f.strip()]
        return [self.path_rewrite(f.replace("file:", "")) for f in files]

    def _c_FileSourceScanExec(self, node: SparkNode) -> _Converted:
        attrs = _parse_output(node)
        meta = node.fields.get("metadata") or {}
        fmt = str(meta.get("Format", "Parquet")).lower()
        files = self._scan_files(node)
        fields = []
        for a in attrs:
            dt, p, s = _dtype_to_proto(a.dtype)
            fields.append(pb.FieldP(name=a.name, dtype=dt, nullable=True,
                                    precision=p, scale=s))
        schema = pb.SchemaP(fields=fields)
        if fmt == "parquet":
            n = pb.PlanNode(parquet_scan=pb.ParquetScanNode(
                files=files, schema=schema,
                columns=[a.name for a in attrs]))
        elif fmt == "orc":
            n = pb.PlanNode(orc_scan=pb.OrcScanNode(
                files=files, schema=schema,
                columns=[a.name for a in attrs]))
        else:
            raise NotImplementedError(f"scan format {fmt}")
        return _Converted(n, attrs, partitions=max(len(files), 1))

    def _c_BatchScanExec(self, node: SparkNode) -> _Converted:
        """DSv2 scans (Iceberg / Paimon / Hudi ride this node): delegate to
        the lakehouse convert-providers (integration/providers.py — the
        reference's ConvertProvider plugin seam, thirdparty/auron-iceberg
        etc.); unmatched scans fall back."""
        from auron_tpu.integration.providers import try_convert_scan
        attrs = _parse_output(node)
        got = try_convert_scan(node, attrs, _dtype_to_proto,
                               self.path_rewrite)
        if got is None:
            raise NotImplementedError(
                "BatchScanExec with no matching scan provider")
        n, partitions, provider = got
        self.report.tag(node, True, f"provider:{provider}")
        return _Converted(n, attrs, partitions=partitions)

    # -- unary row transforms ----------------------------------------------

    def _c_FilterExec(self, node: SparkNode) -> _Converted:
        child = self._convert(node.children[0])
        ec = ExprConverter(child.attrs, self.shims, self._convert_subplan)
        cond = node.field_tree("condition")
        n = pb.PlanNode(filter=pb.FilterNode(
            child=child.node, predicates=[ec.convert(cond)]))
        return _Converted(n, child.attrs, child.partitions)

    def _project(self, child: _Converted,
                 project_list: list) -> _Converted:
        ec = ExprConverter(child.attrs, self.shims, self._convert_subplan)
        exprs, names, attrs = [], [], []
        for t in project_list:
            exprs.append(ec.convert(t))
            name = t.fields.get("name", "col")
            eid = _expr_id(t.fields)
            dtype = t.fields.get("dataType", "")
            if t.simple_name == "Alias" and not dtype:
                dtype = t.children[0].fields.get("dataType", "long")
            names.append(name)
            attrs.append(Attr(name, eid, dtype or "long"))
        n = pb.PlanNode(project=pb.ProjectNode(
            child=child.node, exprs=exprs, names=names))
        return _Converted(n, attrs, child.partitions)

    def _c_ProjectExec(self, node: SparkNode) -> _Converted:
        child = self._convert(node.children[0])
        return self._project(child, node.field_trees("projectList"))

    def _c_SortExec(self, node: SparkNode) -> _Converted:
        child = self._convert(node.children[0])
        ec = ExprConverter(child.attrs, self.shims, self._convert_subplan)
        orders = [ec.sort_order(t) for t in node.field_trees("sortOrder")]
        n = pb.PlanNode(sort=pb.SortNode(child=child.node,
                                         sort_orders=orders, fetch=-1))
        return _Converted(n, child.attrs, child.partitions)

    def _c_TakeOrderedAndProjectExec(self, node: SparkNode) -> _Converted:
        child = self._convert(node.children[0])
        ec = ExprConverter(child.attrs, self.shims, self._convert_subplan)
        orders = [ec.sort_order(t) for t in node.field_trees("sortOrder")]
        limit = int(node.fields.get("limit", -1))
        # global top-k: map-side SortNode(fetch=k) per partition so only
        # n_part * k rows cross the coalescing exchange
        plan = child.node
        if child.partitions > 1:
            plan = pb.PlanNode(sort=pb.SortNode(
                child=plan, sort_orders=orders, fetch=limit))
            plan = pb.PlanNode(shuffle_writer=pb.ShuffleWriterNode(
                child=plan,
                partitioning=pb.PartitioningP(kind="single",
                                              num_partitions=1),
                input_partitions=child.partitions))
        sort = pb.PlanNode(sort=pb.SortNode(child=plan, sort_orders=orders,
                                            fetch=limit))
        out = _Converted(sort, child.attrs, 1)
        plist = node.field_trees("projectList")
        if plist:
            return self._project(out, plist)
        return out

    def _c_LocalLimitExec(self, node: SparkNode) -> _Converted:
        child = self._convert(node.children[0])
        n = pb.PlanNode(limit=pb.LimitNode(
            child=child.node, limit=int(node.fields.get("limit", 0))))
        return _Converted(n, child.attrs, child.partitions)

    def _c_GlobalLimitExec(self, node: SparkNode) -> _Converted:
        child = self._convert(node.children[0])
        plan = child.node
        parts = child.partitions
        limit = int(node.fields.get("limit", 0))
        if parts > 1:
            # map-side LocalLimit caps each partition before the
            # coalescing exchange (the LocalLimit/GlobalLimit pair)
            plan = pb.PlanNode(limit=pb.LimitNode(child=plan, limit=limit))
            plan = pb.PlanNode(shuffle_writer=pb.ShuffleWriterNode(
                child=plan,
                partitioning=pb.PartitioningP(kind="single",
                                              num_partitions=1),
                input_partitions=parts))
            parts = 1
        n = pb.PlanNode(limit=pb.LimitNode(child=plan, limit=limit))
        return _Converted(n, child.attrs, parts)

    def _c_UnionExec(self, node: SparkNode) -> _Converted:
        kids = [self._convert(c) for c in node.children]
        n = pb.PlanNode(union=pb.UnionNode(children=[k.node for k in kids]))
        return _Converted(n, kids[0].attrs,
                          max(k.partitions for k in kids))

    # -- exchanges ----------------------------------------------------------

    def _partitioning(self, tree: SparkNode,
                      ec: ExprConverter) -> tuple[pb.PartitioningP, int]:
        cls = tree.simple_name
        n_out = int(tree.fields.get("numPartitions", 1))
        if cls == "HashPartitioning":
            return pb.PartitioningP(
                kind="hash", num_partitions=n_out,
                hash_keys=[ec.convert(c) for c in tree.children]), n_out
        if cls == "SinglePartition":
            return pb.PartitioningP(kind="single", num_partitions=1), 1
        if cls == "RoundRobinPartitioning":
            return pb.PartitioningP(kind="round_robin",
                                    num_partitions=n_out), n_out
        if cls == "RangePartitioning":
            return pb.PartitioningP(
                kind="range", num_partitions=n_out,
                range_orders=[ec.sort_order(c)
                              for c in tree.children]), n_out
        raise NotImplementedError(f"partitioning {cls}")

    def _c_ShuffleExchangeExec(self, node: SparkNode) -> _Converted:
        child = self._convert(node.children[0])
        ec = ExprConverter(child.attrs, self.shims, self._convert_subplan)
        ptree = node.field_tree("outputPartitioning")
        part, n_out = self._partitioning(ptree, ec)
        n = pb.PlanNode(shuffle_writer=pb.ShuffleWriterNode(
            child=child.node, partitioning=part,
            input_partitions=child.partitions))
        return _Converted(n, child.attrs, n_out)

    def _c_BroadcastExchangeExec(self, node: SparkNode) -> _Converted:
        child = self._convert(node.children[0])
        n = pb.PlanNode(broadcast_exchange=pb.BroadcastExchangeNode(
            child=child.node, input_partitions=child.partitions))
        return _Converted(n, child.attrs, 1)

    # -- joins --------------------------------------------------------------

    _JOIN_TYPE = {"Inner": "inner", "LeftOuter": "left",
                  "RightOuter": "right", "FullOuter": "full",
                  "LeftSemi": "semi", "LeftAnti": "anti",
                  "ExistenceJoin": "existence", "Cross": "inner"}

    def _join_common(self, node: SparkNode):
        jt = _object_name(node.fields.get("joinType", "Inner"))
        # ExistenceJoin(exists#n) renders with a parameter
        jt = "ExistenceJoin" if jt.startswith("ExistenceJoin") else jt
        if jt not in self._JOIN_TYPE:
            raise NotImplementedError(f"join type {jt}")
        if node.fields.get("condition"):
            raise NotImplementedError("non-equi join condition")
        return self._JOIN_TYPE[jt]

    def _c_BroadcastHashJoinExec(self, node: SparkNode) -> _Converted:
        jt = self._join_common(node)
        side = _object_name(node.fields.get("buildSide", "BuildRight"))
        if side != "BuildRight":
            raise NotImplementedError("BuildLeft broadcast join")
        left = self._convert(node.children[0])
        right = self._convert(node.children[1])
        lec, rec = (ExprConverter(left.attrs, self.shims, self._convert_subplan),
                    ExprConverter(right.attrs, self.shims, self._convert_subplan))
        lk = [lec.convert(t) for t in node.field_trees("leftKeys")]
        rk = [rec.convert(t) for t in node.field_trees("rightKeys")]
        n = pb.PlanNode(hash_join=pb.HashJoinNode(
            probe=left.node, build=right.node, probe_keys=lk,
            build_keys=rk, join_type=jt))
        attrs = self._join_attrs(node, jt, left, right)
        return _Converted(n, attrs, left.partitions)

    _c_ShuffledHashJoinExec = _c_BroadcastHashJoinExec

    def _c_SortMergeJoinExec(self, node: SparkNode) -> _Converted:
        jt = self._join_common(node)
        left = self._convert(node.children[0])
        right = self._convert(node.children[1])
        lec, rec = (ExprConverter(left.attrs, self.shims, self._convert_subplan),
                    ExprConverter(right.attrs, self.shims, self._convert_subplan))
        lk = [lec.convert(t) for t in node.field_trees("leftKeys")]
        rk = [rec.convert(t) for t in node.field_trees("rightKeys")]
        n = pb.PlanNode(sort_merge_join=pb.SortMergeJoinNode(
            probe=left.node, build=right.node, probe_keys=lk,
            build_keys=rk, join_type=jt))
        attrs = self._join_attrs(node, jt, left, right)
        return _Converted(n, attrs, left.partitions)

    @staticmethod
    def _join_attrs(node, jt, left, right) -> list[Attr]:
        if jt in ("semi", "anti"):
            return list(left.attrs)
        if jt == "existence":
            declared = _parse_output(node)
            exists = declared[-1] if declared else Attr("exists", -1,
                                                        "boolean")
            return list(left.attrs) + [exists]
        return list(left.attrs) + list(right.attrs)

    # -- aggregation --------------------------------------------------------

    def _agg_parts(self, node: SparkNode):
        groups = node.field_trees("groupingExpressions")
        agg_exprs = node.field_trees("aggregateExpressions")
        modes = {_object_name(a.fields.get("mode", "Complete"))
                 for a in agg_exprs} or {"Complete"}
        if len(modes) > 1:
            raise NotImplementedError(f"mixed agg modes {modes}")
        mode = modes.pop()
        if mode not in ("Partial", "Final", "Complete"):
            # e.g. PartialMerge (distinct rewrites / AQE re-optimizations):
            # unsupported — must become a fallback boundary, not a plan
            # that fails the engine's mode assertion later
            raise NotImplementedError(f"aggregate mode {mode}")
        return groups, agg_exprs, mode

    def _agg_fn(self, agg_expr: SparkNode) -> tuple[str, SparkNode, bool]:
        fn_tree = agg_expr.children[0]
        cls = fn_tree.simple_name
        if cls not in _AGG_FN:
            raise NotImplementedError(f"aggregate function {cls}")
        fn = _AGG_FN[cls]
        distinct = bool(agg_expr.fields.get("isDistinct", False))
        arg = fn_tree.children[0] if fn_tree.children else None
        if fn == "count" and arg is None:
            fn = "count_star"
        return fn, arg, distinct

    def _c_HashAggregateExec(self, node: SparkNode) -> _Converted:
        child = self._convert(node.children[0])
        groups, agg_exprs, mode = self._agg_parts(node)
        ec = ExprConverter(child.attrs, self.shims, self._convert_subplan)
        group_names = [g.fields.get("name", f"k{i}")
                       for i, g in enumerate(groups)]

        aggs, agg_attrs = [], []
        for a in agg_exprs:
            fn, arg, distinct = self._agg_fn(a)
            rid = _expr_id(a.fields)
            fn_tree = a.children[0]
            agg_attrs.append(Attr(fn, rid,
                                  fn_tree.fields.get("dataType", "double")))
            if mode == "Final":
                aggs.append(pb.AggFunctionP(fn=fn, distinct=distinct))
            else:
                aggs.append(pb.AggFunctionP(
                    fn=fn, distinct=distinct,
                    arg=ec.convert(arg) if arg is not None else None))

        if mode == "Final":
            # grouping refs must land on the leading columns of the
            # partial layout flowing through the exchange
            for i, g in enumerate(groups):
                idx = ec.convert(g).column.index
                if idx != i:
                    raise NotImplementedError(
                        "final agg grouping not in partial column order")
            group_protos = [pb.ExprNode(column=pb.ColumnRefE(index=i))
                            for i in range(len(groups))]
        else:
            group_protos = [ec.convert(g) for g in groups]

        agg_names = [a.name for a in agg_attrs]
        n = pb.PlanNode(agg=pb.AggNode(
            child=child.node, group_exprs=group_protos, aggs=aggs,
            mode=mode.lower(), group_names=group_names,
            agg_names=agg_names))
        group_attrs = [Attr(nm, _expr_id(g.fields),
                            g.fields.get("dataType", "long"))
                       for nm, g in zip(group_names, groups)]
        out = _Converted(n, group_attrs + agg_attrs, child.partitions)

        if mode in ("Final", "Complete"):
            result = node.field_trees("resultExpressions")
            if result and not self._is_identity(result, out.attrs):
                return self._project(out, result)
        return out

    _c_SortAggregateExec = _c_HashAggregateExec
    _c_ObjectHashAggregateExec = _c_HashAggregateExec

    @staticmethod
    def _is_identity(result_trees: list, attrs: list) -> bool:
        if len(result_trees) != len(attrs):
            return False
        for t, a in zip(result_trees, attrs):
            tr = t.children[0] if t.simple_name == "Alias" else t
            if tr.simple_name != "AttributeReference":
                return False
            if _expr_id(tr.fields) != a.expr_id:
                return False
            # an Alias that renames is not identity — the projection must
            # run so downstream sees the aliased name
            if t.simple_name == "Alias" and t.fields.get("name") != a.name:
                return False
        return True
