"""Host-engine adaptor SPI — the engine-agnostic core seam.

The reference keeps its core engine-agnostic behind the ``AuronAdaptor``
service-provider interface: Spark and Flink each ship an adaptor
discovered via ServiceLoader, and everything below the adaptor (JNI
bridge, runtime, operators) never mentions a host engine (reference:
auron-core/src/main/java/org/apache/auron/AuronAdaptor.java + the
MockAuronAdaptor test double exercising the wrapper lifecycle without
Spark). This module is that seam for this engine: an adaptor converts a
host plan into the protobuf IR, supplies fallback-subtree rows, and
declares config overrides; the registry is the ServiceLoader analogue.

Shipped adaptors:
  * ``SparkAdaptor`` — wraps integration/spark_converter (plan.toJSON in)
  * ``StreamingCalcAdaptor`` — the Flink-shaped streaming host: its
    "plan" is a calc spec the streaming CalcOperator drives per batch
"""

from __future__ import annotations

from typing import Callable, Optional

from auron_tpu.ir import pb

_REGISTRY: dict[str, "HostEngineAdaptor"] = {}


def register_adaptor(adaptor: "HostEngineAdaptor") -> None:
    _REGISTRY[adaptor.name] = adaptor


def get_adaptor(name: str) -> "HostEngineAdaptor":
    if name not in _REGISTRY:
        raise KeyError(f"no host adaptor {name!r} registered "
                       f"(known: {sorted(_REGISTRY)})")
    return _REGISTRY[name]


def registered_adaptors() -> list[str]:
    return sorted(_REGISTRY)


class HostEngineAdaptor:
    """SPI: what a host engine must provide to attach to this engine."""

    #: registry key (ServiceLoader analogue)
    name: str = "abstract"

    def convert_plan(self, raw_plan, path_rewrite=None):
        """Host plan (engine-native encoding) → (pb.PlanNode, report).
        ``report`` must expose ``never_converted`` and ``boundaries``
        like integration.spark_converter.ConversionReport."""
        raise NotImplementedError

    def fallback_provider(self) -> Optional[Callable]:
        """Callable(table, exec_class, columns) -> pa.Table executing an
        unconvertible subtree host-side, or None when the host engine
        has no interpreter of its own."""
        return None

    def config_overrides(self) -> dict:
        """Engine-specific typed-config overrides (reference: each
        adaptor binds its host's conf system, SparkAuronConfiguration /
        FlinkAuronConfiguration)."""
        return {}


class SparkAdaptor(HostEngineAdaptor):
    name = "spark"

    def __init__(self, spark_version: str = "3.5.0"):
        self.spark_version = spark_version

    def convert_plan(self, raw_plan, path_rewrite=None):
        from auron_tpu.integration.spark_converter import SparkPlanConverter
        conv = SparkPlanConverter(path_rewrite=path_rewrite,
                                  spark_version=self.spark_version)
        return conv.convert(raw_plan)


class StreamingCalcAdaptor(HostEngineAdaptor):
    """The Flink-shaped host: a raw plan here is a calc spec
    ``{"exprs": [ExprNode json...], "names": [...]}`` applied over the
    CalcOperator's buffered input (reference: FlinkNodeConverter
    translating Calc nodes into the same protobuf IR the Spark side
    uses — one IR, many hosts)."""

    name = "streaming_calc"

    def convert_plan(self, raw_plan, path_rewrite=None):
        import json as _json

        from google.protobuf import json_format

        from auron_tpu.integration.spark_converter import ConversionReport
        from auron_tpu.streaming.calc_operator import INPUT_TABLE
        spec = raw_plan if isinstance(raw_plan, dict) \
            else _json.loads(raw_plan)
        scan = pb.PlanNode(memory_scan=pb.MemoryScanNode(
            table_name=INPUT_TABLE))
        exprs = [json_format.ParseDict(e, pb.ExprNode())
                 for e in spec["exprs"]]
        node = pb.PlanNode(project=pb.ProjectNode(
            child=scan, exprs=exprs, names=list(spec["names"])))
        if spec.get("predicates"):
            preds = [json_format.ParseDict(e, pb.ExprNode())
                     for e in spec["predicates"]]
            node = pb.PlanNode(project=pb.ProjectNode(
                child=pb.PlanNode(filter=pb.FilterNode(
                    child=scan, predicates=preds)),
                exprs=exprs, names=list(spec["names"])))
        report = ConversionReport()

        class _N:
            simple_name = "StreamCalc"
        report.tag(_N(), True)
        return node, report


# default registrations (the "service files" of this engine)
register_adaptor(SparkAdaptor())
register_adaptor(StreamingCalcAdaptor())
