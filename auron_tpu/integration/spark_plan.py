"""Decoder for Spark's TreeNode JSON (``plan.toJSON`` /
``df.queryExecution.executedPlan.toJSON``).

Spark serializes a plan (or expression) tree as a JSON array of node
objects in PRE-ORDER, each carrying ``class`` (the JVM class name) and
``num-children``; the tree is reconstructed by consuming children
recursively from the flattened sequence. TreeNode-valued FIELDS (e.g. a
filter's ``condition``, a project's ``projectList`` entries) are encoded
the same way: a JSON array is one flattened expression tree, a list of
arrays is a sequence of trees.

This module only rebuilds the tree; semantics live in spark_converter.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class SparkNode:
    cls: str                  # fully-qualified JVM class name
    fields: dict              # raw JSON fields of this node
    children: list            # child SparkNodes (plan or expression)

    @property
    def simple_name(self) -> str:
        return self.cls.rsplit(".", 1)[-1]

    def field_tree(self, name: str) -> Optional["SparkNode"]:
        """A field holding ONE flattened tree."""
        v = self.fields.get(name)
        if not v:
            return None
        return _decode_flat(v)

    def field_trees(self, name: str) -> list:
        """A field holding a SEQUENCE of flattened trees."""
        v = self.fields.get(name)
        if not v:
            return []
        if isinstance(v[0], dict):
            # some writers inline a single tree without the outer list
            return [_decode_flat(v)]
        return [_decode_flat(t) for t in v]

    def __repr__(self):
        return f"{self.simple_name}({len(self.children)} children)"


def _decode_pre_order(nodes: list, pos: int) -> tuple[SparkNode, int]:
    raw = nodes[pos]
    n = int(raw.get("num-children", 0))
    children = []
    nxt = pos + 1
    for _ in range(n):
        child, nxt = _decode_pre_order(nodes, nxt)
        children.append(child)
    return SparkNode(raw["class"], raw, children), nxt


def _decode_flat(nodes: list) -> SparkNode:
    root, end = _decode_pre_order(nodes, 0)
    if end != len(nodes):
        raise ValueError(
            f"flattened tree has {len(nodes) - end} trailing nodes "
            f"(root {root.cls})")
    return root


def parse_plan(data) -> SparkNode:
    """data: the JSON array (or its json string) produced by plan.toJSON."""
    if isinstance(data, (str, bytes)):
        data = json.loads(data)
    if not isinstance(data, list) or not data:
        raise ValueError("expected a non-empty JSON array of plan nodes")
    return _decode_flat(data)
