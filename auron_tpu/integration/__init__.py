"""Host-engine integration: converters that ingest an external engine's
physical plan and lower its maximal convertible subtrees onto this
engine's protobuf IR.

The L1 layer of the reference (reference:
spark-extension/src/main/scala/org/apache/spark/sql/auron/
AuronConverters.scala:209-310, AuronConvertStrategy.scala:41-76): a
convert strategy tags every node convertible / never-convert-with-reason,
then per-class converters build the native plan, with explicit fallback
boundaries where the host engine keeps executing.
"""

from auron_tpu.integration.spark_plan import SparkNode, parse_plan
from auron_tpu.integration.spark_converter import (ConversionReport,
                                                   SparkPlanConverter)

__all__ = ["SparkNode", "parse_plan", "SparkPlanConverter",
           "ConversionReport"]
