"""Lakehouse scan convert-providers.

The reference ships ConvertProvider plugins that rewrite Iceberg / Paimon
/ Hudi table scans into its native parquet/orc scan (reference:
thirdparty/auron-iceberg/.../NativeIcebergTableScanExec.scala,
auron-paimon, auron-hudi). The contract is the same here: a provider
recognizes a host-engine scan node this converter has no built-in handler
for, resolves the table's CURRENT DATA FILES, and emits the engine's
ParquetScanNode — falling back (NotImplementedError → ConvertToNative
boundary) for table states it cannot serve natively.

File resolution is directory-layout based (the layout all three formats
share: parquet data files under the table root, metadata under
``metadata/`` / ``.hoodie/``); tables with row-level deletes or
positional delete files are declined so the host engine's reader keeps
correctness. Catalog-API integration (REST/Glue/HMS) plugs in by
registering a provider whose ``resolve_files`` asks the catalog instead
of the filesystem.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from auron_tpu.ir import pb


class ScanProvider:
    """One lakehouse format: recognize the scan node, resolve data files."""

    #: short format name used in reports
    name = "base"

    def matches(self, node) -> bool:
        raise NotImplementedError

    def table_root(self, node) -> Optional[str]:
        """Table location from the scan node's metadata (shared logic)."""
        meta = node.fields.get("metadata") or {}
        for key in ("Location", "location", "path", "table"):
            loc = meta.get(key, "")
            if isinstance(loc, str) and loc:
                # "InMemoryFileIndex[/path]" or a plain path
                if "[" in loc:
                    loc = loc[loc.index("[") + 1:loc.rindex("]")]
                    loc = loc.split(",")[0].strip()
                return loc.replace("file:", "")
        return None

    def resolve_files(self, root: str) -> list[str]:
        raise NotImplementedError

    # -- shared helpers ------------------------------------------------------

    @staticmethod
    def _data_parquet_files(root: str, exclude_dirs: tuple[str, ...],
                            require_marker: Optional[str] = None) -> list[str]:
        if require_marker and not os.path.exists(
                os.path.join(root, require_marker)):
            raise NotImplementedError(
                f"not a recognized table root (missing {require_marker}): "
                f"{root}")
        out = []
        for dirpath, dirs, files in os.walk(root):
            dirs[:] = [d for d in dirs if d not in exclude_dirs]
            for f in sorted(files):
                if f.endswith(".parquet"):
                    out.append(os.path.join(dirpath, f))
                elif f.endswith((".delete", ".equality-deletes",
                                 ".position-deletes")):
                    raise NotImplementedError(
                        "table has row-level delete files — native scan "
                        "would return deleted rows; falling back")
        if not out:
            raise NotImplementedError(f"no parquet data files under {root}")
        return out


class IcebergScanProvider(ScanProvider):
    """Iceberg table layout: data under the root, metadata/ dir with
    version-hint/metadata JSON (reference: auron-iceberg's
    NativeIcebergTableScanExec rewrites the current snapshot's data files
    into the native parquet scan)."""

    name = "iceberg"

    def matches(self, node) -> bool:
        blob = str(node.fields.get("scan", "")) + str(
            node.fields.get("metadata", "")) + node.cls
        return "iceberg" in blob.lower()

    def resolve_files(self, root: str) -> list[str]:
        return self._data_parquet_files(
            root, exclude_dirs=("metadata",), require_marker="metadata")


class PaimonScanProvider(ScanProvider):
    name = "paimon"

    def matches(self, node) -> bool:
        blob = str(node.fields.get("scan", "")) + str(
            node.fields.get("metadata", "")) + node.cls
        return "paimon" in blob.lower()

    def resolve_files(self, root: str) -> list[str]:
        return self._data_parquet_files(
            root, exclude_dirs=("snapshot", "manifest", "schema", "index"),
            require_marker="snapshot")


class HudiScanProvider(ScanProvider):
    name = "hudi"

    def matches(self, node) -> bool:
        blob = str(node.fields.get("scan", "")) + str(
            node.fields.get("metadata", "")) + node.cls
        return "hudi" in blob.lower() or "hoodie" in blob.lower()

    def resolve_files(self, root: str) -> list[str]:
        return self._data_parquet_files(
            root, exclude_dirs=(".hoodie",), require_marker=".hoodie")


#: default provider chain (reference: ConvertProvider ServiceLoader)
PROVIDERS: list[ScanProvider] = [IcebergScanProvider(), PaimonScanProvider(),
                                 HudiScanProvider()]


def register_provider(p: ScanProvider) -> None:
    PROVIDERS.insert(0, p)


def try_convert_scan(node, attrs, dtype_to_proto,
                     path_rewrite: Callable[[str], str]):
    """Provider hook called by the Spark plan converter for scan-like nodes
    without a built-in handler. Returns a ParquetScanNode plan or None."""
    for p in PROVIDERS:
        if not p.matches(node):
            continue
        root = p.table_root(node)
        if not root:
            raise NotImplementedError(
                f"{p.name} scan without a table location")
        files = [path_rewrite(f) for f in p.resolve_files(root)]
        fields = []
        for a in attrs:
            dt, prec, sc = dtype_to_proto(a.dtype)
            fields.append(pb.FieldP(name=a.name, dtype=dt, nullable=True,
                                    precision=prec, scale=sc))
        n = pb.PlanNode(parquet_scan=pb.ParquetScanNode(
            files=files, schema=pb.SchemaP(fields=fields),
            columns=[a.name for a in attrs]))
        return n, max(len(files), 1), p.name
    return None
