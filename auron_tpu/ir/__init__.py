"""Plan IR: the protobuf host↔engine contract + physical planner.

auron.proto is the source of truth; auron_pb2.py is generated with
``protoc --python_out=. auron.proto`` (protoc 3.21+) and checked in so the
engine has no build-time protoc dependency.
"""

from auron_tpu.ir import auron_pb2 as pb  # noqa: F401
from auron_tpu.ir.planner import (PhysicalPlanner, PlannerContext,  # noqa: F401
                                  plan_from_bytes)
from auron_tpu.ir.serde import (agg_to_proto, expr_to_proto,  # noqa: F401
                                parse_agg, parse_expr, parse_schema,
                                parse_sort_order, schema_to_proto,
                                sort_order_to_proto)
