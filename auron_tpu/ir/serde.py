"""proto ↔ in-memory IR conversion.

Both directions live here: the front-end (auron_tpu.frontend) serializes
DataFrame plans with ``*_to_proto``; the engine's planner parses incoming
protos with ``parse_*``. The reference splits these across languages (Scala
NativeConverters.scala builds, Rust planner.rs parses); a single module keeps
the contract round-trip tested.
"""

from __future__ import annotations

from typing import Optional

from auron_tpu.columnar.schema import DataType, Field, Schema
from auron_tpu.exprs import ir
from auron_tpu.exprs import udf as udf_registry
from auron_tpu.ir import auron_pb2 as pb

# ---------------------------------------------------------------------------
# types
# ---------------------------------------------------------------------------

_DT_TO_P = {
    DataType.NULL: pb.DT_NULL,
    DataType.BOOL: pb.DT_BOOL,
    DataType.INT8: pb.DT_INT8,
    DataType.INT16: pb.DT_INT16,
    DataType.INT32: pb.DT_INT32,
    DataType.INT64: pb.DT_INT64,
    DataType.FLOAT32: pb.DT_FLOAT32,
    DataType.FLOAT64: pb.DT_FLOAT64,
    DataType.DATE32: pb.DT_DATE32,
    DataType.TIMESTAMP_US: pb.DT_TIMESTAMP_US,
    DataType.DECIMAL: pb.DT_DECIMAL,
    DataType.STRING: pb.DT_STRING,
    DataType.LIST: pb.DT_LIST,
    DataType.MAP: pb.DT_MAP,
    DataType.STRUCT: pb.DT_STRUCT,
}
_P_TO_DT = {v: k for k, v in _DT_TO_P.items()}


def dtype_to_proto(dt: DataType) -> int:
    return _DT_TO_P[dt]


def parse_dtype(p: int) -> DataType:
    return _P_TO_DT[p]


def field_to_proto(f: Field) -> pb.FieldP:
    return pb.FieldP(
        name=f.name, dtype=_DT_TO_P[f.dtype], nullable=f.nullable,
        precision=f.precision, scale=f.scale,
        elem=_DT_TO_P[f.elem] if f.elem is not None else 0,
        key=_DT_TO_P[f.key] if f.key is not None else 0,
        children=[field_to_proto(cf) for cf in f.children])


def parse_field(f: pb.FieldP) -> Field:
    dt = _P_TO_DT[f.dtype]
    return Field(
        f.name, dt, f.nullable, f.precision, f.scale,
        elem=_P_TO_DT[f.elem] if dt in (DataType.LIST, DataType.MAP)
        else None,
        key=_P_TO_DT[f.key] if dt == DataType.MAP else None,
        children=tuple(parse_field(cf) for cf in f.children)
        if dt == DataType.STRUCT else ())


def schema_to_proto(schema: Schema) -> pb.SchemaP:
    return pb.SchemaP(fields=[field_to_proto(f) for f in schema.fields])


def parse_schema(p: pb.SchemaP) -> Schema:
    return Schema(tuple(parse_field(f) for f in p.fields))


# ---------------------------------------------------------------------------
# expressions: IR -> proto
# ---------------------------------------------------------------------------

def _scalar_dtype(v) -> DataType:
    """Best-effort dtype for a bare python scalar (InList values)."""
    if isinstance(v, bool):
        return DataType.BOOL
    if isinstance(v, int):
        return DataType.INT64
    if isinstance(v, float):
        return DataType.FLOAT64
    if isinstance(v, str):
        return DataType.STRING
    raise TypeError(f"unsupported in-list scalar {type(v).__name__}")


def _literal_to_proto(value, dtype: DataType, precision=0, scale=0) -> pb.LiteralE:
    out = pb.LiteralE(dtype=_DT_TO_P[dtype], precision=precision, scale=scale)
    if value is None:
        out.is_null = True
    elif dtype == DataType.STRING:
        out.str = str(value)
    elif dtype in (DataType.FLOAT32, DataType.FLOAT64):
        out.f64 = float(value)
    elif dtype == DataType.BOOL:
        out.i64 = int(bool(value))
    else:
        out.i64 = int(value)
    return out


def expr_to_proto(e: ir.Expr) -> pb.ExprNode:
    if isinstance(e, ir.ColumnRef):
        return pb.ExprNode(column=pb.ColumnRefE(index=e.index, name=e.name))
    if isinstance(e, ir.Literal):
        return pb.ExprNode(literal=_literal_to_proto(
            e.value, e.dtype, e.precision, e.scale))
    if isinstance(e, ir.BinaryExpr):
        return pb.ExprNode(binary=pb.BinaryE(
            op=e.op, left=expr_to_proto(e.left), right=expr_to_proto(e.right)))
    if isinstance(e, ir.Not):
        return pb.ExprNode(unary=pb.UnaryE(op="not", child=expr_to_proto(e.child)))
    if isinstance(e, ir.IsNull):
        return pb.ExprNode(unary=pb.UnaryE(op="is_null", child=expr_to_proto(e.child)))
    if isinstance(e, ir.IsNotNull):
        return pb.ExprNode(unary=pb.UnaryE(op="is_not_null", child=expr_to_proto(e.child)))
    if isinstance(e, ir.Negative):
        return pb.ExprNode(unary=pb.UnaryE(op="negative", child=expr_to_proto(e.child)))
    if isinstance(e, ir.Cast):
        return pb.ExprNode(cast=pb.CastE(
            child=expr_to_proto(e.child), dtype=_DT_TO_P[e.dtype],
            precision=e.precision, scale=e.scale, ansi=not e.safe))
    if isinstance(e, ir.CaseWhen):
        node = pb.CaseWhenE()
        for when, then in e.when_then:
            node.branches.append(pb.CaseWhenE.Branch(
                when=expr_to_proto(when), then=expr_to_proto(then)))
        if e.otherwise is not None:
            node.else_expr.CopyFrom(expr_to_proto(e.otherwise))
        return pb.ExprNode(case_when=node)
    if isinstance(e, ir.InList):
        node = pb.InListE(child=expr_to_proto(e.child), negated=e.negated)
        for v in e.values:
            node.values.append(_literal_to_proto(v, _scalar_dtype(v)))
        return pb.ExprNode(in_list=node)
    if isinstance(e, ir.Like):
        return pb.ExprNode(like=pb.LikeE(
            child=expr_to_proto(e.child), pattern=e.pattern, negated=e.negated))
    if isinstance(e, ir.StringStartsWith):
        return pb.ExprNode(string_pred=pb.StringPredE(
            kind="starts_with", child=expr_to_proto(e.child), pattern=e.prefix))
    if isinstance(e, ir.StringEndsWith):
        return pb.ExprNode(string_pred=pb.StringPredE(
            kind="ends_with", child=expr_to_proto(e.child), pattern=e.suffix))
    if isinstance(e, ir.StringContains):
        return pb.ExprNode(string_pred=pb.StringPredE(
            kind="contains", child=expr_to_proto(e.child), pattern=e.infix))
    if isinstance(e, ir.ScalarFunction):
        node = pb.ScalarFunctionE(
            name=e.name, args=[expr_to_proto(a) for a in e.args])
        if e.dtype is not None:
            node.has_dtype = True
            node.dtype = _DT_TO_P[e.dtype]
            node.precision = e.precision
            node.scale = e.scale
        return pb.ExprNode(scalar_function=node)
    if isinstance(e, ir.RowNum):
        return pb.ExprNode(nullary=pb.NullaryE(kind="row_num"))
    if isinstance(e, ir.SparkPartitionId):
        return pb.ExprNode(nullary=pb.NullaryE(kind="spark_partition_id"))
    if isinstance(e, ir.MonotonicallyIncreasingId):
        return pb.ExprNode(nullary=pb.NullaryE(kind="monotonically_increasing_id"))
    if isinstance(e, ir.HostUDF):
        return pb.ExprNode(host_udf=pb.HostUDFE(
            registry_name=e.name, args=[expr_to_proto(a) for a in e.args],
            dtype=_DT_TO_P[e.dtype]))
    if isinstance(e, ir.GetIndexedField):
        return pb.ExprNode(get_indexed_field=pb.GetIndexedFieldE(
            child=expr_to_proto(e.child), ordinal=e.ordinal))
    if isinstance(e, ir.GetStructField):
        return pb.ExprNode(get_struct_field=pb.GetStructFieldE(
            child=expr_to_proto(e.child), ordinal=e.ordinal))
    if isinstance(e, ir.BloomFilterMightContain):
        return pb.ExprNode(bloom_might_contain=pb.BloomMightContainE(
            value=expr_to_proto(e.value), serialized_filter=e.serialized))
    if isinstance(e, ir.ScalarSubquery):
        sub = pb.PlanNode()
        sub.ParseFromString(e.plan_bytes)
        return pb.ExprNode(scalar_subquery=pb.ScalarSubqueryE(
            plan=sub, dtype=_DT_TO_P[e.dtype], precision=e.precision,
            scale=e.scale, sid=e.sid))
    raise NotImplementedError(f"expr_to_proto: {type(e).__name__}")


# ---------------------------------------------------------------------------
# expressions: proto -> IR
# ---------------------------------------------------------------------------

def _parse_literal(p: pb.LiteralE) -> ir.Literal:
    dt = _P_TO_DT[p.dtype]
    if p.is_null:
        value = None
    elif p.WhichOneof("value") == "str":
        value = p.str
    elif p.WhichOneof("value") == "f64":
        value = p.f64
    else:
        value = bool(p.i64) if dt == DataType.BOOL else p.i64
    return ir.Literal(value, dt, p.precision, p.scale)


def parse_expr(p: pb.ExprNode) -> ir.Expr:
    kind = p.WhichOneof("expr")
    if kind == "column":
        return ir.ColumnRef(p.column.index, p.column.name)
    if kind == "literal":
        return _parse_literal(p.literal)
    if kind == "binary":
        return ir.BinaryExpr(p.binary.op, parse_expr(p.binary.left),
                             parse_expr(p.binary.right))
    if kind == "unary":
        child = parse_expr(p.unary.child)
        return {
            "not": ir.Not, "is_null": ir.IsNull,
            "is_not_null": ir.IsNotNull, "negative": ir.Negative,
        }[p.unary.op](child)
    if kind == "cast":
        # TryCast is null-on-failure regardless of session ANSI mode
        safe = p.cast.try_cast or not p.cast.ansi
        return ir.Cast(parse_expr(p.cast.child), _P_TO_DT[p.cast.dtype],
                       p.cast.precision, p.cast.scale, safe=safe)
    if kind == "case_when":
        branches = tuple((parse_expr(b.when), parse_expr(b.then))
                         for b in p.case_when.branches)
        otherwise = (parse_expr(p.case_when.else_expr)
                     if p.case_when.HasField("else_expr") else None)
        return ir.CaseWhen(branches, otherwise)
    if kind == "in_list":
        return ir.InList(parse_expr(p.in_list.child),
                         tuple(_parse_literal(v).value for v in p.in_list.values),
                         p.in_list.negated)
    if kind == "like":
        return ir.Like(parse_expr(p.like.child), p.like.pattern, p.like.negated)
    if kind == "string_pred":
        cls = {"starts_with": ir.StringStartsWith,
               "ends_with": ir.StringEndsWith,
               "contains": ir.StringContains}[p.string_pred.kind]
        return cls(parse_expr(p.string_pred.child), p.string_pred.pattern)
    if kind == "scalar_function":
        sf = p.scalar_function
        return ir.ScalarFunction(
            sf.name, tuple(parse_expr(a) for a in sf.args),
            dtype=_P_TO_DT[sf.dtype] if sf.has_dtype else None,
            precision=sf.precision, scale=sf.scale)
    if kind == "nullary":
        return {"row_num": ir.RowNum,
                "spark_partition_id": ir.SparkPartitionId,
                "monotonically_increasing_id": ir.MonotonicallyIncreasingId,
                }[p.nullary.kind]()
    if kind == "host_udf":
        fn, dtype, prec, scale = udf_registry.lookup_udf(p.host_udf.registry_name)
        return ir.HostUDF(fn, tuple(parse_expr(a) for a in p.host_udf.args),
                          dtype, p.host_udf.registry_name)
    if kind == "get_indexed_field":
        return ir.GetIndexedField(parse_expr(p.get_indexed_field.child),
                                  p.get_indexed_field.ordinal)
    if kind == "get_struct_field":
        return ir.GetStructField(parse_expr(p.get_struct_field.child),
                                 p.get_struct_field.ordinal)
    if kind == "bloom_might_contain":
        b = p.bloom_might_contain
        if not b.serialized_filter:
            raise NotImplementedError(
                "bloom filter by resource id not supported; embed the "
                "serialized filter bytes")
        return ir.BloomFilterMightContain(parse_expr(b.value),
                                          bytes(b.serialized_filter))
    if kind == "scalar_subquery":
        q = p.scalar_subquery
        return ir.ScalarSubquery(q.plan.SerializeToString(),
                                 _P_TO_DT[q.dtype], q.precision, q.scale,
                                 q.sid)
    raise NotImplementedError(f"parse_expr: {kind}")


# ---------------------------------------------------------------------------
# sort orders / agg functions
# ---------------------------------------------------------------------------

def sort_order_to_proto(o: ir.SortOrder) -> pb.SortOrderP:
    return pb.SortOrderP(expr=expr_to_proto(o.expr), ascending=o.ascending,
                         nulls_first=o.nulls_first)


def parse_sort_order(p: pb.SortOrderP) -> ir.SortOrder:
    return ir.SortOrder(parse_expr(p.expr), p.ascending, p.nulls_first)


def agg_to_proto(a: ir.AggFunction) -> pb.AggFunctionP:
    out = pb.AggFunctionP(fn=a.fn, distinct=a.distinct,
                          expected_items=a.expected_items, fpp=a.fpp)
    if a.arg is not None:
        out.arg.CopyFrom(expr_to_proto(a.arg))
    return out


def parse_agg(p: pb.AggFunctionP) -> ir.AggFunction:
    arg = parse_expr(p.arg) if p.HasField("arg") else None
    return ir.AggFunction(p.fn, arg, p.distinct,
                          p.expected_items, p.fpp)
