"""Fusion plan cost model (Fusion 2.0).

The planner's greedy-maximal chaining (fuse everything fusable, combine
everywhere eligible) is the right static prior, but SystemML's fusion-plan
work (arXiv:1801.00829) and FusionStitching (arXiv:2009.10924) both show
*selected* plans beating maximal chains once real statistics exist. This
module is the selection half: a per-site history of observed exchange
statistics keyed by (plan fingerprint, site) — the PR 16 identity plumbing
— and a small analytic cost model that scores the candidate decisions the
planner enumerates:

  * at each foldable hash exchange: fold with per-batch COMBINE vs fold
    with PASSTHROUGH (state-layout rows cross uncombined). Combining pays
    one O(B log B) stable sort per batch to ship ratio·rows rows instead
    of all of them; on high-cardinality sites (ratio → 1) the sort buys
    nothing and passthrough wins.
  * at each hash join: probe-into-consumer fold vs unfused consumer chain
    (the fold saves a host round-trip per batch but builds one more
    specialized program; it stops paying when observed probe output rows
    per batch are tiny).

History is per-process and advisory: no entry → the static prior decides.
Everything here is plan-SHAPE selection — the chosen plan changes which
programs are built, never what a given program computes, so bit-identity
is the fold's own contract (ops/agg.AggOp.combine_fold_reason), not this
module's.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

# -- cost constants (relative units: 1.0 = one row through a fused
# row-local fragment). WIRE is a row crossing the exchange: serde/buffer
# (or all_to_all slot) + the reduce side re-reducing it. SORT_LOG is the
# per-row-per-log2(B) price of the combine's stable hash-sort. The
# implied break-even combine ratio is 1 - SORT_LOG*log2(B)/WIRE — 0.84
# at the default 64Ki batch, 0.90 at 1Ki — above it, combining ships so
# few fewer rows that the sort is pure loss.
WIRE_COST_PER_ROW = 4.0
SORT_COST_PER_ROW_LOG = 0.04
#: static prior for the combine ratio when a site has no history: assume
#: combining halves the rows (safe: prior-scored combine wins, matching
#: the greedy default, until a real observation says otherwise)
PRIOR_COMBINE_RATIO = 0.5
#: probe fold stops paying below this observed consumer rows/batch (one
#: extra specialized program build amortized over almost no rows)
PROBE_FOLD_MIN_ROWS_PER_BATCH = 256.0

_MAX_SITES = 4096


@dataclass
class SiteStats:
    """Accumulated observations for one (plan_fp, site)."""
    rows_in: int = 0
    rows_out: int = 0
    batches: int = 0
    runs: int = 0

    @property
    def combine_ratio(self) -> float:
        return (self.rows_out / self.rows_in) if self.rows_in else 1.0

    @property
    def rows_per_batch(self) -> float:
        return (self.rows_in / self.batches) if self.batches else 0.0


@dataclass(frozen=True)
class Candidate:
    """One scored fusion decision at a site."""
    mode: str
    cost: float
    detail: str


_LOCK = threading.Lock()
_HISTORY: dict = {}
#: plan-time decisions, site → (kind, mode) — what the planner actually
#: chose at each cost site, for tools/compile_report's plan-diff view
#: (greedy vs cost-model runs) and the fusion battery's assertions
_DECISIONS: dict = {}


def observe(site: Optional[tuple], rows_in: int, rows_out: int,
            batches: int) -> None:
    """Record one run's observed exchange statistics. site is the
    (plan_fp, site_label) stamp the planner left on the op; None (no
    fingerprint — e.g. ad-hoc plans outside plan_from_bytes) is a no-op."""
    if site is None:
        return
    with _LOCK:
        st = _HISTORY.get(site)
        if st is None:
            if len(_HISTORY) >= _MAX_SITES:   # advisory cache: drop, don't grow
                _HISTORY.clear()
            st = _HISTORY[site] = SiteStats()
        st.rows_in += int(rows_in)
        st.rows_out += int(rows_out)
        st.batches += int(batches)
        st.runs += 1


def stats_for(site: Optional[tuple]) -> Optional[SiteStats]:
    if site is None:
        return None
    with _LOCK:
        return _HISTORY.get(site)


def snapshot() -> dict:
    with _LOCK:
        return dict(_HISTORY)


def record_decision(site: Optional[tuple], kind: str, mode: str) -> None:
    """Note the planner's choice at a cost site ('exchange' →
    combine/passthrough, 'probe_fold' → fold/unfused). Advisory, like
    the history; None sites (no plan fingerprint) are not recorded."""
    if site is None:
        return
    with _LOCK:
        if len(_DECISIONS) >= _MAX_SITES:
            _DECISIONS.clear()
        _DECISIONS[site] = (kind, mode)


def decisions_snapshot() -> dict:
    with _LOCK:
        return dict(_DECISIONS)


def clear() -> None:
    with _LOCK:
        _HISTORY.clear()
        _DECISIONS.clear()


# -- candidate scoring -------------------------------------------------------

def exchange_candidates(ratio: float, rows_per_batch: float) -> list:
    """Score the two fold modes of one exchange for a (possibly prior)
    combine ratio and batch size. Costs are per input row."""
    import math
    b = max(rows_per_batch, 2.0)
    sort = SORT_COST_PER_ROW_LOG * math.log2(b)
    return sorted([
        Candidate("combine", sort + ratio * WIRE_COST_PER_ROW,
                  f"ratio={ratio:.3f} sort={sort:.3f}"),
        Candidate("passthrough", WIRE_COST_PER_ROW,
                  f"ratio={ratio:.3f}"),
    ], key=lambda c: c.cost)


def choose_exchange_mode(conf, site: Optional[tuple],
                         batch_capacity: int) -> tuple:
    """('combine'|'passthrough', why) for one foldable exchange.

    cost_model off → greedy-maximal: always combine (unless the combine
    knob itself is off, which the caller resolves first). With the model
    on, observed per-site history feeds the candidate scores; no history
    falls back to the static prior (which scores combine ahead)."""
    from auron_tpu import config as cfg
    if not conf.get(cfg.FUSION_COST_MODEL):
        return "combine", "greedy"
    st = stats_for(site)
    if st is None or st.rows_in == 0:
        ratio, rpb, src = PRIOR_COMBINE_RATIO, float(batch_capacity), "prior"
    else:
        ratio, rpb, src = st.combine_ratio, st.rows_per_batch, "observed"
    best = exchange_candidates(ratio, rpb)[0]
    return best.mode, f"{src}:{best.detail}"


def choose_probe_fold(conf, site: Optional[tuple]) -> bool:
    """Whether the hash-join probe should fold into its consumer chain.
    Greedy (cost_model off) and the no-history prior both fold; history
    showing near-empty probe outputs per batch declines the fold."""
    from auron_tpu import config as cfg
    if not conf.get(cfg.FUSION_COST_MODEL):
        return True
    st = stats_for(site)
    if st is None or st.batches == 0:
        return True
    return (st.rows_out / st.batches) >= PROBE_FOLD_MIN_ROWS_PER_BATCH
