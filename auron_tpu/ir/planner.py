"""Physical planner: proto PlanNode → PhysicalOp tree.

The engine-side half of the contract — the analogue of the reference's
``PhysicalPlanner::create_plan`` (reference:
native-engine/auron-planner/src/planner.rs:121-856), recursively
materializing executable operators from the IR. Scans resolve named tables
through a catalog; exchange/broadcast nodes resolve cross-stage data through
a resource map (the analogue of JniBridge.putResource/getResource,
reference: auron-core/src/main/java/org/apache/auron/jni/JniBridge.java).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import pyarrow as pa

from auron_tpu.columnar.schema import Schema
from auron_tpu.ir import auron_pb2 as pb
from auron_tpu.ir import serde
from auron_tpu.ops.base import PhysicalOp


@dataclass
class PlannerContext:
    """Host-side environment plans resolve against.

    catalog: table name → pyarrow.Table (or list of per-partition
      RecordBatch lists) for MemoryScanNode.
    resources: id → engine object (bucketed shuffle output, broadcast
      batches, bloom filters...) for IpcReader/BroadcastJoin nodes.
    """

    catalog: dict[str, Any] = field(default_factory=dict)
    resources: dict[str, Any] = field(default_factory=dict)
    # None = resolve from the typed config (auron.batch.capacity)
    batch_capacity: Optional[int] = None
    config: Optional[Any] = None
    #: plan fingerprint of the task being planned (runtime/journal
    #: .plan_fingerprint, set by plan_from_bytes) — keys the ir/cost.py
    #: per-site statistics history so a re-planned query sees what its
    #: previous runs observed; None for ad-hoc trees
    plan_fp: Optional[str] = None
    #: (table name, column index) -> (table ref, (min, max)) — memoizes
    #: the O(n) key-column stats scan the dense-kernel derivation needs,
    #: so repeated planning over a registered table pays it once. The
    #: entry holds a STRONG reference to the scanned table and hits only
    #: on identity (`is`): a re-registered table can never alias a
    #: recycled id and serve stale stats for different data
    _col_stats: dict = field(default_factory=dict)

    def __post_init__(self):
        from auron_tpu import config as cfg
        if self.config is None:
            self.config = cfg.get_config()
        if self.batch_capacity is None:
            self.batch_capacity = self.config.get(cfg.BATCH_CAPACITY)

    def put_resource(self, rid: str, value: Any) -> None:
        self.resources[rid] = value

    def get_resource(self, rid: str) -> Any:
        if rid not in self.resources:
            raise KeyError(f"unknown resource id {rid!r}")
        return self.resources[rid]


class PhysicalPlanner:
    def __init__(self, ctx: Optional[PlannerContext] = None):
        self.ctx = ctx or PlannerContext()

    # -- entry points -------------------------------------------------------

    def plan_task(self, task: pb.TaskDefinition) -> PhysicalOp:
        if _collect_subqueries(task.plan):
            # resolve every uncorrelated scalar subquery in the tree ONCE
            # at task start, then re-plan with literals substituted
            # (reference: spark_scalar_subquery_wrapper.rs role); the
            # binder applies the stage-fusion pass after substitution
            from auron_tpu.ops.subquery import ScalarSubqueryBinderOp
            return ScalarSubqueryBinderOp(task.plan, self.ctx)
        return self.finalize_plan(self.create_plan(task.plan))

    def finalize_plan(self, op: PhysicalOp) -> PhysicalOp:
        """Post-planning passes over the materialized operator tree:
        whole-stage fusion (fuse_stages — greedy chains plus the
        cost-selected combine/probe folds), then the SPMD mesh
        annotation (annotate_mesh — a no-op while auron.mesh.enabled is
        off)."""
        from auron_tpu.parallel import mesh as mesh_mod
        return annotate_mesh(
            fuse_stages(op, self.ctx.config, plan_fp=self.ctx.plan_fp),
            mesh_mod.current_plane())

    def create_plan(self, node: pb.PlanNode) -> PhysicalOp:
        kind = node.WhichOneof("node")
        if kind is None:
            raise ValueError("empty PlanNode")
        handler = getattr(self, f"_plan_{kind}", None)
        if handler is None:
            raise NotImplementedError(f"plan node {kind!r} not supported yet")
        return handler(getattr(node, kind))

    # -- sources ------------------------------------------------------------

    def _file_scan_batch_rows(self) -> int:
        """File-scan batch sizing: ``auron.scan.batch_rows`` when set;
        0 (the default) resolves per platform — 2^17 on the CPU mesh,
        where larger batches amortize the per-batch host glue that
        dominates throughput (PERF.md 'Pipelined execution'), else the
        legacy ``auron.io.parquet.batch_rows``. The scan clamps its
        conversion capacity to the partition's actual row-count bucket,
        so the larger default never inflates small files' padding."""
        from auron_tpu import config as cfg
        explicit = int(self.ctx.config.get(cfg.SCAN_BATCH_ROWS))
        if explicit > 0:
            return explicit
        try:
            import jax
            platform = jax.default_backend()
        except Exception:   # backend init failure: stay conservative
            platform = "cpu"
        if platform == "cpu":
            return 1 << 17
        return self.ctx.config.get(cfg.PARQUET_BATCH_ROWS)

    def _plan_parquet_scan(self, n: pb.ParquetScanNode) -> PhysicalOp:
        from auron_tpu.io.parquet import ParquetScanOp
        return ParquetScanOp(
            files=list(n.files),
            schema=serde.parse_schema(n.schema) if n.schema.fields else None,
            columns=list(n.columns) or None,
            predicates=[serde.parse_expr(p) for p in n.predicates],
            batch_rows=n.batch_rows or self._file_scan_batch_rows(),
        )

    def _plan_orc_scan(self, n: pb.OrcScanNode) -> PhysicalOp:
        from auron_tpu.io.orc import OrcScanOp
        return OrcScanOp(
            files=list(n.files),
            schema=serde.parse_schema(n.schema) if n.schema.fields else None,
            columns=list(n.columns) or None,
            batch_rows=n.batch_rows or self._file_scan_batch_rows(),
        )

    def _plan_memory_scan(self, n: pb.MemoryScanNode) -> PhysicalOp:
        from auron_tpu.columnar.arrow_bridge import schema_from_arrow
        from auron_tpu.io.parquet import MemoryScanOp
        if n.table_name not in self.ctx.catalog:
            raise KeyError(
                f"table {n.table_name!r} not in planner catalog "
                f"(known: {sorted(self.ctx.catalog)})")
        table = self.ctx.catalog[n.table_name]
        if isinstance(table, pa.Table):
            partitions = [table.to_batches(
                max_chunksize=n.batch_rows or self.ctx.batch_capacity)]
            schema = schema_from_arrow(table.schema)
        else:  # pre-partitioned: list[list[RecordBatch]]
            partitions = table
            schema = schema_from_arrow(partitions[0][0].schema)
        return MemoryScanOp(partitions, schema,
                            capacity=n.batch_rows or self.ctx.batch_capacity)

    def _plan_ipc_reader(self, n: pb.IpcReaderNode) -> PhysicalOp:
        from auron_tpu.io.parquet import DeviceBatchScanOp
        partitions = self.ctx.get_resource(n.resource_id)
        return DeviceBatchScanOp(partitions, serde.parse_schema(n.schema))

    def _plan_empty_partitions(self, n: pb.EmptyPartitionsNode) -> PhysicalOp:
        from auron_tpu.ops.limit import EmptyPartitionsOp
        return EmptyPartitionsOp(serde.parse_schema(n.schema),
                                 n.num_partitions)

    def _plan_kafka_scan(self, n: pb.KafkaScanNode) -> PhysicalOp:
        from auron_tpu.streaming.kafka import KafkaScanOp
        return KafkaScanOp(topic=n.topic, bootstrap=n.bootstrap,
                           schema=serde.parse_schema(n.schema),
                           fmt=n.format or "json",
                           max_batches=n.max_batches or None,
                           group_id=n.group_id or None)

    def _plan_streaming_window_agg(
            self, n: pb.StreamingWindowAggNode) -> PhysicalOp:
        from auron_tpu.streaming.window import StreamingWindowAggOp
        return StreamingWindowAggOp(
            self.create_plan(n.child), n.time_col, n.window_us,
            [serde.parse_expr(e) for e in n.group_exprs],
            [serde.parse_agg(a) for a in n.aggs],
            ooo_bound_us=n.ooo_bound_us,
            group_names=list(n.group_names) or None,
            agg_names=list(n.agg_names) or None)

    # -- row transforms -----------------------------------------------------

    def _plan_filter(self, n: pb.FilterNode) -> PhysicalOp:
        from auron_tpu.ops.project import FilterOp
        return FilterOp(self.create_plan(n.child),
                        [serde.parse_expr(p) for p in n.predicates])

    def _plan_project(self, n: pb.ProjectNode) -> PhysicalOp:
        from auron_tpu.ops.project import ProjectOp
        return ProjectOp(self.create_plan(n.child),
                         [serde.parse_expr(e) for e in n.exprs],
                         list(n.names))

    def _plan_agg(self, n: pb.AggNode) -> PhysicalOp:
        from auron_tpu import config as cfg
        from auron_tpu.ops.agg import AggOp
        child = self.create_plan(n.child)
        group_exprs = [serde.parse_expr(e) for e in n.group_exprs]
        aggs = [serde.parse_agg(a) for a in n.aggs]
        return AggOp(
            child, group_exprs, aggs,
            mode=n.mode or "complete",
            group_names=list(n.group_names) or None,
            agg_names=list(n.agg_names) or None,
            initial_capacity=self.ctx.config.get(cfg.AGG_INITIAL_CAPACITY),
            key_domain=self._agg_key_domain(n, child, group_exprs, aggs),
        )

    def _agg_key_domain(self, n: pb.AggNode, child: PhysicalOp,
                        group_exprs, aggs) -> Optional[int]:
        """Table-stats key-domain bound for the dense grouped-agg kernels
        (auron_tpu/kernels): proven, not guessed. A bound is derived only
        when the single group key is a direct ColumnRef reaching a
        catalog memory table through schema-preserving nodes, the key
        column is integer, null-free and non-negative, and every
        aggregate is exact under the dense formulation (count/min/max,
        and sum/avg over integers — float sums re-associate on the MXU
        grids, so auto-selection skips them; an explicit AggOp
        key_domain hint still enables the float path). The bound is
        re-verified at runtime by the operator (ops/agg.py)."""
        from auron_tpu import config as cfg
        from auron_tpu.columnar.schema import DataType
        from auron_tpu.exprs import ir
        from auron_tpu.exprs.eval import infer_dtype
        conf = self.ctx.config
        try:
            if not conf.get(cfg.KERNELS_ENABLED):
                return None
            if (n.mode or "complete") not in ("partial", "complete"):
                return None
            if len(group_exprs) != 1 or not isinstance(group_exprs[0],
                                                       ir.ColumnRef):
                return None
            from auron_tpu.kernels.dispatch import DENSE_VALUE_DTYPES
            schema = child.schema()
            ints = (DataType.INT8, DataType.INT16, DataType.INT32,
                    DataType.INT64)
            for a in aggs:
                if a.distinct and a.fn not in ("min", "max"):
                    return None
                # mirror the runtime dispatch's value-dtype filter so
                # the stats scan below is never paid for a plan that
                # falls back at execute time anyway
                if a.arg is not None and \
                        infer_dtype(a.arg, schema)[0] not in \
                        DENSE_VALUE_DTYPES:
                    return None
                if a.fn in ("count", "count_star", "min", "max"):
                    continue
                if a.fn in ("sum", "avg") and a.arg is not None \
                        and infer_dtype(a.arg, schema)[0] in ints:
                    continue
                return None
            # walk to a memory scan through schema-preserving nodes
            node = n.child
            while True:
                kind = node.WhichOneof("node")
                if kind == "filter":
                    node = node.filter.child
                elif kind == "coalesce_batches":
                    node = node.coalesce_batches.child
                elif kind == "memory_scan":
                    break
                else:
                    return None
            table = self.ctx.catalog.get(node.memory_scan.table_name)
            if not isinstance(table, pa.Table):
                return None
            idx = group_exprs[0].index
            if not 0 <= idx < table.num_columns:
                return None
            col = table.column(idx)
            if not pa.types.is_integer(col.type) or col.null_count \
                    or table.num_rows == 0:
                return None
            ckey = (node.memory_scan.table_name, idx)
            cached = self.ctx._col_stats.get(ckey)
            if cached is not None and cached[0] is table:
                stats = cached[1]
            else:
                import pyarrow.compute as pc
                mm = pc.min_max(col)
                stats = (mm["min"].as_py(), mm["max"].as_py())
                self.ctx._col_stats[ckey] = (table, stats)
            lo, hi = stats
            if lo is None or lo < 0:
                return None
            if hi + 1 > conf.get(cfg.KERNELS_MAX_KEY_DOMAIN):
                return None
            return int(hi) + 1
        except Exception:
            # stats derivation is advisory; a failure here must never
            # fail planning — the sort path is always correct
            return None

    def _plan_sort(self, n: pb.SortNode) -> PhysicalOp:
        from auron_tpu.ops.sort import SortOp
        # proto3 leaves unset fetch at 0; a 0-row top-k is meaningless, so
        # any fetch <= 0 means "no limit"
        return SortOp(self.create_plan(n.child),
                      [serde.parse_sort_order(o) for o in n.sort_orders],
                      fetch=None if n.fetch <= 0 else n.fetch)

    def _plan_limit(self, n: pb.LimitNode) -> PhysicalOp:
        from auron_tpu.ops.limit import LimitOp
        return LimitOp(self.create_plan(n.child), n.limit)

    def _plan_union(self, n: pb.UnionNode) -> PhysicalOp:
        from auron_tpu.ops.limit import UnionOp
        return UnionOp([self.create_plan(c) for c in n.children])

    def _plan_coalesce_batches(self, n: pb.CoalesceBatchesNode) -> PhysicalOp:
        from auron_tpu.ops.limit import CoalesceBatchesOp
        return CoalesceBatchesOp(self.create_plan(n.child), n.target_rows)

    def _plan_rename_columns(self, n: pb.RenameColumnsNode) -> PhysicalOp:
        from auron_tpu.ops.limit import RenameColumnsOp
        return RenameColumnsOp(self.create_plan(n.child), list(n.names))

    def _plan_debug(self, n: pb.DebugNode) -> PhysicalOp:
        from auron_tpu.ops.debug import DebugOp
        return DebugOp(self.create_plan(n.child), n.label)

    def _plan_window(self, n: pb.WindowNode) -> PhysicalOp:
        from auron_tpu.ops.window import WindowFunctionSpec, WindowOp
        fns = []
        for f in n.functions:
            default = None
            if f.HasField("default_value"):
                default = serde._parse_literal(f.default_value).value
            fns.append(WindowFunctionSpec(
                kind=f.kind, fn=f.fn,
                arg=serde.parse_expr(f.arg) if f.HasField("arg") else None,
                offset=f.offset if f.HasField("offset") else 1,
                default=default,
                frame=((f.frame_lo, f.frame_hi)
                       if (f.HasField("frame_lo")
                           or f.HasField("frame_hi")) else None)))
        return WindowOp(
            self.create_plan(n.child),
            partition_by=[serde.parse_expr(e) for e in n.partition_by],
            order_by=[serde.parse_sort_order(o) for o in n.order_by],
            functions=fns,
            output_names=list(n.output_names) or None,
            group_limit=None if n.group_limit < 0 else (n.group_limit or None),
        )

    def _plan_expand(self, n: pb.ExpandNode) -> PhysicalOp:
        from auron_tpu.ops.expand import ExpandOp
        return ExpandOp(
            self.create_plan(n.child),
            [[serde.parse_expr(e) for e in proj.exprs]
             for proj in n.projections],
            list(n.names) or None,
        )

    def _plan_generate(self, n: pb.GenerateNode) -> PhysicalOp:
        from auron_tpu.ops.generate import GenerateOp
        return GenerateOp(
            self.create_plan(n.child),
            kind=n.kind,
            generator=serde.parse_expr(n.generator)
            if n.HasField("generator") else None,
            json_fields=list(n.json_fields),
            udtf_name=n.udtf_registry_name or None,
            required_child_output=list(n.required_child_output),
            outer=n.outer,
            output_names=list(n.output_names) or None,
        )

    # -- joins --------------------------------------------------------------

    def _plan_hash_join(self, n: pb.HashJoinNode) -> PhysicalOp:
        from auron_tpu.ops.joins import HashJoinOp
        return HashJoinOp(
            self.create_plan(n.probe), self.create_plan(n.build),
            [serde.parse_expr(e) for e in n.probe_keys],
            [serde.parse_expr(e) for e in n.build_keys],
            join_type=n.join_type or "inner",
        )

    def _plan_sort_merge_join(self, n: pb.SortMergeJoinNode) -> PhysicalOp:
        from auron_tpu.ops.joins import SortMergeJoinOp
        return SortMergeJoinOp(
            self.create_plan(n.probe), self.create_plan(n.build),
            [serde.parse_expr(e) for e in n.probe_keys],
            [serde.parse_expr(e) for e in n.build_keys],
            join_type=n.join_type or "inner",
        )

    def _plan_broadcast_join(self, n: pb.BroadcastJoinNode) -> PhysicalOp:
        from auron_tpu.io.parquet import DeviceBatchScanOp
        from auron_tpu.ops.joins import HashJoinOp
        build_partitions = self.ctx.get_resource(n.build_resource_id)
        build = DeviceBatchScanOp(build_partitions,
                                  serde.parse_schema(n.build_schema))
        return HashJoinOp(
            self.create_plan(n.probe), build,
            [serde.parse_expr(e) for e in n.probe_keys],
            [serde.parse_expr(e) for e in n.build_keys],
            join_type=n.join_type or "inner",
        )

    # -- exchange -----------------------------------------------------------

    def _parse_partitioning(self, p: pb.PartitioningP):
        from auron_tpu.parallel.partitioning import (HashPartitioning,
                                                     RoundRobinPartitioning,
                                                     SinglePartitioning)
        if p.kind == "hash":
            return HashPartitioning(
                tuple(serde.parse_expr(e) for e in p.hash_keys),
                p.num_partitions)
        if p.kind == "round_robin":
            return RoundRobinPartitioning(p.num_partitions)
        if p.kind == "single":
            return SinglePartitioning()
        if p.kind == "range":
            # bounds are sampled at execution time by the exchange operator
            from auron_tpu.parallel.partitioning import RangePartitioning
            return RangePartitioning(
                tuple(serde.parse_sort_order(o) for o in p.range_orders),
                p.num_partitions, bounds=())
        raise NotImplementedError(f"partitioning {p.kind!r}")

    def _plan_shuffle_writer(self, n: pb.ShuffleWriterNode) -> PhysicalOp:
        rss_root, shuffle_id, orphan_sweep = n.rss_root, n.shuffle_id, True
        journal = None
        if rss_root:
            # explicit RSS root with a journal active (a journaled —
            # or RESUMING — serving task): restrict the service's
            # startup sweep to .part files. The full sweep rmtree's a
            # dead predecessor's UNCOMMITTED shuffle dirs, which is
            # exactly where the individually-committed map outputs a
            # task-scope journal recorded live until resume reuses
            # them (eager GC of such dirs falls to non-journaled
            # constructions of the same root).
            from auron_tpu.runtime import journal as jrn
            if jrn.active_journal() is not None:
                orphan_sweep = "parts"
        if not rss_root:
            # crash-safe journal routing (runtime/journal.py): while a
            # journal is active for the driving thread's query, its
            # shuffles lower through the DURABLE RSS tier under the
            # journal's run directory, with shuffle ids assigned in
            # plan-walk order — deterministic, so a fresh process
            # re-planning the identical bytes reproduces them and
            # resume can match committed stages to plan nodes. The
            # journal's own sweep governs whole-dir lifecycle there
            # (a dead predecessor's partial maps are what resume
            # reuses), so the service sweeps .part files only.
            from auron_tpu.runtime import journal as jrn
            journal = jrn.active_journal()
            if journal is not None:
                shuffle_id = journal.next_shuffle_id()
                # mesh-aware journal routing: consume the plan-walk
                # shuffle id UNCONDITIONALLY (resume re-plans the same
                # bytes and must reproduce every id, whichever tier
                # each exchange lands on), then route by the CURRENT
                # exchange_route verdict. An exchange the mesh can
                # carry stays on the all_to_all fast path — journaling
                # a query must not silently forfeit 8-wide exchanges to
                # the durable tier — at the price of that one stage's
                # resumability. The exception is an exchange the
                # journal already holds durable state for (a RESUME
                # onto a possibly NARROWER mesh): its committed maps
                # live on the RSS tier, so it re-plans there
                # regardless of what the current plane could carry.
                from auron_tpu.parallel import mesh as mesh_mod
                route, _ = mesh_mod.exchange_route(
                    self._parse_partitioning(n.partitioning),
                    n.partitioning.num_partitions,
                    n.input_partitions or 1, mesh_mod.current_plane())
                if route == "all_to_all" \
                        and not journal.has_shuffle_state(shuffle_id):
                    journal = None   # non-durable mesh fast path
                else:
                    rss_root = journal.rss_root
                    orphan_sweep = "parts"
        if rss_root:
            # RSS tier: push partition frames to the host shuffle service
            # so other hosts can read them (exchange.RssShuffleExchangeOp)
            from auron_tpu.parallel.exchange import RssShuffleExchangeOp
            from auron_tpu.parallel.shuffle_service import FileShuffleService
            op = RssShuffleExchangeOp(
                self.create_plan(n.child),
                self._parse_partitioning(n.partitioning),
                FileShuffleService(rss_root, orphan_sweep=orphan_sweep),
                shuffle_id,
                input_partitions=n.input_partitions or 1)
            if journal is not None:
                journal.record_exchange(
                    shuffle_id, n.input_partitions or 1,
                    n.partitioning.num_partitions,
                    n.partitioning.kind or "single")
        else:
            from auron_tpu.parallel.exchange import ShuffleExchangeOp
            op = ShuffleExchangeOp(self.create_plan(n.child),
                                   self._parse_partitioning(n.partitioning),
                                   input_partitions=n.input_partitions or 1)
        if n.output_resource_id:
            self.ctx.put_resource(n.output_resource_id, op)
        return op

    def _plan_rss_shuffle_read(self, n: pb.RssShuffleReadNode) -> PhysicalOp:
        from auron_tpu.parallel.exchange import RssShuffleReadOp
        from auron_tpu.parallel.shuffle_service import FileShuffleService
        from auron_tpu.runtime import journal as jrn
        # same sweep restriction as _plan_shuffle_writer, and for the
        # same reason: read nodes plan BEFORE writer nodes, so a full
        # sweep here would rmtree the dead predecessor's uncommitted
        # dirs (and memoize the root) before the writer's 'parts'
        # guard ever ran — destroying the committed maps a task-scope
        # journal recorded for resume
        sweep = "parts" if jrn.active_journal() is not None else True
        return RssShuffleReadOp(
            FileShuffleService(n.rss_root, orphan_sweep=sweep),
            n.shuffle_id, serde.parse_schema(n.schema),
            n.num_partitions or 1)

    def _plan_broadcast_exchange(self, n: pb.BroadcastExchangeNode) -> PhysicalOp:
        from auron_tpu.parallel.exchange import BroadcastExchangeOp
        # warm-path subplan identity (auron.cache.subplan): fingerprint
        # the broadcast SUBTREE as its own plan — same identity
        # components as a full result (cache/identity.py), with the
        # input fan-out folded in — so successive/concurrent queries
        # whose outer plans differ still share the built relation
        subplan_key = None
        try:
            from auron_tpu.cache import result_cache as _rcache
            cache = _rcache.get_cache()
            if cache.subplan_enabled():
                subplan_key = cache.subplan_cache_key(
                    pb.TaskDefinition(plan=n.child).SerializeToString(),
                    self.ctx.catalog,
                    input_partitions=n.input_partitions or 1)
        except Exception:   # planning must survive a cache-plane bug
            subplan_key = None
        op = BroadcastExchangeOp(self.create_plan(n.child),
                                 input_partitions=n.input_partitions or 1,
                                 subplan_key=subplan_key)
        if n.output_resource_id:
            self.ctx.put_resource(n.output_resource_id, op)
        return op

    # -- sinks --------------------------------------------------------------

    def _plan_parquet_sink(self, n: pb.ParquetSinkNode) -> PhysicalOp:
        from auron_tpu.io.sinks import ParquetSinkOp
        return ParquetSinkOp(self.create_plan(n.child), n.path,
                             partition_by=list(n.partition_by),
                             compression=n.compression or "snappy")

    def _plan_orc_sink(self, n: pb.OrcSinkNode) -> PhysicalOp:
        from auron_tpu.io.sinks import OrcSinkOp
        return OrcSinkOp(self.create_plan(n.child), n.path,
                         compression=n.compression or "zstd")


def plan_from_bytes(data: bytes,
                    ctx: Optional[PlannerContext] = None) -> PhysicalOp:
    """Decode a serialized TaskDefinition and materialize its plan — the
    `callNative` entry analogue (reference: auron/src/exec.rs:42-118)."""
    task = pb.TaskDefinition.FromString(data)
    planner = PhysicalPlanner(ctx)
    if planner.ctx.plan_fp is None:
        # identity for the ir/cost.py site history (the PR 16 cache/
        # journal key): same bytes → same fingerprint → prior runs'
        # observed stats feed this planning pass
        from auron_tpu.runtime import journal as journal_mod
        planner.ctx.plan_fp = journal_mod.plan_fingerprint(data)
    return planner.plan_task(task)


# ---------------------------------------------------------------------------
# whole-stage fusion pass
# ---------------------------------------------------------------------------

#: bound on the fan-out product (expand projections multiply the batch
#: count inside one program) a fused stage may unroll
_MAX_STAGE_FANOUT = 16


def fuse_stages(op: PhysicalOp, config=None,
                plan_fp: Optional[str] = None) -> PhysicalOp:
    """Whole-stage fusion (ops/fused.py): greedily group maximal chains
    of fusable row-local operators into FusedStageOp nodes, and push the
    key/value projection of partial/complete aggregations below the agg
    so its expression evaluation joins the fused chain. Stage breakers —
    agg cores, joins, sorts, exchanges, window, generate, scans — never
    implement the fragment protocol, so a chain cannot cross them by
    construction. Gated on ``auron.fusion.enabled``; chain length is
    bounded by ``auron.fusion.max_stage_ops``.

    After the greedy chaining, the Fusion 2.0 selection pass
    (_fold_combine) walks the tree: eligible exchange-over-partial-agg
    shapes get the map-side combine fold stamped, and each decision site
    is scored by ir/cost.py against recorded history keyed on
    ``plan_fp`` (greedy-maximal when auron.fusion.cost_model is off)."""
    from auron_tpu import config as cfg
    conf = config if config is not None else cfg.get_config()
    # the pre-agg projection normalization runs regardless of the fusion
    # switch: it moves key/value expression evaluation from the agg's
    # eager per-batch loop into a jitted project kernel, and eager vs
    # jitted float arithmetic differ in the last ulp (XLA contracts
    # elementwise chains) — applying it on BOTH settings keeps
    # fusion.enabled on/off bit-identical, the differential battery's
    # contract
    op = _normalize(op)
    if not conf.get(cfg.FUSION_ENABLED):
        return op
    max_ops = max(2, conf.get(cfg.FUSION_MAX_STAGE_OPS))
    op = _fuse(op, max_ops)
    _fold_combine(op, conf, plan_fp)
    return op


def _normalize(op: PhysicalOp) -> PhysicalOp:
    op = _elide_agg_child_projection(op)
    op = _push_agg_projection(op)
    _replace_children(op, _normalize)
    return op


def _wrap_single(child: PhysicalOp) -> PhysicalOp:
    """Wrap a lone computing fusable op so a fold-capable parent (the
    exchange's split, the hash join's probe) can absorb its fragment
    into ONE program. Pass-through ops (limit/rename) stay bare — their
    host-side bookkeeping is free, a program for it would not be."""
    from auron_tpu.ops.fused import FusedStageOp
    if getattr(child, "fusable", False) and child.fragment_computes \
            and not isinstance(child, FusedStageOp):
        return FusedStageOp([child])
    return child


def _fuse(op: PhysicalOp, max_ops: int) -> PhysicalOp:
    from auron_tpu.ops.fused import FusedStageOp
    from auron_tpu.ops.joins import HashJoinOp
    from auron_tpu.parallel.exchange import ShuffleExchangeOp
    if isinstance(op, (ShuffleExchangeOp, HashJoinOp)):
        _replace_children(op, lambda c: _fuse(c, max_ops))
        # wrap a lone computing child: the exchange folds the stage's
        # fragments into its split program, the join into its probe
        # program (chain + pids/keys + sort/search = ONE XLA launch)
        if isinstance(op, ShuffleExchangeOp):
            op.child = _wrap_single(op.child)
        else:
            op.probe = _wrap_single(op.probe)
        return op
    if getattr(op, "fusable", False):
        # collect the maximal chain op → … → deepest fusable descendant
        chain = [op]
        fanout = op.fusion_fanout
        while True:
            child = chain[-1].children[0]
            if not getattr(child, "fusable", False):
                break
            if len(chain) >= max_ops:
                break
            if fanout * child.fusion_fanout > _MAX_STAGE_FANOUT:
                break
            chain.append(child)
            fanout *= child.fusion_fanout
        # recurse below the stage input, keeping the member links intact
        tail = chain[-1]
        _replace_children(tail, lambda c: _fuse(c, max_ops))
        if len(chain) >= 2 and any(m.fragment_computes for m in chain):
            # a chain of pure pass-throughs (limit→rename) would compile
            # a program for work the host loop does for free — skip
            return FusedStageOp(list(reversed(chain)))
        return op
    _replace_children(op, lambda c: _fuse(c, max_ops))
    return op


def _fold_combine(op: PhysicalOp, conf, plan_fp: Optional[str]) -> None:
    """Fusion 2.0 selection walk (runs after _fuse): stamp the map-side
    combine fold on every hash exchange whose child is an eligible
    partial AggOp, and the probe-into-consumer decision on every hash
    join. Each site gets a stable (plan_fp, label) identity so the
    runtime can record observed stats into ir/cost.py and the next
    planning of the SAME plan can select against them.

    The fold keeps the agg node in the tree (schema, metrics and explain
    stay intact — the folded-chain convention); at materialize time the
    exchange executes the agg's child with the combine stage folded into
    its split program. The fold mode is a TRACE-SEMANTIC decision
    resolved from the PROCESS-GLOBAL config (auron.fusion.{combine,
    cost_model} ride config.trace_salt()), never the session override."""
    from auron_tpu import config as cfg
    from auron_tpu.exprs import ir as xir
    from auron_tpu.ir import cost as cost_mod
    from auron_tpu.ops.agg import AggOp
    from auron_tpu.ops.joins import HashJoinOp
    from auron_tpu.parallel.exchange import ShuffleExchangeOp
    from auron_tpu.parallel.partitioning import (HashPartitioning,
                                                 SinglePartitioning)
    gconf = cfg.get_config()
    capacity = conf.get(cfg.BATCH_CAPACITY)
    sites = iter(range(1 << 30))

    def keys_only(exchange, n_keys: int) -> bool:
        # every partitioning expr must be a plain ref into the group-key
        # prefix of the partial layout: a group's rows (combined or not)
        # then land on ONE reducer — the fold's correctness condition
        if isinstance(exchange.partitioning, SinglePartitioning):
            return True
        if not isinstance(exchange.partitioning, HashPartitioning):
            return False
        return all(isinstance(e, xir.ColumnRef) and e.index < n_keys
                   for e in exchange.partitioning.exprs)

    def walk(o: PhysicalOp) -> None:
        if isinstance(o, ShuffleExchangeOp):
            site = (plan_fp, f"x{next(sites)}") if plan_fp else None
            o.cost_site = site
            child = o.child
            if isinstance(child, AggOp):
                reason = child.combine_fold_reason()
                if reason is None \
                        and not keys_only(o, len(child.group_exprs)):
                    reason = "partitioning_not_on_keys"
                if reason is None:
                    if not gconf.get(cfg.FUSION_COMBINE):
                        mode, why = "passthrough", "combine_off"
                    else:
                        mode, why = cost_mod.choose_exchange_mode(
                            gconf, site, capacity)
                    o.combine_mode, o.combine_why = mode, why
                    cost_mod.record_decision(site, "exchange", mode)
                    # a lone computing op under the agg folds as a chain
                    child.child = _wrap_single(child.child)
                else:
                    o.combine_mode, o.combine_why = None, reason
        elif isinstance(o, HashJoinOp):
            site = (plan_fp, f"j{next(sites)}") if plan_fp else None
            o.cost_site = site
            o.probe_fold_consumer = cost_mod.choose_probe_fold(gconf,
                                                               site)
            cost_mod.record_decision(
                site, "probe_fold",
                "fold" if o.probe_fold_consumer else "unfused")
        for c in o.children:
            walk(c)

    walk(op)


def _replace_children(op: PhysicalOp, fn) -> None:
    """Apply ``fn`` to every direct child and swap the rewritten ops back
    into the parent's attributes (operators hold children as plain
    attributes — ``child``, ``probe``/``build``, ``inputs`` lists)."""
    for name, val in list(vars(op).items()):
        if isinstance(val, PhysicalOp):
            setattr(op, name, fn(val))
        elif isinstance(val, list) and val \
                and all(isinstance(v, PhysicalOp) for v in val):
            setattr(op, name, [fn(v) for v in val])


def _elide_agg_child_projection(op: PhysicalOp) -> PhysicalOp:
    """Drop a pure column-pick ProjectOp feeding an aggregation: when the
    agg's group/arg expressions are plain ColumnRefs into a projection
    whose referenced outputs are themselves plain ColumnRefs, the
    projection does no device compute the agg needs — the agg's
    per-batch contribution step picks columns by index anyway, so the
    refs are remapped to the projection's input and one whole program
    per (exprs, schema, capacity) disappears from the plan. Values are
    untouched (identical column arrays), so results are bit-identical
    under both fusion settings."""
    from auron_tpu.exprs import ir as eir
    from auron_tpu.ops.agg import AggOp
    from auron_tpu.ops.project import ProjectOp
    if not isinstance(op, AggOp) or op.mode not in ("partial", "complete"):
        return op
    child = op.children[0]
    if not isinstance(child, ProjectOp):
        return op
    for a in op.aggs:
        if a.fn == "bloom_filter" or a.fn.startswith("udaf:"):
            return op
    used = list(op.group_exprs) + [a.arg for a in op.aggs
                                   if a.arg is not None]
    if not used or not all(isinstance(e, eir.ColumnRef) for e in used):
        return op
    refs = {e.index for e in used}
    if not all(0 <= i < len(child.exprs)
               and isinstance(child.exprs[i], eir.ColumnRef)
               for i in refs):
        return op
    remap = {i: child.exprs[i].index for i in refs}
    from dataclasses import replace as _dc_replace
    new_groups = [eir.ColumnRef(remap[e.index]) for e in op.group_exprs]
    new_aggs = [a if a.arg is None
                else _dc_replace(a, arg=eir.ColumnRef(remap[a.arg.index]))
                for a in op.aggs]
    rewritten = AggOp(child.children[0], new_groups, new_aggs, mode=op.mode,
                      group_names=op.group_names, agg_names=op.agg_names,
                      initial_capacity=op.initial_capacity,
                      key_domain=op.key_domain)
    if rewritten.schema() != op.schema():
        return op
    # the child's child may itself be a pure projection: elide again
    return _elide_agg_child_projection(rewritten)


def _push_agg_projection(op: PhysicalOp) -> PhysicalOp:
    """Pre-agg key/value projection: rewrite AggOp(group_exprs, aggs)
    over arbitrary expressions into AggOp(ColumnRefs) over a ProjectOp
    evaluating those expressions — the projection then fuses with the
    chain below the agg, so key/value evaluation runs inside the fused
    stage program instead of eagerly per batch in the agg's host loop.
    Only for partial/complete device-side aggregations; the rewrite is
    expression-for-expression, so results are bit-identical."""
    from auron_tpu.exprs import ir as eir
    from auron_tpu.ops.agg import AggOp
    from auron_tpu.ops.project import ProjectOp
    if not isinstance(op, AggOp) or op.mode not in ("partial", "complete"):
        return op
    for a in op.aggs:
        # host-side accumulator states (bloom/udaf) evaluate their own
        # inputs against the child schema — leave those plans untouched
        if a.fn == "bloom_filter" or a.fn.startswith("udaf:"):
            return op
    if not getattr(op.children[0], "fusable", False):
        # nothing below to fuse the projection into (agg over a join /
        # exchange / scan): a standalone projection would ADD a program
        # without saving one — leave key/value evaluation to the agg's
        # per-batch loop, identically under both fusion settings
        return op
    used = list(op.group_exprs) + [a.arg for a in op.aggs
                                   if a.arg is not None]
    if not used or all(isinstance(e, eir.ColumnRef) for e in used):
        return op   # nothing to push down

    proj_exprs: list = []
    index_of: dict = {}

    def col(e):
        if e not in index_of:
            index_of[e] = len(proj_exprs)
            proj_exprs.append(e)
        return eir.ColumnRef(index_of[e])

    new_groups = [col(e) for e in op.group_exprs]
    from dataclasses import replace as _dc_replace
    new_aggs = [a if a.arg is None else _dc_replace(a, arg=col(a.arg))
                for a in op.aggs]
    proj = ProjectOp(op.children[0], proj_exprs,
                     [f"_pre{i}" for i in range(len(proj_exprs))])
    rewritten = AggOp(proj, new_groups, new_aggs, mode=op.mode,
                      group_names=op.group_names, agg_names=op.agg_names,
                      initial_capacity=op.initial_capacity,
                      key_domain=op.key_domain)
    if rewritten.schema() != op.schema():
        # defensive: a projection that would change the agg's output
        # contract (shouldn't happen — infer_field is deterministic)
        # must never reach execution
        return op
    return rewritten


# ---------------------------------------------------------------------------
# SPMD mesh annotation pass
# ---------------------------------------------------------------------------

def annotate_mesh(op: PhysicalOp, plane) -> PhysicalOp:
    """Stamp each node's resolved SPMD spec (``op.mesh_spec``) when the
    mesh plane is active:

    - eligible hash exchanges become ``"gang"`` — their materialization
      occupies the whole mesh (parallel/exchange._materialize_mesh);
    - nodes declaring a buffer kind (``mesh_buffer_kind``) resolve
      through the replicate-vs-shard table (parallel/mesh.buffer_spec):
      broadcast relations and hash-join build sides ``"replicate"``
      (every shard reads them whole), scan batches / shuffle entries /
      partial-agg rows ``"shard"`` on the batch dim;
    - everything else shards (the default — throughput scales with
      devices; replication is the exception).

    The annotation is the static half of the routing contract — the
    runtime decision (exchange_route at materialize time) re-derives it
    from the same pure function, so the plan a user inspects and the
    route the engine takes can never disagree."""
    if plane is None:
        return op
    _annotate_mesh(op, plane)
    return op


def _annotate_mesh(op: PhysicalOp, plane) -> None:
    from auron_tpu.ops.joins import HashJoinOp
    from auron_tpu.parallel import mesh as mesh_mod
    from auron_tpu.parallel.exchange import ShuffleExchangeOp
    if isinstance(op, ShuffleExchangeOp):
        route, _reason = mesh_mod.exchange_route(
            op.partitioning, op.num_partitions, op.input_partitions,
            plane)
        op.mesh_spec = "gang" if route == "all_to_all" else "shard"
    else:
        op.mesh_spec = mesh_mod.buffer_spec(op.mesh_buffer_kind)
    for c in op.children:
        _annotate_mesh(c, plane)
    if isinstance(op, HashJoinOp) and op.build.mesh_spec != "gang":
        # the build side replicates: every probe shard reads the full
        # build relation (the join declares the kind — mesh_build_kind
        # — so the decision stays in the replicate-vs-shard table). A
        # gang-annotated build exchange keeps its stamp: the exchange
        # itself is mesh-routed; it is the COLLECTED hash table that
        # replicates.
        op.build.mesh_spec = mesh_mod.buffer_spec(op.mesh_build_kind)


def _collect_subqueries(msg) -> list:
    """All ScalarSubqueryE messages reachable from ``msg`` (any proto
    node), outermost occurrences only — a subquery's own plan is scanned
    again when IT is planned."""
    found = []
    for fd, val in msg.ListFields():
        if fd.type != fd.TYPE_MESSAGE:
            continue
        vals = val if fd.is_repeated else [val]
        for v in vals:
            if isinstance(v, pb.ExprNode) \
                    and v.WhichOneof("expr") == "scalar_subquery":
                found.append(v.scalar_subquery)
            elif isinstance(v, pb.ScalarSubqueryE):
                continue   # do not descend into the subquery's own plan
            else:
                found.extend(_collect_subqueries(v))
    return found


def subquery_key(q) -> bytes:
    """Dedup key for a ScalarSubqueryE: the plan + result type WITHOUT the
    sid — two structurally equal subqueries (built separately, so stamped
    with different sids) must share one resolution."""
    k = pb.ScalarSubqueryE()
    k.CopyFrom(q)
    k.sid = 0
    return k.SerializeToString()


def substitute_subqueries(node: pb.PlanNode,
                          values: dict[bytes, "pb.ExprNode"]) -> pb.PlanNode:
    """Copy of ``node`` with every scalar_subquery ExprNode replaced by
    the resolved literal ExprNode from ``values`` (keyed by
    ``subquery_key`` — identical subqueries share one resolution)."""
    out = pb.PlanNode()
    out.CopyFrom(node)

    def walk(msg):
        for fd, val in msg.ListFields():
            if fd.type != fd.TYPE_MESSAGE:
                continue
            vals = val if fd.is_repeated else [val]
            for v in vals:
                if isinstance(v, pb.ExprNode) \
                        and v.WhichOneof("expr") == "scalar_subquery":
                    v.CopyFrom(values[subquery_key(v.scalar_subquery)])
                else:
                    walk(v)

    walk(out)
    return out
