"""Warm-path serving plane: plan-fingerprint result/subplan cache + AOT.

Two planes, both keyed on the crash-tested journal identity machinery
(``runtime/journal.py``: ``plan_fingerprint`` / ``source_fingerprints``):

- ``result_cache``: a process-wide LRU of materialized Arrow results
  (and broadcast-subplan relations) keyed on
  ``(plan_fingerprint, source_fingerprints, trace_salt)``. Exact
  re-submissions are answered from host memory instead of silicon;
  source mutation or a semantics-knob flip changes the key, so stale
  data can never be served. Entries are memmgr-registered sheddable
  consumers — the pressure ladder evicts them (rung ``cache_evict``)
  before any working state is force-spilled.
- ``aot``: the ahead-of-time program plane. ``Session`` records plan
  signatures next to the persistent XLA cache (``auron.xla_cache_dir``)
  and, at startup, warms the top-N signatures through the normal
  planner/executor path so compiles land in the central program
  registry and the persistent XLA cache before the first user query.

``identity`` holds the ONE implementation of "is this recorded state
the same query over the same data" — shared by journal adoption
(``find_reusable``) and cache lookup, so the two can never drift.

Knobs: ``auron.cache.{enabled,max_bytes,subplan,aot_top_n}``.
"""

from auron_tpu.cache.result_cache import get_cache  # noqa: F401
