"""Shared query-identity screening: ONE implementation of "is this
recorded state the same query over the same data".

Both consumers of the journal's fingerprint machinery go through here:

- journal adoption (``runtime/journal.py find_reusable``): screen a
  candidate journal's header, then the loaded journal, then its
  recorded source fingerprints against the live catalog;
- the warm-path result cache (``cache/result_cache.py``): build a
  lookup key whose components are exactly the things that must match
  for a cached result to be byte-correct — the plan fingerprint, the
  live source fingerprints, and the process trace salt (the semantics
  knobs that change query OUTPUT, ``config.trace_salt()``).

Keeping both on one module means a screening bug (or a new component
of query identity) is fixed in one place for journal reuse AND the
cache — they can never drift apart and disagree about staleness.
"""

from __future__ import annotations

from typing import Any, Optional

from auron_tpu.runtime.journal import (
    _owner_is_other_live_process,
    plan_fingerprint,
    plan_has_host_fns,
    source_fingerprints,
)

#: sentinel value source_fingerprints records for an unreadable source;
#: any key containing it is uncacheable (identity can't be established)
MISSING = "missing:"


def screen_header(header: Optional[dict], plan_fp: str,
                  scope: str = "collect") -> bool:
    """Cheap pre-load screen of a journal header dict (``_peek_header``):
    True when the candidate MAY be the same query — same plan
    fingerprint, same scope, and not owned by another live process."""
    if header is None:
        return False
    if header.get("plan_fp") != plan_fp:
        return False
    if header.get("scope", "collect") != scope:
        return False
    if _owner_is_other_live_process(header.get("owner", "")):
        return False
    return True


def screen_loaded(jr: Any, plan_fp: str, scope: str = "collect") -> bool:
    """Authoritative post-load re-screen of a loaded journal object
    (the header screen raced against concurrent writers; this one reads
    the parsed journal)."""
    if jr.plan_fp != plan_fp:
        return False
    if jr.scope != scope:
        return False
    if _owner_is_other_live_process(getattr(jr, "owner", "")):
        return False
    return True


class SourceProbe:
    """Lazily-computed live source fingerprints for one plan.

    ``fingerprints()`` walks the plan's sources (file stat / table
    digest) ONCE and memoizes — ``find_reusable`` probes many journal
    candidates against one submission, and the cache key needs the same
    map, so the walk must not repeat per candidate."""

    def __init__(self, plan_bytes: bytes, catalog: Optional[dict]):
        self._plan_bytes = plan_bytes
        self._catalog = catalog
        self._fps: Optional[dict] = None

    def fingerprints(self) -> dict:
        if self._fps is None:
            self._fps = source_fingerprints(self._plan_bytes, self._catalog)
        return self._fps

    def matches(self, recorded: dict) -> bool:
        """True when ``recorded`` (a journal's ``sources`` map) is
        byte-for-byte the live state of every source."""
        return recorded == self.fingerprints()

    def any_missing(self) -> bool:
        return any(v == MISSING for v in self.fingerprints().values())


def cacheable(plan_bytes: bytes) -> bool:
    """A plan is cacheable when its identity is fully capturable: no
    host-fn sources (their output is process-local and re-registered
    per execution, so no durable fingerprint exists)."""
    return not plan_has_host_fns(plan_bytes)


def result_key(plan_bytes: bytes, catalog: Optional[dict],
               scope: str = "collect", partition: int = -1):
    """The full cache key for one materialized result, or None when the
    plan's identity cannot be established (host fns, unreadable
    sources).

    Components mirror the journal's reuse screen exactly:
    ``(plan_fp, source_fps, trace_salt, scope, partition)`` — source
    fingerprints IN the key make invalidation automatic (a mutated
    source produces a different key, so the stale entry is simply
    never hit again and ages out of the LRU)."""
    if not cacheable(plan_bytes):
        return None
    probe = SourceProbe(plan_bytes, catalog)
    if probe.any_missing():
        return None
    from auron_tpu import config as cfg
    return (plan_fingerprint(plan_bytes),
            frozenset(probe.fingerprints().items()),
            cfg.trace_salt(), scope, int(partition))
