"""AOT program plane: plan-signature inventory + startup warmer.

Productionizes the persistent XLA cache (``auron.xla_cache_dir``, bound
into jax at Session init) into an end-to-end cold-start story:

- **record** (``record_plan``): every completed top-level query whose
  plan reads only durable sources writes its plan bytes + a submission
  count under ``<xla_cache_dir>/aot_plans/<plan_fp>.{plan,json}``. The
  inventory is the mined "what does this deployment actually run".
- **warm** (``warm``): at Session init (``auron.cache.aot_top_n`` > 0)
  the top-N signatures by submission count — union of the aot_plans
  inventory and any resumable journals' recorded plans — are executed
  through the NORMAL planner/executor path. That drives every compile
  through the central program registry (per-site build/hit attribution
  stays correct) and the persistent XLA cache, and — when the result
  cache is enabled — leaves the warmed results ready to serve, so the
  process's first user query pays neither compile nor execution.

``warm`` runs OFF the construction path: Session init only spawns a
daemon thread, so warming overlaps the first user query's planning
instead of serializing ahead of it — ``last_stats()['overlapped_ms']``
is the wall the warmer ran concurrently. ``wait()`` joins the in-flight
warm (Session.close does, bounding the thread's lifetime to the session
that started it), and readers that need the FINAL summary call it
before ``last_stats``.

``warm`` NEVER raises (Session init must survive a corrupt inventory);
failures are collected in ``last_stats()['errors']`` and the perf_gate
cache arm fails loudly when the warmer errored silently.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import threading
from typing import Optional

logger = logging.getLogger("auron.cache.aot")

_LOCK = threading.Lock()
_LAST: dict = {"warmed": 0, "skipped": 0, "errors": [],
               "overlapped_ms": 0.0}
#: the in-flight background warm, if any (one at a time: ``warm`` joins
#: the previous session's thread before starting its own)
_THREAD: Optional[threading.Thread] = None


def aot_dir(conf=None) -> str:
    """Inventory directory: rides next to the persistent XLA cache.
    Empty string (= plane disarmed) when ``auron.xla_cache_dir`` is
    unset — without a durable compile cache there is nothing for the
    inventory to amortize across processes."""
    from auron_tpu import config as cfg
    if conf is None:
        conf = cfg.get_config()
    root = conf.get(cfg.XLA_CACHE_DIR)
    return os.path.join(root, "aot_plans") if root else ""


def record_plan(plan_bytes: bytes, catalog: Optional[dict],
                num_partitions: int = 1, conf=None) -> None:
    """Mine-side write: bump this plan's submission count in the
    inventory. Best-effort and silent — recording must never affect the
    query that triggered it."""
    try:
        d = aot_dir(conf)
        if not d:
            return
        from auron_tpu.cache import identity
        if not identity.cacheable(plan_bytes):
            return
        # durable sources only: a plan over in-memory tables cannot be
        # re-bound in a fresh process, so warming it would only error
        probe = identity.SourceProbe(plan_bytes, catalog)
        if any(not k.startswith("file:") for k in probe.fingerprints()):
            return
        fp = identity.plan_fingerprint(plan_bytes)
        os.makedirs(d, exist_ok=True)
        plan_path = os.path.join(d, fp + ".plan")
        if not os.path.exists(plan_path):
            tmp = plan_path + ".part"
            with open(tmp, "wb") as f:
                f.write(plan_bytes)
            os.replace(tmp, plan_path)
        meta_path = os.path.join(d, fp + ".json")
        meta = {"count": 0}
        try:
            with open(meta_path, encoding="utf-8") as f:
                meta.update(json.load(f))
        except (OSError, ValueError):
            pass
        meta["count"] = int(meta.get("count", 0)) + 1
        meta["num_partitions"] = int(num_partitions)
        tmp = meta_path + ".part"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(meta, f)
        os.replace(tmp, meta_path)
    except Exception:
        logger.debug("aot: record_plan failed", exc_info=True)


def _inventory(conf) -> dict:
    """fp -> (count, plan_bytes, num_partitions): the aot_plans
    inventory unioned with resumable journals' recorded plans (a
    crashed process's in-flight query is a strong warm candidate)."""
    out: dict = {}
    d = aot_dir(conf)
    if d and os.path.isdir(d):
        for name in sorted(os.listdir(d)):
            if not name.endswith(".plan"):
                continue
            fp = name[:-len(".plan")]
            try:
                with open(os.path.join(d, name), "rb") as f:
                    plan_bytes = f.read()
            except OSError:
                continue
            meta = {}
            try:
                with open(os.path.join(d, fp + ".json"),
                          encoding="utf-8") as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                pass
            out[fp] = (int(meta.get("count", 1)), plan_bytes,
                       int(meta.get("num_partitions", 1)))
    from auron_tpu.runtime import journal as jrn
    jdir = jrn.journal_dir(conf)
    if jdir and os.path.isdir(jdir):
        for name in sorted(os.listdir(jdir)):
            path = os.path.join(jdir, name)
            header = jrn._peek_header(path)
            if not header or "plan_b64" not in header:
                continue
            try:
                plan_bytes = base64.b64decode(header["plan_b64"])
            except (ValueError, TypeError):
                continue
            fp = header.get("plan_fp", "")
            if fp and fp not in out:
                out[fp] = (1, plan_bytes,
                           int(header.get("num_partitions", 1)))
    return out


def warm(session) -> dict:
    """Start warming the top-N inventory plans through ``session``'s
    normal plan/execute path on a BACKGROUND daemon thread and return
    immediately — Session construction no longer blocks on the warm,
    which instead overlaps the first user query's planning. The final
    ``{"warmed", "skipped", "errors", "overlapped_ms"}`` summary lands
    in ``last_stats`` when the thread completes; ``wait()`` joins it.
    Never raises (a broken warmer must not fail construction)."""
    global _LAST, _THREAD
    import time
    # one warm at a time: a second Session arming the warmer while the
    # first is still warming would race the shared inventory/stats
    wait()
    stats: dict = {"warmed": 0, "skipped": 0, "errors": [],
                   "overlapped_ms": 0.0}
    top_n = 0
    try:
        from auron_tpu import config as cfg
        conf = session.config
        top_n = int(conf.get(cfg.CACHE_AOT_TOP_N))
    except Exception as e:   # Session init must survive a broken warmer
        stats["errors"].append(f"warm: {type(e).__name__}: {e}")
        logger.warning("aot: warm failed", exc_info=True)
    if top_n <= 0:
        with _LOCK:
            _LAST = dict(stats, errors=list(stats["errors"]))
        return stats

    def _run() -> None:
        global _LAST
        t0 = time.perf_counter()
        out: dict = {"warmed": 0, "skipped": 0, "errors": []}
        try:
            out = _warm_inner(session, conf, top_n)
        except Exception as e:   # same contract as the sync era
            out["errors"].append(f"warm: {type(e).__name__}: {e}")
            logger.warning("aot: warm failed", exc_info=True)
        out["overlapped_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        with _LOCK:
            _LAST = out

    th = threading.Thread(target=_run, name="auron-aot-warm", daemon=True)
    with _LOCK:
        _THREAD = th
    th.start()
    return stats


def wait(timeout: Optional[float] = None) -> bool:
    """Join the in-flight background warm; no-op when none is running.
    Returns True when no warm is left in flight (so ``last_stats`` is
    the FINAL summary), False on a timeout expiring first."""
    global _THREAD
    with _LOCK:
        th = _THREAD
    if th is None:
        return True
    th.join(timeout)
    if th.is_alive():
        return False
    with _LOCK:
        if _THREAD is th:
            _THREAD = None
    return True


def _warm_inner(session, conf, top_n: int) -> dict:
    from auron_tpu.cache import identity
    from auron_tpu.cache import result_cache as rcache
    from auron_tpu.ir.planner import plan_from_bytes
    from auron_tpu.obs import trace
    from auron_tpu.runtime import lifecycle, programs
    from auron_tpu.runtime.executor import collect as _collect

    stats: dict = {"warmed": 0, "skipped": 0, "errors": []}
    ranked = sorted(_inventory(conf).items(),
                    key=lambda kv: (-kv[1][0], kv[0]))[:top_n]
    for fp, (count, plan_bytes, num_partitions) in ranked:
        probe = identity.SourceProbe(plan_bytes, session.ctx.catalog)
        if probe.any_missing():
            # source vanished since it was recorded: not an error —
            # the inventory outlives datasets by design
            stats["skipped"] += 1
            continue
        token = lifecycle.CancelToken(query_id=f"aot-{fp[:12]}")
        try:
            with trace.span("cache", "aot.warm", plan_fp=fp,
                            count=count, partitions=num_partitions):
                op = plan_from_bytes(plan_bytes, session.ctx)
                table = _collect(op, num_partitions=num_partitions,
                                 mem_manager=session.mem_manager,
                                 config=conf, cancel_token=token)
            key = rcache.get_cache().result_key(
                plan_bytes, session.ctx.catalog)
            if key is not None:
                rcache.get_cache().put_result(key, table)
            stats["warmed"] += 1
        except Exception as e:
            stats["errors"].append(f"{fp}: {type(e).__name__}: {e}")
            logger.warning("aot: warming %s failed", fp, exc_info=True)
        finally:
            programs.pop_query(token.query_id)
    return stats


def last_stats() -> dict:
    """The most recent COMPLETED ``warm`` summary (perf_gate's
    silent-failure check and the ops endpoints read this). With a warm
    still in flight this is the previous summary — call ``wait()``
    first when the final figures are needed."""
    with _LOCK:
        return {"warmed": _LAST["warmed"], "skipped": _LAST["skipped"],
                "errors": list(_LAST["errors"]),
                "overlapped_ms": _LAST.get("overlapped_ms", 0.0)}
