"""Process-wide warm-path result/subplan cache.

One LRU holds two planes of entries, both keyed by
``cache/identity.py`` (plan fingerprint + source fingerprints +
trace salt, so staleness is structurally impossible — a mutated source
or flipped semantics knob produces a DIFFERENT key):

- ``result`` plane: materialized ``pyarrow.Table`` query results, hit
  on exact re-submission (Session collect scope, serving task scope);
- ``subplan`` plane: materialized broadcast relations — the host-side
  entry list a ``BroadcastExchangeOp`` replays — shared across queries
  whose plans differ but whose broadcast subtree is identical.

Memory discipline: the cache is a memmgr-registered consumer
(``pressure_evictable = True``). Under pressure the ladder's
``cache_evict`` rung (memmgr/manager.py) calls ``spill()`` — cached
results are pure derived state, re-creatable at the cost of one query,
so they are ALWAYS evicted before any working state is force-spilled.
Capacity (``auron.cache.max_bytes``) evicts LRU-first on insert.

Lock order (GL008): ``_lock`` guards the OrderedDict + counters and is
leaf-level — no memmgr call is ever made while holding it; manager
accounting (``update_mem_used``) happens strictly after release.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, NamedTuple, Optional


class _Entry(NamedTuple):
    value: Any
    nbytes: int
    plane: str          # "result" | "subplan"


class _Settings(NamedTuple):
    enabled: bool
    max_bytes: int
    subplan: bool


def _table_nbytes(tbl) -> int:
    try:
        return int(tbl.nbytes)
    except Exception:   # older pyarrow: no Table.nbytes
        return int(tbl.get_total_buffer_size())


class QueryResultCache:
    """The process-wide cache instance (use the ``get_cache()``
    singleton — per-Session instances would defeat cross-session
    sharing and double-register with the memmgr)."""

    # memmgr consumer protocol
    consumer_name = "result_cache"
    spill_thread_safe = True    # evictable from any thread's pressure walk
    #: ladder marker: the cache_evict rung (memmgr/manager.py
    #: _pressure_ladder) targets consumers holding re-creatable derived
    #: state — evict these BEFORE force-spilling working state
    pressure_evictable = True

    def __init__(self):
        self._lock = threading.Lock()
        self._mgr_lock = threading.Lock()
        self._entries: "OrderedDict[Any, _Entry]" = OrderedDict()
        self._bytes = 0
        self._managers: dict = {}   # MemManager -> attach refcount
        # monotonic counters (under _lock)
        self._hits = 0
        self._misses = 0
        self._inserts = 0
        self._evictions = 0
        self._pressure_evictions = 0
        self._subplan_hits = 0
        self._subplan_misses = 0
        # config-epoch-cached settings
        self._settings_epoch = -1
        self._settings = _Settings(False, 0, True)

    # -- settings -----------------------------------------------------------

    def _resolve_settings(self) -> _Settings:
        from auron_tpu import config as cfg
        epoch = cfg.config_epoch()
        if epoch != self._settings_epoch:
            conf = cfg.get_config()
            self._settings = _Settings(
                bool(conf.get(cfg.CACHE_ENABLED)),
                int(conf.get(cfg.CACHE_MAX_BYTES)),
                bool(conf.get(cfg.CACHE_SUBPLAN)))
            self._settings_epoch = epoch
        return self._settings

    def enabled(self) -> bool:
        return self._resolve_settings().enabled

    def subplan_enabled(self) -> bool:
        s = self._resolve_settings()
        return s.enabled and s.subplan

    # -- key construction (identity lives in cache/identity.py) -------------

    def result_key(self, plan_bytes: bytes, catalog: Optional[dict],
                   scope: str = "collect", partition: int = -1):
        """Lookup key for a full result, or None when caching is off or
        the plan's identity cannot be established."""
        if not self.enabled():
            return None
        from auron_tpu.cache import identity
        return identity.result_key(plan_bytes, catalog, scope, partition)

    def subplan_cache_key(self, subtree_bytes: bytes,
                          catalog: Optional[dict],
                          input_partitions: int = 1):
        """Key for a materialized subplan output. ``input_partitions``
        is folded in: the collected entry LIST depends on the input
        fan-out, and replay order must be bit-stable."""
        if not self.subplan_enabled():
            return None
        from auron_tpu.cache import identity
        return identity.result_key(subtree_bytes, catalog,
                                   scope="subplan",
                                   partition=input_partitions)

    # -- lookups / inserts --------------------------------------------------

    def get_result(self, key):
        """Cached ``pyarrow.Table`` for ``key``, or None (miss)."""
        return self._get(key, "result")

    def put_result(self, key, table) -> bool:
        return self._put(key, table, _table_nbytes(table), "result")

    def warm_plan_fps(self) -> list:
        """Sorted plan fingerprints of every live entry (result AND
        subplan planes) — the process's warm inventory, scraped by the
        fleet router's affinity routing so a re-submission lands where
        its 173x warm path already lives. Fingerprints only: no keys,
        no values, nothing an ops scrape could leak."""
        with self._lock:
            return sorted({key[0] for key in self._entries})

    def get_subplan(self, key):
        """Cached broadcast entry list for ``key``, or None."""
        return self._get(key, "subplan")

    def put_subplan(self, key, entries, nbytes: int) -> bool:
        return self._put(key, entries, int(nbytes), "subplan")

    def _get(self, key, plane: str):
        from auron_tpu.obs import trace
        if key is None:
            return None
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None and ent.plane == plane:
                self._entries.move_to_end(key)
                if plane == "subplan":
                    self._subplan_hits += 1
                else:
                    self._hits += 1
                value = ent.value
            else:
                if plane == "subplan":
                    self._subplan_misses += 1
                else:
                    self._misses += 1
                value = None
        trace.event("cache", "cache.hit" if value is not None
                    else "cache.miss", plane=plane, plan_fp=key[0])
        return value

    def _put(self, key, value, nbytes: int, plane: str) -> bool:
        from auron_tpu.obs import trace
        if key is None:
            return False
        s = self._resolve_settings()
        if not s.enabled or nbytes > s.max_bytes:
            return False
        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            while self._entries and self._bytes + nbytes > s.max_bytes:
                _, dropped = self._entries.popitem(last=False)
                self._bytes -= dropped.nbytes
                evicted += 1
            self._entries[key] = _Entry(value, nbytes, plane)
            self._bytes += nbytes
            self._inserts += 1
            self._evictions += evicted
        trace.event("cache", "cache.store", plane=plane, plan_fp=key[0],
                    nbytes=nbytes, evicted=evicted)
        self._update_managers()
        return True

    # -- memmgr consumer protocol -------------------------------------------

    def mem_used(self) -> int:
        with self._lock:
            return self._bytes

    def spill(self) -> int:
        """Pressure eviction: drop EVERYTHING (LRU order is moot — the
        whole cache is derived state and the ladder only calls this when
        working state would otherwise be force-spilled). Returns bytes
        freed. Does NOT call back into manager accounting: the invoking
        ladder adjusts its own ledger from the return value, and a
        re-entrant ``update_mem_used`` here could recurse into another
        pressure walk mid-eviction."""
        from auron_tpu.obs import trace
        with self._lock:
            freed, dropped = self._bytes, len(self._entries)
            self._entries.clear()
            self._bytes = 0
            self._evictions += dropped
            self._pressure_evictions += dropped
        if dropped:
            trace.event("cache", "cache.evict", reason="pressure",
                        entries=dropped, freed=freed)
        return freed

    def shrink(self) -> int:
        """Advisory trim (ladder rung 1): drop the LRU half."""
        from auron_tpu.obs import trace
        freed = dropped = 0
        with self._lock:
            for _ in range(len(self._entries) // 2):
                _, ent = self._entries.popitem(last=False)
                self._bytes -= ent.nbytes
                freed += ent.nbytes
                dropped += 1
            self._evictions += dropped
            self._pressure_evictions += dropped
        if dropped:
            trace.event("cache", "cache.evict", reason="shrink",
                        entries=dropped, freed=freed)
        return freed

    # -- manager attachment (Session init/close, refcounted) ----------------

    def attach(self, mem_manager) -> bool:
        """Register with ``mem_manager`` (first attach per manager).
        No-op (False) when caching is disabled or there is no manager —
        the consumer ledger must stay untouched for cache-off runs."""
        if mem_manager is None or not self.enabled():
            return False
        with self._mgr_lock:
            n = self._managers.get(mem_manager, 0)
            self._managers[mem_manager] = n + 1
            first = n == 0
        if first:
            mem_manager.register_consumer(self)
            self._account(mem_manager)
        return True

    def detach(self, mem_manager) -> None:
        if mem_manager is None:
            return
        with self._mgr_lock:
            n = self._managers.get(mem_manager)
            if n is None:
                return
            if n <= 1:
                del self._managers[mem_manager]
                last = True
            else:
                self._managers[mem_manager] = n - 1
                last = False
        if last:
            mem_manager.unregister_consumer(self)

    def _account(self, manager) -> None:
        try:
            manager.update_mem_used(self, self.mem_used())
        except Exception:
            # an over-budget manager may deny the grant (MemoryExhausted
            # under the shed policy): a cache insert must never kill the
            # query that performed it — drop the cache instead
            self.spill()

    def _update_managers(self) -> None:
        with self._mgr_lock:
            managers = list(self._managers)
        for m in managers:
            self._account(m)

    # -- introspection ------------------------------------------------------

    def stats(self) -> dict:
        enabled = self.enabled()
        with self._lock:
            return {
                "enabled": enabled,
                "entries": len(self._entries),
                "bytes": self._bytes,
                "hits": self._hits,
                "misses": self._misses,
                "inserts": self._inserts,
                "evictions": self._evictions,
                "pressure_evictions": self._pressure_evictions,
                "subplan_hits": self._subplan_hits,
                "subplan_misses": self._subplan_misses,
            }

    def clear(self, reset_counters: bool = False) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            if reset_counters:
                self._hits = self._misses = self._inserts = 0
                self._evictions = self._pressure_evictions = 0
                self._subplan_hits = self._subplan_misses = 0
        self._update_managers()


_CACHE = QueryResultCache()


def get_cache() -> QueryResultCache:
    """The process-wide cache singleton."""
    return _CACHE
