"""Backend watchdog: bounded device init + first compile, CPU fallback.

Four rounds of bench windows died to the same failure mode: the
tunneled accelerator client wedges INSIDE backend init (``jax.devices``
never returns — VERDICT r5), so nothing downstream ever runs and no
exception ever surfaces to classify. The watchdog turns that silent
wedge into a bounded, observable decision:

- ``ensure_backend``: probe REAL backend init in a sacrificial child
  process with a deadline (``auron.watchdog.init_timeout_s``). The
  wedge happens inside jax's ``backends()`` while it holds the global
  ``_backend_lock`` — an in-process probe thread abandoned mid-init
  would keep that lock forever and deadlock the CPU fallback's own
  ``jax.devices("cpu")``. Confining the first touch of the plugin to a
  child means the parent never enters the lock until a probe has
  already proven init completes; on timeout the child is killed, the
  parent flips to the CPU platform (config + ``JAX_PLATFORMS`` env so
  subprocesses inherit the flip) and verifies CPU init inside the same
  deadline. Only when the fallback ALSO fails does a classified
  ``BackendInitError`` (non-transient — re-entering a wedged client
  cannot help) surface.
- ``first_compile_probe``: same contract for the first jit compile
  (``auron.watchdog.compile_timeout_s``) — a backend that initializes
  but cannot compile is equally wedged. This wedge is post-init (the
  lock is free), so the probe runs in an abandoned-on-timeout daemon
  thread, and the fallback drops jax's cached backend dict before the
  platform flip — ``backends()`` caches its result, so flipping
  ``jax_platforms`` alone would leave every later compile on the wedged
  platform.

Both default OFF (deadline 0) so nothing eagerly initializes a backend
that lazy paths would not have touched; Session arms them from config.
Injected faults (see below) are simulated in a bounded daemon thread —
never inside jax — so a chaos ``hang`` exercises the timeout path
without wedging the real backend lock. Fallbacks are counted
(``stats``/``totals``) and the process-level total surfaces as
``watchdog_fallbacks`` in every finalize metrics snapshot.

Injection site: ``backend.init`` (kind ``hang`` + ``auron.faults.hang_s``
simulates the wedge; ``io_error`` a failing init).
"""

from __future__ import annotations

import json
import logging
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from auron_tpu import errors

logger = logging.getLogger("auron_tpu")

_LOCK = threading.Lock()
_STATS = {"probes": 0, "timeouts": 0, "fallbacks": 0, "stalls": 0,
          "mesh_rounds_forgiven": 0}

#: bump when ProbeReport.to_dict() keys change (consumers: bench.py's
#: ``probe_report`` field, probe_report.json next to traces, and the
#: schema-stability test in tests/test_perf_gate.py)
PROBE_SCHEMA_VERSION = 1

#: probe ladder step names, in execution order
PROBE_STEPS = ("env", "plugin", "devices", "first_compile")


@dataclass
class ProbeStep:
    """One rung of the backend probe ladder: what ran, whether it
    passed, and — unlike the clipped ``accel_error`` blobs of
    BENCH_r02–r05 — the FULL exception type and message when it did
    not."""

    name: str
    ok: bool
    detail: str = ""
    error_type: str = ""
    error_message: str = ""
    elapsed_s: float = 0.0

    def to_dict(self) -> dict:
        return {"name": self.name, "ok": self.ok, "detail": self.detail,
                "error_type": self.error_type,
                "error_message": self.error_message,
                "elapsed_s": round(self.elapsed_s, 3)}


@dataclass
class ProbeReport:
    """Structured outcome of the backend probe ladder
    (env vars → plugin registration → jax.devices() → first-compile
    smoke). ``ok`` means the ambient accelerator platform is usable end
    to end; a failed report pinpoints WHICH rung broke and carries the
    classified exception, so 'nothing has run on the accelerator since
    r01' becomes an actionable diagnosis instead of a truncated
    traceback."""

    ok: bool
    platform: str = ""
    steps: list = field(default_factory=list)
    schema_version: int = PROBE_SCHEMA_VERSION

    def to_dict(self) -> dict:
        return {"schema_version": self.schema_version, "ok": self.ok,
                "platform": self.platform,
                "steps": [s.to_dict() for s in self.steps]}

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    def failed_step(self) -> Optional[ProbeStep]:
        return next((s for s in self.steps if not s.ok), None)

    def summary(self) -> str:
        """One grep-able line: the first failing rung's
        ``step: Type: message``, or the live platform on success."""
        if self.ok:
            return f"platform={self.platform}"
        s = self.failed_step()
        if s is None:   # pragma: no cover - ok=False implies a failure
            return "probe failed"
        head = f"{s.name}: "
        if s.error_type:
            head += f"{s.error_type}: {s.error_message}"
        else:
            head += s.detail or "failed"
        return head[:300]


#: most recent ProbeReport this process produced (run_probe_ladder) —
#: the ops plane's /healthz reports it without re-running the ladder
#: (the ladder spawns a sacrificial child; a health scrape must be
#: cheap and side-effect-free)
_LAST_PROBE: Optional["ProbeReport"] = None


def last_probe_report() -> Optional["ProbeReport"]:
    return _LAST_PROBE


def stats() -> dict:
    with _LOCK:
        return dict(_STATS)


def totals() -> int:
    """Monotonic process-level fallback count (surfaced in finalize)."""
    with _LOCK:
        return _STATS["fallbacks"]


def stall_totals() -> int:
    """Monotonic process-level stall-detection count (registry +
    chaos-report surface)."""
    with _LOCK:
        return _STATS["stalls"]


def _count(key: str) -> None:
    with _LOCK:
        _STATS[key] += 1


def _run_bounded(fn: Callable, deadline_s: float, what: str
                 ) -> tuple[bool, Optional[BaseException], object]:
    """Run ``fn`` in a daemon thread; (completed, error, value) within
    the deadline. A timeout leaves the thread running — wedged native
    init cannot be interrupted, only abandoned."""
    result: dict = {}

    def worker():
        try:
            result["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — classified by caller
            result["error"] = e

    t = threading.Thread(target=worker, daemon=True,
                         name=f"auron-watchdog-{what}")
    t.start()
    t.join(deadline_s)
    if t.is_alive():
        return False, None, None
    return True, result.get("error"), result.get("value")


def _fault_probe():
    """Injected faults only — bounded in-process, BEFORE jax is ever
    touched, so an injected hang simulates the wedge without holding
    jax's real backend lock."""
    from auron_tpu.runtime import faults
    faults.maybe_fail("backend.init", errors.BackendInitError)


def _initialized_platform() -> Optional[str]:
    """Lock-free peek: the platform name when jax backends are ALREADY
    initialized in this process, else None. Never triggers init and
    never enters jax's ``_backend_lock`` (which a wedged init would
    hold)."""
    import sys
    if sys.modules.get("jax") is None:
        return None
    try:
        from jax._src import xla_bridge as xb
        if not getattr(xb, "_backends", None):
            return None
        default = getattr(xb, "_default_backend", None)
        if default is not None:
            return default.platform
        return next(iter(xb._backends))
    except Exception:   # pragma: no cover - jax internals drift
        return None


_CHILD_PROBE = ("import jax, sys; jax.devices(); "
                "sys.stdout.write(jax.default_backend())")


def _subprocess_init_probe(deadline_s: float) -> tuple[bool, str]:
    """Probe REAL backend init in a sacrificial child process: a wedged
    plugin client wedges (and is killed with) the child, and the parent
    never enters jax's ``_backend_lock``, so the later CPU fallback
    cannot deadlock on a lock held by an abandoned in-process thread.
    Returns (ok, detail) — detail is the platform on success, 'timeout'
    or an error tail otherwise."""
    import os
    import subprocess
    import sys
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD_PROBE],
            capture_output=True, text=True, timeout=deadline_s,
            env=dict(os.environ))
    except subprocess.TimeoutExpired:
        return False, "timeout"
    except Exception as e:   # pragma: no cover - spawn failure
        return False, f"probe spawn failed: {e}"
    if proc.returncode != 0:
        tail = " | ".join((proc.stderr or "").strip().splitlines()[-3:])
        return False, tail or f"probe exited {proc.returncode}"
    return True, (proc.stdout or "").strip()


def _drop_noncpu_backends() -> None:
    """Post-init fallback (first-compile wedge): ``backends()`` caches
    its dict, so flipping ``jax_platforms`` alone leaves every later
    compile on the wedged platform — drop the cache so the next
    ``backends()`` re-initializes CPU-only. No-op when nothing is
    initialized yet or CPU is already the default. Safe here: init
    completed, so the backend lock is free."""
    try:
        from jax._src import xla_bridge as xb
        if not getattr(xb, "_backends", None):
            return
        default = getattr(xb, "_default_backend", None)
        if default is not None and default.platform == "cpu":
            return
        from jax.extend import backend as jex_backend
        jex_backend.clear_backends()
    except Exception as e:   # pragma: no cover - jax internals drift
        logger.warning(
            "backend watchdog: could not drop cached non-CPU backends "
            "after the platform flip (%s) — already-compiled programs "
            "may stay pinned to the wedged platform", e)


def _fallback_to_cpu(deadline_s: float, why: str) -> None:
    """Flip jax to the CPU platform and verify it initializes; raise
    BackendInitError when even that fails."""
    import os
    import jax
    logger.error(
        "backend watchdog: %s — falling back to the CPU platform "
        "(rerun with JAX_PLATFORMS=cpu to skip the probe entirely)", why)
    _count("fallbacks")
    from auron_tpu.obs import trace
    trace.event("watchdog", "watchdog.fallback", why=why[:200])
    try:
        jax.config.update("jax_platforms", "cpu")
        os.environ["JAX_PLATFORMS"] = "cpu"   # subprocesses inherit the flip
    except Exception as e:   # pragma: no cover - jax-version dependent
        raise errors.BackendInitError(
            f"watchdog could not select the CPU platform after: {why} "
            f"({e})") from e
    _drop_noncpu_backends()
    done, err, _ = _run_bounded(lambda: __import__("jax").devices("cpu"),
                                max(deadline_s, 5.0), "cpu-fallback")
    if not done or err is not None:
        raise errors.BackendInitError(
            f"watchdog CPU fallback failed after: {why} "
            f"({err if err is not None else 'cpu init timed out'})")


# ---------------------------------------------------------------------------
# probe ladder: the structured accelerator diagnosis (ProbeReport)
# ---------------------------------------------------------------------------

#: env vars that decide (or witness) which PJRT backend init will pick
_PLATFORM_ENV_VARS = ("JAX_PLATFORMS", "JAX_PLATFORM_NAME", "TPU_NAME",
                      "TPU_WORKER_ID", "TPU_SKIP_MDS_QUERY",
                      "PJRT_DEVICE", "TPU_LIBRARY_PATH")

#: ladder child: devices + first-compile smoke, each step flushed as its
#: own line the MOMENT it finishes — a killed (timed-out) child still
#: leaves every completed step parseable in the captured stdout
_LADDER_CHILD = r"""
import json, sys, time

def emit(step):
    sys.stdout.write("PROBE_STEP=" + json.dumps(step) + "\n")
    sys.stdout.flush()

def run(name, fn):
    t0 = time.perf_counter()
    try:
        detail = fn()
        emit({"name": name, "ok": True, "detail": detail,
              "error_type": "", "error_message": "",
              "elapsed_s": round(time.perf_counter() - t0, 3)})
        return True
    except BaseException as e:
        emit({"name": name, "ok": False, "detail": "",
              "error_type": type(e).__name__,
              "error_message": str(e)[:500],
              "elapsed_s": round(time.perf_counter() - t0, 3)})
        return False

state = {}

def devices():
    import jax
    d = jax.devices()
    state["platform"] = d[0].platform
    return "%d x %s" % (len(d), d[0].platform)

def first_compile():
    import jax
    import jax.numpy as jnp
    jax.jit(lambda x: x + 1)(jnp.ones((8,), jnp.int32)
                             ).block_until_ready()
    return "jit smoke ok"

if run("devices", devices):
    run("first_compile", first_compile)
sys.stdout.write("PROBE_PLATFORM=" + state.get("platform", "") + "\n")
"""


def _env_step() -> ProbeStep:
    """Rung 1: which platform the environment is steering init toward.
    Informational — it cannot fail, but its detail is the first thing a
    human needs when rung 3 wedges."""
    import os
    seen = {v: os.environ[v] for v in _PLATFORM_ENV_VARS
            if v in os.environ}
    detail = (", ".join(f"{k}={v}" for k, v in sorted(seen.items()))
              or "no platform env vars set (jax auto-detects)")
    return ProbeStep("env", True, detail=detail)


def _requested_platforms() -> list[str]:
    import os
    raw = os.environ.get("JAX_PLATFORMS") \
        or os.environ.get("JAX_PLATFORM_NAME") or ""
    return [p.strip().lower() for p in raw.split(",") if p.strip()]


def _plugin_step() -> ProbeStep:
    """Rung 2: PJRT plugin registration WITHOUT initializing anything —
    entry points in the ``jax_plugins`` group plus the namespace-package
    modules. Fails only when the env explicitly requests a non-CPU
    platform that no installed plugin can provide (the
    'plugin never installed' failure mode, distinguishable from the
    'plugin wedges at init' one rung 3 catches)."""
    plugins = []
    try:
        from importlib import metadata
        plugins.extend(ep.name for ep in
                       metadata.entry_points(group="jax_plugins"))
    except Exception:   # pragma: no cover  # graft: disable=GL004 -- plugin enumeration is diagnostic only (importlib API drift)
        pass
    try:
        import pkgutil

        import jax_plugins   # namespace package
        plugins.extend(
            m.name for m in pkgutil.iter_modules(jax_plugins.__path__))
    except Exception:  # graft: disable=GL004 -- plugin enumeration is diagnostic only
        pass
    plugins = sorted(set(plugins))
    detail = ("registered PJRT plugins: " + ", ".join(plugins)
              if plugins else "no PJRT plugin entry points registered")
    requested = [p for p in _requested_platforms() if p != "cpu"]
    if requested and not plugins:
        return ProbeStep(
            "plugin", False, detail=detail,
            error_type="PluginNotRegistered",
            error_message=(f"JAX_PLATFORMS requests {requested} but no "
                           f"PJRT plugin is registered"))
    return ProbeStep("plugin", True, detail=detail)


def _parse_ladder_stdout(stdout: str) -> tuple[list[ProbeStep], str]:
    steps, platform = [], ""
    for line in (stdout or "").splitlines():
        if line.startswith("PROBE_STEP="):
            try:
                d = json.loads(line[len("PROBE_STEP="):])
                steps.append(ProbeStep(**d))
            except Exception:   # pragma: no cover  # graft: disable=GL004 -- a malformed probe line degrades to a shorter ladder report
                pass
        elif line.startswith("PROBE_PLATFORM="):
            platform = line[len("PROBE_PLATFORM="):].strip()
    return steps, platform


def run_probe_ladder(deadline_s: float = 60.0) -> ProbeReport:
    """The full backend diagnosis: env vars → plugin registration →
    ``jax.devices()`` → first-compile smoke. Rungs 3–4 run in ONE
    sacrificial child under ``deadline_s`` (init wedges with — and is
    killed with — the child; each completed step is flushed before the
    next starts, so a timeout still reports how far init got). Never
    raises; never touches jax in THIS process."""
    import os
    import subprocess
    import sys
    import time as _time

    steps = [_env_step(), _plugin_step()]
    t0 = _time.perf_counter()
    timed_out = False
    stdout = ""
    stderr = ""
    returncode = 0
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _LADDER_CHILD],
            capture_output=True, text=True, timeout=deadline_s,
            env=dict(os.environ))
        stdout = proc.stdout or ""
        stderr = proc.stderr or ""
        returncode = proc.returncode
    except subprocess.TimeoutExpired as e:
        timed_out = True
        out = e.stdout
        stdout = (out.decode(errors="replace")
                  if isinstance(out, bytes) else (out or ""))
    except Exception as e:   # pragma: no cover - spawn failure
        steps.append(ProbeStep(
            "devices", False, error_type=type(e).__name__,
            error_message=f"probe child spawn failed: {e}"[:500],
            elapsed_s=_time.perf_counter() - t0))
        return ProbeReport(ok=False, steps=steps)
    child_steps, platform = _parse_ladder_stdout(stdout)
    steps.extend(child_steps)
    reported = {s.name for s in child_steps}
    if timed_out:
        # whichever rung never reported is the one that wedged
        stuck = ("devices" if "devices" not in reported
                 else "first_compile")
        steps.append(ProbeStep(
            stuck, False, error_type="TimeoutError",
            error_message=(f"{stuck} probe exceeded the "
                           f"{deadline_s:.0f}s deadline "
                           f"(child killed — the wedged-init signature, "
                           f"VERDICT r5)"),
            elapsed_s=_time.perf_counter() - t0))
    elif returncode != 0 or "first_compile" not in reported:
        # a hard child crash (SIGSEGV/abort in native plugin code is not
        # catchable by the harness' except) can land AFTER a rung already
        # flushed ok — every unreported rung is then a failure, and the
        # step output alone must never prove health without the child's
        # clean exit (a rung that DID report a failure keeps its own
        # richer record instead of a synthetic one)
        missing = [name for name in ("devices", "first_compile")
                   if name not in reported]
        child_failed = any(not s.ok for s in child_steps)
        if missing and not child_failed:
            tail = " | ".join(stderr.strip().splitlines()[-3:])
            sig = (f"probe child died rc={returncode} during the "
                   f"{missing[0]} rung (native crash is the "
                   f"wedged-plugin signature)")
            steps.append(ProbeStep(
                missing[0], False, error_type="ChildCrashed",
                error_message=(f"{sig}: {tail}" if tail else sig)[:500],
                elapsed_s=_time.perf_counter() - t0))
    ok = all(s.ok for s in steps) and not timed_out \
        and returncode == 0 and "first_compile" in reported
    report = ProbeReport(ok=ok, platform=platform, steps=steps)
    global _LAST_PROBE
    _LAST_PROBE = report
    return report


def write_report(report: ProbeReport,
                 dir_path: Optional[str] = None) -> Optional[str]:
    """Persist a ProbeReport as ``probe_report.json`` next to the traces
    (``auron.trace.dir`` unless ``dir_path`` overrides); returns the
    path, or None when no directory is configured. Best-effort — a
    diagnosis must never become a failure of its own."""
    import os
    if dir_path is None:
        try:
            from auron_tpu import config as cfg
            dir_path = cfg.get_config().get(cfg.TRACE_DIR)
        except Exception:   # pragma: no cover
            dir_path = ""
    if not dir_path:
        return None
    try:
        os.makedirs(dir_path, exist_ok=True)
        path = os.path.join(dir_path, "probe_report.json")
        tmp = path + ".part"
        with open(tmp, "w") as f:
            f.write(report.to_json() + "\n")
        os.replace(tmp, path)
        return path
    except Exception:   # pragma: no cover - best-effort sink
        logger.exception("probe report write to %r failed", dir_path)
        return None


def ensure_backend(config=None) -> Optional[str]:
    """Bound backend init by ``auron.watchdog.init_timeout_s``; returns
    the live platform name, or None when the watchdog is disabled
    (deadline 0 — no eager backend init happens at all)."""
    from auron_tpu import config as cfg
    conf = config if config is not None else cfg.get_config()
    deadline = float(conf.get(cfg.WATCHDOG_INIT_TIMEOUT_S))
    if deadline <= 0:
        return None
    from auron_tpu.obs import trace
    with trace.span("watchdog", "watchdog.init_probe",
                    deadline_s=deadline):
        return _ensure_backend_probed(deadline)


def _ensure_backend_probed(deadline: float) -> Optional[str]:
    _count("probes")
    # injected faults first, bounded in-process (a chaos `hang` must
    # exercise the timeout path without wedging jax's backend lock)
    done, err, _ = _run_bounded(_fault_probe, deadline, "init")
    if not done or err is not None:
        if not done:
            _count("timeouts")
        why = (f"backend init exceeded the {deadline:.1f}s deadline"
               if not done else f"backend init failed: {err}")
        _fallback_to_cpu(deadline, why)
        import jax
        return jax.default_backend()
    # already initialized in this process: init completed once, there is
    # nothing left to bound (and re-probing in a child would be waste)
    live = _initialized_platform()
    if live is not None:
        return live
    ok, detail = _subprocess_init_probe(deadline)
    if not ok:
        if detail == "timeout":
            _count("timeouts")
            why = (f"backend init exceeded the {deadline:.1f}s deadline "
                   f"(probe child killed)")
        else:
            why = f"backend init failed: {detail}"
        _fallback_to_cpu(deadline, why)
    import jax
    return jax.default_backend()


# ---------------------------------------------------------------------------
# task-level stall watchdog: the heartbeat plane (PR 8)
# ---------------------------------------------------------------------------
#
# The init/compile probes above bound the BACKEND's liveness; this plane
# bounds every running TASK's. Executor and shuffle/spill loops beat a
# per-attempt TaskHeartbeat through ExecContext.checkpoint(site); a
# monitor thread flags any task silent past auron.watchdog.stall_timeout_s,
# emits a structured StallReport (task identity, last heartbeat site,
# driving thread's stack) into auron.trace.dir, and sets the heartbeat's
# ``stalled`` flag — the next cooperative poll raises the classified
# ``errors.TaskStalled``, which the retry driver treats as transient
# ONCE. A truly wedged native call never polls again; the report is then
# the diagnosis (the same observable-decision contract as the init
# watchdog) and the query deadline remains the hard bound.

#: bump when StallReport.to_dict() keys change
STALL_SCHEMA_VERSION = 1


@dataclass
class TaskHeartbeat:
    """One task attempt's liveness record. ``beat`` is the hot path —
    two attribute stores, no lock (torn reads merely skew the stall
    estimate by one beat)."""

    task_id: int = 0
    stage_id: int = 0
    partition_id: int = 0
    attempt: int = 0
    #: stall timeout RESOLVED AT REGISTRATION from the registering
    #: task's config (a session-scoped knob must arm detection for its
    #: own tasks even when the process-global default is 0)
    timeout_s: float = 0.0
    last_site: str = ""
    last_beat_ns: int = 0
    started_ns: int = 0
    #: set by the monitor; the task's next checkpoint raises TaskStalled
    stalled: bool = False
    stalled_at_ns: int = 0
    thread_ident: Optional[int] = None

    def beat(self, site: str = "") -> None:
        self.last_beat_ns = _now_ns()
        if site:
            self.last_site = site

    def silent_s(self) -> float:
        return (_now_ns() - self.last_beat_ns) * 1e-9


@dataclass
class StallReport:
    """Structured stall diagnosis written next to the traces
    (``stall_report_<task>.json``): which task went silent, where its
    last heartbeat came from, and what the driving thread was doing when
    the monitor caught it."""

    task_id: int
    stage_id: int
    partition_id: int
    attempt: int
    last_site: str
    silent_s: float
    stall_timeout_s: float
    thread_stack: list = field(default_factory=list)
    schema_version: int = STALL_SCHEMA_VERSION

    def to_dict(self) -> dict:
        return {"schema_version": self.schema_version,
                "task_id": self.task_id, "stage_id": self.stage_id,
                "partition_id": self.partition_id, "attempt": self.attempt,
                "last_site": self.last_site,
                "silent_s": round(self.silent_s, 3),
                "stall_timeout_s": self.stall_timeout_s,
                "thread_stack": self.thread_stack}

    def to_json(self) -> str:
        return json.dumps(self.to_dict())


def _now_ns() -> int:
    import time
    return time.monotonic_ns()


_HB_LOCK = threading.Lock()
_HEARTBEATS: dict[int, TaskHeartbeat] = {}
_MONITOR: Optional[threading.Thread] = None


def stall_timeout_s(config=None) -> float:
    from auron_tpu import config as cfg
    conf = config if config is not None else cfg.get_config()
    return float(conf.get(cfg.WATCHDOG_STALL_TIMEOUT_S))


def register_heartbeat(task_id: int = 0, stage_id: int = 0,
                       partition_id: int = 0, attempt: int = 0,
                       config=None) -> Optional[TaskHeartbeat]:
    """Register one task attempt with the stall monitor; returns None
    when the plane is disarmed (``auron.watchdog.stall_timeout_s`` <= 0)
    so the disarmed path costs one config read per attempt. Starts the
    monitor thread lazily on the first armed registration."""
    timeout = stall_timeout_s(config)
    if timeout <= 0:
        return None
    hb = TaskHeartbeat(task_id=task_id, stage_id=stage_id,
                       partition_id=partition_id, attempt=attempt,
                       timeout_s=timeout, started_ns=_now_ns(),
                       thread_ident=threading.get_ident())
    hb.beat("task.start")
    with _HB_LOCK:
        _HEARTBEATS[id(hb)] = hb
        _ensure_monitor_locked()
    return hb


def unregister_heartbeat(hb: Optional[TaskHeartbeat]) -> None:
    if hb is None:
        return
    with _HB_LOCK:
        _HEARTBEATS.pop(id(hb), None)


def live_heartbeats() -> int:
    with _HB_LOCK:
        return len(_HEARTBEATS)


def _ensure_monitor_locked() -> None:
    """Start the monitor thread if none is running (caller holds
    _HB_LOCK). The thread exits when the registry drains, so an idle
    process carries no watchdog thread."""
    global _MONITOR
    if _MONITOR is not None and _MONITOR.is_alive():
        return
    _MONITOR = threading.Thread(target=_monitor_loop, daemon=True,
                                name="auron-stall-watchdog")
    _MONITOR.start()


def _monitor_loop() -> None:
    import time
    last_compiles = -1
    poll = 0.25
    while True:
        time.sleep(poll)
        with _HB_LOCK:
            if not _HEARTBEATS:
                return          # registry drained: thread retires
            beats = list(_HEARTBEATS.values())
        # each heartbeat carries ITS OWN timeout (resolved from the
        # registering task's config — a session-scoped knob must work
        # with the global default at 0); poll at a quarter of the
        # tightest live timeout so detection latency stays bounded by
        # timeout + poll <= 1.25 x timeout, inside the 2x gate
        tightest = min(hb.timeout_s for hb in beats)
        poll = max(min(tightest / 4.0, 1.0), 0.01)
        # compile-aware: an XLA backend compile runs ON the driving
        # thread with no chance to beat — when compiles completed since
        # the last poll, credit every live task with a beat so a slow
        # first-compile is never misread as a stall (a single compile
        # LONGER than the timeout still flags: size the knob above the
        # platform's worst single-program compile time)
        try:
            from auron_tpu.utils import compile_stats
            n = compile_stats.snapshot().count
        except Exception:   # pragma: no cover
            n = last_compiles
        if n != last_compiles:
            if last_compiles >= 0:
                for hb in beats:
                    if not hb.stalled:
                        hb.beat("xla.compile")
            last_compiles = n
            continue
        for hb in beats:
            if not hb.stalled and hb.silent_s() > hb.timeout_s:
                _flag_stalled(hb, hb.timeout_s)


def _flag_stalled(hb: TaskHeartbeat, timeout: float) -> None:
    """One stall verdict: count it, put it on the timeline, persist the
    StallReport, THEN set the flag (the report must exist before the
    task can observe the flag and unwind past its trace scope)."""
    report = StallReport(
        task_id=hb.task_id, stage_id=hb.stage_id,
        partition_id=hb.partition_id, attempt=hb.attempt,
        last_site=hb.last_site, silent_s=hb.silent_s(),
        stall_timeout_s=timeout,
        thread_stack=_thread_stack(hb.thread_ident))
    _count("stalls")
    logger.error(
        "stall watchdog: task %d (stage %d, partition %d, attempt %d) "
        "silent %.2fs past the last heartbeat at %r — flagging TaskStalled",
        hb.task_id, hb.stage_id, hb.partition_id, hb.attempt,
        report.silent_s, hb.last_site)
    try:
        from auron_tpu.obs import trace
        trace.event("watchdog", "watchdog.stall", task=hb.task_id,
                    stage=hb.stage_id, partition=hb.partition_id,
                    attempt=hb.attempt, last_site=hb.last_site,
                    silent_s=round(report.silent_s, 3))
    except Exception:   # pragma: no cover  # graft: disable=GL004 -- stall-event tee is best-effort; the StallReport is the verdict
        pass
    try:
        from auron_tpu.obs import registry as obs_registry
        if obs_registry.enabled():
            obs_registry.get_registry().counter(
                "auron_stall_detections_total").inc()
    except Exception:   # pragma: no cover  # graft: disable=GL004 -- counter tee is best-effort; the StallReport is the verdict
        pass
    write_stall_report(report)
    hb.stalled_at_ns = _now_ns()
    hb.stalled = True


def _thread_stack(ident: Optional[int]) -> list:
    """The driving thread's current stack (frames innermost-last), the
    StallReport's 'what was it doing' payload. Best-effort."""
    if ident is None:
        return []
    import sys
    import traceback
    try:
        frame = sys._current_frames().get(ident)
        if frame is None:
            return []
        return [f"{f.filename}:{f.lineno} {f.name}"
                for f in traceback.extract_stack(frame)][-20:]
    except Exception:   # pragma: no cover
        return []


def write_stall_report(report: StallReport,
                       dir_path: Optional[str] = None) -> Optional[str]:
    """Persist a StallReport as ``stall_report_<task>.json`` next to the
    traces (``auron.trace.dir``); returns the path, or None when no
    directory is configured. Best-effort, like write_report."""
    import os
    if dir_path is None:
        try:
            from auron_tpu import config as cfg
            dir_path = cfg.get_config().get(cfg.TRACE_DIR)
        except Exception:   # pragma: no cover
            dir_path = ""
    if not dir_path:
        return None
    try:
        os.makedirs(dir_path, exist_ok=True)
        path = os.path.join(dir_path,
                            f"stall_report_{report.task_id}.json")
        tmp = path + ".part"
        with open(tmp, "w") as f:
            f.write(report.to_json() + "\n")
        os.replace(tmp, path)
        return path
    except Exception:   # pragma: no cover - best-effort sink
        logger.exception("stall report write to %r failed", dir_path)
        return None


# ---------------------------------------------------------------------------
# mesh fault domain: per-round gang-aware liveness + straggler defense
# ---------------------------------------------------------------------------
#
# A gang-scheduled all-to-all round blocks the driving thread inside an
# uninterruptible collective, so the stall monitor above will flag the
# task silent — but a flagged ROUND is not automatically a dead one. The
# guard below is the arbiter at the round boundary:
#
# - a round that COMPLETES after being flagged was merely SLOW (a
#   straggling chip): the guard forgives the stall (clears the flag and
#   re-beats, exactly like the compile-credit precedent — waiting out a
#   slow collective is liveness, not a wedge) and hands the duration to
#   the straggler defense;
# - a round that RAISES is DEAD: the error classifies at the collective
#   boundary (errors.classify_runtime → MeshUnavailable) and the
#   exchange's demotion handler routes the remaining rounds host-side;
# - a round that NEVER RETURNS is beyond cooperative recovery — the
#   StallReport is the diagnosis and the query deadline the hard bound,
#   same contract as the init watchdog.


class MeshRoundStats:
    """Rolling per-round duration window: the straggler defense's
    baseline. ``observe`` feeds a bounded deque (and the registry
    histogram ``auron_mesh_round_seconds``); ``is_straggler`` compares
    one round against ``factor`` × the rolling p50, arming only after
    ``min_rounds`` observations so the first cold-compile rounds never
    self-report. Pure host arithmetic — unit-testable without a mesh."""

    def __init__(self, window: int = 64, min_rounds: int = 4):
        self.min_rounds = min_rounds
        self._durations: deque = deque(maxlen=window)
        self._lock = threading.Lock()

    def p50(self) -> Optional[float]:
        with self._lock:
            if len(self._durations) < self.min_rounds:
                return None
            ordered = sorted(self._durations)
            return ordered[len(ordered) // 2]

    def is_straggler(self, seconds: float, factor: float) -> bool:
        """Verdict BEFORE ``seconds`` joins the window (a straggler must
        not drag the baseline it is judged against)."""
        if factor <= 0:
            return False
        p50 = self.p50()
        return p50 is not None and p50 > 0 and seconds > factor * p50

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._durations.append(seconds)
        try:
            from auron_tpu.obs import registry as obs_registry
            if obs_registry.enabled():
                obs_registry.get_registry().histogram(
                    "auron_mesh_round_seconds").observe(seconds)
        except Exception:   # pragma: no cover  # graft: disable=GL004 -- round histogram is best-effort telemetry
            pass


class MeshRoundGuard:
    """Context manager around ONE all-to-all round (dispatch + the
    output-boundary readback): beats the task heartbeat on entry with
    the ``mesh.round`` site, measures the round, and — when the stall
    monitor flagged the task MID-round but the round then completed —
    forgives the stall (slow, not dead; see the module section comment).
    After exit, ``elapsed_s`` carries the round duration for the
    straggler defense and ``forgiven`` whether a stall verdict was
    downgraded."""

    def __init__(self, heartbeat: Optional[TaskHeartbeat]):
        self.heartbeat = heartbeat
        self.elapsed_s = 0.0
        self.forgiven = False
        self._t0 = 0
        self._stalled_on_entry = False

    def __enter__(self) -> "MeshRoundGuard":
        hb = self.heartbeat
        if hb is not None:
            self._stalled_on_entry = hb.stalled
            if not hb.stalled:
                hb.beat("mesh.round")
        self._t0 = _now_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.elapsed_s = (_now_ns() - self._t0) * 1e-9
        hb = self.heartbeat
        if hb is None:
            return
        if exc_type is None and hb.stalled and not self._stalled_on_entry:
            # flagged DURING a round that completed: slow, not dead —
            # forgive (a pre-existing flag is someone else's verdict and
            # survives; the exchange's straggler defense takes it from
            # here)
            self.forgive_stall()
        elif exc_type is None and not hb.stalled:
            hb.beat("mesh.round")

    def forgive_stall(self) -> None:
        """Downgrade a stall flagged MID-round to a slow round. Called
        by ``__exit__`` for completed rounds, and by the exchange's
        DEMOTION handler for failed ones — the loss is being recovered
        in place, and a pending TaskStalled would abort (at the next
        checkpoint) exactly the recovery it was supposed to enable. A
        flag that predates the round is someone else's verdict and is
        never cleared here."""
        hb = self.heartbeat
        if hb is None or not hb.stalled or self._stalled_on_entry:
            return
        hb.stalled = False
        hb.stalled_at_ns = 0
        hb.beat("mesh.round")
        self.forgiven = True
        _count("mesh_rounds_forgiven")
        try:
            from auron_tpu.obs import trace
            trace.event("watchdog", "watchdog.round_slow",
                        task=hb.task_id,
                        elapsed_s=round(self.elapsed_s, 3),
                        stall_timeout_s=hb.timeout_s)
        except Exception:   # pragma: no cover  # graft: disable=GL004 -- slow-round event is best-effort telemetry
            pass


def mesh_rounds_forgiven() -> int:
    """Monotonic count of stall verdicts downgraded to slow rounds."""
    with _LOCK:
        return _STATS["mesh_rounds_forgiven"]


def first_compile_probe(config=None) -> Optional[float]:
    """Bound the first jit compile by ``auron.watchdog.compile_timeout_s``
    (0 = skip); returns compile seconds, or None when skipped. A timeout
    or failure falls back to CPU like ensure_backend."""
    import time

    from auron_tpu import config as cfg
    conf = config if config is not None else cfg.get_config()
    deadline = float(conf.get(cfg.WATCHDOG_COMPILE_TIMEOUT_S))
    if deadline <= 0:
        return None
    from auron_tpu.obs import trace
    with trace.span("watchdog", "watchdog.compile_probe",
                    deadline_s=deadline):
        return _first_compile_probed(deadline)


def _first_compile_probed(deadline: float) -> Optional[float]:
    import time
    _count("probes")
    if _initialized_platform() is None:
        # the jit probe would otherwise be the FIRST thing to enter
        # backend init — inside jax's backend lock, in a thread we may
        # abandon. Prove init completes in a sacrificial child first so
        # a timeout here stays recoverable (same contract as
        # ensure_backend).
        ok, detail = _subprocess_init_probe(deadline)
        if not ok:
            if detail == "timeout":
                _count("timeouts")
                why = (f"backend init (first-compile probe) exceeded the "
                       f"{deadline:.1f}s deadline (probe child killed)")
            else:
                why = f"backend init (first-compile probe) failed: {detail}"
            _fallback_to_cpu(deadline, why)
            return None

    def probe():
        import jax
        import jax.numpy as jnp
        t0 = time.perf_counter()
        # unique constant per call: never served from a stale jit cache
        salt = int(t0 * 1e6) % (1 << 20)
        # graft: disable=GL001 -- the watchdog probe exists to measure the device wait itself
        jax.jit(lambda x: x + salt)(jnp.ones((8,), jnp.int32)
                                    ).block_until_ready()
        return time.perf_counter() - t0

    done, err, dt = _run_bounded(probe, deadline, "first-compile")
    if done and err is None:
        return dt
    why = (f"first compile exceeded the {deadline:.1f}s deadline"
           if not done else f"first compile failed: {err}")
    if not done:
        _count("timeouts")
    _fallback_to_cpu(deadline, why)
    return None
