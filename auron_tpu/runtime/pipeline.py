"""Pipelined asynchronous execution.

The PR 6 forensics plane measured where q01's wall time actually goes on
the CPU mesh: the device accounts for ~9% of attributed time while
``dispatch`` and ``convert`` (synchronous parquet decode) dominate —
the engine serialized decode → dispatch → block_until_ready per batch,
wasting exactly the overlap Zerrow-style zero-copy Arrow pipelines
(PAPERS.md, 2504.06151) and inter-kernel pipelining (FlashFuser,
2512.12949) exploit. This module is the small shared core of the fix;
the three planes that consume it:

- **prefetching scan** (io/parquet.Prefetcher): decode row-group N+1 on
  a bounded background worker while the device computes batch N;
- **double-buffered dispatch** (runtime/executor.arrow_batches +
  obs/profile.ProfiledProgram): per-batch ``block_until_ready`` calls
  disappear — XLA's async dispatch queues batch N+1 while N's arrays
  are in flight, and execution synchronizes only at operator boundaries
  that semantically require materialization (sort collect, shuffle
  materialize, to_arrow), where the wait is attributed to
  ``elapsed_device``;
- **donation sweep** (ops/fused, ops/joins, ops/agg): owned-batch hot
  loops donate their dead inputs to XLA behind the existing
  ``yields_owned_batches`` gate (runtime/programs.jit keeps donation
  off the CPU backend, where it is advisory and warns).

The mode is one knob (``auron.pipeline.enabled``, default on) resolved
through the cached epoch-compare pattern every hot-path plane uses
(trace/faults/profile): the disabled path costs one int compare.
Pipelined and serial execution are bit-identical by construction —
overlap changes WHEN results materialize, never their values or order —
and tests/test_pipeline.py holds that line over the TPC-DS battery.
"""

from __future__ import annotations

from typing import Iterator, Optional

#: (config epoch, enabled) verdict cache for the PROCESS-GLOBAL config
_CACHED: tuple[int, Optional[bool]] = (-1, None)

_SENTINEL = object()


def enabled(conf=None) -> bool:
    """Is pipelined execution on? PROCESS-GLOBAL by contract (the
    map-key-dedup precedent): the mode decides where SYNC POINTS live
    across planes that cannot see a session config — the profiler's
    program wrapper most of all — so honoring a session-scoped override
    in some planes but not others would desynchronize operator timers
    from the wrapper (serial timers blocking while the wrapper skips
    its block, or vice versa). ``conf`` is accepted for call-site
    symmetry but resolution is always the process-global config (set
    via ``AuronConfig.set`` on ``get_config()``, or the env binding
    read before first use); one cached epoch-compare on the hot path."""
    from auron_tpu import config as cfg
    global _CACHED
    epoch, val = _CACHED
    if epoch == cfg.config_epoch() and val is not None:
        return val
    epoch = cfg.config_epoch()
    val = bool(cfg.get_config().get(cfg.PIPELINE_ENABLED))
    _CACHED = (epoch, val)
    return val


def lookahead(it: Iterator, depth: int = 1) -> Iterator:
    """Double-buffered drive: pull item N+1 from ``it`` BEFORE yielding
    item N, so the producer's async work (kernel dispatch, prefetch
    refill) for the next batch is already queued while the consumer
    blocks on the current one (host materialization, sink writes).

    Order is preserved exactly — this is a window, not a reorder. A
    producer exception surfaces on the pull that raised it, which under
    lookahead is up to ``depth`` items earlier than serial drive would
    have surfaced it; all-or-nothing consumers (collect) can't tell the
    difference. ``close()`` propagates to the inner iterator so
    cancellation unwinds generators exactly as serial drive does."""
    if depth <= 0:
        yield from it
        return
    it = iter(it)
    window: list = []
    try:
        for _ in range(depth):
            item = next(it, _SENTINEL)
            if item is _SENTINEL:
                break
            window.append(item)
        while window:
            nxt = next(it, _SENTINEL)
            yield window.pop(0)
            if nxt is not _SENTINEL:
                window.append(nxt)
    finally:
        close = getattr(it, "close", None)
        if close is not None:
            close()
