"""Concurrent query scheduler: admission control, bounded run queue,
weighted-round-robin task fairness, overload shedding.

Everything below Session assumed one query at a time; "millions of
users" (ROADMAP [serving]) means a Session — and the serving engine
process — must multiplex. This module is the control plane that makes
that safe:

- **Admission control.** ``acquire`` is the single door every top-level
  query enters through (Session.execute, the AuronServer handler). At
  most ``auron.sched.max_concurrent`` queries RUN; up to
  ``auron.sched.queue_depth`` more wait in the bounded run queue; past
  that — or when a registry signal breaches its threshold (queue-wait
  p99, memmgr used/budget ratio) — the query is rejected FAST with the
  classified ``errors.AdmissionRejected`` (transient, ``retry_after_s``
  hint). Rejection happens before any executor, memmgr consumer or
  durable-tier artifact exists, so shedding is free.

- **Queue-time lifecycle.** A queued query's CancelToken stays live:
  a serving CANCEL frame, a client disconnect, ``session.cancel``,
  ``Session.close`` ("session-closed") or the deadline expiring while
  queued all DEQUEUE it without ever starting — the waiting loop polls
  the token and unwinds with its classified verdict (QueryCancelled /
  DeadlineExceeded), never spinning up a runtime for a dead query.

- **Fair task scheduling.** Running queries interleave at TASK
  granularity by weighted round-robin: before each task the driver
  calls ``Slot.task_turn``, which lets a query proceed only while it is
  within one virtual-time unit of the most-behind running query (a
  task advances virtual time by 1/weight, so heavier queries run more
  tasks per round) — fair queueing with the cheapest possible bookkeeping
  (one lock + compare per task; the most-behind slot NEVER waits, so
  some thread always progresses). A solo query takes the uncontended
  fast path, measured by the perf-gate smoke's concurrency-tax gate
  (< 2%).

- **Nested executes inherit.** A host-fn child or scalar subquery runs
  on the thread of a query that already HOLDS a slot; queueing it
  behind the parent would deadlock the pair (parent waits for child,
  child waits for parent's slot). Session.execute therefore enters the
  scheduler only for top-level queries — nested ones ride the enclosing
  token (and its slot), so one admission covers the whole tree.

Observability: every decision lands on the process registry
(``auron_sched_{admitted,rejected,dequeued}_total``, running/queued
gauges, the ``auron_sched_queue_wait_seconds`` histogram that feeds the
queue-wait admission signal back) and the ``sched`` trace category
(``sched.admit`` / ``sched.reject`` / ``sched.dequeue`` events), and
the scheduler keeps registry-independent internal counters so
``tools/load_report.py`` prints the same table with telemetry off.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Optional

#: every live scheduler, weakly held — the scrape-time source of the
#: running/queued gauges (obs/registry._collect_runtime sums states BY
#: NAME across live schedulers; per-change gauge sets would collide
#: last-writer-wins when several Sessions share the "session" name)
_SCHEDULERS: "weakref.WeakSet" = weakref.WeakSet()


def aggregate_states() -> dict:
    """{scheduler name: {"running": n, "queued": n}} summed across the
    process's live schedulers."""
    out: dict = {}
    for s in list(_SCHEDULERS):
        with s._cond:
            r, q = len(s._running), len(s._queued)
        ent = out.setdefault(s.name, {"running": 0, "queued": 0})
        ent["running"] += r
        ent["queued"] += q
    return out


def aggregate_query_table() -> list[dict]:
    """Live query table across every scheduler in the process — the ops
    plane's ``/queries`` body and the serving STATS frame's table."""
    rows: list[dict] = []
    for s in list(_SCHEDULERS):
        rows.extend(s.query_table())
    rows.sort(key=lambda r: (r["scheduler"], r["query"]))
    return rows


class Slot:
    """One admitted query's seat in the scheduler.

    Carries the fairness state (``tasks_run`` / ``weight`` — virtual
    time is their ratio) and the scheduler-overhead ledger the
    concurrency-tax gate reads (``overhead_ns``: time spent inside
    acquire + every task_turn + release, NOT time spent waiting for
    fairness or a queue slot — the tax is the bookkeeping, the waits
    are the policy)."""

    __slots__ = ("scheduler", "token", "query_id", "weight", "tasks_run",
                 "vbase", "queue_wait_s", "overhead_ns", "granted",
                 "released", "granted_at")

    def __init__(self, scheduler: "QueryScheduler", token, weight: float):
        self.scheduler = scheduler
        self.token = token
        self.query_id = getattr(token, "query_id", "") or ""
        self.weight = max(float(weight), 1e-6)
        self.tasks_run = 0
        #: virtual-time origin, set at GRANT to the current minimum
        #: vtime of the running slots (start-time fair queueing): a
        #: newcomer joins the round in progress instead of at zero,
        #: which would stall every established query until it catches
        #: up on their whole task history
        self.vbase = 0.0
        self.queue_wait_s = 0.0
        self.overhead_ns = 0
        self.granted = False
        self.released = False
        #: monotonic stamp of the grant — the /queries table's
        #: wall-so-far origin (0.0 until seated)
        self.granted_at = 0.0

    @property
    def vtime(self) -> float:
        """Weighted virtual time: origin at admission + tasks run per
        unit weight. The WRR invariant is vtime(any running slot) <
        min(vtime) + 1."""
        return self.vbase + self.tasks_run / self.weight

    def task_turn(self) -> None:
        """Block until this query may start its next task (weighted
        round-robin across the running queries); raises the token's
        classified error when cancelled while waiting."""
        self.scheduler.task_turn(self)

    def release(self) -> None:
        self.scheduler.release(self)


#: poll granularity of queue/turn waits: a cancel or promotion lands
#: within one tick (condition notify usually wakes sooner)
_WAIT_POLL_S = 0.05

#: age window of queue-wait samples feeding the ADMISSION signal: the
#: p99 that sheds new arrivals must describe the RECENT queue, not a
#: burst an hour ago — without the window the signal latches (a tripped
#: threshold blocks the queued admissions that would refresh the ring,
#: so a stale p99 rejects over-capacity arrivals forever)
_WAIT_SIGNAL_WINDOW_S = 30.0

#: work-conserving bound on one fairness wait: a leader parks at most
#: this long for a laggard to advance, then takes its turn anyway.
#: Without the cap, a laggard stuck inside ONE long task (vtime only
#: advances at task START) would freeze min_v and idle-block every
#: other running query for the task's full duration — head-of-line
#: blocking that costs more throughput than the fairness it buys. With
#: it, heterogeneous workloads lose at most this much per turn to
#: fairness, while homogeneous short-task queries still interleave
#: tightly (their laggards advance within the window).
_TURN_WAIT_CAP_S = 2.0


class QueryScheduler:
    """One Session's (or one serving process's) query-admission plane."""

    def __init__(self, name: str = "session", mem_manager=None,
                 config=None):
        self.name = name
        #: admission memory signal source (auron.sched.admit.mem_ratio);
        #: attach_mem_manager late-binds it for sessions built before
        #: their manager
        self.mem_manager = mem_manager
        #: knob source: the owning Session's config when given (its
        #: auron.sched.* overrides are honored — scheduler state is
        #: per-Session, unlike the process-global pipeline contract),
        #: else the process config (the serving process)
        self.config = config
        # RLock-backed: admission helpers (_reject, _retry_after_s) run
        # under the condition from inside acquire's critical section
        self._cond = threading.Condition(threading.RLock())
        self._running: list[Slot] = []
        self._queued: list[Slot] = []
        #: registry-independent counters (tools/load_report.py reads
        #: these via stats() so the table works with telemetry off)
        self._counts = {"admitted": 0, "rejected": 0, "dequeued": 0}
        self._reject_reasons: dict[str, int] = {}
        self._dequeue_reasons: dict[str, int] = {}
        #: recent queue waits as (monotonic stamp, seconds) — the local
        #: p50/p99 source for the admission signal (age-windowed) and
        #: the retry-after hint; the registry histogram mirrors it for
        #: scrapes
        self._waits: list[tuple[float, float]] = []
        #: scheduler bookkeeping cost of the most recently RELEASED
        #: slot — the perf-gate smoke's concurrency-tax numerator
        self.last_overhead_ns = 0
        _SCHEDULERS.add(self)

    # -- admission -----------------------------------------------------------

    def attach_mem_manager(self, mem_manager) -> None:
        if mem_manager is not None:
            self.mem_manager = mem_manager

    def _conf(self):
        from auron_tpu import config as cfg
        return self.config if self.config is not None else cfg.get_config()

    def _knobs(self) -> tuple[int, int]:
        from auron_tpu import config as cfg
        conf = self._conf()
        return (max(int(conf.get(cfg.SCHED_MAX_CONCURRENT)), 1),
                max(int(conf.get(cfg.SCHED_QUEUE_DEPTH)), 0))

    def _queue_wait_p(self, p: float,
                      window_s: Optional[float] = None) -> float:
        """Observed queue-wait percentile; ``window_s`` restricts the
        sample to the last N seconds (the admission signal's recency
        contract) AND folds in the ages of the queries queued RIGHT NOW
        — under sustained saturation nothing is granted, so completed
        samples alone would read 0.0 exactly when the signal must shed.
        None uses every retained completed sample (stats/hints)."""
        now = time.monotonic()
        cutoff = now - window_s if window_s is not None else None
        with self._cond:
            waits = [w for t, w in self._waits
                     if cutoff is None or t >= cutoff]
            if window_s is not None:
                # queue_wait_s holds the ENQUEUE stamp until grant
                waits += [now - s.queue_wait_s for s in self._queued]
        if not waits:
            return 0.0
        waits.sort()
        idx = min(int(p * len(waits)), len(waits) - 1)
        return waits[idx]

    def _retry_after_s(self) -> float:
        """Caller backoff hint: roughly one median queue-wait per
        occupant ahead, floored so a cold scheduler still spreads
        resubmissions instead of answering 'now'."""
        with self._cond:
            backlog = len(self._queued) + len(self._running)
        p50 = self._queue_wait_p(0.50)
        return round(max(p50, 0.05) * max(backlog, 1), 3)

    def _reject(self, reason: str, detail: str):
        from auron_tpu import errors
        from auron_tpu.obs import trace
        hint = self._retry_after_s()
        with self._cond:
            self._counts["rejected"] += 1
            self._reject_reasons[reason] = \
                self._reject_reasons.get(reason, 0) + 1
        trace.event("sched", "sched.reject", scheduler=self.name,
                    reason=reason, retry_after_s=hint)
        self._observe(lambda r: r.counter(
            "auron_sched_rejected_total", reason=reason).inc())
        raise errors.AdmissionRejected(
            f"query admission rejected ({reason}): {detail}; "
            f"retry after ~{hint}s", reason=reason, retry_after_s=hint,
            site="sched.admit")

    def acquire(self, token, weight: float = 1.0) -> Slot:
        """Admit one top-level query: returns its granted Slot, raises
        ``AdmissionRejected`` (shed) or the token's classified error
        (cancelled/deadline while queued). The caller MUST release the
        slot in a finally."""
        from auron_tpu.obs import trace
        from auron_tpu.runtime import faults
        t0 = time.perf_counter_ns()
        slot = Slot(self, token, weight)
        # the sched.admit chaos site: a seeded deny sheds this query as
        # if a threshold were breached — overload behavior on demand
        if faults.fires("sched.admit", "deny"):
            self._reject("injected", "injected sched.admit deny")
        # memory signal: checked for EVERY arrival (a free slot does
        # not make an exhausted budget admissible)
        self._check_memory_signal()
        queued = False
        with self._cond:
            while not slot.granted:
                maxc, depth = self._knobs()
                if not queued:
                    if len(self._running) < maxc and not self._queued:
                        self._grant_locked(slot)
                        break
                    # would queue: hard depth bound, then the observed
                    # queue-latency signal
                    if len(self._queued) >= depth:
                        self._reject(
                            "queue_full",
                            f"{len(self._running)} running, "
                            f"{len(self._queued)}/{depth} queued")
                    limit = self._admit_wait_limit()
                    if limit > 0:
                        p99 = self._queue_wait_p(
                            0.99, window_s=_WAIT_SIGNAL_WINDOW_S)
                        if p99 > limit:
                            self._reject(
                                "queue_wait",
                                f"queue-wait p99 {p99:.3f}s > "
                                f"{limit:.3f}s (last "
                                f"{_WAIT_SIGNAL_WINDOW_S:.0f}s)")
                    queued = True
                    slot.queue_wait_s = time.monotonic()   # t-enqueue
                    self._queued.append(slot)
                elif self._queued and self._queued[0] is slot \
                        and len(self._running) < maxc:
                    # FIFO self-promotion (covers capacity freed by a
                    # knob change between releases)
                    self._queued.pop(0)
                    self._grant_locked(slot)
                    break
                # park: promotion (release) or cancellation wakes us
                slot.overhead_ns += time.perf_counter_ns() - t0
                self._cond.wait(_WAIT_POLL_S)
                t0 = time.perf_counter_ns()
                if not slot.granted and token is not None \
                        and token.is_set():
                    # dequeued without ever starting: the queued-cancel
                    # contract (serving CANCEL/disconnect, deadline,
                    # session close)
                    if slot in self._queued:
                        self._queued.remove(slot)
                    reason = getattr(token, "reason", None) or "cancelled"
                    self._counts["dequeued"] += 1
                    self._dequeue_reasons[reason] = \
                        self._dequeue_reasons.get(reason, 0) + 1
                    trace.event("sched", "sched.dequeue",
                                scheduler=self.name, reason=reason,
                                query=slot.query_id)
                    self._observe(lambda r: r.counter(
                        "auron_sched_dequeued_total", reason=reason).inc())
                    token.raise_for_status()
                    raise AssertionError(   # pragma: no cover - above
                        "cancelled token did not raise")
            if queued:
                slot.queue_wait_s = time.monotonic() - slot.queue_wait_s
                self._waits.append((time.monotonic(),
                                    slot.queue_wait_s))
                if len(self._waits) > 256:
                    del self._waits[:-256]
            else:
                slot.queue_wait_s = 0.0
            self._counts["admitted"] += 1
        slot.overhead_ns += time.perf_counter_ns() - t0
        trace.event("sched", "sched.admit", scheduler=self.name,
                    query=slot.query_id,
                    queue_wait_s=round(slot.queue_wait_s, 4))
        self._observe(self._admit_observation(slot))
        return slot

    def _grant_locked(self, slot: Slot) -> None:
        """Seat a slot (caller holds the condition lock): start-time
        fair queueing — the newcomer's virtual clock begins at the
        running round's minimum, so admission neither stalls the
        established queries nor grants the newcomer their history."""
        slot.vbase = (min(s.vtime for s in self._running)
                      if self._running else 0.0)
        slot.granted = True
        slot.granted_at = time.monotonic()
        self._running.append(slot)

    def _admit_wait_limit(self) -> float:
        from auron_tpu import config as cfg
        return float(self._conf().get(cfg.SCHED_ADMIT_QUEUE_WAIT_P99_S))

    def _check_memory_signal(self) -> None:
        from auron_tpu import config as cfg
        ratio_limit = float(self._conf().get(cfg.SCHED_ADMIT_MEM_RATIO))
        if ratio_limit <= 0:
            return
        mm = self.mem_manager
        if mm is None:
            # the knob is ARMED but this scheduler has no manager to
            # read (Session built without mem_manager, or the serving
            # process which runs managerless): say so ONCE instead of
            # silently admitting into the pressure the knob exists to
            # reject
            if not getattr(self, "_warned_no_mm", False):
                self._warned_no_mm = True
                import logging
                logging.getLogger("auron_tpu").warning(
                    "auron.sched.admit.mem_ratio=%s is set but scheduler "
                    "%r has no attached MemManager — the memory admission "
                    "signal is DISARMED (pass mem_manager= to Session, or "
                    "attach_mem_manager())", ratio_limit, self.name)
            return
        try:
            used, total = mm.used_total, mm.total
        except Exception:   # pragma: no cover - duck-typed manager
            return
        if total > 0 and used / total > ratio_limit:
            self._reject("memory",
                         f"memmgr used/budget {used}/{total} = "
                         f"{used / total:.2f} > {ratio_limit:.2f}")

    @staticmethod
    def _admit_observation(slot: Slot):
        def observe(r):
            r.counter("auron_sched_admitted_total").inc()
            r.histogram("auron_sched_queue_wait_seconds").observe(
                slot.queue_wait_s)
        return observe

    # -- fairness ------------------------------------------------------------

    def task_turn(self, slot: Slot) -> None:
        """Weighted round-robin gate, called by the driver before each
        task: proceed while within ONE VIRTUAL-TIME UNIT of the
        most-behind RUNNING query (each task advances a query's virtual
        time by 1/weight, so a weight-2 query runs two tasks per round);
        otherwise wait for the laggard to advance (or finish). The
        most-behind slot never waits, so some thread always progresses;
        and every wait is capped at ``_TURN_WAIT_CAP_S`` so a laggard
        wedged inside one long task cannot idle-block its neighbors
        (work conservation beats strict fairness past the cap). Raises
        the token's classified error on cancel/deadline — fairness
        waits must not outlive the query."""
        token = slot.token
        t0 = time.perf_counter_ns()
        wait_deadline = None
        with self._cond:
            while len(self._running) > 1 and slot in self._running:
                min_v = min(s.vtime for s in self._running)
                if slot.vtime < min_v + 1.0 - 1e-9:
                    break
                now = time.monotonic()
                if wait_deadline is None:
                    wait_deadline = now + _TURN_WAIT_CAP_S
                elif now >= wait_deadline:
                    break       # work-conserving: stop paying for the laggard
                slot.overhead_ns += time.perf_counter_ns() - t0
                self._cond.wait(_WAIT_POLL_S)
                t0 = time.perf_counter_ns()
                if token is not None and token.is_set():
                    token.raise_for_status()
            slot.tasks_run += 1
            # my vtime rose: wake waiters whose window may have moved
            # (they recompute; spurious wakes cost one compare each)
            self._cond.notify_all()
        slot.overhead_ns += time.perf_counter_ns() - t0

    # -- release / drain -----------------------------------------------------

    def release(self, slot: Slot) -> None:
        """Return a granted slot and promote the queue head into the
        freed capacity. Idempotent (close paths race the normal
        finally)."""
        t0 = time.perf_counter_ns()
        with self._cond:
            if slot.released:
                return
            slot.released = True
            if slot in self._running:
                self._running.remove(slot)
            maxc, _depth = self._knobs()
            while self._queued and len(self._running) < maxc:
                head = self._queued[0]
                tok = head.token
                if tok is not None and tok.is_set():
                    # cancelled/deadline while queued: NEVER grant a
                    # dead query (the 'dequeued without ever starting'
                    # contract). Pop it; the dequeue accounting and the
                    # classified raise happen on its own acquire
                    # thread's next poll.
                    self._queued.pop(0)
                    continue
                self._queued.pop(0)
                self._grant_locked(head)
            self._cond.notify_all()
        slot.overhead_ns += time.perf_counter_ns() - t0
        self.last_overhead_ns = slot.overhead_ns

    def drain(self, reason: str = "session-closed") -> None:
        """Deterministic shutdown order (Session.close): cancel QUEUED
        queries first — their waiting acquires dequeue without ever
        starting — then the running tokens. Cancellation stays
        cooperative; the caller waits for unwind as before."""
        with self._cond:
            queued = list(self._queued)
            running = list(self._running)
        for s in queued:
            if s.token is not None:
                s.token.cancel(reason)
        for s in running:
            if s.token is not None:
                s.token.cancel(reason)
        with self._cond:
            self._cond.notify_all()

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        """Registry-independent counter snapshot (load_report's table)."""
        with self._cond:
            out = {
                "admitted": self._counts["admitted"],
                "rejected": self._counts["rejected"],
                "rejected_by_reason": dict(self._reject_reasons),
                "dequeued": self._counts["dequeued"],
                "dequeued_by_reason": dict(self._dequeue_reasons),
                "running": len(self._running),
                "queued": len(self._queued),
                "queue_wait_p50_s": round(self._queue_wait_p(0.50), 4),
                "queue_wait_p99_s": round(self._queue_wait_p(0.99), 4),
            }
        # gang-slot accounting: a sharded stage occupies the WHOLE mesh
        # (one slot = the mesh — parallel/mesh.MeshPlane.gang takes this
        # scheduler's WRR turn on entry, so fairness operates BETWEEN
        # sharded stages); surfaced here so load/mesh reports show the
        # mesh occupancy next to the query-slot numbers. The plane's
        # stats also carry its FAULT DOMAIN ledger (quarantined devices,
        # usable width, demotions by reason, straggler/device-loss
        # counts) — an operator reading the scheduler surface sees a
        # degraded mesh, not just a slow one
        try:
            from auron_tpu.parallel import mesh as _mesh
            plane = _mesh.current_plane()
            if plane is not None:
                out["mesh_gang"] = plane.stats()
        except Exception:   # pragma: no cover  # graft: disable=GL004 -- gang stats are best-effort
            pass
        return out

    def query_table(self) -> list[dict]:
        """The live query table (the ops plane's ``/queries`` rows):
        one row per running/queued slot — query id, state, wall so far,
        driver task progress (the token's collect-loop counters),
        per-query memory usage vs quota (the attached manager's
        ledger), and the query's program-cache builds/hits. Reads are
        lock-bounded snapshots; a row is internally consistent but the
        table is not a transaction across queries (scrape semantics)."""
        now = time.monotonic()
        with self._cond:
            seats = ([("running", s) for s in self._running]
                     + [("queued", s) for s in self._queued])
            rows = []
            for state, s in seats:
                tok = s.token
                wall = (now - s.granted_at if state == "running"
                        # queue_wait_s holds the ENQUEUE stamp until
                        # the slot is granted (acquire's contract)
                        else now - s.queue_wait_s)
                rows.append({
                    "query": s.query_id,
                    "scheduler": self.name,
                    "state": state,
                    "wall_s": round(max(wall, 0.0), 3),
                    "tasks_run": s.tasks_run,
                    "tasks_done": getattr(tok, "tasks_done", 0),
                    "tasks_total": getattr(tok, "tasks_total", 0),
                })
        mm = self.mem_manager
        for row in rows:
            if mm is not None:
                try:
                    row["mem_used_bytes"] = mm.query_used(row["query"])
                    row["mem_quota_bytes"] = mm.query_quota()
                except Exception:   # pragma: no cover  # graft: disable=GL004 -- duck-typed mm; the live table renders without memory columns
                    pass
            try:
                from auron_tpu.runtime import programs
                snap = programs.query_totals(row["query"])
                row["program_builds"] = snap.builds
                row["program_hits"] = snap.hits
            except Exception:   # pragma: no cover  # graft: disable=GL004 -- program-ledger stats are best-effort
                pass
        return rows

    def running_count(self) -> int:
        with self._cond:
            return len(self._running)

    def queued_count(self) -> int:
        with self._cond:
            return len(self._queued)

    @staticmethod
    def _observe(fn) -> None:
        """Apply ``fn`` to the process registry when enabled;
        best-effort — telemetry must never fail an admission decision."""
        try:
            from auron_tpu.obs import registry as obs_registry
            if not obs_registry.enabled():
                return
            fn(obs_registry.get_registry())
        except Exception:   # pragma: no cover  # graft: disable=GL004 -- registry telemetry is best-effort by contract
            pass


def turn(cancel_token) -> None:
    """Driver-side fairness hook (runtime/executor.collect): take the
    query's task turn when its token carries a scheduler slot; a bare
    token / direct collect() call costs one getattr."""
    slot = getattr(cancel_token, "slot", None)
    if slot is not None:
        slot.task_turn()
