"""Crash-safe query journal: process-restart recovery from committed
shuffle stages.

Every robustness plane before this one (fault injection, lifecycle,
admission, mesh fault domain) assumes the Python process survives; a
SIGKILL/OOM/preemption lost every in-flight query even though the RSS
tier already persists map outputs with CRC'd frames and an atomic
commit trailer (``parallel/shuffle_service.py``).  This module closes
that gap with the checkpoint/resume discipline the host engine's
lineage contract implies (Spark stage retry; Flare's rule that a native
engine must preserve the host's fault-tolerance semantics):

- **QueryJournal** — one append-only file per top-level query under
  ``auron.journal.dir``: a header naming the plan fingerprint, the
  source-snapshot fingerprints, the owner process tag
  (``utils/liveness``) and the serialized plan itself, followed by
  exchange-DAG records and an append-only log of committed RSS map
  outputs (shuffle_id/map_id/size/trailer CRC).  Map records are
  appended AFTER the durable tier's atomic rename — the journal never
  claims more than storage holds — and ride an **async appender**
  thread so the hot path pays an enqueue, with fsync only at the
  header and at shuffle-level commit records (``auron.journal.fsync``).
  Every record carries its own CRC; a torn tail (crash mid-append) is
  dropped on load, a corrupt interior line is ``JournalCorrupt``.

- **Routing** — while a journal is active for the driving thread's
  query (``active_journal()``), the planner lowers the plan's shuffle
  writers through the durable RSS tier under the journal's own run
  directory with deterministically assigned shuffle ids
  (``next_shuffle_id``: plan-walk order, identical across processes
  for identical plan bytes), so every shuffle stage is resumable.

- **Resume** — ``Session.resume(query_id)`` (and adoption of a
  matching journal by an identical re-submission under
  ``auron.journal.reuse``) re-plans from the journal's plan bytes,
  validates both fingerprint sets (mismatch → the classified
  ``JournalInvalidated``; stale state is garbage-collected, never
  believed), then lets each RSS exchange consult the journal: a
  fully-committed exchange is **satisfied** (map side skipped entirely,
  reducers fetch straight from the journaled files), a
  partially-committed hash/round-robin/single exchange skips exactly
  its committed maps, and everything else recomputes.  Resumed results
  are bit-identical to a fresh run, group order included — the RSS
  reducer read path is map-major and deterministic, and the engine is
  functional so recomputed maps rewrite identical bytes.

- **Sweep** — ``sweep_orphans`` garbage-collects journal artifacts of
  DEAD processes (pid+epoch liveness): ``.part`` temp files, journals
  that are not resumable (corrupt/torn-header), and RSS run
  directories whose journal is gone.  A dead process's *resumable*
  journal is deliberately KEPT — it is the resume inventory.

Fault sites (runtime/faults.py): ``journal.write`` / ``journal.commit``
(swallowed — journaling degrades to off for that query, the query
completes identically) and ``journal.load`` (classified).

Overhead contract: the hot path (enqueue + commit-drain/fsync waits) is
self-ledgered in ``hot_ns`` and gated <2% of query wall by
``tools/perf_gate.py --smoke`` — deterministic like the PR 9 scheduler
tax, immune to this container's wall-clock noise.
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import os
import queue
import threading
import time
import zlib
from typing import Optional

from auron_tpu import errors

logger = logging.getLogger("auron_tpu")

#: journal format version; unknown versions are rejected as corrupt
#: (version skew must never be misread into a wrong resume decision)
VERSION = 1

#: record kinds: h header | x exchange | m map commit | c shuffle commit
_KINDS = ("h", "x", "m", "c")

#: newest resume reports (report_*.json) the startup sweep keeps
REPORT_RETENTION = 64


# ---------------------------------------------------------------------------
# record codec: one CRC-framed JSON record per line
# ---------------------------------------------------------------------------

def _encode(rec: dict) -> bytes:
    payload = json.dumps(rec, sort_keys=True,
                         separators=(",", ":")).encode()
    return b"%08x %s\n" % (zlib.crc32(payload) & 0xFFFFFFFF, payload)


def _decode_line(line: bytes):
    """(rec, ok): ok=False marks an undecodable line (caller decides
    whether it is a tolerable torn tail or corruption)."""
    try:
        crc_s, payload = line.split(b" ", 1)
        if int(crc_s, 16) != (zlib.crc32(payload) & 0xFFFFFFFF):
            return None, False
        return json.loads(payload), True
    except (ValueError, json.JSONDecodeError):
        return None, False


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------

def plan_fingerprint(plan_bytes: bytes) -> str:
    """Stable fingerprint of a serialized TaskDefinition (plan shape,
    expressions, partition counts — everything the proto carries)."""
    return hashlib.sha256(plan_bytes).hexdigest()[:32]


def _walk_plan(node, visit) -> None:
    """Pre-order walk over a PlanNode tree (the session host-fn walk's
    shape), calling ``visit(kind, inner)`` per node."""
    from auron_tpu.ir import pb
    kind = node.WhichOneof("node")
    if kind is None:
        return
    inner = getattr(node, kind)
    visit(kind, inner)
    for _f, sub in inner.ListFields():
        if isinstance(sub, pb.PlanNode):
            _walk_plan(sub, visit)
        elif hasattr(sub, "__iter__") and not isinstance(sub, (str, bytes)):
            for item in sub:
                if isinstance(item, pb.PlanNode):
                    _walk_plan(item, visit)


def _table_digest(tbl) -> str:
    """Bounded content digest of an Arrow table: CRC over the first
    4 KiB + length of every column buffer.  (schema, rows, nbytes)
    alone cannot tell two same-shape tables apart — fixed-width
    columns with different VALUES have identical byte counts — and a
    snapshot fingerprint that misses a content change would resume
    against different data."""
    crc = 0
    try:
        for col in tbl.columns:
            for chunk in col.chunks:
                for buf in chunk.buffers():
                    if buf is None:
                        continue
                    crc = zlib.crc32(memoryview(buf)[:4096], crc)
                    crc = zlib.crc32(
                        len(buf).to_bytes(8, "little"), crc)
    except Exception:   # noqa: BLE001 — exotic layout: degrade honest
        return "nodigest"
    return f"{crc & 0xFFFFFFFF:08x}"


def source_fingerprints(plan_bytes: bytes, catalog: dict) -> dict:
    """Snapshot fingerprints of every source the plan reads: file scans
    by (size, mtime_ns) — cheap stats that catch a rewrite — and
    catalog tables by (schema, rows, nbytes, sampled content CRC).  A
    source the process cannot see fingerprints as ``missing:`` so
    resume validation fails loudly instead of recomputing against
    different data."""
    from auron_tpu.ir import pb
    task = pb.TaskDefinition.FromString(plan_bytes)
    out: dict = {}

    def visit(kind, inner):
        if kind in ("parquet_scan", "orc_scan"):
            for path in inner.files:
                key = f"file:{path}"
                if key in out:
                    continue
                try:
                    st = os.stat(path)
                    out[key] = f"{st.st_size}:{st.st_mtime_ns}"
                except OSError:
                    out[key] = "missing:"
        elif kind == "memory_scan":
            name = inner.table_name
            key = f"table:{name}"
            if key in out:
                return
            tbl = catalog.get(name)
            if tbl is None:
                out[key] = "missing:"
            elif hasattr(tbl, "schema") and hasattr(tbl, "num_rows"):
                schema_fp = hashlib.sha256(
                    str(tbl.schema).encode()).hexdigest()[:12]
                out[key] = (f"{schema_fp}:{tbl.num_rows}"
                            f":{getattr(tbl, 'nbytes', 0)}"
                            f":{_table_digest(tbl)}")
            else:
                # per-partition RecordBatch lists (planner catalogs)
                try:
                    rows = sum(b.num_rows for part in tbl for b in part)
                except Exception:
                    rows = -1
                out[key] = f"batches:{rows}"

    _walk_plan(task.plan, visit)
    return out


def plan_has_host_fns(plan_bytes: bytes) -> bool:
    """Plans referencing host-fallback tables are excluded from
    journaling: their children execute as separate nested queries whose
    shuffle-id sequence a fresh process cannot replay."""
    from auron_tpu.ir import pb
    task = pb.TaskDefinition.FromString(plan_bytes)
    found = [False]

    def visit(kind, inner):
        if kind == "memory_scan" \
                and inner.table_name.startswith("__hostfn_"):
            found[0] = True

    _walk_plan(task.plan, visit)
    return found[0]


# ---------------------------------------------------------------------------
# process-level ledgers
# ---------------------------------------------------------------------------

_LEDGER_LOCK = threading.Lock()
#: journal stems currently OPEN (being written/resumed) in THIS process
#: — the reuse path must never adopt a journal another live query of
#: this process is driving, and the leak-audit fixture reads the count
_OPEN_STEMS: set = set()
#: every journal dir this process touched (the leak audit's glob roots)
_SEEN_DIRS: set = set()
#: stats of the most recently completed journal (the perf-gate smoke
#: arm reads them right after its journaled run finishes)
_LAST_STATS: dict = {}


def open_journal_count() -> int:
    with _LEDGER_LOCK:
        return len(_OPEN_STEMS)


def seen_dirs() -> list:
    with _LEDGER_LOCK:
        return sorted(_SEEN_DIRS)


def last_stats() -> dict:
    """Hot-path ledger of the most recently COMPLETED journal:
    {hot_ns, records, commits, maps_skipped, maps_recomputed,
    bytes_reused}."""
    with _LEDGER_LOCK:
        return dict(_LAST_STATS)


def _register_open(stem: str, path_dir: str) -> None:
    with _LEDGER_LOCK:
        _OPEN_STEMS.add(stem)
        _SEEN_DIRS.add(path_dir)


def _unregister_open(stem: str) -> None:
    with _LEDGER_LOCK:
        _OPEN_STEMS.discard(stem)


def _forget_open_stems() -> None:
    """TEST HOOK: simulate a process restart — every journal this
    process holds open becomes adoptable/resumable, exactly as if the
    process had died and a fresh one started."""
    with _LEDGER_LOCK:
        _OPEN_STEMS.clear()


# ---------------------------------------------------------------------------
# config resolution
# ---------------------------------------------------------------------------

def journal_dir(conf=None) -> str:
    from auron_tpu import config as cfg
    conf = conf or cfg.get_config()
    return conf.get(cfg.JOURNAL_DIR)


def enabled(conf=None) -> bool:
    return bool(journal_dir(conf))


def active_journal():
    """The driving thread's bound query journal (the planner's routing
    oracle); None when journaling is off or this query opted out."""
    from auron_tpu.runtime import lifecycle
    tok = lifecycle.current_token()
    return getattr(tok, "journal", None) if tok is not None else None


# ---------------------------------------------------------------------------
# the journal
# ---------------------------------------------------------------------------

class QueryJournal:
    """One query's crash-safe journal (see module docstring).

    Hot-path surface: ``next_shuffle_id`` / ``record_exchange`` (plan
    time), ``record_map`` (async append after each map-output rename),
    ``record_shuffle_commit`` (drain + flush + fsync — the durability
    boundary), the resume oracles ``satisfied``/``reusable_map``, and
    ``complete``/``suspend``.  All appends are swallowed-on-error: the
    journal degrades to disabled for this query (``journal.disable``
    event), never failing the query it exists to protect."""

    def __init__(self, path: str, query_id: str, plan_bytes: bytes,
                 num_partitions: int, plan_fp: str, sources: dict,
                 fsync: bool = True, resumed: bool = False,
                 state: Optional[dict] = None, scope: str = "collect"):
        self.path = path
        self.dir = os.path.dirname(path)
        self.stem = os.path.splitext(os.path.basename(path))[0]
        self.query_id = query_id
        self.plan_bytes = plan_bytes
        self.num_partitions = num_partitions
        self.plan_fp = plan_fp
        self.sources = sources
        self.fsync = fsync
        #: which partitions the journaled run DRIVES — "collect"
        #: (Session: the driver collects every partition 0..N-1) or
        #: "task" (serving SUBMIT: the host engine owns the partition
        #: fan-out, this journal covers exactly the task's own
        #: partition_id).  Resume must replay the same scope: a
        #: collect-scoped query resumed at task scope would silently
        #: drop every partition after the first.
        self.scope = scope
        #: True when this journal was loaded from disk (resume/adopt):
        #: only then do the resume oracles consult committed state
        self.resumed = resumed
        #: committed map outputs {(shuffle_id, map_id): {size, crc}}
        self.committed: dict = (state or {}).get("committed", {})
        #: shuffle-level commits {shuffle_id: num_maps}
        self.shuffle_commits: dict = (state or {}).get(
            "shuffle_commits", {})
        #: planned exchange DAG {shuffle_id: {maps, partitions, kind}}
        self.exchanges: dict = (state or {}).get("exchanges", {})
        #: the journal's own RSS run directory (all journal-routed
        #: shuffles of this query live under it)
        self.rss_root = os.path.join(self.dir, "rss", self.stem)
        self._shuffle_seq = 0
        self._seq_lock = threading.Lock()
        #: hot-path cost ledger (ns): enqueue + commit-drain waits —
        #: what the perf-gate smoke arm divides by wall
        self.hot_ns = 0
        self.records = 0
        self.commits = 0
        #: resume outcome ledger (per shuffle) for the report/tools
        self.resume_log: dict = {}
        self.maps_skipped = 0
        self.maps_recomputed = 0
        self.bytes_reused = 0
        self._failed = False
        self._closed = False
        #: True while this process holds the cross-process
        #: ``<stem>.claim`` (adoption/resume paths only)
        self._claimed = False
        self._file = None
        self._q: queue.Queue = queue.Queue()
        self._appender: Optional[threading.Thread] = None
        #: guards the lazy appender start: two partition drivers'
        #: FIRST records racing would spawn two threads draining one
        #: queue (and _stop_appender's single sentinel joins only one)
        self._appender_lock = threading.Lock()
        _register_open(self.stem, self.dir)

    # -- construction --------------------------------------------------------

    @classmethod
    def create(cls, dir_: str, query_id: str, plan_bytes: bytes,
               num_partitions: int, catalog: dict,
               conf=None, scope: str = "collect") -> Optional["QueryJournal"]:
        """Mint a fresh journal (header written + fsynced before any
        execution).  Returns None — journaling disabled for this query
        — when the header cannot be written: the journal must never
        fail the query it protects."""
        from auron_tpu import config as cfg
        from auron_tpu.utils import liveness
        conf = conf or cfg.get_config()
        stem = f"{query_id}_{os.getpid()}"
        path = os.path.join(dir_, f"{stem}.journal")
        jr = cls(path, query_id, plan_bytes, num_partitions,
                 plan_fingerprint(plan_bytes),
                 source_fingerprints(plan_bytes, catalog),
                 fsync=conf.get(cfg.JOURNAL_FSYNC), scope=scope)
        header = {
            "k": "h", "v": VERSION, "query_id": query_id,
            "owner": liveness.own_tag(),
            "plan_fp": jr.plan_fp, "sources": jr.sources,
            "num_partitions": num_partitions, "scope": scope,
            "plan_b64": base64.b64encode(plan_bytes).decode(),
            "created": time.time(),
        }
        try:
            from auron_tpu.runtime import faults
            faults.maybe_fail("journal.write", errors.JournalIOError)
            os.makedirs(dir_, exist_ok=True)
            os.makedirs(jr.rss_root, exist_ok=True)
            with open(os.path.join(jr.rss_root, ".owner"), "w") as f:
                f.write(liveness.own_tag())
            # header staged on a .part and RENAMED into place (the RSS
            # tier's commit discipline): a *.journal file therefore
            # NEVER exists with an empty/torn header, so a concurrent
            # process's startup sweep — which treats an unreadable-
            # header journal with no provable owner as a dead husk —
            # cannot unlink a live journal mid-create.  The appends
            # keep riding the same fd across the rename.
            jr._file = open(path + ".part", "ab")
            jr._file.write(_encode(header))
            jr._file.flush()
            if jr.fsync:
                os.fsync(jr._file.fileno())
            os.rename(path + ".part", path)
        except Exception as e:   # noqa: BLE001 — degrade, never fail
            logger.warning("query journal disabled for %s: header "
                           "write failed (%s)", query_id, e)
            jr._teardown_failed()
            return None
        return jr

    # -- plan-time routing ---------------------------------------------------

    def next_shuffle_id(self) -> int:
        """Deterministic shuffle-id assignment: plan-walk encounter
        order.  Identical plan bytes planned in a fresh process replay
        the identical sequence — the resume contract's key."""
        with self._seq_lock:
            sid = self._shuffle_seq
            self._shuffle_seq += 1
        return sid

    def begin_plan(self) -> None:
        """Reset the shuffle-id sequence for one planning pass (resume
        re-plans the same bytes and must re-assign the same ids)."""
        with self._seq_lock:
            self._shuffle_seq = 0

    def record_exchange(self, shuffle_id: int, num_maps: int,
                        num_partitions: int, kind: str) -> None:
        self.exchanges[shuffle_id] = {
            "maps": num_maps, "partitions": num_partitions, "kind": kind}
        self._append({"k": "x", "sid": shuffle_id, "maps": num_maps,
                      "partitions": num_partitions, "kind": kind})

    # -- commit-boundary records ---------------------------------------------

    def record_map(self, shuffle_id: int, map_id: int, size: int,
                   trailer_crc: int) -> None:
        """One committed map output (called AFTER the atomic rename —
        the journal never claims more than the durable tier holds)."""
        self.committed[(shuffle_id, map_id)] = {
            "size": size, "crc": trailer_crc}
        self._append({"k": "m", "sid": shuffle_id, "mid": map_id,
                      "size": size, "crc": trailer_crc})

    def record_shuffle_commit(self, shuffle_id: int,
                              num_maps: int) -> None:
        """Shuffle-level commit: drain the appender, flush, fsync —
        the journal's only durability waits (the <2% gate's subject;
        ``_append`` ledgers the enqueue + drain wait on ``hot_ns``
        itself — timing it here too would double-count the fsync)."""
        self.shuffle_commits[shuffle_id] = num_maps
        try:
            from auron_tpu.runtime import faults
            faults.maybe_fail("journal.commit", errors.JournalIOError)
            self._append({"k": "c", "sid": shuffle_id,
                          "maps": num_maps}, flush=True)
            self.commits += 1
        except Exception as e:   # noqa: BLE001 — degrade, never fail
            self._disable(e)

    # -- async appender ------------------------------------------------------

    def _append(self, rec: dict, flush: bool = False) -> None:
        if self._failed or self._closed:
            return
        t0 = time.perf_counter_ns()
        try:
            if self._appender is None:
                with self._appender_lock:
                    if self._appender is None:
                        self._appender = threading.Thread(
                            target=self._append_loop, daemon=True,
                            name=f"journal-{self.stem}")
                        self._appender.start()
            if flush:
                done = threading.Event()
                self._q.put((rec, done))
                done.wait(timeout=30.0)
            else:
                self._q.put((rec, None))
            self.records += 1
        finally:
            self.hot_ns += time.perf_counter_ns() - t0

    def _append_loop(self) -> None:
        from auron_tpu.runtime import faults
        while True:
            item = self._q.get()
            if item is None:
                return
            rec, done = item
            try:
                if not self._failed:
                    faults.maybe_fail("journal.write",
                                      errors.JournalIOError)
                    line = faults.maybe_corrupt("journal.write",
                                                _encode(rec))
                    self._file.write(line)
                    # flush EVERY record (appender thread — off the hot
                    # path): the page cache survives a SIGKILL, so a
                    # crash between shuffle commits still leaves the
                    # already-appended map records resumable; a record
                    # stuck in the USER-SPACE buffer would die with the
                    # process. fsync stays commit-only — map records
                    # claim only what the durable tier already holds,
                    # so losing them to a MACHINE crash just recomputes.
                    self._file.flush()
                    if done is not None and self.fsync:
                        os.fsync(self._file.fileno())
            except Exception as e:   # noqa: BLE001 — degrade
                self._disable(e)
            finally:
                if done is not None:
                    done.set()

    def _disable(self, exc) -> None:
        if self._failed:
            return
        self._failed = True
        logger.warning("query journal %s disabled mid-query (%s: %s) — "
                       "the query continues without resumability",
                       self.stem, type(exc).__name__, exc)
        try:
            from auron_tpu.obs import trace
            trace.event("journal", "journal.disable", stem=self.stem,
                        error=type(exc).__name__)
        except Exception:  # graft: disable=GL004 -- degrade-event tee is best-effort; the degrade itself already logged
            pass

    @property
    def failed(self) -> bool:
        return self._failed

    # -- resume oracles ------------------------------------------------------

    def _validate_map(self, service, shuffle_id: int,
                      map_id: int) -> Optional[int]:
        """Size of the committed map output when the journal record
        matches the on-storage file (existence + size + trailer CRC);
        None otherwise."""
        rec = self.committed.get((shuffle_id, map_id))
        if rec is None:
            return None
        stat = service.map_output_stat(shuffle_id, map_id)
        if stat is None:
            return None
        size, crc = stat
        if size != rec["size"] or crc != rec["crc"]:
            return None
        return size

    def satisfied(self, shuffle_id: int, num_maps: int,
                  service) -> bool:
        """Is this exchange fully committed AND intact on storage?  A
        satisfied exchange's map side is skipped; reducers fetch the
        journaled files directly."""
        if not self.resumed:
            return False
        if self.shuffle_commits.get(shuffle_id) != num_maps:
            return False
        if service.manifest_maps(shuffle_id) != num_maps:
            return False
        total = 0
        for m in range(num_maps):
            size = self._validate_map(service, shuffle_id, m)
            if size is None:
                return False
            total += size
        self.note_satisfied(shuffle_id, num_maps, total)
        return True

    def reusable_map(self, shuffle_id: int, map_id: int,
                     service) -> Optional[int]:
        """Map-level resume oracle for a partially-committed exchange:
        the committed size when this single map output can be skipped,
        else None (recompute)."""
        if not self.resumed:
            return None
        return self._validate_map(service, shuffle_id, map_id)

    def has_shuffle_state(self, shuffle_id: int) -> bool:
        """Does the journal carry ANY durable state for this exchange —
        a full shuffle commit or at least one committed map output?
        The planner's route oracle: a resumed exchange with journaled
        state must re-plan onto the RSS tier (where that state lives);
        one with none is free to take the current mesh fast path."""
        if shuffle_id in self.shuffle_commits:
            return True
        return any(sid == shuffle_id for sid, _ in self.committed)

    # -- resume ledger -------------------------------------------------------

    def _log_entry(self, shuffle_id: int) -> dict:
        return self.resume_log.setdefault(
            shuffle_id, {"satisfied": False, "maps_skipped": 0,
                         "maps_recomputed": 0, "bytes_reused": 0})

    def note_satisfied(self, shuffle_id: int, num_maps: int,
                       nbytes: int) -> None:
        e = self._log_entry(shuffle_id)
        e["satisfied"] = True
        e["maps_skipped"] = num_maps
        e["bytes_reused"] += nbytes
        self.maps_skipped += num_maps
        self.bytes_reused += nbytes

    def note_map_skipped(self, shuffle_id: int, nbytes: int) -> None:
        e = self._log_entry(shuffle_id)
        e["maps_skipped"] += 1
        e["bytes_reused"] += nbytes
        self.maps_skipped += 1
        self.bytes_reused += nbytes

    def note_map_recomputed(self, shuffle_id: int) -> None:
        self._log_entry(shuffle_id)["maps_recomputed"] += 1
        self.maps_recomputed += 1

    def stats(self) -> dict:
        return {"hot_ns": self.hot_ns, "records": self.records,
                "commits": self.commits,
                "maps_skipped": self.maps_skipped,
                "maps_recomputed": self.maps_recomputed,
                "bytes_reused": self.bytes_reused,
                "resume_log": {str(k): dict(v)
                               for k, v in self.resume_log.items()}}

    # -- lifecycle -----------------------------------------------------------

    def _stop_appender(self) -> None:
        if self._appender is not None:
            self._q.put(None)
            self._appender.join(timeout=10.0)
            self._appender = None
        if self._file is not None:
            try:
                self._file.flush()
                self._file.close()
            except Exception:  # graft: disable=GL004 -- closing a possibly-degraded journal; the degrade path logged the cause
                pass
            self._file = None

    def _release_cross_claim(self) -> None:
        if self._claimed:
            _release_claim(self.dir, self.stem)
            self._claimed = False

    def _teardown_failed(self) -> None:
        self._failed = True
        self._closed = True
        self._stop_appender()
        for p in (self.path, self.path + ".part"):
            try:
                if os.path.exists(p):
                    os.unlink(p)
            except OSError:
                pass
        self._release_cross_claim()
        _unregister_open(self.stem)

    def suspend(self) -> None:
        """The query failed in-process: flush and keep the journal on
        disk (an identical re-submission under ``auron.journal.reuse``
        — or a Session.resume — can pick the committed stages up), but
        release the open-stem claim so adoption is possible."""
        if self._closed:
            return
        self._closed = True
        self._stop_appender()
        self._release_cross_claim()
        _unregister_open(self.stem)

    def complete(self, write_report: bool = False) -> None:
        """The query finished: its journal and RSS run directory are
        garbage.  Optionally persists the resume report first (the
        tools/journal_report.py input for completed resumes)."""
        global _LAST_STATS
        if self._closed and not os.path.exists(self.path):
            return
        self._closed = True
        self._stop_appender()
        if write_report and (self.resumed or self.maps_skipped):
            try:
                report = {
                    "query_id": self.query_id, "stem": self.stem,
                    "plan_fp": self.plan_fp,
                    "exchanges": {str(k): dict(v)
                                  for k, v in self.exchanges.items()},
                    "stats": self.stats(),
                    "completed": time.time(),
                }
                rp = os.path.join(self.dir, f"report_{self.stem}.json")
                with open(rp, "w") as f:
                    json.dump(report, f, indent=1, sort_keys=True)
            except OSError:
                pass
        import shutil
        shutil.rmtree(self.rss_root, ignore_errors=True)
        try:
            os.unlink(self.path)
        except OSError:
            pass
        self._release_cross_claim()
        with _LEDGER_LOCK:
            _LAST_STATS = {
                "hot_ns": self.hot_ns, "records": self.records,
                "commits": self.commits,
                "maps_skipped": self.maps_skipped,
                "maps_recomputed": self.maps_recomputed,
                "bytes_reused": self.bytes_reused,
            }
            _OPEN_STEMS.discard(self.stem)
        try:
            from auron_tpu.obs import trace
            trace.event("journal", "journal.complete", stem=self.stem,
                        maps_skipped=self.maps_skipped,
                        maps_recomputed=self.maps_recomputed,
                        bytes_reused=self.bytes_reused)
        except Exception:  # graft: disable=GL004 -- completion-event tee is best-effort
            pass


# ---------------------------------------------------------------------------
# load / resume / reuse
# ---------------------------------------------------------------------------

def _read_records(path: str):
    """(header, records, valid_len) of a journal file — ``valid_len``
    is the byte length of the intact prefix, which the adopt/resume
    reopen truncates to before appending (appending AFTER torn bytes
    would fuse them with the next record into one CRC-invalid interior
    line, turning a second crash into JournalCorrupt instead of a
    clean tail drop).  Raises JournalCorrupt on an unreadable header,
    unknown version, or a corrupt interior line; a torn FINAL line
    (crash mid-append — no trailing newline) is dropped silently."""
    from auron_tpu.runtime import faults
    try:
        faults.maybe_fail("journal.load", errors.JournalIOError)
        with open(path, "rb") as f:
            data = f.read()
    except (OSError, errors.JournalIOError) as e:
        # an unreadable journal and a corrupt one get the SAME verdict:
        # the inventory is not trustworthy, the safe recovery is a
        # fresh run (resume surfaces it; reuse falls back silently)
        raise errors.JournalCorrupt(
            f"journal unreadable: {path} ({e})", reason="corrupt",
            site="journal.load") from e
    data = faults.maybe_corrupt("journal.load", data)
    lines = data.split(b"\n")
    torn_tail_len = 0
    if not data.endswith(b"\n"):
        # the crash-interrupted final fragment: dropped WHOLE even if
        # it happens to CRC (a record missing only its newline would
        # otherwise fuse with the next append)
        torn_tail_len = len(lines[-1])
        lines = lines[:-1]
    body = [ln for ln in lines if ln]
    if not body:
        raise errors.JournalCorrupt(f"journal empty: {path}",
                                    reason="corrupt",
                                    site="journal.load")
    header, ok = _decode_line(body[0])
    if not ok or header.get("k") != "h":
        raise errors.JournalCorrupt(
            f"journal header corrupt: {path}", reason="corrupt",
            site="journal.load")
    if header.get("v") != VERSION:
        raise errors.JournalCorrupt(
            f"journal version skew: {path} carries v"
            f"{header.get('v')!r}, this engine reads v{VERSION} — "
            "rejected, not misread", reason="corrupt",
            site="journal.load")
    records = []
    for i, ln in enumerate(body[1:], start=1):
        rec, ok = _decode_line(ln)
        if not ok:
            raise errors.JournalCorrupt(
                f"journal record {i} corrupt: {path}",
                reason="corrupt", site="journal.load")
        records.append(rec)
    return header, records, len(data) - torn_tail_len


def _load(path: str, conf=None) -> QueryJournal:
    """Parse one journal file into a resumed QueryJournal (no
    fingerprint validation here — see load_for_resume)."""
    from auron_tpu import config as cfg
    conf = conf or cfg.get_config()
    header, records, valid_len = _read_records(path)
    state = {"committed": {}, "shuffle_commits": {}, "exchanges": {}}
    for rec in records:
        k = rec.get("k")
        if k == "m":
            state["committed"][(rec["sid"], rec["mid"])] = {
                "size": rec["size"], "crc": rec["crc"]}
        elif k == "c":
            state["shuffle_commits"][rec["sid"]] = rec["maps"]
        elif k == "x":
            state["exchanges"][rec["sid"]] = {
                "maps": rec["maps"], "partitions": rec["partitions"],
                "kind": rec["kind"]}
    try:
        plan_bytes = base64.b64decode(header["plan_b64"])
    except (KeyError, ValueError) as e:
        raise errors.JournalCorrupt(
            f"journal plan bytes unreadable: {path}", reason="corrupt",
            site="journal.load") from e
    jr = QueryJournal(path, header.get("query_id", ""), plan_bytes,
                      int(header.get("num_partitions", 1)),
                      header.get("plan_fp", ""),
                      header.get("sources", {}),
                      fsync=conf.get(cfg.JOURNAL_FSYNC), resumed=True,
                      state=state, scope=header.get("scope", "collect"))
    jr.owner = header.get("owner", "")
    jr._valid_len = valid_len
    return jr


def _owner_is_other_live_process(owner: str) -> bool:
    """True when a journal's header names a DIFFERENT process that is
    still alive — the cross-process complement of the in-process
    ``_OPEN_STEMS`` claim: such a journal may still be actively driven
    (its suspend/complete state is unknowable from here), so adoption
    and resume must refuse it.  This very process's own tag — the
    suspended-after-in-process-failure case — and dead owners are both
    fair game."""
    from auron_tpu.utils import liveness
    return bool(owner) and owner != liveness.own_tag() \
        and liveness.is_live(owner)


def _peek_header(path: str) -> Optional[dict]:
    """Best-effort decode of a journal (or ``.part`` staging) file's
    first line — the header carries owner/plan_fp/scope, letting hot
    paths screen candidates WITHOUT the full read+CRC+base64 of
    ``_load``; None when the header is unreadable/torn."""
    try:
        with open(path, "rb") as f:
            line = f.readline().rstrip(b"\n")
    except OSError:
        return None
    rec, ok = _decode_line(line)
    if ok and isinstance(rec, dict) and rec.get("k") == "h":
        return rec
    return None


def _try_read_owner(path: str) -> str:
    """Best-effort owner tag from a journal (or ``.part`` staging)
    file's first line; '' when the header is unreadable/torn."""
    header = _peek_header(path)
    return header.get("owner", "") if header else ""


def _claim_stem(dir_: str, stem: str) -> bool:
    """Cross-process adoption/resume claim: atomically create
    ``<stem>.claim`` (O_EXCL) naming this process.  The in-process
    ``_OPEN_STEMS`` set cannot arbitrate BETWEEN processes sharing a
    journal dir — without this, two processes resuming/adopting one
    dead owner's journal would interleave appenders in one file and
    race complete()'s rss_root rmtree.  A dead claimer's stale claim
    is broken (liveness-checked) and retried once; released via
    ``_release_claim`` on every journal unwind."""
    from auron_tpu.utils import liveness
    path = os.path.join(dir_, f"{stem}.claim")
    for _ in range(2):
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            try:
                os.write(fd, liveness.own_tag().encode())
            finally:
                os.close(fd)
            return True
        except FileExistsError:
            try:
                with open(path) as f:
                    owner = f.read().strip()
            except OSError:
                continue   # claimer mid-write or just released: retry
            if owner == liveness.own_tag() or not liveness.is_live(owner):
                try:
                    os.unlink(path)   # stale (dead claimer) / our own
                except OSError:
                    pass
                continue
            return False   # another LIVE process holds the claim
        except OSError:
            return False
    return False


def _release_claim(dir_: str, stem: str) -> None:
    try:
        os.unlink(os.path.join(dir_, f"{stem}.claim"))
    except OSError:
        pass


def _reopen_for_append(jr: QueryJournal) -> None:
    """Open a LOADED journal for continued appends, truncating the
    crash-torn trailing fragment (if any) first — see _read_records."""
    valid = getattr(jr, "_valid_len", None)
    try:
        if valid is not None and os.path.getsize(jr.path) > valid:
            with open(jr.path, "rb+") as f:
                f.truncate(valid)
    except OSError:   # heal is best-effort; the append may still work
        pass
    jr._file = open(jr.path, "ab")


def _candidates(dir_: str, query_id: str) -> list:
    """Journal paths whose stem matches ``query_id`` (exact stem or the
    ``<qid>_<pid>`` form a fresh process must find)."""
    try:
        names = os.listdir(dir_)
    except OSError:
        return []
    out = []
    for n in sorted(names):
        if not n.endswith(".journal"):
            continue
        stem = n[:-len(".journal")]
        if stem == query_id or stem.rsplit("_", 1)[0] == query_id:
            out.append(os.path.join(dir_, n))
    return out


def load_for_resume(dir_: str, query_id: str, catalog: dict,
                    conf=None) -> QueryJournal:
    """Load + validate the journal behind ``query_id`` for resumption.

    Raises the classified taxonomy: ResumeUnavailable (no/ambiguous
    journal, journaling disabled, missing sources), JournalCorrupt
    (unreadable/version-skewed/CRC-failed), JournalInvalidated
    (fingerprint mismatch — the stale journal AND its RSS run dir are
    garbage-collected so the wrong answer can never be produced)."""
    if not dir_:
        raise errors.ResumeUnavailable(
            "journaling is disabled (auron.journal.dir is empty)",
            query_id=query_id, reason="journaling_disabled")
    cands = _candidates(dir_, query_id)
    if not cands:
        raise errors.ResumeUnavailable(
            f"no journal for query {query_id!r} under {dir_} (unknown "
            "id, or the query completed and its journal was deleted)",
            query_id=query_id, reason="no_journal")
    if len(cands) > 1:
        # query ids recycle across process restarts (serving's per-
        # process counter: server A's crashed 'serving-1' and server
        # B's LIVE 'serving-1' coexist as different stems) — candidates
        # another live process owns would be refused with reason='open'
        # anyway, so they cannot make the id ambiguous; only a tie
        # among genuinely-resumable journals does
        resumable = [c for c in cands
                     if not _owner_is_other_live_process(
                         _try_read_owner(c))]
        if len(resumable) != 1:
            raise errors.ResumeUnavailable(
                f"query id {query_id!r} is ambiguous under {dir_}: "
                f"{[os.path.basename(c) for c in (resumable or cands)]}",
                query_id=query_id, reason="ambiguous")
        cands = resumable
    path = cands[0]
    stem = os.path.splitext(os.path.basename(path))[0]
    # check-and-CLAIM atomically: two concurrent resumes of one query
    # id must never both pass the gate and double-drive the journal
    # (separate appender handles interleaving one file, one complete()
    # rmtree-ing the rss_root under the other's reducers)
    with _LEDGER_LOCK:
        if stem in _OPEN_STEMS:
            raise errors.ResumeUnavailable(
                f"journal {stem} is open in this process (the query is "
                "still running)", query_id=query_id, reason="open")
        _OPEN_STEMS.add(stem)
        _SEEN_DIRS.add(dir_)
    # ...and the CROSS-process half of the same gate: the stem ledger
    # dies with its process, so concurrent resumes from two surviving
    # processes arbitrate through an O_EXCL claim file instead
    if not _claim_stem(dir_, stem):
        _unregister_open(stem)
        raise errors.ResumeUnavailable(
            f"journal {stem} is claimed by another live process",
            query_id=query_id, reason="open")
    try:
        jr = _load(path, conf)
    except BaseException:
        _release_claim(dir_, stem)
        _unregister_open(stem)   # suspend/_teardown below release the
        raise                    # claim; a failed load must too
    jr._claimed = True
    if _owner_is_other_live_process(getattr(jr, "owner", "")):
        # the stem ledger is per-process; on a SHARED journal dir the
        # header's owner tag is the cross-process half of the same
        # guard — another live process may still be driving this query
        jr.suspend()
        raise errors.ResumeUnavailable(
            f"journal {stem} is owned by a live process "
            f"({jr.owner}) — the query may still be running there",
            query_id=query_id, reason="open")
    live_fps = source_fingerprints(jr.plan_bytes, catalog)
    if any(v == "missing:" for v in live_fps.values()):
        missing = sorted(k for k, v in live_fps.items()
                         if v == "missing:")
        jr.suspend()
        raise errors.ResumeUnavailable(
            f"cannot re-bind sources for query {query_id!r}: "
            f"{missing} (register the catalog tables / restore the "
            "files before resuming)", query_id=query_id,
            reason="missing_source")
    if live_fps != jr.sources:
        changed = sorted(k for k in set(live_fps) | set(jr.sources)
                         if live_fps.get(k) != jr.sources.get(k))
        # stale state must never be believed NOR linger: GC it
        jr._teardown_failed()
        import shutil
        shutil.rmtree(jr.rss_root, ignore_errors=True)
        raise errors.JournalInvalidated(
            f"journal {stem} snapshot fingerprints no longer match the "
            f"live sources ({changed}): the journaled shuffle outputs "
            "were computed from different data — invalidated, run "
            "fresh", query_id=query_id, reason="fingerprint_mismatch")
    try:
        from auron_tpu.obs import trace
        trace.event("journal", "journal.resume", stem=stem,
                    shuffles_committed=len(jr.shuffle_commits),
                    maps_committed=len(jr.committed))
    except Exception:  # graft: disable=GL004 -- resume-event tee is best-effort
        pass
    return jr


def resume_inventory(dir_: str) -> list:
    """The router's failover inventory: every journal under ``dir_``
    summarized from its header line alone (``_peek_header`` — no full
    read/CRC/base64), with a liveness verdict per owner.  A fleet
    router scrapes this to answer "which crashed queries can a survivor
    RESUME, and under which stem?" without importing any engine state.
    Entries whose owner is still alive are included (flagged) so the
    caller can distinguish in-flight from resumable; torn headers are
    skipped — an unreadable journal is not inventory."""
    from auron_tpu.utils import liveness
    out = []
    try:
        names = sorted(os.listdir(dir_))
    except OSError:
        return out
    for n in names:
        if not n.endswith(".journal"):
            continue
        stem = n[:-len(".journal")]
        header = _peek_header(os.path.join(dir_, n))
        if header is None:
            continue
        owner = header.get("owner", "")
        out.append({
            "stem": stem,
            "query_id": header.get("query_id", stem),
            "owner": owner,
            "owner_alive": bool(owner) and liveness.is_live(owner),
            "claimed": os.path.exists(
                os.path.join(dir_, f"{stem}.claim")),
            "plan_fp": header.get("plan_fp", ""),
            "num_partitions": int(header.get("num_partitions", 1)),   # graft: disable=GL001 -- JSON header field, host data
            "scope": header.get("scope", "collect"),
        })
    return out


def find_reusable(dir_: str, plan_bytes: bytes, catalog: dict,
                  conf=None, scope: str = "collect") -> Optional[QueryJournal]:
    """The ``auron.journal.reuse`` path: an existing resumable journal
    whose plan AND source fingerprints — and driving ``scope`` — match
    ``plan_bytes``, adopted by an identical re-submission.  Every
    failure mode (corrupt, open, mismatch) falls back to None = fresh
    run; never a wrong answer.

    The screening itself lives in ``cache/identity.py`` — ONE
    implementation of "same plan over the same data" shared with the
    warm-path result cache, so journal adoption and cache lookup can
    never drift apart about staleness."""
    from auron_tpu.cache import identity
    fp = plan_fingerprint(plan_bytes)
    try:
        names = sorted(os.listdir(dir_))
    except OSError:
        return None
    probe = identity.SourceProbe(plan_bytes, catalog)
    for n in names:
        if not n.endswith(".journal"):
            continue
        stem = n[:-len(".journal")]
        path = os.path.join(dir_, n)
        # header screen BEFORE the full load: every journaled
        # submission scans the whole pending inventory here, and
        # _load is a full read + per-record CRC + base64 plan decode —
        # the one-line header already names plan_fp/scope/owner, which
        # rejects nearly every candidate for pennies (mismatches are
        # re-checked authoritatively after the load)
        if not identity.screen_header(_peek_header(path), fp, scope):
            continue
        # check-and-CLAIM atomically (the load_for_resume discipline):
        # two identical concurrent re-submissions must never both
        # adopt one journal — the loser of the claim mints fresh
        with _LEDGER_LOCK:
            if stem in _OPEN_STEMS:
                continue
            _OPEN_STEMS.add(stem)
            _SEEN_DIRS.add(dir_)
        # the cross-process half (O_EXCL claim file): the stem ledger
        # cannot see another surviving process's adoption in flight
        if not _claim_stem(dir_, stem):
            _unregister_open(stem)
            continue
        try:
            jr = _load(path, conf)
        except errors.JournalError as e:
            logger.warning("journal reuse skipped %s: %s", n, e)
            _release_claim(dir_, stem)
            _unregister_open(stem)
            continue
        jr._claimed = True
        if not identity.screen_loaded(jr, fp, scope):
            # a scope mismatch (a serving task adopting a Session
            # collect journal or vice versa) would re-head the file
            # with the WRONG replay contract for a later crash-resume;
            # a live FOREIGN owner may still be driving the query —
            # adopting it would interleave two appenders in one file
            # and race its complete()'s rss_root rmtree
            jr.suspend()
            continue
        if not probe.matches(jr.sources):
            logger.warning(
                "journal reuse skipped %s: source fingerprints "
                "changed — stale journal invalidated", n)
            jr._teardown_failed()
            import shutil
            shutil.rmtree(jr.rss_root, ignore_errors=True)
            continue
        # adopt: re-open the file for continued appends (healing a
        # torn tail so new records never fuse with crash debris)
        try:
            _reopen_for_append(jr)
        except OSError as e:
            logger.warning("journal reuse skipped %s: %s", n, e)
            jr.suspend()
            continue
        try:
            from auron_tpu.obs import trace
            trace.event("journal", "journal.reuse", stem=stem,
                        shuffles_committed=len(jr.shuffle_commits))
        except Exception:  # graft: disable=GL004 -- reuse-event tee is best-effort
            pass
        return jr
    return None


# ---------------------------------------------------------------------------
# session/serving glue
# ---------------------------------------------------------------------------

def begin(token, plan_bytes: bytes, num_partitions: int, catalog: dict,
          conf=None, scope: str = "collect") -> Optional[QueryJournal]:
    """Open (adopt or mint) the journal for one top-level query and
    bind it to the query's CancelToken. None = journaling off for this
    query (disarmed, host-fn plan, or a degraded header write)."""
    from auron_tpu import config as cfg
    conf = conf or cfg.get_config()
    dir_ = journal_dir(conf)
    if not dir_:
        return None
    if plan_has_host_fns(plan_bytes):
        logger.info("query %s not journaled: plan references host-"
                    "fallback tables", getattr(token, "query_id", "?"))
        return None
    jr = None
    if conf.get(cfg.JOURNAL_REUSE):
        jr = find_reusable(dir_, plan_bytes, catalog, conf, scope=scope)
        if jr is not None:
            _register_open(jr.stem, dir_)
    if jr is None:
        jr = QueryJournal.create(dir_, token.query_id, plan_bytes,
                                 num_partitions, catalog, conf,
                                 scope=scope)
    if jr is not None:
        token.journal = jr
        jr.begin_plan()
    return jr


def attach_resumed(token, jr: QueryJournal) -> QueryJournal:
    """Bind an already-loaded (resume-path) journal to the resuming
    query's token and re-open it for continued appends (healing a
    torn tail so new records never fuse with crash debris)."""
    if jr._file is None:
        _reopen_for_append(jr)
    jr._closed = False
    _register_open(jr.stem, jr.dir)
    token.journal = jr
    jr.begin_plan()
    return jr


# ---------------------------------------------------------------------------
# startup orphan sweep
# ---------------------------------------------------------------------------

_SWEPT_DIRS_LOCK = threading.Lock()
_SWEPT_DIRS: set = set()


def sweep_orphans(dir_: str, force: bool = False) -> int:
    """Garbage-collect journal artifacts of DEAD processes under
    ``dir_`` (once per process per dir unless ``force``):

    - ``*.part`` / stray temp files of dead owners,
    - ``*.claim`` adoption/resume claims whose claimer died mid-run,
    - journals that are NOT resumable (corrupt/torn header) with a
      dead owner — a resumable dead-owner journal is KEPT: it is the
      resume inventory, capped by ``auron.journal.retention_s`` (aged
      inventory nobody resumes GCs along with its RSS run dir),
    - ``rss/<stem>`` run directories whose journal file is gone and
      whose ``.owner`` tag is dead (a completed query removes its own;
      these are crash leftovers past their journal's deletion),
    - ``report_*.json`` resume reports beyond the newest
      ``REPORT_RETENTION`` (they are pure telemetry for
      tools/journal_report.py; without a cap a long-lived deployment
      grows one per resumed query forever).

    Returns how many artifacts were removed; counted on
    ``auron_journal_orphans_swept_total``."""
    import shutil

    from auron_tpu.utils import liveness
    if not dir_ or not os.path.isdir(dir_):
        return 0
    with _SWEPT_DIRS_LOCK:
        if dir_ in _SWEPT_DIRS and not force:
            return 0
        _SWEPT_DIRS.add(dir_)
    from auron_tpu import config as cfg
    retention_s = float(cfg.get_config().get(cfg.JOURNAL_RETENTION_S))
    now = time.time()

    def _mtime(p: str) -> float:
        try:
            return os.path.getmtime(p)
        except OSError:
            return now   # unknowable age: conservative = fresh
    removed = 0
    live_stems = set()
    for n in sorted(os.listdir(dir_)):
        path = os.path.join(dir_, n)
        if n.endswith(".journal"):
            stem = n[:-len(".journal")]
            try:
                header = _read_records(path)[0]
                owner = header.get("owner", "")
                resumable = True
            except errors.JournalError as e:
                if isinstance(e.__cause__,
                              (OSError, errors.JournalIOError)):
                    # could not READ the file just now (transient IO,
                    # injected journal.load fault) — that is not proof
                    # of a husk; keep it, a later sweep decides
                    live_stems.add(n[:-len(".journal")])
                    continue
                # corrupt journal: salvage the owner from the header
                # line if it survived — a LIVE owner's corrupt-interior
                # journal (e.g. an injected journal.write corrupt
                # fault) is the owner's to reclaim, not ours to sweep
                owner, resumable = _try_read_owner(path), False
            if resumable and (not owner or liveness.is_live(owner)):
                live_stems.add(stem)
                continue
            if resumable and owner and not liveness.is_live(owner):
                # dead owner, resumable: KEEP — the resume inventory —
                # unless it has aged past auron.journal.retention_s
                # (mtime = last append = the crash/suspend instant):
                # inventory nobody resumes must not hold journal + RSS
                # shuffle bytes forever
                if 0 < retention_s < now - _mtime(path):
                    try:
                        os.unlink(path)
                        removed += 1
                    except OSError:
                        live_stems.add(stem)
                    continue
                live_stems.add(stem)
                continue
            # not resumable: with a live owner the writer may be mid-
            # header; only a dead (or unknowable) owner's husk sweeps
            if owner and liveness.is_live(owner):
                live_stems.add(stem)
                continue
            try:
                os.unlink(path)
                removed += 1
            except OSError:
                pass
        elif n.endswith(".part"):
            # a ``<stem>.journal.part`` may be a LIVE process's header
            # staging file (QueryJournal.create writes+flushes the
            # header there before the atomic rename): once the header
            # hits the file its owner is readable — keep the live
            # owner's.  An unparseable .part is swept; the remaining
            # open→first-flush window is microseconds and losing the
            # race merely degrades that query's journaling (create's
            # rename fails → logged fresh-run posture, never a wrong
            # answer).
            owner = _try_read_owner(path)
            if owner and liveness.is_live(owner):
                continue
            try:
                os.unlink(path)
                removed += 1
            except OSError:
                pass
        elif n.endswith(".claim"):
            # adoption/resume claim whose claimer died mid-run: the
            # claim breaks lazily on the next _claim_stem anyway, this
            # just keeps the dir tidy (a LIVE claimer's is kept)
            try:
                with open(path) as f:
                    claimer = f.read().strip()
            except OSError:
                continue
            # an EMPTY tag is a claimer between its O_EXCL create and
            # the tag write — treat as live (is_live's conservative
            # default, and what _claim_stem itself does); the lazy
            # break in _claim_stem handles genuinely dead claimers
            if not claimer or liveness.is_live(claimer):
                continue
            try:
                os.unlink(path)
                removed += 1
            except OSError:
                pass
    reports = [os.path.join(dir_, n) for n in os.listdir(dir_)
               if n.startswith("report_") and n.endswith(".json")]
    if len(reports) > REPORT_RETENTION:
        reports.sort(key=lambda p: (os.path.getmtime(p)
                                    if os.path.exists(p) else 0))
        for p in reports[:-REPORT_RETENTION]:
            try:
                os.unlink(p)
                removed += 1
            except OSError:
                pass
    rss_dir = os.path.join(dir_, "rss")
    if os.path.isdir(rss_dir):
        for stem in sorted(os.listdir(rss_dir)):
            if stem in live_stems:
                continue
            run_dir = os.path.join(rss_dir, stem)
            if not os.path.isdir(run_dir):
                continue
            owner = ""
            try:
                with open(os.path.join(run_dir, ".owner")) as f:
                    owner = f.read().strip()
            except OSError:
                pass
            if owner and liveness.is_live(owner):
                continue
            shutil.rmtree(run_dir, ignore_errors=True)
            removed += 1
    liveness.note_swept("auron_journal_orphans_swept_total", removed,
                        dir_, "journal")
    return removed
