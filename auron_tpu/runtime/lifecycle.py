"""Query lifecycle control plane: deadlines + cooperative cancellation.

The reference brackets every task with hard teardown semantics
(callNative / nextBatch / finalizeNative — a task can always be
finalized mid-stream from the host side, rt.rs:76-300); this module is
the host-side half of that contract for the TPU engine: a per-query
``CancelToken`` that every layer polls cooperatively.

One token per top-level query, created by ``Session.execute`` (or the
serving handler) and threaded through the retry driver into every
ExecContext as its ``cancel_event``. The token is a drop-in for the
legacy ``threading.Event`` registry — it implements ``set``/``is_set``/
``wait`` — but additionally carries:

- an optional **deadline** (monotonic): the first ``is_set`` check past
  it self-cancels with reason ``deadline``, so deadline enforcement
  needs no timer thread — any poll site notices;
- a **reason** (``cancelled`` | ``deadline``) that decides which
  classified error unwinds the task (``errors.QueryCancelled`` vs
  ``errors.DeadlineExceeded``);
- the **cancel timestamp**, which the retry driver turns into the
  ``auron_cancel_latency_seconds`` registry histogram — the measured
  cancel-to-unwind latency the acceptance gate reads (PERF.md
  "Lifecycle guarantees").

Cancellation is FIRST-WINS and idempotent: a second ``cancel`` (the
after-DONE no-op of the race battery) keeps the original reason and
timestamp.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class CancelToken:
    """Per-query cancellation registry with an optional deadline.

    Event-compatible (``set``/``is_set``/``wait``) so it slots directly
    into ``ExecContext.cancel_event`` and the serving handler's window
    loop; richer callers use ``cancel(reason)`` / ``raise_for_status`` /
    ``sleep`` (the interruptible, deadline-clamped backoff primitive).
    """

    __slots__ = ("query_id", "_event", "_lock", "_deadline", "reason",
                 "cancelled_at_ns", "slot", "journal", "tasks_total",
                 "tasks_done", "plan_tree", "served_from", "cost_ledger")

    def __init__(self, query_id: str = "", deadline_s: Optional[float] = None):
        self.query_id = query_id
        self._event = threading.Event()
        self._lock = threading.Lock()
        #: driver progress surfaced on the ops plane's /queries table
        #: (runtime/executor.collect stamps total and bumps done per
        #: finished partition; 0/0 until the drive loop starts)
        self.tasks_total = 0
        self.tasks_done = 0
        #: the query's positional metric tree (obs/metric_tree) when the
        #: bundle plane armed one — a failure bundle renders it as the
        #: explain-with-metrics snapshot (obs/bundle.py)
        self.plan_tree = None
        #: the query's scheduler seat (runtime/scheduler.Slot) once
        #: admitted; nested executes ride the enclosing token, so the
        #: slot travels with it (executor.collect's fairness hook)
        self.slot = None
        #: the query's crash-safe journal (runtime/journal.QueryJournal)
        #: when auron.journal.dir arms the plane: the planner's shuffle
        #: routing oracle and the RSS exchange's commit-record sink /
        #: resume oracle both resolve it through this token
        self.journal = None
        self._deadline = (time.monotonic() + deadline_s
                         if deadline_s is not None and deadline_s > 0
                         else None)
        #: "cache" when the query was answered from the warm-path
        #: result cache (auron_tpu/cache) instead of executing — the
        #: served_from label on auron_query_duration_seconds
        self.served_from: Optional[str] = None
        #: the query's per-query cost ledger (obs/ledger.build) stamped
        #: at finalize — rides the DONE frame and the failure bundle
        self.cost_ledger: Optional[dict] = None
        #: first-wins cancel reason: "cancelled" | "deadline"
        self.reason: Optional[str] = None
        #: monotonic ns of the winning cancel (the latency-histogram t0)
        self.cancelled_at_ns: Optional[int] = None

    # -- deadline ------------------------------------------------------------

    def arm_deadline(self, deadline_s: float) -> "CancelToken":
        """(Re-)arm the deadline ``deadline_s`` seconds from now (the
        serving handler arms it after the SUBMIT frame arrives)."""
        if deadline_s and deadline_s > 0:
            self._deadline = time.monotonic() + deadline_s
        return self

    def remaining(self) -> Optional[float]:
        """Seconds of deadline budget left; None = no deadline. Already
        clamped at 0 — callers use it to bound sleeps and IO waits."""
        if self._deadline is None:
            return None
        return max(self._deadline - time.monotonic(), 0.0)

    # -- cancel (Event-compatible surface) -----------------------------------

    def cancel(self, reason: str = "cancelled") -> None:
        """Flip the token (thread-safe, idempotent, first reason wins)."""
        with self._lock:
            if self.reason is None:
                self.reason = reason
                self.cancelled_at_ns = time.monotonic_ns()
                try:
                    from auron_tpu.obs import trace
                    trace.event("task", "query.cancel", reason=reason,
                                query=self.query_id)
                except Exception:   # pragma: no cover  # graft: disable=GL004 -- obs tee is best-effort; the cancel itself must complete
                    pass
        self._event.set()

    def set(self) -> None:
        """threading.Event alias (the serving control reader calls it)."""
        self.cancel()

    def finish(self) -> None:
        """Quiet completion: release every waiter (the serving
        handler's finally must unblock its control-reader thread after
        a SUCCESSFUL task) WITHOUT recording a cancel reason, timestamp
        or trace event — a finished query is not a cancelled one, and
        telemetry must not show a spurious cancel per success."""
        self._event.set()

    def is_set(self) -> bool:
        if self._event.is_set():
            return True
        if self._deadline is not None \
                and time.monotonic() >= self._deadline:
            self.cancel("deadline")
            return True
        return False

    @property
    def cancelled(self) -> bool:
        return self.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Event-compatible wait, clamped to the deadline budget."""
        rem = self.remaining()
        if rem is not None:
            timeout = rem if timeout is None else min(timeout, rem)
        got = self._event.wait(timeout)
        return got or self.is_set()

    # -- cooperative unwind --------------------------------------------------

    def raise_for_status(self) -> None:
        """Raise the classified lifecycle error when the token is set
        (QueryCancelled / DeadlineExceeded by reason); no-op otherwise.
        ExecContext.check_cancelled delegates here, so every operator
        poll site unwinds with the right verdict for free."""
        if not self.is_set():
            return
        from auron_tpu import errors
        qid = self.query_id
        if self.reason == "deadline":
            raise errors.DeadlineExceeded(
                f"query {qid or '?'} exceeded its deadline", query_id=qid)
        raise errors.QueryCancelled(
            f"query {qid or '?'} was cancelled", query_id=qid)

    def sleep(self, seconds: float) -> None:
        """Interruptible sleep: wakes the moment the token is cancelled
        and never sleeps past the deadline (the retry driver's backoff
        primitive — a jittered backoff must not outlive the budget it is
        spending). Raises via raise_for_status when woken cancelled."""
        if seconds > 0:
            self.wait(seconds)
        self.raise_for_status()

    def unwind_latency_s(self) -> Optional[float]:
        """Seconds between the winning cancel and NOW — observed by the
        retry driver when the classified error finally unwinds (the
        cancel-to-unwind latency of the acceptance criterion)."""
        if self.cancelled_at_ns is None:
            return None
        return (time.monotonic_ns() - self.cancelled_at_ns) * 1e-9

    def __repr__(self):
        state = self.reason or ("set" if self._event.is_set() else "live")
        return f"CancelToken({self.query_id!r}, {state})"


# ---------------------------------------------------------------------------
# thread-local query binding (the concurrent runtime's attribution key)
# ---------------------------------------------------------------------------

#: the driving thread's current query token. Bound by Session.execute /
#: the serving handler around execution so planes with no ExecContext at
#: hand (memmgr consumer registration, the central program cache) can
#: attribute work to the query that caused it — the per-query ledger the
#: concurrent scheduler's fairness decisions read.
_TLS = threading.local()


def bind_token(token: Optional[CancelToken]):
    """Bind ``token`` as this thread's current query; returns the
    previous binding for the caller's finally-restore (nested executes
    re-bind the same token, so restore keeps the enclosing query)."""
    prev = getattr(_TLS, "token", None)
    _TLS.token = token
    return prev


def current_token() -> Optional[CancelToken]:
    return getattr(_TLS, "token", None)


def current_query_id() -> str:
    """Query id of the driving thread's bound token; "" when no query
    is bound (direct executor.collect calls, tests) — the anonymous
    ledger bucket."""
    tok = getattr(_TLS, "token", None)
    return getattr(tok, "query_id", "") if tok is not None else ""


def observe_unwind(token_or_latency, kind: str = "cancel") -> None:
    """Feed one cancel-to-unwind latency into the process registry
    (``auron_cancel_latency_seconds{kind=...}``); kind is ``cancel`` |
    ``deadline`` | ``stall``. Best-effort — latency telemetry must never
    fail an unwinding task."""
    try:
        lat = (token_or_latency if isinstance(token_or_latency, (int, float))
               else token_or_latency.unwind_latency_s())
        if lat is None:
            return
        from auron_tpu.obs import registry as obs_registry
        if not obs_registry.enabled():
            return
        obs_registry.get_registry().histogram(
            "auron_cancel_latency_seconds", kind=kind).observe(lat)
    except Exception:   # pragma: no cover  # graft: disable=GL004 -- latency telemetry is best-effort by contract
        pass
