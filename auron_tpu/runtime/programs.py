"""Central program-cache registry.

Every jit-kernel builder in the engine used to memoize behind its own
module-level ``functools.lru_cache`` (~15 scattered sites: project/filter
kernels, sort, SMJ, hash-join, agg merge, shuffle split, window, explode,
bloom probe, SPMD exchange, ...). That shape had two costs:

- ``auron.max_live_programs`` (utils/compile_stats.maybe_clear) cleared
  jax's compiled caches but could not drop the builder memos, so the
  python-side kernel closures and their cache keys kept growing unbounded
  and no single place could answer "how many live programs does this
  process hold, and which compile site built them";
- per-site build/hit counts were invisible — the compile-budget numbers
  in PERF.md had to be reverse-engineered from raw backend-compile
  events.

This module replaces all of those with one registry: each compile site
declares a ``@program_cache("site.name")`` around its builder function and
gets LRU memoization (same semantics as the old ``lru_cache``) plus
central accounting. ``maybe_clear`` (utils/compile_stats) consults
``total_live()`` and calls ``clear_all()`` together with
``jax.clear_caches()``, so the documented ceiling now bounds every
compile site, builder memos included.

``snapshot()`` / ``delta()`` expose per-site and aggregate build/hit
counters — the per-query numbers ``tools/compile_report.py`` prints and
the per-task ``programs`` entry in ExecutionRuntime.finalize.
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from typing import Callable, NamedTuple, Optional

_LOCK = threading.Lock()
_SITES: "OrderedDict[str, ProgramCache]" = OrderedDict()

#: per-QUERY build/hit attribution (query id from the lifecycle plane's
#: thread-local token). The cache itself is shared ACROSS concurrent
#: queries — a hit compiled by query A serves query B — so the process
#: totals can no longer attribute per query by delta; this ledger can.
#: Popped at query end (Session._end_query) so memory stays bounded;
#: read by explain(analyze=True)'s program-cache footer.
_QUERY_LOCK = threading.Lock()
_QUERY_COUNTS: dict[str, list] = {}


def _note_query(built: bool) -> None:
    from auron_tpu.runtime import lifecycle
    qid = lifecycle.current_query_id()
    if not qid:
        return
    with _QUERY_LOCK:
        ent = _QUERY_COUNTS.setdefault(qid, [0, 0])
        ent[0 if built else 1] += 1


def query_totals(qid: str) -> "ProgramSnapshot":
    """(builds, hits) attributed to ``qid`` so far."""
    with _QUERY_LOCK:
        ent = _QUERY_COUNTS.get(qid, (0, 0))
        return ProgramSnapshot(ent[0], ent[1])


def pop_query(qid: str) -> "ProgramSnapshot":
    """Remove and return ``qid``'s attribution (query teardown)."""
    with _QUERY_LOCK:
        ent = _QUERY_COUNTS.pop(qid, (0, 0))
        return ProgramSnapshot(ent[0], ent[1])


class ProgramSnapshot(NamedTuple):
    builds: int
    hits: int


class ProgramCache:
    """One compile site's builder memo: LRU-bounded, centrally counted.

    ``get_or_build`` returns ``(value, built)`` — ``built`` is True when
    the builder ran (a new program was constructed), letting call sites
    mirror build/hit counts into per-task metrics without racing on the
    monotonic totals.
    """

    def __init__(self, site: str, maxsize: int = 256):
        self.site = site
        self.maxsize = maxsize
        self._memo: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        #: monotonic totals (survive clear(): they describe history,
        #: not current residency)
        self.builds = 0
        self.hits = 0
        self.evictions = 0
        # offsets for lru_cache-compatible cache_info() (which resets
        # its counters on cache_clear; the monotonic totals above don't)
        self._builds_at_clear = 0
        self._hits_at_clear = 0

    def get_or_build(self, key, builder: Callable):
        # trace-semantic config values partition every cache key: a
        # builder's trace may read them (e.g. the map-key dedup policy),
        # so a changed value must build a FRESH function object — jax's
        # jit cache keys on function identity, making the re-trace real
        from auron_tpu import config as _cfg
        from auron_tpu.obs import profile as _profile
        key = (key, _cfg.trace_salt())
        value = None
        hit = False
        with self._lock:
            if key in self._memo:
                self._memo.move_to_end(key)
                self.hits += 1
                value = self._memo[key]
                hit = True
        from auron_tpu.obs import trace as _trace
        if hit:
            # per-site hit events make the compile economics visible on
            # the timeline; narrow auron.trace.events to drop them
            _trace.event("program", "program.hit", site=self.site)
            _note_query(built=False)
            # the memo holds the RAW program (stable identity for the
            # cache); the per-invocation host/device timing proxy wraps
            # only what leaves the registry (obs/profile.wrap_program —
            # a pass-through when profiling is off)
            return _profile.wrap_program(value, self.site), False
        from auron_tpu import errors as _errors
        from auron_tpu.runtime import faults as _faults
        _faults.maybe_fail("program.build", _errors.DeviceExecutionError)
        with _trace.span("program", "program.build", site=self.site):
            value = builder()   # build outside the lock: builders recurse
        with self._lock:
            if key in self._memo:   # raced with another thread: keep first
                self.hits += 1
                _note_query(built=False)
                return _profile.wrap_program(self._memo[key],
                                             self.site), False
            self._memo[key] = value
            self.builds += 1
            while len(self._memo) > self.maxsize:
                self._memo.popitem(last=False)
                self.evictions += 1
        _note_query(built=True)
        return _profile.wrap_program(value, self.site), True

    def live(self) -> int:
        with self._lock:
            return len(self._memo)

    def clear(self) -> None:
        with self._lock:
            self._memo.clear()
            self._builds_at_clear = self.builds
            self._hits_at_clear = self.hits

    def cache_info(self):
        """functools.lru_cache-compatible view (counters since the last
        clear), so converted sites stay drop-in for existing callers."""
        import functools
        with self._lock:
            return functools._CacheInfo(
                self.hits - self._hits_at_clear,
                self.builds - self._builds_at_clear,
                self.maxsize, len(self._memo))

    def stats(self) -> dict:
        with self._lock:
            return {"builds": self.builds, "hits": self.hits,
                    "live": len(self._memo), "evictions": self.evictions}


def register(cache: ProgramCache) -> ProgramCache:
    with _LOCK:
        assert cache.site not in _SITES, \
            f"duplicate program-cache site {cache.site!r}"
        _SITES[cache.site] = cache
    return cache


def site(name: str) -> Optional[ProgramCache]:
    with _LOCK:
        return _SITES.get(name)


def program_cache(site_name: str, maxsize: int = 256):
    """Decorator replacing ``functools.lru_cache`` on kernel builders.

    The wrapped builder keeps its call signature (positional, hashable
    args — the same contract ``lru_cache`` enforced) and gains a
    ``.cache`` attribute exposing the registered ProgramCache.
    """

    def deco(fn: Callable) -> Callable:
        cache = register(ProgramCache(site_name, maxsize))

        @functools.wraps(fn)
        def wrapper(*args):
            value, _built = cache.get_or_build(args, lambda: fn(*args))
            return value

        wrapper.cache = cache
        # lru_cache drop-in compat for existing call sites
        wrapper.cache_clear = cache.clear
        wrapper.cache_info = cache.cache_info
        return wrapper

    return deco


# ---------------------------------------------------------------------------
# aggregate views
# ---------------------------------------------------------------------------

def snapshot() -> dict:
    """{site: {builds, hits, live, evictions}} over every registered
    compile site."""
    with _LOCK:
        sites = list(_SITES.values())
    return {c.site: c.stats() for c in sites}


def totals() -> ProgramSnapshot:
    with _LOCK:
        sites = list(_SITES.values())
    b = sum(c.builds for c in sites)
    h = sum(c.hits for c in sites)
    return ProgramSnapshot(b, h)


def delta(since: ProgramSnapshot) -> ProgramSnapshot:
    now = totals()
    return ProgramSnapshot(now.builds - since.builds, now.hits - since.hits)


def total_live() -> int:
    """Programs currently held across every site's memo — what
    ``auron.max_live_programs`` bounds (utils/compile_stats.maybe_clear)."""
    with _LOCK:
        sites = list(_SITES.values())
    return sum(c.live() for c in sites)


def clear_all() -> None:
    """Drop every site's memo (the registry side of a compile-cache
    clear; jax.clear_caches() is the caller's half — see
    utils/compile_stats.maybe_clear)."""
    with _LOCK:
        sites = list(_SITES.values())
    for c in sites:
        c.clear()


# ---------------------------------------------------------------------------
# donation-aware jit
# ---------------------------------------------------------------------------

def jit(fun=None, *, donate_argnums=(), **kwargs):
    """``jax.jit`` that applies ``donate_argnums`` only where donation is
    real. The XLA CPU backend treats donation as advisory (every donated
    buffer is copied anyway and jax warns about it), so kernels that
    donate their dead inputs — the sort/gather kernels, the shuffle
    split — compile with donation on accelerators and without it on the
    CPU mesh, keeping tier-1 runs warning-free while halving peak HBM for
    those steps on a real chip."""
    import jax

    def wrap(f):
        if donate_argnums:
            try:
                platform = jax.default_backend()
            except Exception:   # backend init failure: stay conservative
                platform = "cpu"
            if platform != "cpu":
                # graft: donation-ok -- the donation-aware wrapper
                # itself; every caller annotates its own site
                return jax.jit(f, donate_argnums=donate_argnums, **kwargs)
        return jax.jit(f, **kwargs)

    if fun is None:
        return wrap
    return wrap(fun)
