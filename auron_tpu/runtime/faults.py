"""Deterministic, seeded fault-injection plane.

Every recovery-relevant boundary in the engine carries a *named
injection site*; a fault plan — one config string — arms sites with
failure kinds and probabilities, and every decision is a pure function
of ``(seed, site, kind, per-rule event index)``, so a failing chaos run
replays EXACTLY by re-running with the same seed. No fault plan armed
(the default) costs one config-epoch compare per site check (the
armed/disarmed verdict is cached until a config mutation).

Plan grammar (``auron.faults.plan``)::

    site:kind@prob[;site:kind@prob...]
    rss.fetch:corrupt@0.05;spill.read:io_error@0.1;device.compute:io_error

``@prob`` defaults to 1.0. Kinds:

- ``io_error``  — raise the site's transient error class (the call site
  passes it; e.g. ``RssUnavailableError`` at rss.*, ``SpillIOError`` at
  spill.*, ``DeviceExecutionError`` at device.compute/program.build).
- ``fatal``     — raise ``errors.InjectedFatalError`` (deterministic:
  chaos tests assert it is never retried).
- ``corrupt``   — at byte boundaries (``maybe_corrupt``), flip one byte
  of the payload AFTER its checksum was computed, simulating storage
  bit rot the integrity layer must catch. Ignored at fail-only sites.
- ``hang``      — sleep ``auron.faults.hang_s`` seconds (simulates the
  wedged axon backend init; pair with the watchdog deadline). The sleep
  POLLS the caller's cancel registry (``maybe_fail(..., cancel=ctx)``)
  so a cooperative cancel — or a stall-watchdog flag — terminates an
  injected hang promptly instead of blocking the full interval.
- ``cancel``    — lifecycle chaos (``maybe_cancel``): fire the task's
  cancel registry at a seeded event index, racing cancellation against
  live batch traffic (the ``cancel.race`` site).
- ``deny``      — forced-decision chaos (``fires``): make a survivable
  refusal happen as if its threshold were breached — the memory
  manager's degradation ladder at ``memmgr.deny``, an admission-control
  rejection (``errors.AdmissionRejected``) at ``sched.admit``.

Named sites threaded through the engine:

    rss.write | rss.flush | rss.commit | rss.fetch      (shuffle tier)
    spill.write | spill.read                            (spill tier)
    device.compute                                      (per batch)
    task.hang                                           (per batch, mid-drive)
    cancel.race                                         (per batch, lifecycle)
    program.build                                       (compile sites)
    backend.init                                        (watchdog probe)
    memmgr.deny                                         (pressure ladder)
    sched.admit                                         (admission control)
    mesh.all_to_all                                     (per sharded round)
    mesh.gang                                           (gang door, cancel)
    journal.write | journal.commit | journal.load       (query journal)

``journal.write``/``journal.commit`` fire on the crash-safe query
journal's append/fsync path (runtime/journal.py): ``io_error``/``fatal``
are SWALLOWED by the journal — journaling degrades to off for that
query (a ``journal.disable`` event on the timeline), the query itself
completes identically; ``corrupt`` flips a byte of the appended record
AFTER its CRC, surfacing as ``JournalCorrupt`` only when a later resume
loads the file. ``journal.load`` fires on resume/reuse loads: the
classified ``JournalCorrupt`` (resume) or a logged fresh-run fallback
(reuse).

``mesh.all_to_all`` fires once per all-to-all round of a mesh-routed
exchange: ``io_error`` raises the classified ``errors.MeshUnavailable``
(a lost device — the demotion ladder must route the exchange's
remaining rounds host-side), ``fatal`` an InjectedFatalError carrying
the mesh site (same demotion path: a deterministic mesh failure is
recovered by routing AROUND the mesh, not by retrying into it), and
``hang`` a straggling chip (the straggler defense's signal).
``mesh.gang`` (kind ``cancel``) fires the task's cancel registry while
it queues at the gang door — the parked ticket must dequeue without
ever starting a round.

The plane is resolved from the PROCESS-GLOBAL config (the sites live in
code paths with no ExecContext at hand — file services, spill files),
and injection counters are exposed via ``snapshot``/``totals`` so the
per-task metrics snapshot can attribute injected faults.
"""

from __future__ import annotations

import threading
import time
import zlib
from dataclasses import dataclass
from typing import Optional

from auron_tpu import errors

#: the engine's named injection sites (documentation + plan validation)
SITES = (
    "rss.write", "rss.flush", "rss.commit", "rss.fetch",
    "spill.write", "spill.read",
    "device.compute", "program.build", "backend.init",
    "task.hang", "cancel.race", "memmgr.deny", "sched.admit",
    "mesh.all_to_all", "mesh.gang",
    "journal.write", "journal.commit", "journal.load",
    "fleet.route", "fleet.forward",
)

KINDS = ("io_error", "fatal", "corrupt", "hang", "cancel", "deny")


@dataclass(frozen=True)
class Rule:
    site: str
    kind: str
    prob: float


def parse_plan(plan: str) -> list[Rule]:
    """Parse the ``site:kind@prob;...`` grammar; raises ValueError on an
    unknown site/kind or malformed probability (a typo'd chaos plan must
    fail loudly, not silently inject nothing)."""
    rules = []
    for part in filter(None, (p.strip() for p in plan.split(";"))):
        try:
            site, rest = part.split(":", 1)
            kind, _, prob_s = rest.partition("@")
            prob = float(prob_s) if prob_s else 1.0
        except ValueError as e:
            raise ValueError(f"malformed fault rule {part!r} "
                             f"(want site:kind@prob)") from e
        site, kind = site.strip(), kind.strip()
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r}; known: {SITES}")
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; known: {KINDS}")
        if not 0.0 <= prob <= 1.0:
            raise ValueError(f"fault probability out of range: {part!r}")
        rules.append(Rule(site, kind, prob))
    return rules


class FaultPlane:
    """One parsed plan + its deterministic decision state."""

    def __init__(self, plan: str, seed: int, hang_s: float = 2.0):
        self.plan = plan
        self.seed = seed
        self.hang_s = hang_s
        self._rules: dict[str, list[Rule]] = {}
        for r in parse_plan(plan):
            self._rules.setdefault(r.site, []).append(r)
        self._lock = threading.Lock()
        #: per-rule event index — each rule sees its own deterministic
        #: Bernoulli sequence, independent of other rules' traffic
        self._events: dict[tuple[str, str], int] = {}
        self.injected: dict[tuple[str, str], int] = {}

    def _decide(self, rule: Rule) -> Optional[int]:
        """Advance the rule's event counter; return the event index when
        this event injects, else None. hash(seed|site|kind|n) → [0,1)."""
        with self._lock:
            n = self._events.get((rule.site, rule.kind), 0)
            self._events[(rule.site, rule.kind)] = n + 1
            h = zlib.crc32(
                f"{self.seed}|{rule.site}|{rule.kind}|{n}".encode())
            if (h & 0xFFFFFFFF) / 2**32 >= rule.prob:
                return None
            self.injected[(rule.site, rule.kind)] = \
                self.injected.get((rule.site, rule.kind), 0) + 1
            return n

    def fire(self, site: str, kinds: tuple[str, ...]) -> Optional[Rule]:
        """First armed rule of ``site`` among ``kinds`` that injects on
        this event, advancing every matching rule's counter."""
        hit = None
        for rule in self._rules.get(site, ()):
            if rule.kind in kinds and self._decide(rule) is not None \
                    and hit is None:
                hit = rule
        return hit

    def snapshot(self) -> dict[str, dict[str, int]]:
        with self._lock:
            out: dict[str, dict[str, int]] = {}
            for (site, kind), n in self.injected.items():
                out.setdefault(site, {})[kind] = n
            return out

    def totals(self) -> int:
        with self._lock:
            return sum(self.injected.values())


# ---------------------------------------------------------------------------
# process-global plane, resolved from config
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_PLANE: Optional[FaultPlane] = None
_PLANE_KEY: Optional[tuple] = None
#: (config epoch, plane) verdict cache: the unarmed hot path — every
#: batch, frame and program build calls a site check — must cost one
#: tuple read + int compare, not a config lock + os.environ lookup.
#: A single-assignment tuple so readers never see a torn update.
_CACHED: tuple[int, Optional[FaultPlane]] = (-1, None)
#: monotonic injected-fault count across plane rebuilds (per-task metric
#: attribution survives a reconfigure mid-process)
_TOTAL_BASE = 0


def _active() -> Optional[FaultPlane]:
    """The plane for the current config; None when no plan is armed.
    The verdict is cached against the config-mutation epoch, so the
    common unarmed check is one int compare — plan changes go through
    ``AuronConfig.set/unset`` (or :func:`reset`), which bump the epoch."""
    from auron_tpu import config as cfg
    epoch, plane = _CACHED
    if epoch == cfg.config_epoch():
        return plane
    return _resolve()


def _resolve() -> Optional[FaultPlane]:
    global _PLANE, _PLANE_KEY, _TOTAL_BASE, _CACHED
    from auron_tpu import config as cfg
    # read the epoch BEFORE the config values: a concurrent set() bumps
    # it after we read, so the stale cache entry misses on the next call
    epoch = cfg.config_epoch()
    conf = cfg.get_config()
    plan = conf.get(cfg.FAULTS_PLAN)
    if not plan:
        if _PLANE is not None:
            with _LOCK:
                if _PLANE is not None:
                    _TOTAL_BASE += _PLANE.totals()
                    _PLANE, _PLANE_KEY = None, None
        _CACHED = (epoch, None)
        return None
    key = (plan, conf.get(cfg.FAULTS_SEED), conf.get(cfg.FAULTS_HANG_S))
    plane = _PLANE
    if plane is None or _PLANE_KEY != key:
        with _LOCK:
            if _PLANE is None or _PLANE_KEY != key:
                if _PLANE is not None:
                    _TOTAL_BASE += _PLANE.totals()
                _PLANE = FaultPlane(*key)
                _PLANE_KEY = key
            plane = _PLANE
    _CACHED = (epoch, plane)
    return plane


def reset() -> None:
    """Drop the active plane's decision state so the NEXT site check
    replays event 0 (chaos harness: one reset per run = exact replay).
    Also invalidates the verdict cache — the one hook that notices a
    direct os.environ change."""
    global _PLANE, _PLANE_KEY, _TOTAL_BASE, _CACHED
    with _LOCK:
        if _PLANE is not None:
            _TOTAL_BASE += _PLANE.totals()
        _PLANE, _PLANE_KEY = None, None
        _CACHED = (-1, None)


def _stop_requested(cancel) -> bool:
    """Duck-typed poll of a cancel registry: ExecContext (``should_stop``
    covers both the cancel event and the stall flag), CancelToken /
    threading.Event (``is_set``)."""
    if cancel is None:
        return False
    stop = getattr(cancel, "should_stop", None)
    if stop is not None:
        return bool(stop)
    is_set = getattr(cancel, "is_set", None)
    return bool(is_set()) if is_set is not None else False


#: poll granularity of interruptible injected hangs (a cancel lands
#: within one tick, far inside the watchdog's stall resolution)
_HANG_POLL_S = 0.02


def maybe_fail(site: str, exc_cls=errors.TransientError,
               cancel=None) -> None:
    """Injection hook for failure sites: raises the plan's armed fault
    (``exc_cls`` for io_error — the call site's transient error class —
    InjectedFatalError for fatal), or sleeps for hang. No-op when the
    site is unarmed.

    ``cancel`` (an ExecContext, CancelToken or Event) makes an injected
    hang INTERRUPTIBLE: the sleep polls it and returns early on a
    cooperative cancel or stall flag, so chaos cancel tests terminate
    promptly — the caller's next checkpoint raises the classified
    error."""
    plane = _active()
    if plane is None:
        return
    rule = plane.fire(site, ("io_error", "fatal", "hang"))
    if rule is None:
        return
    # injected faults carry site/kind on the timeline so chaos runs are
    # self-explaining (obs/trace.py; correlated by tools/chaos_report)
    from auron_tpu.obs import trace
    trace.event("fault", "fault.injected", site=site, kind=rule.kind,
                seed=plane.seed)
    if rule.kind == "hang":
        _interruptible_sleep(plane.hang_s, cancel)
        return
    if rule.kind == "fatal":
        raise errors.InjectedFatalError(
            f"injected deterministic fault at {site} "
            f"(seed={plane.seed})", site=site)
    raise exc_cls(f"injected {rule.kind} at {site} (seed={plane.seed})",
                  site=site)


def _interruptible_sleep(seconds: float, cancel) -> None:
    """The injected-hang sleep: returns early the moment the caller's
    cancel registry (or stall flag) trips."""
    end = time.monotonic() + seconds
    while True:
        left = end - time.monotonic()
        if left <= 0 or _stop_requested(cancel):
            return
        wait = getattr(cancel, "wait", None)
        if wait is not None:
            # event/token wait wakes the instant a cancel lands
            wait(min(_HANG_POLL_S, left))
        else:
            time.sleep(min(_HANG_POLL_S, left))


def maybe_hang(site: str, cancel=None) -> bool:
    """Hang-only injection hook for checkpoint sites (``task.hang``):
    sleeps the armed hang interval — interruptibly, polling ``cancel``
    — and reports whether a hang was injected. Never raises: checkpoint
    callers surface whatever the hang provoked (stall flag, cancel)
    through check_cancelled."""
    plane = _active()
    if plane is None:
        return False
    rule = plane.fire(site, ("hang",))
    if rule is None:
        return False
    from auron_tpu.obs import trace
    trace.event("fault", "fault.injected", site=site, kind="hang",
                seed=plane.seed)
    _interruptible_sleep(plane.hang_s, cancel)
    return True


def lifecycle_poll(ctx) -> None:
    """The checkpoint-site fast path: ONE armed/disarmed verdict check
    covering both lifecycle sites (``cancel.race`` + ``task.hang``).
    ExecContext.checkpoint calls this per loop iteration, so the
    unarmed cost must stay one function call + one epoch compare."""
    if _active() is None:
        return
    maybe_cancel("cancel.race", ctx)
    maybe_hang("task.hang", cancel=ctx)


def maybe_cancel(site: str, target) -> bool:
    """Lifecycle injection hook (site ``cancel.race``, kind ``cancel``):
    fire the task's cancel registry at this seeded event index — racing
    cancellation against live traffic so the chaos battery proves every
    interleaving unwinds classified and leak-free. ``target`` is
    anything with ``cancel()`` (ExecContext, CancelToken). Returns True
    when the cancel fired."""
    plane = _active()
    if plane is None:
        return False
    rule = plane.fire(site, ("cancel",))
    if rule is None:
        return False
    from auron_tpu.obs import trace
    trace.event("fault", "fault.injected", site=site, kind="cancel",
                seed=plane.seed)
    cancel = getattr(target, "cancel", None)
    if cancel is not None:
        cancel()
    return True


def fires(site: str, kind: str) -> bool:
    """Boolean injection hook for sites whose fault is a forced DECISION
    rather than a raise (``memmgr.deny``: pretend the budget is
    exhausted and walk the degradation ladder). Advances the rule's
    deterministic event counter like every other site."""
    plane = _active()
    if plane is None:
        return False
    rule = plane.fire(site, (kind,))
    if rule is None:
        return False
    from auron_tpu.obs import trace
    trace.event("fault", "fault.injected", site=site, kind=kind,
                seed=plane.seed)
    return True


def maybe_corrupt(site: str, data: bytes) -> bytes:
    """Injection hook for byte boundaries: flips one deterministic byte
    of ``data`` when the site's ``corrupt`` rule injects. Call AFTER the
    checksum over the clean bytes is computed — the corruption must be
    the integrity layer's problem, not the writer's."""
    plane = _active()
    if plane is None or not data:
        return data
    rule = plane.fire(site, ("corrupt",))
    if rule is None:
        return data
    from auron_tpu.obs import trace
    trace.event("fault", "fault.injected", site=site, kind="corrupt",
                seed=plane.seed, bytes=len(data))
    pos = zlib.crc32(f"{plane.seed}|{site}|pos|{len(data)}".encode()) \
        % len(data)
    corrupted = bytearray(data)
    corrupted[pos] ^= 0xFF
    return bytes(corrupted)


def snapshot() -> dict[str, dict[str, int]]:
    """{site: {kind: injected count}} of the active plane ({} unarmed)."""
    plane = _PLANE
    return plane.snapshot() if plane is not None else {}


def totals() -> int:
    """Monotonic injected-fault count (survives plane rebuilds) — the
    per-task metrics delta source."""
    with _LOCK:
        base = _TOTAL_BASE
        plane = _PLANE
    return base + (plane.totals() if plane is not None else 0)
