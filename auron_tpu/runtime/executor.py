"""Per-task execution runtime.

The analogue of the reference's NativeExecutionRuntime (reference:
native-engine/auron/src/rt.rs:64-300): owns one partition's execution of a
physical plan — drives the operator stream, surfaces batches to the caller
(host Arrow or downstream stage), translates failures, and mirrors metrics
back on finalize. The tokio runtime + 1-slot channel of the reference maps
to the double-buffered generator chain here: jax dispatch is already async
(XLA executions overlap with host orchestration until a result is read).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional

import pyarrow as pa

from auron_tpu.columnar.arrow_bridge import to_arrow
from auron_tpu.columnar.batch import DeviceBatch
from auron_tpu.ops.base import ExecContext, PhysicalOp

logger = logging.getLogger("auron_tpu")


@dataclass
class TaskDefinition:
    """Mirror of the proto TaskDefinition (reference: auron.proto:804-813)."""
    stage_id: int = 0
    partition_id: int = 0
    task_id: int = 0
    num_partitions: int = 1


class ExecutionRuntime:
    """Runs one (plan, partition) pair to completion.

    ``attempt``/``retry_stats`` carry the retry driver's recovery
    counters into the finalize snapshot: the runtime that finally
    succeeds reports how many attempts the task took."""

    def __init__(self, plan: PhysicalOp, task: TaskDefinition,
                 mem_manager=None, config=None, attempt: int = 0,
                 retry_stats: Optional[dict] = None, cancel_token=None):
        self.plan = plan
        self.task = task
        self.attempt = attempt
        self.retry_stats = retry_stats if retry_stats is not None else {}
        self.cancel_token = cancel_token
        self.ctx = ExecContext(
            stage_id=task.stage_id,
            partition_id=task.partition_id,
            task_id=task.task_id,
            num_partitions=task.num_partitions,
            mem_manager=mem_manager,
            config=config,
        )
        if cancel_token is not None:
            # the query's CancelToken IS the task's cancellation
            # registry: socket-level CANCEL, session.cancel(query_id)
            # and deadline expiry all land through one mechanism
            self.ctx.cancel_event = cancel_token
        self._started = time.time()
        # per-task XLA compile attribution (round-5 directive 7): NEW
        # program builds during this task surface in the finalize metrics
        try:
            from auron_tpu.utils import compile_stats
            self._compile_start = compile_stats.snapshot()
        except Exception:
            self._compile_start = None
        # per-task program-cache attribution (central registry,
        # runtime/programs.py): builds vs hits across every compile
        # site. Under the concurrent scheduler a PROCESS-total delta
        # would blame this task for neighbor queries' compiles, so when
        # the task runs under a query token the delta is taken from the
        # per-QUERY ledger instead (cross-query safety audit). The
        # ledger is only WRITTEN while the lifecycle thread-local is
        # bound to this query (Session/serving do that); a bare token
        # handed straight to collect() would read permanent zeros from
        # it, so such callers keep the legacy process delta.
        try:
            from auron_tpu.runtime import lifecycle, programs
            qid = (getattr(cancel_token, "query_id", "")
                   if cancel_token is not None else "")
            self._programs_query = \
                qid if qid and lifecycle.current_query_id() == qid else ""
            self._programs_start = (
                programs.query_totals(self._programs_query)
                if self._programs_query else programs.totals())
        except Exception:
            self._programs_query = ""
            self._programs_start = None
        # per-task fault attribution (runtime/faults)
        from auron_tpu.runtime import faults as _faults
        self._faults_start = _faults.totals()

    def batches(self) -> Iterator[DeviceBatch]:
        """Device-batch stream (stays on device; used for stage chaining).

        Under ``auron.profile`` the whole task executes inside a
        jax.profiler trace (xprof/tensorboard-viewable) — the reference
        exposes the same capability as pprof flamegraph HTTP endpoints
        (auron/src/http/mod.rs:25-108); here the profiler is the XLA
        one, which attributes time to compiled kernels directly."""
        from auron_tpu import config as cfg
        conf = self.ctx.conf
        if conf.get(cfg.PROFILE):
            import tempfile
            import jax
            trace_dir = conf.get(cfg.PROFILE_DIR) or tempfile.mkdtemp(
                prefix=f"auron_profile_t{self.task.task_id}_")
            self.profile_dir = trace_dir
            with jax.profiler.trace(trace_dir):
                yield from self._batches_inner()
            return
        yield from self._batches_inner()

    def cancel(self) -> None:
        """Tear the running task down: operators polling the context's
        cancellation registry unwind within one batch (reference:
        cancel_all_tasks, rt.rs:296)."""
        self.ctx.cancel()

    def _batches_inner(self) -> Iterator[DeviceBatch]:
        from auron_tpu import errors
        from auron_tpu.obs import profile as _profile
        from auron_tpu.obs import trace
        from auron_tpu.ops.base import TaskCancelled
        from auron_tpu.runtime import faults, watchdog
        # drive-loop glue (cancel polls, fault checks, generator
        # bookkeeping between batches) attributed to the ROOT plan node
        # as the "iter" host bucket — the per-batch host tax the fused
        # pipelines pay even when every kernel is warm
        iter_c = (self.ctx.metrics_for(self.plan)
                  .counter("elapsed_host_iter")
                  if _profile.enabled() else None)
        # stall-watchdog heartbeat: per ATTEMPT (a retry self-clears the
        # stall flag by registering a fresh heartbeat); None disarmed
        hb = watchdog.register_heartbeat(
            task_id=self.task.task_id, stage_id=self.task.stage_id,
            partition_id=self.task.partition_id, attempt=self.attempt,
            config=self.ctx.config)
        self.ctx.heartbeat = hb
        try:
            with trace.span("task", "task.attempt",
                            stage=self.task.stage_id,
                            partition=self.task.partition_id,
                            task=self.task.task_id,
                            attempt=self.attempt):
                for batch in self.plan.execute(self.task.partition_id,
                                               self.ctx):
                    t0 = (time.perf_counter_ns() if iter_c is not None
                          else 0)
                    # checkpoint covers the lifecycle plane: heartbeat,
                    # cancel.race / task.hang injection, cancel raise
                    self.ctx.checkpoint("task.batch")
                    faults.maybe_fail("device.compute",
                                      errors.DeviceExecutionError)
                    if iter_c is not None:
                        iter_c.add(time.perf_counter_ns() - t0)
                    yield batch
        except (TaskCancelled, errors.QueryCancelled):
            # reference behavior: task-kill is teardown, not failure
            # (is_task_running checks, rt.rs:208-238); the classified
            # QueryCancelled/DeadlineExceeded unwind the same way but
            # keep their verdict for the caller
            logger.info(
                "task cancelled: stage=%d partition=%d task=%d",
                self.task.stage_id, self.task.partition_id,
                self.task.task_id)
            raise
        except NotImplementedError:
            # the one NO_RETRY_TYPES member that IS a RuntimeError
            # subclass: shield it from classify_runtime below (callers
            # catch it to reject unsupported plans); the other
            # deterministic builtins reach the generic handler unchanged
            logger.exception(
                "task failed: stage=%d partition=%d task=%d",
                self.task.stage_id, self.task.partition_id, self.task.task_id)
            raise
        except RuntimeError as e:
            # the device-compute boundary: XLA wraps BOTH transient
            # resource failures and deterministic lowering defects in
            # bare RuntimeError — classify here, at the boundary that
            # owns the ambiguity, so the retry driver routes purely on
            # the AuronError taxonomy (classified errors pass through)
            logger.exception(
                "task failed: stage=%d partition=%d task=%d",
                self.task.stage_id, self.task.partition_id, self.task.task_id)
            if isinstance(e, errors.AuronError):
                raise
            raise errors.classify_runtime(e) from e
        except Exception:
            # real failures surface with task identity attached
            logger.exception(
                "task failed: stage=%d partition=%d task=%d",
                self.task.stage_id, self.task.partition_id, self.task.task_id)
            raise
        finally:
            watchdog.unregister_heartbeat(hb)

    def arrow_batches(self) -> Iterator[pa.RecordBatch]:
        """Host materialization (the FFI export boundary of the reference).

        Under pipelined execution (auron.pipeline.enabled) the drive is
        double-buffered: batch N+1 is pulled from the operator chain —
        dispatching its kernels asynchronously and refilling the scan
        prefetcher — BEFORE batch N materializes to Arrow, so the
        device computes N+1 while the host converts N. to_arrow is the
        semantic sync point; the wait for N's in-flight arrays is
        fenced explicitly there and attributed to the root node's
        ``elapsed_device`` (async-aware timing: the sync moved, the
        attribution still sums to wall).

        The device→host export runs jitted gather/concat programs, so
        XLA's ambiguous RuntimeErrors surface here exactly as they do in
        the compute loop — classify them at this boundary too, or a
        deterministic lowering defect in the export path would retry as
        if transient."""
        from auron_tpu import errors
        from auron_tpu.obs import profile as _profile
        schema = self.plan.schema()
        profiling = _profile.enabled()
        # the device→host materialization is pure arrow↔jax conversion:
        # attributed to the root plan node's "convert" host bucket
        convert_c = (self.ctx.metrics_for(self.plan)
                     .counter("elapsed_host_convert")
                     if profiling else None)
        source = self.batches()
        pipelined = self.ctx.pipelined
        if pipelined:
            from auron_tpu.runtime import pipeline
            source = pipeline.lookahead(source, depth=1)
        fence_sink = (self.ctx.metrics_for(self.plan)
                      if (pipelined and profiling) else None)
        for batch in source:
            if fence_sink is not None:
                # materialization boundary: wait out batch N's in-flight
                # kernels HERE (N+1 is already dispatched) and book the
                # wait as device time — BEFORE the num_rows readback
                # below silently absorbs it
                _profile.device_fence(batch, fence_sink)
            if int(batch.num_rows) > 0:
                t0 = (time.perf_counter_ns() if convert_c is not None
                      else 0)
                try:
                    rb = to_arrow(batch, schema)
                except NotImplementedError:
                    raise
                except RuntimeError as e:
                    if isinstance(e, errors.AuronError):
                        raise
                    logger.exception(
                        "host materialization failed: stage=%d "
                        "partition=%d task=%d", self.task.stage_id,
                        self.task.partition_id, self.task.task_id)
                    raise errors.classify_runtime(e) from e
                if convert_c is not None:
                    convert_c.add(time.perf_counter_ns() - t0)
                yield rb

    def collect(self) -> pa.Table:
        from auron_tpu.columnar.arrow_bridge import schema_to_arrow
        batches = list(self.arrow_batches())
        if not batches:
            return pa.table(
                {f.name: [] for f in schema_to_arrow(self.plan.schema())},
                schema=schema_to_arrow(self.plan.schema()))
        return pa.Table.from_batches(batches)

    def finalize(self) -> dict:
        """Metric mirror-back (reference: update_metric_node, rt.rs:302-308).
        With profiling on, attaches the trace directory and the per-op
        device-time attribution (the flamegraph's data, queryable)."""
        snap = self.ctx.metrics_snapshot()
        if self._compile_start is not None:
            from auron_tpu.utils import compile_stats
            d = compile_stats.delta(self._compile_start)
            snap["xla_compiles"] = d.count
            snap["xla_compile_seconds"] = round(d.seconds, 4)
        if self._programs_start is not None:
            from auron_tpu.runtime import programs
            now = (programs.query_totals(self._programs_query)
                   if self._programs_query else programs.totals())
            snap["program_builds"] = now.builds - self._programs_start.builds
            snap["program_hits"] = now.hits - self._programs_start.hits
        # recovery counters (robustness plane): attempts/retries from the
        # retry driver, corruption recomputes from the RSS exchange's
        # ctx counters (already under the "recovery" metrics key),
        # fault/watchdog deltas from their monotonic totals
        from auron_tpu.runtime import faults as _faults
        from auron_tpu.runtime import watchdog as _watchdog
        rec = snap.setdefault("recovery", {})
        rec.setdefault("corruption_recomputes", 0)
        rec["attempts"] = self.attempt + 1
        rec["transient_retries"] = self.retry_stats.get(
            "transient_retries", self.attempt)
        # process-level, not a per-task delta: watchdog probes run at
        # Session init (before any task exists), so the meaningful
        # number is how many fallbacks this process has taken in total
        rec["watchdog_fallbacks"] = _watchdog.totals()
        rec["faults_injected"] = _faults.totals() - self._faults_start
        # SPMD plane occupancy (process-level like the watchdog number:
        # the gang ledger spans queries by design — one slot = the mesh)
        try:
            from auron_tpu.parallel import mesh as _mesh
            plane = _mesh.current_plane()
            if plane is not None:
                snap["mesh"] = plane.stats()
        except Exception:   # pragma: no cover - observability only  # graft: disable=GL004 -- observability export is best-effort by contract
            pass
        if getattr(self, "profile_dir", None):
            op_times = {
                op: vals["elapsed_compute"] * 1e-9   # counters are ns
                for op, vals in snap.items()
                if isinstance(vals, dict) and "elapsed_compute" in vals
            }
            snap["profile"] = {
                "trace_dir": self.profile_dir,
                "op_device_time_s": op_times,
                "device_time_total_s": round(sum(op_times.values()), 6),
                "wall_time_s": round(time.time() - self._started, 6),
            }
        return snap


def _retry_backoff_s(attempt: int, base: float, cap: float) -> float:
    """Exponential backoff with FULL jitter (attempt k draws uniform
    from [0, min(cap, base * 2^k)]): concurrently failed partitions
    spread their retries instead of hammering the healing external
    system in lockstep."""
    import random
    if base <= 0:
        return 0.0
    return random.uniform(0.0, min(cap, base * (2.0 ** attempt)))


def _observe_task(rt: "ExecutionRuntime", table: pa.Table,
                  metric_tree=None) -> None:
    """Post-success observability for one task: mirror the per-op metric
    sets onto the positional metric tree (obs/metric_tree — the
    update_metric_node walk) and feed the process registry. Both halves
    are cheap and gated; failures here must never fail a finished
    task."""
    try:
        from auron_tpu.obs import metric_tree as mt
        from auron_tpu.obs import profile as obs_profile
        from auron_tpu.obs import registry as obs_registry
        if metric_tree is not None:
            mt.mirror(metric_tree, rt.plan, rt.ctx)
        # per-op host/device attribution record into auron.trace.dir
        # (profile_<trace>.jsonl — the tools/hotspot_report.py input)
        obs_profile.export_task(rt.ctx, rt.plan)
        if obs_registry.enabled():
            # finalize(), not the raw ctx snapshot: only finalize
            # injects the recovery counters (transient_retries from the
            # retry driver) the registry exists to expose
            obs_registry.observe_task(
                time.time() - rt._started, rt.finalize(),
                output_rows=table.num_rows)
    except Exception:   # pragma: no cover - observability is best-effort
        logger.exception("task observability update failed")


def run_task_with_retries(plan: PhysicalOp, partition: int,
                          num_partitions: int, mem_manager=None,
                          config=None, metric_tree=None,
                          cancel_token=None) -> pa.Table:
    """Run one (plan, partition) task, retrying transient failures at
    partition granularity — the retry driver the reference delegates to
    Spark's task scheduler (SURVEY §5.3; rt.rs's is_task_running checks
    distinguish kill from failure the same way). The engine is
    functional, so an attempt is an exact recompute: sinks are
    retry-idempotent and RSS attempts invalidate, making re-execution
    safe end to end. Each attempt gets a fresh ExecutionRuntime and a
    distinct task_id (attempt number in the low bits, like Spark TIDs).

    Routing is purely the error taxonomy (auron_tpu/errors.py):
    classified errors carry their own ``transient`` verdict — the
    device-compute boundary classifies XLA's ambiguous RuntimeErrors
    before they get here, so NO message-pattern matching happens on the
    retry path. Cancellation is surfaced immediately, never retried
    (and its cancel-to-unwind latency feeds the registry histogram);
    a stall verdict (errors.TaskStalled) retries exactly ONCE.

    ``cancel_token`` (runtime/lifecycle.CancelToken) is the query's
    cancellation registry: checked before every attempt, installed as
    every runtime's cancel_event, and it bounds the backoff sleeps —
    clamped to the remaining deadline budget and woken by a cancel."""
    import time as _time

    from auron_tpu import config as cfg
    from auron_tpu import errors
    from auron_tpu.ops.base import TaskCancelled
    from auron_tpu.runtime import lifecycle

    conf = config if config is not None else cfg.get_config()
    retries = max(0, int(conf.get(cfg.TASK_MAX_RETRIES)))
    backoff = float(conf.get(cfg.TASK_RETRY_BACKOFF_S))
    backoff_cap = float(conf.get(cfg.TASK_RETRY_BACKOFF_MAX_S))
    retry_stats = {"transient_retries": 0, "stall_retries": 0}
    last_err = None
    for attempt in range(retries + 1):
        if cancel_token is not None:
            # a cancel that lands between attempts must not start one
            cancel_token.raise_for_status()
        rt = ExecutionRuntime(
            plan,
            TaskDefinition(partition_id=partition,
                           num_partitions=num_partitions,
                           task_id=partition * 1000 + attempt),
            mem_manager=mem_manager, config=config,
            attempt=attempt, retry_stats=retry_stats,
            cancel_token=cancel_token)
        try:
            table = rt.collect()
            _observe_task(rt, table, metric_tree)
            return table
        except TaskCancelled:
            raise
        except errors.QueryCancelled:
            # classified cancellation (cancel or deadline): surface
            # immediately and record how long the unwind took from the
            # moment the token flipped — the acceptance gate's number
            if cancel_token is not None:
                lifecycle.observe_unwind(
                    cancel_token, kind=cancel_token.reason or "cancel")
            raise
        except errors.TaskStalled as e:
            # the watchdog's verdict is transient ONCE: a wedged
            # external dependency may have healed, but an infinite
            # stall-retry loop would hide a deterministic wedge forever
            lifecycle.observe_unwind(_stall_latency_s(rt), kind="stall")
            if retry_stats["stall_retries"] >= 1 or attempt >= retries:
                raise
            retry_stats["stall_retries"] += 1
            retry_stats["transient_retries"] += 1
            last_err = e
            logger.warning(
                "task attempt %d/%d stalled for partition %d (%s); "
                "retrying once", attempt + 1, retries + 1, partition, e)
            from auron_tpu.obs import trace
            trace.event("task", "task.retry", partition=partition,
                        attempt=attempt, backoff_s=0.0,
                        error=type(e).__name__)
        except Exception as e:         # noqa: BLE001 — retry boundary
            # non-transient classes — plan/schema/engine defects,
            # classified corruption needing a DIFFERENT recovery
            # granularity (ShuffleCorruption → map recompute, not a
            # blind reducer rerun) — surface immediately instead of
            # paying retries+1 full computes; transient classes retry
            if not errors.is_transient(e):
                raise
            last_err = e
            if attempt >= retries:
                break
            retry_stats["transient_retries"] += 1
            if isinstance(e, errors.MeshUnavailable):
                # a device loss that ESCAPED the exchange's in-place
                # demotion (e.g. prior rounds' mesh-resident shards were
                # unreadable too): the retry re-routes against the
                # already-quarantined plane, so name that in the log —
                # this recompute will run host-side, not re-enter the
                # dead chip
                try:
                    from auron_tpu.parallel import mesh as _mesh
                    plane = _mesh.current_plane()
                    quarantined = (plane.quarantined()
                                   if plane is not None else [])
                except Exception:   # pragma: no cover - log best-effort
                    quarantined = []
                logger.warning(
                    "task attempt %d/%d lost a mesh device for "
                    "partition %d (%s); retrying against the "
                    "quarantined plane (quarantined=%s)",
                    attempt + 1, retries + 1, partition, e, quarantined)
            else:
                logger.warning(
                    "task attempt %d/%d failed for partition %d (%s); "
                    "retrying", attempt + 1, retries + 1, partition, e)
            delay = _retry_backoff_s(attempt, backoff, backoff_cap)
            if cancel_token is not None:
                rem = cancel_token.remaining()
                if rem is not None:
                    # never sleep past the deadline budget: a backoff
                    # that outlives the deadline just converts a retry
                    # into a guaranteed DeadlineExceeded later
                    delay = min(delay, rem)
            from auron_tpu.obs import trace
            trace.event("task", "task.retry", partition=partition,
                        attempt=attempt, backoff_s=round(delay, 4),
                        error=type(e).__name__)
            if delay > 0:
                if cancel_token is not None:
                    # interruptible: wakes (and raises) on cancellation
                    # instead of sleeping out the full jittered interval
                    cancel_token.sleep(delay)
                else:
                    _time.sleep(delay)
    raise last_err


def _stall_latency_s(rt: "ExecutionRuntime"):
    """Stall-flag-to-unwind latency of one attempt (None when the
    heartbeat carries no stall timestamp)."""
    hb = getattr(rt.ctx, "heartbeat", None)
    if hb is None or not getattr(hb, "stalled_at_ns", 0):
        return None
    import time as _time
    return (_time.monotonic_ns() - hb.stalled_at_ns) * 1e-9


def collect(plan: PhysicalOp, num_partitions: int = 1,
            mem_manager=None, config=None, metric_tree=None,
            cancel_token=None) -> pa.Table:
    """Run every partition of a plan and concatenate (driver-side
    collect), with per-partition transient-failure retries.
    ``metric_tree`` (obs/metric_tree.build_tree(plan)) accumulates every
    task's per-op metrics positionally — the EXPLAIN ANALYZE source.
    ``cancel_token`` threads the query's cancellation registry through
    every partition's retry driver."""
    from auron_tpu import errors as _errors
    from auron_tpu.runtime import lifecycle as _lifecycle
    from auron_tpu.runtime import scheduler as _scheduler
    # driver progress for the ops plane's /queries table: total stamped
    # up front, done bumped per finished partition (CancelToken carries
    # the counters; a bare Event / None costs nothing). Only the
    # OUTERMOST collect on a token tracks — a nested execute (host-fn
    # child, scalar subquery) rides the ENCLOSING token and must not
    # clobber the parent's progress
    track = (cancel_token is not None
             and getattr(cancel_token, "tasks_total", None) == 0)
    if track:
        cancel_token.tasks_total = num_partitions
        cancel_token.tasks_done = 0
    tables = []
    for p in range(num_partitions):
        # task-level fairness: a token admitted by the concurrent
        # scheduler carries its slot — take the weighted-round-robin
        # turn before each task so running queries interleave instead
        # of one query monopolizing the driver (one getattr for bare
        # tokens / direct collect calls)
        try:
            _scheduler.turn(cancel_token)
        except _errors.QueryCancelled:
            # a cancel landing during the fairness wait still counts
            # on the cancel-latency histogram (run_task_with_retries
            # observes mid-task cancels; this is the between-task site)
            _lifecycle.observe_unwind(
                cancel_token,
                kind=getattr(cancel_token, "reason", None) or "cancel")
            raise
        tables.append(run_task_with_retries(
            plan, p, num_partitions, mem_manager=mem_manager,
            config=config, metric_tree=metric_tree,
            cancel_token=cancel_token))
        if track:
            cancel_token.tasks_done += 1
    return pa.concat_tables(tables)
