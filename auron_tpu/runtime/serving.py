"""Cross-process serving boundary: the live host-attach protocol.

The reference's host engine drives the native runtime per task through
three JNI entry points — callNative (submit a TaskDefinition), nextBatch
(pull one Arrow batch through the FFI), finalizeNative (metrics +
teardown) — JniBridge.java:49-55 driven by
AuronCallNativeWrapper.java:78-190 over rt.rs:76-300. This module is the
same lifecycle WITHOUT a JVM: a length-prefixed framed protocol over a
TCP (or Unix) socket that any process — a Spark executor plugin, a test
client, another language — can speak.

Wire format (all integers little-endian):

    frame  := u8 kind | u32 len | payload[len]
    kinds  : 1 SUBMIT   client→server  TaskDefinition protobuf bytes
             2 BATCH    server→client  one Arrow IPC stream holding one
                                       RecordBatch (self-describing)
             3 DONE     server→client  metrics JSON (finalize)
             4 ERROR    server→client  utf-8 traceback; terminates task
             5 SHUTDOWN client→server  stop serving (tests/admin)

One SUBMIT per connection mirrors the per-task lifecycle of the
reference (each Spark task owns one native execution runtime).
"""

from __future__ import annotations

import io
import json
import socket
import socketserver
import struct
import threading
import traceback

import pyarrow as pa

KIND_SUBMIT = 1
KIND_BATCH = 2
KIND_DONE = 3
KIND_ERROR = 4
KIND_SHUTDOWN = 5

_HDR = struct.Struct("<BI")


def write_frame(sock, kind: int, payload: bytes) -> None:
    sock.sendall(_HDR.pack(kind, len(payload)) + payload)


def read_frame(sock) -> tuple[int, bytes]:
    hdr = _read_exact(sock, _HDR.size)
    kind, ln = _HDR.unpack(hdr)
    return kind, _read_exact(sock, ln)


def _read_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


def _ipc_bytes(rb: pa.RecordBatch) -> bytes:
    out = io.BytesIO()
    with pa.ipc.new_stream(out, rb.schema) as w:
        w.write_batch(rb)
    return out.getvalue()


def _ipc_batch(data: bytes) -> pa.RecordBatch:
    with pa.ipc.open_stream(io.BytesIO(data)) as r:
        return next(iter(r))


class _TaskHandler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            kind, payload = read_frame(self.request)
        except ConnectionError:
            return
        if kind == KIND_SHUTDOWN:
            self.server._shutdown_requested = True
            threading.Thread(target=self.server.shutdown,
                             daemon=True).start()
            return
        if kind != KIND_SUBMIT:
            write_frame(self.request, KIND_ERROR,
                        f"expected SUBMIT, got kind={kind}".encode())
            return
        try:
            self._run_task(payload)
        except Exception:
            try:
                write_frame(self.request, KIND_ERROR,
                            traceback.format_exc(limit=12).encode())
            except OSError:
                pass

    def _run_task(self, task_bytes: bytes) -> None:
        # imported lazily so the server process controls jax platform
        # selection before anything initializes a backend
        from auron_tpu.columnar.arrow_bridge import to_arrow
        from auron_tpu.ir import pb
        from auron_tpu.ir.planner import PlannerContext, plan_from_bytes
        from auron_tpu.runtime.executor import (ExecutionRuntime,
                                                TaskDefinition)
        task = pb.TaskDefinition()
        task.ParseFromString(task_bytes)
        op = plan_from_bytes(task_bytes, PlannerContext())
        rt = ExecutionRuntime(
            op, TaskDefinition(partition_id=task.partition_id,
                               num_partitions=task.num_partitions or 1,
                               stage_id=task.stage_id,
                               task_id=task.task_id))
        for batch in rt.batches():
            rb = to_arrow(batch, op.schema())
            if rb.num_rows:
                write_frame(self.request, KIND_BATCH, _ipc_bytes(rb))
        metrics = rt.finalize()
        write_frame(self.request, KIND_DONE,
                    json.dumps(metrics, default=str).encode())


class AuronServer(socketserver.ThreadingTCPServer):
    """Task-serving endpoint; one engine process serves many host tasks
    concurrently (threaded — batch compute holds the GIL only outside
    XLA execution)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        super().__init__((host, port), _TaskHandler)
        self._shutdown_requested = False

    @property
    def address(self) -> tuple[str, int]:
        return self.server_address

    def serve_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t


class AuronClient:
    """The host-engine side of the protocol: callNative is ``execute``'s
    SUBMIT, nextBatch is the BATCH stream, finalizeNative is DONE."""

    def __init__(self, host: str, port: int, timeout_s: float = 300.0):
        self.addr = (host, port)
        self.timeout_s = timeout_s

    def execute(self, task_bytes: bytes):
        """Submit one TaskDefinition; returns (pa.Table, metrics dict).
        Raises RuntimeError with the remote traceback on engine errors."""
        batches, metrics = [], None
        for kind, payload in self.stream(task_bytes):
            if kind == KIND_BATCH:
                batches.append(_ipc_batch(payload))
            else:
                metrics = json.loads(payload.decode())
        if batches:
            tbl = pa.Table.from_batches(batches)
        else:
            tbl = None
        return tbl, metrics

    def stream(self, task_bytes: bytes):
        """Yield (kind, payload) frames for one task submission."""
        with socket.create_connection(self.addr,
                                      timeout=self.timeout_s) as s:
            write_frame(s, KIND_SUBMIT, task_bytes)
            while True:
                kind, payload = read_frame(s)
                if kind == KIND_ERROR:
                    raise RuntimeError("engine error:\n"
                                       + payload.decode())
                yield kind, payload
                if kind == KIND_DONE:
                    return

    def shutdown(self) -> None:
        with socket.create_connection(self.addr, timeout=10) as s:
            write_frame(s, KIND_SHUTDOWN, b"")


def serve_main(argv=None) -> int:
    """``python -m auron_tpu.runtime.serving --port N`` — run a serving
    engine process (prints the bound port for the parent to scrape)."""
    import argparse
    import os
    import sys
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)
    srv = AuronServer(args.host, args.port)
    print(f"AURON_SERVING {srv.address[0]}:{srv.address[1]}", flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(serve_main())
