"""Cross-process serving boundary: the live host-attach protocol.

The reference's host engine drives the native runtime per task through
three JNI entry points — callNative (submit a TaskDefinition), nextBatch
(pull one Arrow batch through the FFI), finalizeNative (metrics +
teardown) — JniBridge.java:49-55 driven by
AuronCallNativeWrapper.java:78-190 over rt.rs:76-300. This module is the
same lifecycle WITHOUT a JVM: a length-prefixed framed protocol over a
TCP (or Unix) socket that any process — a Spark executor plugin, a test
client, another language — can speak.

Wire format (all integers little-endian):

    frame  := u8 kind | u32 len | payload[len]
    kinds  : 1 SUBMIT      client→server  TaskDefinition protobuf bytes
             2 BATCH       server→client  one Arrow IPC stream holding one
                                          RecordBatch (self-describing)
             3 DONE        server→client  JSON {metrics, schema_ipc b64,
                                          report?} — schema always present
                                          so empty results stay typed
             4 ERROR       server→client  utf-8 traceback; terminates task
             5 SHUTDOWN    client→server  stop serving (tests/admin)
             6 SUBMIT_PLAN client→server  JSON {plan: Spark plan.toJSON
                                          tree, path_rewrites?, partition_id?,
                                          num_partitions?, spark_version?} —
                                          the engine converts AND executes,
                                          the live-attach composition the
                                          reference does in
                                          AuronConverters.scala:209-310 +
                                          JniBridge.callNative
             7 ACK         client→server  consumed one BATCH (flow control)
             8 CANCEL      client→server  tear down the running task
             9 NEED_TABLES server→client  JSON [{table, exec, columns}] —
                                          unconvertible subtrees the host
                                          must execute (ConvertToNative
                                          boundary, AuronConvertStrategy)
            10 TABLE       client→server  u32 name_len | name | Arrow IPC
                                          stream with the subtree's rows
            11 RESUME      client→server  JSON {query_id} — continue a
                                          journaled query after a server
                                          restart (runtime/journal.py);
                                          streams BATCH/DONE like SUBMIT
                                          or answers a structured ERROR
                                          (first line "ResumeUnavailable
                                          reason=...").  Replays the
                                          journaled DRIVING SCOPE: a
                                          Session-journaled ("collect")
                                          query streams every partition
                                          0..N-1 — the dead driver's
                                          fan-out — while a serving-
                                          journaled ("task") one replays
                                          exactly its own partition_id

            14 TRACE       client→server  JSON {trace, parent, role,
                                          pid} — OPTIONAL prefix frame
                                          ahead of SUBMIT/SUBMIT_PLAN/
                                          RESUME carrying the sender's
                                          trace context
                                          (obs/trace.wire_context); the
                                          receiver adopts it so spans
                                          on both sides share one
                                          trace id. Sent only when
                                          auron.trace.{enabled,
                                          propagate} are on AND a trace
                                          is active — the wire is
                                          byte-identical otherwise, and
                                          a receiver with tracing off
                                          just skips the frame
            13 HELLO       client→server  empty payload — replica
                                          registration handshake: one
                                          DONE frame with JSON {pid,
                                          tag, host, port, ops_port,
                                          window, journal_dir} so a
                                          fleet router learns a
                                          replica's liveness identity
                                          (utils/liveness pid+epoch
                                          tag), its ops scrape port,
                                          and its journal dir without
                                          any side channel

CANCEL doubles as a FIRST frame carrying JSON {query_id}: cancel a live
query by id over a fresh connection (DONE {cancelled} on success, a
structured ERROR "UnknownQuery reason=unknown_query_id ..." when the id
is unknown or already finished).

A SUBMIT_PLAN whose JSON carries ``"router_tag": true`` (the fleet
router sets it; extra keys are ignored by older servers, so the client
wire contract is unchanged) receives one EARLY server→client ACK frame
with JSON {query_id, pid} before any BATCH: the router learns the
server-assigned query id (hence the journal stem ``<query_id>_<pid>``)
so it can CANCEL-by-id or RESUME the query on a survivor after this
replica dies mid-stream.

Flow control mirrors rt.rs's bound-1 sync channel, generalized to a
window: the server keeps at most ``window`` un-ACKed BATCH frames in
flight, so a slow host applies backpressure instead of unbounded socket
buffering. A CANCEL frame — or the client closing the socket — stops the
producer within one batch (reference: is_task_running checks,
rt.rs:208-238).

One SUBMIT/SUBMIT_PLAN per connection mirrors the per-task lifecycle of
the reference (each Spark task owns one native execution runtime).
"""

from __future__ import annotations

import base64
import io
import itertools
import json
import os
import queue
import socket
import socketserver
import struct
import threading
import traceback

import pyarrow as pa

from auron_tpu import errors

#: process-unique serving query ids: they key the process-global
#: per-query ledgers (program cache, memmgr), so handlers must not share
_SERVING_QUERY_SEQ = itertools.count(1)

KIND_SUBMIT = 1
KIND_BATCH = 2
KIND_DONE = 3
KIND_ERROR = 4
KIND_SHUTDOWN = 5
KIND_SUBMIT_PLAN = 6
KIND_ACK = 7
KIND_CANCEL = 8
KIND_NEED_TABLES = 9
KIND_TABLE = 10
#: first-frame RESUME: payload JSON {"query_id": ...} (or a bare utf-8
#: query id) — continue a journaled query after a server restart
#: (runtime/journal.py). The server streams the resumed result exactly
#: like a SUBMIT, or answers a STRUCTURED first-line ERROR naming why
#: not (ResumeUnavailable reason=no_journal|corrupt|
#: fingerprint_mismatch|journaling_disabled|ambiguous|missing_source).
KIND_RESUME = 11
#: first-frame STATS: answers one DONE frame with the ops plane's live
#: query table + admission counters + server stats as JSON — the
#: /queries endpoint over the EXISTING wire protocol, for clients
#: behind firewalls that cannot reach the HTTP port (AuronClient.stats)
KIND_STATS = 12
#: first-frame HELLO: the fleet router's registration handshake —
#: answers one DONE frame with this process's identity (pid + liveness
#: tag), serving address, ops port, and journal dir
KIND_HELLO = 13
#: OPTIONAL trace-context prefix frame ahead of SUBMIT/SUBMIT_PLAN/
#: RESUME (fleet-scope observability): JSON {trace, parent, role, pid}
#: from obs/trace.wire_context — the receiver adopts the trace id as
#: its query-span parent (obs/trace.wire_scope), so client, router and
#: replica exports stitch into ONE timeline. Never sent unless
#: auron.trace.enabled + auron.trace.propagate are on and a trace is
#: active.
KIND_TRACE = 14

#: max un-ACKed BATCH frames in flight (rt.rs uses a bound-1 channel; a
#: small window amortizes the network round trip without losing the
#: backpressure property)
DEFAULT_WINDOW = 4

_HDR = struct.Struct("<BI")


def write_frame(sock, kind: int, payload: bytes) -> None:
    sock.sendall(_HDR.pack(kind, len(payload)) + payload)


def _journal_error_frame(e) -> bytes:
    """ERROR payload for a JournalError verdict: ONE machine-parseable
    first line (``<Type> reason=<reason> query_id=<id>``) ahead of the
    human message — the single formatter every by-id control path uses
    (RESUME refusals, CANCEL-by-id unknowns), so the wire contract
    cannot drift between them."""
    return (f"{type(e).__name__} reason={e.reason or 'error'} "
            f"query_id={e.query_id or ''}\n{e}").encode()


def parse_shed(text: str):
    """``(reason, retry_after_s)`` parsed from a structured
    ``AdmissionRejected`` ERROR payload's first line, or None when the
    text is not a shed.  ONE parser for every consumer of the shed
    contract — the client's ``retry_sheds`` fallback and the fleet
    router's spill-over — so the wire format cannot drift between
    them.  ``retry_after_s`` is None when the server had no estimate
    (the literal ``None`` the f-string emits)."""
    first = text.splitlines()[0] if text else ""
    if not first.startswith("AdmissionRejected"):
        return None
    reason, retry = "unknown", None
    for tok in first.split()[1:]:
        key, _, val = tok.partition("=")
        if key == "reason":
            reason = val
        elif key == "retry_after_s":
            try:
                retry = float(val)   # graft: disable=GL001 -- parsing a wire-protocol token, host data
            except ValueError:
                retry = None
    return reason, retry


def read_frame(sock) -> tuple[int, bytes]:
    hdr = _read_exact(sock, _HDR.size)
    kind, ln = _HDR.unpack(hdr)
    return kind, _read_exact(sock, ln)


def _read_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


def _ipc_bytes(rb: pa.RecordBatch) -> bytes:
    out = io.BytesIO()
    with pa.ipc.new_stream(out, rb.schema) as w:
        w.write_batch(rb)
    return out.getvalue()


def _ipc_table(data: bytes) -> pa.Table:
    with pa.ipc.open_stream(io.BytesIO(data)) as r:
        return r.read_all()


def _ipc_batch(data: bytes) -> pa.RecordBatch:
    with pa.ipc.open_stream(io.BytesIO(data)) as r:
        return next(iter(r))


def _schema_ipc_b64(schema: pa.Schema) -> str:
    return base64.b64encode(schema.serialize().to_pybytes()).decode()


def _schema_from_b64(b64: str) -> pa.Schema:
    return pa.ipc.read_schema(pa.py_buffer(base64.b64decode(b64)))


class _TaskHandler(socketserver.BaseRequestHandler):
    def setup(self):
        # the handler's cancel registry IS a query CancelToken: the
        # CANCEL frame, a client disconnect, and a request deadline all
        # flip the SAME token the execution runtime polls — socket-level
        # and API-level cancel are one mechanism (runtime/lifecycle.py)
        from auron_tpu.runtime.lifecycle import CancelToken
        self._cancel = CancelToken(
            query_id=f"serving-{next(_SERVING_QUERY_SEQ)}")
        self._window = threading.Semaphore(
            getattr(self.server, "window", DEFAULT_WINDOW))
        self._tables: queue.Queue = queue.Queue()
        self._reader = None

    def handle(self):
        try:
            kind, payload = read_frame(self.request)
            self._wire_ctx = None
            if kind == KIND_TRACE:
                # optional trace-context prefix (fleet observability):
                # adopt it around the REAL first frame that follows; a
                # malformed payload degrades to no adoption, never an
                # error — telemetry must not fail a query
                try:
                    ctx = json.loads(payload.decode() or "{}")
                    if isinstance(ctx, dict):
                        self._wire_ctx = ctx
                except (ValueError, UnicodeDecodeError):
                    pass
                kind, payload = read_frame(self.request)
        except ConnectionError:
            return
        if kind == KIND_SHUTDOWN:
            self.server._shutdown_requested = True
            threading.Thread(target=self.server.shutdown,
                             daemon=True).start()
            return
        if kind == KIND_CANCEL:
            # first-frame CANCEL-BY-ID (a reconnecting/admin client
            # cancelling a query it no longer holds the socket for):
            # a live id cancels and DONEs; an unknown/expired id gets
            # the STRUCTURED verdict, never a generic traceback
            self._cancel_by_id(payload)
            return
        if kind == KIND_STATS:
            self._send_stats()
            return
        if kind == KIND_HELLO:
            self._send_hello()
            return
        if kind not in (KIND_SUBMIT, KIND_SUBMIT_PLAN, KIND_RESUME):
            write_frame(self.request, KIND_ERROR,
                        f"expected SUBMIT, got kind={kind}".encode())
            return
        # from here on, all socket READS belong to the control-reader
        # thread (ACK / CANCEL / TABLE / disconnect); the handler only
        # writes
        self.server.task_started()
        self._reader = threading.Thread(target=self._control_reader,
                                        daemon=True)
        self._reader.start()
        from auron_tpu import errors as _errors
        from auron_tpu.obs import trace as _trace
        self.server.register_query(self._cancel)
        try:
            # adopt the inbound wire trace context (no-op without one):
            # every span this handler thread records — the query scope,
            # task/operator spans — joins the SENDER's trace id
            with _trace.wire_scope(self._wire_ctx):
                if kind == KIND_SUBMIT:
                    self._run_task(payload)
                elif kind == KIND_RESUME:
                    self._run_resume(payload)
                else:
                    self._run_plan_task(payload)
        except _Cancelled:
            self.server.stats["cancelled"] += 1
        except _errors.JournalError as e:
            # resume verdicts carry a machine-readable reason on the
            # STRUCTURED first line (the AdmissionRejected precedent):
            # a reconnecting client learns WHY its query cannot be
            # continued without scraping a traceback
            self.server.stats["resume_refused"] += 1
            try:
                write_frame(self.request, KIND_ERROR,
                            _journal_error_frame(e))
            except OSError:
                pass
        except _errors.AdmissionRejected as e:
            # overload shed: a STRUCTURED first line (machine-parseable
            # reason + retry-after hint) ahead of the message, so a
            # client can back off without scraping a traceback
            self.server.stats["rejected"] += 1
            try:
                write_frame(self.request, KIND_ERROR,
                            (f"AdmissionRejected reason={e.reason} "
                             f"retry_after_s={e.retry_after_s}\n{e}")
                            .encode())
            except OSError:
                pass
        except Exception:
            try:
                write_frame(self.request, KIND_ERROR,
                            traceback.format_exc(limit=12).encode())
            except OSError:
                pass
        finally:
            self.server.unregister_query(self._cancel)
            # quiet completion, NOT a cancel: the token must release the
            # control reader without recording a cancel reason/event on
            # every successful request
            self._cancel.finish()
            try:
                # long-lived engine process: bound accumulated XLA
                # programs — but ONLY while no other handler thread is
                # mid-task (clear_caches during a concurrent trace would
                # race the very caches it prunes)
                self.server.task_done_maybe_trim()
            except Exception:   # graft: disable=GL004 -- post-request cache trim is opportunistic; the reply already shipped
                pass

    # -- control plane -----------------------------------------------------

    def _control_reader(self):
        """Reads client frames while the task runs: ACK releases window
        slots, CANCEL / disconnect stop the producer, TABLE feeds
        fallback-boundary rows."""
        try:
            while not self._cancel.is_set():
                kind, payload = read_frame(self.request)
                if kind == KIND_ACK:
                    self._window.release()
                elif kind == KIND_CANCEL:
                    return
                elif kind == KIND_TABLE:
                    (nlen,) = struct.unpack("<I", payload[:4])
                    name = payload[4:4 + nlen].decode()
                    self._tables.put((name, _ipc_table(payload[4 + nlen:])))
                else:
                    return   # protocol violation: treat as disconnect
        except Exception:   # graft: disable=GL004 -- reader teardown: dead peer/malformed frame ends the loop; the finally cancels the task
            pass   # malformed frame / peer went away: stop computing
        finally:
            # EVERY mid-task reader exit must cancel: a live handler
            # with a dead reader would otherwise spin on the window
            # semaphore forever. After the handler already finished
            # (token released quietly) there is nothing to cancel — a
            # post-DONE socket close must not record a spurious one.
            if not self._cancel.is_set():
                self._cancel.set()

    def _send_batch(self, rb: pa.RecordBatch) -> None:
        """Backpressured BATCH send; raises _Cancelled when the client
        cancelled or disconnected instead of writing into the void. A
        DEADLINE that expires while blocked on the window (slow or
        stopped consumer) raises the classified DeadlineExceeded so the
        client still gets the ERROR frame — the budget verdict must be
        visible even when the task itself never got to poll."""

        def stop():
            if self._cancel.reason == "deadline":
                self._cancel.raise_for_status()
            raise _Cancelled()

        while not self._window.acquire(timeout=0.1):
            if self._cancel.is_set():
                stop()
        if self._cancel.is_set():
            stop()
        try:
            write_frame(self.request, KIND_BATCH, _ipc_bytes(rb))
            self.server.stats["batches_sent"] += 1
        except OSError:
            raise _Cancelled()

    @staticmethod
    def _parse_query_id(payload: bytes) -> str:
        """Query id from a by-id control frame: JSON ``{"query_id"}``
        or a bare utf-8 id.  ONE definition for both CANCEL-by-id and
        RESUME so the wire contract cannot drift between them."""
        try:
            req = json.loads(payload.decode() or "{}")
            return req.get("query_id", "") if isinstance(req, dict) \
                else str(req)
        except (ValueError, UnicodeDecodeError):
            return payload.decode("utf-8", "replace").strip()

    def _send_stats(self) -> None:
        """First-frame STATS: one DONE frame carrying the live query
        table (every scheduler in the process — the ops plane's
        /queries body), this server's admission stats and wire
        counters, and the ops endpoint's port when it is running — so
        a client that can reach the serving socket needs no second
        port to observe the process."""
        from auron_tpu.obs import ops_server as _ops
        from auron_tpu.runtime import scheduler as sched_mod
        body = {
            "queries": sched_mod.aggregate_query_table(),
            "admission": self.server.scheduler.stats(),
            "server": dict(self.server.stats),
        }
        try:
            from auron_tpu.cache import aot as _aot
            from auron_tpu.cache import result_cache as _rcache
            body["cache"] = _rcache.get_cache().stats()
            body["aot"] = _aot.last_stats()
        except Exception:   # graft: disable=GL004 -- stats tee is best-effort
            pass
        try:
            from auron_tpu.obs import ledger as _ledger
            body["cost_ledgers"] = _ledger.recent(16)
        except Exception:   # graft: disable=GL004 -- stats tee is best-effort
            pass
        ops = _ops.current()
        if ops is not None:
            body["ops_port"] = ops.port
        try:
            write_frame(self.request, KIND_DONE,
                        json.dumps(body, default=str).encode())
        except OSError:   # pragma: no cover - client went away
            pass

    def _send_hello(self) -> None:
        """First-frame HELLO: the fleet router's registration
        handshake. One DONE frame carrying this process's pid AND its
        liveness tag (host:pid:epoch — the router's provably-dead
        verdict needs the epoch, a recycled pid must not mask a death),
        the serving address, the ops scrape port, and the journal dir
        (empty when journaling is off) so the router knows whether
        failover can RESUME here or must re-execute."""
        from auron_tpu.runtime import journal as _jrn
        from auron_tpu.utils import liveness
        body = {
            "pid": os.getpid(),
            "tag": liveness.own_tag(),
            "host": self.server.address[0],
            "port": self.server.address[1],
            "window": getattr(self.server, "window", DEFAULT_WINDOW),
            "journal_dir": _jrn.journal_dir() or "",
            "ops_port": self.server.stats.get("ops_port"),
        }
        try:
            write_frame(self.request, KIND_DONE,
                        json.dumps(body).encode())
        except OSError:   # pragma: no cover - router went away
            pass

    def _cancel_by_id(self, payload: bytes) -> None:
        """First-frame CANCEL with a query-id payload: cancel another
        connection's live query on this server, or answer the
        structured ``UnknownQuery`` verdict."""
        qid = self._parse_query_id(payload)
        token = self.server.find_query(qid)
        if token is None:
            from auron_tpu import errors as _errors
            verdict = _errors.UnknownQuery(
                f"query {qid!r} is not live on this server (unknown "
                "id, or it already finished — cancel-after-DONE is a "
                "no-op)", query_id=qid, reason="unknown_query_id")
            try:
                write_frame(self.request, KIND_ERROR,
                            _journal_error_frame(verdict))
            except OSError:
                pass
            return
        token.cancel()
        try:
            write_frame(self.request, KIND_DONE,
                        json.dumps({"cancelled": qid}).encode())
        except OSError:
            pass

    # -- task execution ----------------------------------------------------

    def _run_task(self, task_bytes: bytes) -> None:
        from auron_tpu.ir.planner import PlannerContext
        self._execute(task_bytes, PlannerContext(), report=None)

    def _run_resume(self, payload: bytes) -> None:
        """RESUME: continue a journaled query after a server restart.
        The journal is loaded + validated (classified JournalError
        verdicts reach handle()'s structured ERROR frame), bound to
        this handler's token, and the journaled TaskDefinition replays
        through the normal execute path — satisfied exchanges skip
        their map sides, reducers fetch the journaled RSS files, and
        the client receives the continued stream exactly as a fresh
        SUBMIT would have delivered it."""
        from auron_tpu import config as cfg
        from auron_tpu import errors
        from auron_tpu.ir.planner import PlannerContext
        from auron_tpu.runtime import journal as jrn
        qid = self._parse_query_id(payload)
        conf = cfg.get_config()
        if not jrn.enabled(conf):
            raise errors.ResumeUnavailable(
                "journaling is disabled on this server "
                "(auron.journal.dir is empty)", query_id=qid,
                reason="journaling_disabled")
        jr = jrn.load_for_resume(jrn.journal_dir(conf), qid, {}, conf)
        # replay the journaled DRIVING SCOPE: a Session-journaled query
        # ("collect") streams every partition 0..N-1 — the driver that
        # owned the fan-out is dead, so the server takes its place; a
        # serving-journaled task ("task") replays exactly its own
        # partition_id (the host engine still owns the other tasks)
        parts = (list(range(jr.num_partitions))
                 if jr.scope == "collect" else None)
        try:
            # attach INSIDE the guard: a failed reopen (ENOSPC, the
            # file raced away) must release the open-stem/.claim too,
            # or the query is unresumable until this server restarts
            jrn.attach_resumed(self._cancel, jr)
            self._execute(jr.plan_bytes, PlannerContext(), report=None,
                          journal=jr, partitions=parts)
        except BaseException:
            # _execute suspends the journal only once INSIDE its slot;
            # an AdmissionRejected from the acquire (or any pre-slot
            # unwind) would otherwise leave the stem claimed 'open'
            # forever — suspend here too, idempotently
            jr.suspend()
            raise

    def _run_plan_task(self, payload: bytes) -> None:
        """SUBMIT_PLAN: convert a raw host plan server-side through the
        adaptor SPI (default: Spark plan.toJSON via SparkAdaptor), source
        any ConvertToNative boundaries from the client, execute."""
        from auron_tpu.integration.adaptor import SparkAdaptor, get_adaptor
        from auron_tpu.ir import pb
        from auron_tpu.ir.planner import PlannerContext
        req = json.loads(payload.decode())
        rewrites = req.get("path_rewrites") or {}
        # request-scoped deadline: arrives on the SUBMIT_PLAN frame so
        # the server enforces it even when the client vanishes
        timeout_s = req.get("timeout_s")
        if timeout_s:
            self._cancel.arm_deadline(float(timeout_s))
        if req.get("router_tag"):
            # fleet-router registration: echo the server-assigned query
            # id (and pid — together the journal stem) EARLY, before
            # any admission/planning work, so the router can CANCEL or
            # journal-RESUME this query even if the replica dies before
            # its first BATCH. Plain clients never set the key and the
            # server never volunteers the frame — the wire protocol is
            # unchanged for them.
            try:
                write_frame(self.request, KIND_ACK,
                            json.dumps({"query_id": self._cancel.query_id,
                                        "pid": os.getpid()}).encode())
            except OSError:
                raise _Cancelled()

        def rewrite(p):
            return rewrites.get(p) or rewrites.get(os.path.basename(p), p)

        name = req.get("adaptor", "spark")
        if name == "spark":
            adaptor = SparkAdaptor(req.get("spark_version", "3.5.0"))
        else:
            adaptor = get_adaptor(name)
        node, report = adaptor.convert_plan(req["plan"],
                                            path_rewrite=rewrite)

        catalog = {}
        if report.boundaries:
            need = [{"table": t, "exec": cls,
                     "columns": [a.name for a in attrs]}
                    for t, cls, attrs in report.boundaries]
            write_frame(self.request, KIND_NEED_TABLES,
                        json.dumps(need).encode())
            expected = {n["table"] for n in need}
            for _ in need:
                while True:
                    try:
                        name, tbl = self._tables.get(timeout=0.1)
                        break
                    except queue.Empty:
                        if self._cancel.is_set():
                            raise _Cancelled()
                # validate at receive time: a misnamed/duplicate TABLE
                # frame fails loudly here, not as an opaque missing-table
                # error mid-execution
                if name not in expected:
                    raise ValueError(
                        f"TABLE frame {name!r} does not match any "
                        f"requested boundary (outstanding: "
                        f"{sorted(expected)})")
                expected.discard(name)
                catalog[name] = tbl

        task_bytes = pb.TaskDefinition(
            plan=node,
            partition_id=int(req.get("partition_id", 0)),
            num_partitions=int(req.get("num_partitions", 1)),
        ).SerializeToString()
        self._execute(task_bytes, PlannerContext(catalog=catalog),
                      report={"converted": len(report.tags)
                              - len(report.never_converted),
                              "fallbacks": [
                                  {"exec": cls, "reason": reason}
                                  for cls, reason in
                                  report.never_converted],
                              "summary": report.summary()})

    def _execute(self, task_bytes: bytes, planner_ctx, report,
                 journal=None, partitions=None) -> None:
        """End-to-end observation wrapper around the execution body:
        every exit — DONE, shed, cancel, deadline, failure — lands on
        the ``auron_query_duration_seconds{outcome}`` histogram, and a
        classified failure writes its post-mortem bundle from THIS
        unwind (the serving half of the Session contract)."""
        import time as _time

        from auron_tpu.obs import bundle as _bundle
        from auron_tpu.obs import registry as _obs_registry

        def observe(exc) -> None:
            try:
                _obs_registry.observe_query(
                    _time.monotonic() - t0,
                    _obs_registry.classify_outcome(exc),
                    served_from=getattr(self._cancel, "served_from",
                                        None))
            except Exception:   # pragma: no cover  # graft: disable=GL004 -- per-query outcome telemetry is best-effort
                pass

        t0 = _time.monotonic()
        try:
            self._execute_inner(task_bytes, planner_ctx, report,
                                journal=journal, partitions=partitions)
        except BaseException as e:
            _bundle.maybe_write(e, token=self._cancel,
                                scheduler=self.server.scheduler)
            observe(e)
            raise
        else:
            observe(None)

    def _execute_inner(self, task_bytes: bytes, planner_ctx, report,
                       journal=None, partitions=None) -> None:
        # imported lazily so the server process controls jax platform
        # selection before anything initializes a backend
        from auron_tpu.columnar.arrow_bridge import (schema_to_arrow,
                                                     to_arrow)
        from auron_tpu.ir import pb
        from auron_tpu.ir.planner import plan_from_bytes
        from auron_tpu import errors
        from auron_tpu.ops.base import TaskCancelled
        from auron_tpu.runtime import lifecycle
        from auron_tpu.runtime.executor import (ExecutionRuntime,
                                                TaskDefinition)
        # admission control BEFORE any plan building: the server's
        # scheduler bounds concurrent executing tasks; past the bounded
        # queue (or a breached registry signal) this request is shed
        # with AdmissionRejected — mapped to a structured ERROR frame by
        # handle(). A CANCEL frame / client disconnect / deadline expiry
        # WHILE QUEUED dequeues here and tears down silently: no
        # runtime, no consumer or spill ledger entry ever exists.
        try:
            slot = self.server.scheduler.acquire(self._cancel)
        except errors.DeadlineExceeded:
            # ordering matters: DeadlineExceeded IS-A QueryCancelled,
            # and a deadline expiring WHILE QUEUED is just as much a
            # client-visible budget verdict as one mid-stream — it must
            # reach the ERROR frame, not vanish as a silent cancel
            lifecycle.observe_unwind(self._cancel, kind="deadline")
            raise
        except (TaskCancelled, errors.QueryCancelled):
            # queue-phase cancels feed the same cancel-latency
            # histogram as mid-execution ones — the acceptance gate
            # reads it as covering every cancel class
            lifecycle.observe_unwind(
                self._cancel, kind=self._cancel.reason or "cancel")
            raise _Cancelled()
        self._cancel.slot = slot
        prev_bind = lifecycle.bind_token(self._cancel)
        import time as _time

        from auron_tpu.obs import ledger as _ledger
        ledger_on = _ledger.enabled()
        t_led = _time.monotonic()
        snaps: list = []
        rows_sent = batches_sent = 0
        jr = journal
        cache_key = None

        def _finish_ledger(outcome: str) -> dict:
            # the per-query accounting record (obs/ledger.py): stashed
            # on the token (the bundle writer reads it), retained in
            # the process ring (STATS frame / AuronClient.stats), and
            # — on success — ridden on the DONE frame
            led = _ledger.build(
                snaps, query_id=self._cancel.query_id, rows=rows_sent,
                batches=batches_sent, partitions=len(snaps),
                wall_s=_time.monotonic() - t_led,
                cache_hit=getattr(self._cancel, "served_from",
                                  None) == "cache",
                served_from=getattr(self._cancel, "served_from",
                                    None) or "",
                outcome=outcome)
            self._cancel.cost_ledger = led
            _ledger.record(led)
            return led
        try:
            task = pb.TaskDefinition()
            task.ParseFromString(task_bytes)
            # warm-path lookup (auron_tpu/cache) BEFORE journal/plan
            # work — plain SUBMITs only (a RESUME or pre-adopted
            # journal means committed partial state exists and must be
            # driven to completion, not shadowed by a cached answer)
            from auron_tpu.cache import result_cache as _rcache
            cache = _rcache.get_cache()
            if journal is None and partitions is None:
                cache_key = cache.result_key(
                    task_bytes, planner_ctx.catalog, scope="task",
                    partition=task.partition_id)
            if cache_key is not None:
                hit = cache.get_result(cache_key)
                if hit is not None:
                    self._cancel.served_from = "cache"
                    self._cancel.tasks_total = 1
                    for rb in hit.to_batches():
                        if rb.num_rows:
                            self._send_batch(rb)
                            rows_sent += rb.num_rows
                            batches_sent += 1
                    self._cancel.tasks_done = 1
                    # the flag rides the first RESPONSE frame the
                    # protocol can carry it in: BATCH frames are raw
                    # Arrow IPC, so that is DONE (and for an empty
                    # result DONE literally IS the first frame)
                    done = {"metrics": {"cache_hit": True},
                            "cache_hit": True,
                            "schema_ipc": _schema_ipc_b64(hit.schema)}
                    if report is not None:
                        done["report"] = report
                    if ledger_on:
                        done["cost_ledger"] = _finish_ledger("ok")
                    write_frame(self.request, KIND_DONE,
                                json.dumps(done, default=str).encode())
                    return
            if jr is None:
                # journal this served task (when auron.journal.dir is
                # armed) so a server restart can RESUME it — the
                # reconnect contract; a None return degrades to the
                # pre-journal posture
                from auron_tpu.runtime import journal as jrn
                jr = jrn.begin(self._cancel, task_bytes,
                               task.num_partitions or 1,
                               planner_ctx.catalog, scope="task")
            op = plan_from_bytes(task_bytes, planner_ctx)
            # SUBMIT serves the host engine's one-task-per-partition
            # model (one runtime at task.partition_id); RESUME of a
            # collect-scoped journal passes the full partition list —
            # the dead driver's fan-out — streamed in partition order
            # so the reassembled stream is bit-identical to what the
            # driver would have collected
            parts = (partitions if partitions is not None
                     else [task.partition_id])
            # /queries task progress (the token is this handler's
            # CancelToken — one query per connection, so no nested
            # ownership question like the Session collect path)
            self._cancel.tasks_total = len(parts)
            self._cancel.tasks_done = 0
            cached_batches = [] if cache_key is not None else None
            # the handler's cancel TOKEN is the task's cancellation
            # registry: operators polling between child batches unwind
            # even MID-operator, not just between output batches
            try:
                for p in parts:
                    rt = ExecutionRuntime(
                        op, TaskDefinition(
                            partition_id=p,
                            num_partitions=task.num_partitions or 1,
                            stage_id=task.stage_id,
                            task_id=task.task_id),
                        cancel_token=self._cancel)
                    for batch in rt.batches():
                        rb = to_arrow(batch, op.schema())
                        if rb.num_rows:
                            self._send_batch(rb)
                            rows_sent += rb.num_rows
                            batches_sent += 1
                            if cached_batches is not None:
                                cached_batches.append(rb)
                    snaps.append(rt.finalize())
                    self._cancel.tasks_done += 1
            except errors.DeadlineExceeded:
                # a deadline is a CLIENT-VISIBLE verdict (ERROR frame
                # with the classified type), unlike a cancel (silent
                # teardown)
                lifecycle.observe_unwind(self._cancel, kind="deadline")
                raise
            except (TaskCancelled, errors.QueryCancelled):
                lifecycle.observe_unwind(
                    self._cancel, kind=self._cancel.reason or "cancel")
                raise _Cancelled()
            metrics = (snaps[0] if len(snaps) == 1
                       else {"num_partitions": len(snaps),
                             "per_partition": snaps})
        except BaseException:
            if ledger_on:
                try:
                    # partial ledger: whatever the finished partitions
                    # cost rides the token into the failure bundle
                    _finish_ledger("failed")
                except Exception:   # graft: disable=GL004 -- ledger assembly must never shadow the real failure
                    pass
            if jr is not None:
                # a failed/cancelled/died-mid-stream serving task keeps
                # its journal: the RESUME frame's inventory
                jr.suspend()
            raise
        finally:
            lifecycle.bind_token(prev_bind)
            slot.release()
            from auron_tpu.runtime import programs
            programs.pop_query(self._cancel.query_id)
        if jr is not None:
            jr.complete(write_report=True)
        if cache_key is not None:
            import pyarrow as _pa
            arrow_schema = schema_to_arrow(op.schema())
            cache.put_result(cache_key, _pa.Table.from_batches(
                cached_batches, schema=arrow_schema) if cached_batches
                else arrow_schema.empty_table())
        from auron_tpu.cache import aot as _aot
        _aot.record_plan(task_bytes, planner_ctx.catalog,
                         task.num_partitions or 1)
        done = {"metrics": metrics,
                "schema_ipc": _schema_ipc_b64(schema_to_arrow(op.schema()))}
        if report is not None:
            done["report"] = report
        if ledger_on:
            done["cost_ledger"] = _finish_ledger("ok")
        write_frame(self.request, KIND_DONE,
                    json.dumps(done, default=str).encode())


class _Cancelled(Exception):
    pass


class AuronServer(socketserver.ThreadingTCPServer):
    """Task-serving endpoint; one engine process serves many host tasks
    concurrently (threaded — batch compute holds the GIL only outside
    XLA execution)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 window: int = DEFAULT_WINDOW):
        super().__init__((host, port), _TaskHandler)
        self._shutdown_requested = False
        self.window = window
        self.stats = {"batches_sent": 0, "cancelled": 0, "rejected": 0,
                      "resume_refused": 0}
        self._active_lock = threading.Lock()
        self._active_tasks = 0
        #: live query tokens by id — the CANCEL-by-id frame's registry
        self._queries_lock = threading.Lock()
        self._live_queries: dict = {}
        # journal startup sweep: a restarted server reclaims its dead
        # predecessor's torn journals/unreferenced RSS run dirs while
        # KEEPING resumable ones — the RESUME frame's inventory
        from auron_tpu.runtime import journal as _jrn
        if _jrn.enabled():
            _jrn.sweep_orphans(_jrn.journal_dir())
        # the serving process's admission plane: handler threads are
        # cheap, EXECUTIONS are not — at most auron.sched.max_concurrent
        # tasks compute concurrently, auron.sched.queue_depth more wait,
        # the rest shed with a structured AdmissionRejected ERROR frame
        from auron_tpu.runtime.scheduler import QueryScheduler
        self.scheduler = QueryScheduler(name="serving")
        # ops plane (obs/ops_server.py): the serving process exposes
        # the same live telemetry endpoint Sessions do — refcounted, so
        # a Session in the same process shares it; the bound port rides
        # the stats dict (and the STATS frame) for discovery
        from auron_tpu.obs import ops_server as _ops_srv
        self._ops = _ops_srv.ensure_started()
        if self._ops is not None:
            self.stats["ops_port"] = self._ops.port

    def register_query(self, token) -> None:
        with self._queries_lock:
            self._live_queries[token.query_id] = token

    def unregister_query(self, token) -> None:
        with self._queries_lock:
            self._live_queries.pop(token.query_id, None)

    def find_query(self, query_id: str):
        """Live CancelToken behind ``query_id``, or None (the
        CANCEL-by-id lookup; expired ids return None by construction —
        tokens unregister when their handler finishes)."""
        with self._queries_lock:
            return self._live_queries.get(query_id)

    def task_started(self) -> None:
        with self._active_lock:
            self._active_tasks += 1

    def task_done_maybe_trim(self) -> None:
        """Decrement the active-task count; when it reaches zero, bound
        accumulated XLA programs (utils/compile_stats.maybe_clear). The
        quiescence check prevents clear_caches from racing another
        handler thread's in-flight trace/compile."""
        with self._active_lock:
            self._active_tasks -= 1
            quiescent = self._active_tasks == 0
        if quiescent:
            from auron_tpu.utils import compile_stats
            compile_stats.maybe_clear()

    def server_close(self) -> None:
        super().server_close()
        # drop the ops-endpoint acquisition (last release stops it)
        if getattr(self, "_ops", None) is not None:
            from auron_tpu.obs import ops_server as _ops_srv
            _ops_srv.release()
            self._ops = None

    @property
    def address(self) -> tuple[str, int]:
        return self.server_address

    def serve_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t


class AuronClient:
    """The host-engine side of the protocol: callNative is ``execute``'s
    SUBMIT, nextBatch is the BATCH stream, finalizeNative is DONE.

    Every socket operation is budgeted: connect attempts retry with
    jittered backoff inside ``timeout_s`` (default: the
    ``auron.client.timeout_s`` knob), and each frame read carries the
    same per-operation timeout — a dead or wedged server surfaces as a
    classified ``RemoteEngineError`` instead of hanging the caller
    forever. ``timeout_s<=0`` restores the legacy block-forever
    behavior."""

    def __init__(self, host: str, port: int,
                 timeout_s: "Optional[float]" = None,
                 connect_retries: int = 3):
        self.addr = (host, port)
        if timeout_s is None:
            from auron_tpu import config as cfg
            timeout_s = cfg.get_config().get(cfg.CLIENT_TIMEOUT_S)
        self.timeout_s = timeout_s if timeout_s and timeout_s > 0 else None
        self.connect_retries = max(0, int(connect_retries))   # graft: disable=GL001 -- constructor argument, host data

    def _connect(self):
        """Deadline-bounded connect with jittered reconnect: up to
        ``connect_retries`` extra attempts inside the ``timeout_s``
        budget (a replica restarting under a supervisor comes back
        within a beat — one refused SYN must not fail the query), then
        the classified ``RemoteEngineError``. The returned socket
        carries the same timeout for every subsequent read/write."""
        if self.timeout_s is None:
            return socket.create_connection(self.addr)
        import random
        import time as _time
        deadline = _time.monotonic() + self.timeout_s
        last = None
        for attempt in range(self.connect_retries + 1):
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                break
            try:
                return socket.create_connection(
                    self.addr, timeout=min(self.timeout_s, remaining))
            except OSError as e:
                last = e
                delay = min(0.05 * (2 ** attempt), 1.0)
                delay *= 0.5 + random.random() / 2   # full jitter
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
                _time.sleep(min(delay, remaining))
        raise errors.RemoteEngineError(
            f"cannot connect to engine at {self.addr[0]}:{self.addr[1]} "
            f"after {self.connect_retries + 1} attempts within the "
            f"{self.timeout_s}s budget (auron.client.timeout_s): {last}")

    def _timeout_error(self) -> errors.RemoteEngineError:
        return errors.RemoteEngineError(
            f"engine at {self.addr[0]}:{self.addr[1]} timed out "
            f"({self.timeout_s}s per-operation budget, "
            "auron.client.timeout_s) — server dead or wedged")

    def execute(self, task_bytes: bytes):
        """Submit one TaskDefinition; returns (pa.Table, metrics dict).
        Empty results return a typed empty table (schema rides DONE).
        Raises RuntimeError with the remote traceback on engine errors."""
        tbl, done = self._drive(KIND_SUBMIT, task_bytes, None)
        return tbl, self._metrics_from_done(done)

    @staticmethod
    def _metrics_from_done(done: dict) -> dict:
        """The metrics view of a DONE body. The per-query cost ledger
        rides DONE at top level (next to metrics — the router augments
        it there without touching engine metrics); surface it in the
        returned dict so callers see one flat observability record."""
        metrics = done.get("metrics", done)
        if "cost_ledger" in done and isinstance(metrics, dict) \
                and metrics is not done:
            metrics = dict(metrics, cost_ledger=done["cost_ledger"])
        return metrics

    def execute_plan(self, plan, path_rewrites=None, partition_id: int = 0,
                     num_partitions: int = 1, spark_version: str = "3.5.0",
                     fallback_provider=None,
                     timeout_s: "Optional[float]" = None,
                     retry_sheds: bool = False):
        """Live attach: submit a raw Spark ``plan.toJSON`` tree (parsed
        JSON list/dict). The engine converts it server-side; when the
        conversion hits unconvertible subtrees it asks back for their
        rows, sourced from ``fallback_provider(table, exec_class,
        columns) -> pa.Table`` (the role NativeHelper/ConvertToNativeExec
        plays host-side in the reference).

        Returns (pa.Table, done dict) where done carries metrics plus the
        conversion report (fallbacks + summary). ``timeout_s`` rides the
        frame as a SERVER-SIDE deadline: the engine's own CancelToken
        enforces it (errors.DeadlineExceeded on the ERROR frame), so the
        budget holds even if this client dies mid-stream.

        ``retry_sheds=True`` opts into honoring the server's
        ``AdmissionRejected retry_after_s=`` hint client-side: sleep
        the hinted interval (jittered, clamped to the remaining
        ``timeout_s``/client budget) and retry ONCE — the single-
        replica fallback of the fleet router's spill-over. Default off:
        a shed stays a structured error for callers that do their own
        backoff."""
        req = {"plan": plan, "partition_id": partition_id,
               "num_partitions": num_partitions,
               "spark_version": spark_version}
        if timeout_s:
            req["timeout_s"] = float(timeout_s)
        if path_rewrites:
            req["path_rewrites"] = dict(path_rewrites)
        payload = json.dumps(req).encode()
        if not retry_sheds:
            return self._drive(KIND_SUBMIT_PLAN, payload,
                               fallback_provider)
        import random
        import time as _time
        budget = timeout_s or self.timeout_s
        deadline = (_time.monotonic() + budget) if budget else None
        try:
            return self._drive(KIND_SUBMIT_PLAN, payload,
                               fallback_provider)
        except errors.RemoteEngineError as e:
            shed = parse_shed(str(e).partition("engine error:\n")[2])
            if shed is None:
                raise
            hint = shed[1] if shed[1] is not None else 0.05
            delay = hint * (0.75 + random.random() / 2)   # jitter
            if deadline is not None:
                delay = min(delay, max(0.0,
                                       deadline - _time.monotonic()))
            _time.sleep(delay)
            return self._drive(KIND_SUBMIT_PLAN, payload,
                               fallback_provider)

    def _drive(self, kind: int, payload: bytes, fallback_provider):
        import contextlib

        from auron_tpu.obs import trace as _trace
        scopes = contextlib.ExitStack()
        wire_ctx = None
        if (kind in (KIND_SUBMIT, KIND_SUBMIT_PLAN, KIND_RESUME)
                and _trace.enabled()):
            # standalone client use (no enclosing Session scope): the
            # conversation becomes its own exported trace; inside a
            # scope it joins the active trace. The fleet.submit span is
            # the parent the remote side's spans hang under.
            if _trace.tracer().current_trace == 0:
                scopes.enter_context(_trace.query_scope("client.drive"))
            scopes.enter_context(_trace.span(
                "fleet", "fleet.submit", kind=kind,
                server=f"{self.addr[0]}:{self.addr[1]}"))
            wire_ctx = _trace.wire_context()
        batches, done = [], None
        with scopes:
            return self._drive_framed(kind, payload, fallback_provider,
                                      wire_ctx, batches)

    def _drive_framed(self, kind, payload, fallback_provider, wire_ctx,
                      batches):
        done = None
        try:
            with self._connect() as s:
                if wire_ctx is not None:
                    write_frame(s, KIND_TRACE,
                                json.dumps(wire_ctx).encode())
                write_frame(s, kind, payload)
                while True:
                    fkind, fpayload = read_frame(s)
                    if fkind == KIND_ERROR:
                        raise errors.RemoteEngineError(
                            "engine error:\n" + fpayload.decode())
                    if fkind == KIND_BATCH:
                        batches.append(_ipc_batch(fpayload))
                        write_frame(s, KIND_ACK, b"")
                    elif fkind == KIND_NEED_TABLES:
                        need = json.loads(fpayload.decode())
                        if fallback_provider is None:
                            raise errors.RemoteEngineError(
                                "engine requested fallback tables "
                                f"{[n['table'] for n in need]} but no "
                                "fallback_provider was given")
                        for ent in need:
                            tbl = fallback_provider(ent["table"],
                                                    ent["exec"],
                                                    ent["columns"])
                            name = ent["table"].encode()
                            sink = io.BytesIO()
                            with pa.ipc.new_stream(sink, tbl.schema) as w:
                                w.write_table(tbl)
                            write_frame(s, KIND_TABLE,
                                        struct.pack("<I", len(name)) + name
                                        + sink.getvalue())
                    elif fkind == KIND_DONE:
                        done = json.loads(fpayload.decode())
                        break
        except TimeoutError as e:
            # socket timeout mid-conversation: the per-operation budget
            # expired with no frame — classify, never hang/raw-OSError
            raise self._timeout_error() from e
        if batches:
            tbl = pa.Table.from_batches(batches)
        elif done and done.get("schema_ipc"):
            tbl = _schema_from_b64(done["schema_ipc"]).empty_table()
        else:
            tbl = None
        return tbl, done

    def resume(self, query_id: str):
        """Continue a journaled query after a server restart (RESUME
        frame): returns (pa.Table, metrics) like ``execute``. A
        non-resumable id raises RuntimeError whose message LEADS with
        the server's structured verdict line
        (``ResumeUnavailable reason=...`` etc.)."""
        tbl, done = self._drive(
            KIND_RESUME, json.dumps({"query_id": query_id}).encode(),
            None)
        return tbl, self._metrics_from_done(done)

    def hello(self) -> dict:
        """Replica registration handshake (HELLO frame): the server's
        identity — {pid, tag, host, port, ops_port, window,
        journal_dir} — consumed by the fleet router at registration
        time (and usable by any supervisor for discovery)."""
        try:
            with self._connect() as s:
                write_frame(s, KIND_HELLO, b"")
                kind, payload = read_frame(s)
        except TimeoutError as e:
            raise self._timeout_error() from e
        if kind == KIND_ERROR:
            raise errors.RemoteEngineError(
                "engine error:\n" + payload.decode())
        return json.loads(payload.decode())

    def stats(self) -> dict:
        """The server's live observability over the wire (STATS frame):
        the /queries table + admission counters + server stats as one
        dict — for clients behind firewalls that cannot reach the ops
        HTTP port. The dict carries ``ops_port`` when the HTTP endpoint
        is also running."""
        try:
            with self._connect() as s:
                write_frame(s, KIND_STATS, b"")
                kind, payload = read_frame(s)
        except TimeoutError as e:
            raise self._timeout_error() from e
        if kind == KIND_ERROR:
            raise errors.RemoteEngineError(
                "engine error:\n" + payload.decode())
        return json.loads(payload.decode())

    def cancel_query(self, query_id: str) -> bool:
        """Cancel a live query BY ID over a fresh connection (the
        reconnect/admin path — no need to hold the original socket).
        True when a live query was cancelled; raises RuntimeError with
        the structured ``UnknownQuery reason=unknown_query_id`` first
        line when the id is unknown or already finished."""
        try:
            with self._connect() as s:
                write_frame(s, KIND_CANCEL,
                            json.dumps({"query_id": query_id}).encode())
                kind, payload = read_frame(s)
        except TimeoutError as e:
            raise self._timeout_error() from e
        if kind == KIND_ERROR:
            raise errors.RemoteEngineError(
                "engine error:\n" + payload.decode())
        return bool(json.loads(payload.decode()).get("cancelled"))

    def stream(self, task_bytes: bytes):
        """Yield (kind, payload) frames for one task submission, ACKing
        each BATCH (legacy-shaped helper used by tests)."""
        with self._connect() as s:
            write_frame(s, KIND_SUBMIT, task_bytes)
            while True:
                kind, payload = read_frame(s)
                if kind == KIND_ERROR:
                    raise errors.RemoteEngineError(
                        "engine error:\n" + payload.decode())
                if kind == KIND_BATCH:
                    write_frame(s, KIND_ACK, b"")
                yield kind, payload
                if kind == KIND_DONE:
                    return

    def shutdown(self) -> None:
        with socket.create_connection(self.addr, timeout=10) as s:
            write_frame(s, KIND_SHUTDOWN, b"")


def serve_main(argv=None) -> int:
    """``python -m auron_tpu.runtime.serving --port N`` — run a serving
    engine process (prints the bound port for the parent to scrape)."""
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--window", type=int, default=DEFAULT_WINDOW)
    args = ap.parse_args(argv)
    # this process IS a replica: stamp every flight/trace export it
    # writes so stitched fleet telemetry stays attributable
    from auron_tpu.obs import flight_recorder as _flight
    _flight.set_role("replica")
    srv = AuronServer(args.host, args.port, window=args.window)
    print(f"AURON_SERVING {srv.address[0]}:{srv.address[1]}", flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(serve_main())
