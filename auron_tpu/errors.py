"""Structured error taxonomy for the recovery plane.

The retry driver (runtime/executor.run_task_with_retries) used to decide
transient-vs-deterministic by matching substrings of RuntimeError
messages — fragile against XLA version drift and impossible to extend
from the durable tiers. This module replaces that with a typed
hierarchy: every recovery-relevant boundary (RSS write/fetch, spill
write/read, device compute, program build, backend init) raises an
``AuronError`` subclass whose ``transient`` attribute IS the retry
decision, and the retry driver routes purely on the taxonomy
(``is_transient``) — no message inspection anywhere on the retry path.

The one place pattern knowledge survives is ``classify_runtime``: the
*device-compute boundary* (ExecutionRuntime._batches_inner) calls it to
split XLA's ambiguous bare RuntimeError into its deterministic
(lowering/shape defect → ``KernelLoweringError``) and transient
(resource/backend blip → ``DeviceExecutionError``) halves at the moment
the error crosses out of the engine. That is classification at the
boundary that owns the ambiguity, not string matching in the scheduler —
the shape Spark's task scheduler + shuffle-integrity layer give the
reference (SURVEY §5.3).

Subclasses double-inherit the legacy builtin class they replace
(``KernelLoweringError`` is-a RuntimeError, ``StorageIOError`` is-a
OSError) so existing ``except`` sites and tests keep working while new
code routes on the taxonomy.
"""

from __future__ import annotations

from typing import Optional


class AuronError(Exception):
    """Base of the engine's classified errors.

    ``transient`` is the retry contract: True means a clean re-execution
    of the failed unit (task attempt, map recompute) can succeed — the
    failure lives outside the plan (IO blip, backend hiccup, corrupted
    durable frame that a recompute rewrites). False means recomputing
    the same unit is guaranteed to fail again (plan/schema/engine
    defect) or must be recovered at a DIFFERENT granularity than a blind
    retry (e.g. ShuffleCorruption needs a map recompute, not a reducer
    rerun), so the retry driver surfaces it immediately.
    """

    #: class-level default; instances may override via __init__
    transient: bool = False
    #: optional fault-plane site name this error was raised at
    site: Optional[str] = None

    def __init__(self, *args, site: Optional[str] = None):
        super().__init__(*args)
        if site is not None:
            self.site = site


# ---------------------------------------------------------------------------
# deterministic classes — retrying cannot succeed
# ---------------------------------------------------------------------------

class PlanError(AuronError):
    """Deterministic plan/schema/engine defect (the no-retry class)."""
    transient = False


class KernelLoweringError(PlanError, RuntimeError):
    """XLA lowering / shape / Mosaic defect: the compiled-program
    analogue of a syntax error. RuntimeError subclass so legacy
    ``except RuntimeError`` sites (and tests matching on the message)
    keep working."""


class InjectedFatalError(PlanError):
    """A fault plan's ``fatal`` kind: a deliberately deterministic
    injected failure (chaos tests assert it is never retried)."""


class BackendInitError(AuronError):
    """Device/backend init or first-compile exceeded the watchdog
    deadline and the CPU fallback also failed (or was disallowed).
    Not transient: an in-process retry re-enters the same wedged
    client (the axon-init failure mode, VERDICT r5)."""
    transient = False


class ShuffleCorruption(AuronError):
    """A committed RSS map-output frame failed its checksum (or carries
    an unknown format version). NOT transient: the bytes on storage are
    stable, so a blind reducer retry re-reads the same corrupt frame —
    recovery is map-output invalidation + map-task recompute, which
    RssShuffleExchangeOp performs itself (it owns the map subtree);
    a foreign-host RssShuffleReadOp surfaces this classified error to
    whoever can reschedule the map."""
    transient = False

    def __init__(self, message: str, *, shuffle_id: Optional[int] = None,
                 map_id: Optional[int] = None, path: Optional[str] = None,
                 site: Optional[str] = None):
        super().__init__(message, site=site)
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.path = path


class ScalarSubqueryError(PlanError, RuntimeError):
    """A scalar subquery used as an expression returned more than one
    row: a deterministic plan/data defect — recomputing the partition
    re-reads the same rows. RuntimeError subclass so legacy ``except
    RuntimeError`` sites (and tests matching on the message) keep
    working."""


class RemoteEngineError(AuronError, RuntimeError):
    """The serving tier's client half received a structured ERROR frame
    the server did not classify further: the failure already happened
    (and was classified, retried, or shed) SERVER-side, so a blind
    client-side retry of the same submission is not the recovery — the
    caller decides. RuntimeError subclass so existing ``except
    RuntimeError``/``pytest.raises(RuntimeError, match='engine error')``
    consumers keep working."""
    transient = False


class ReplicaUnavailable(AuronError):
    """A fleet replica could not serve this submission: connect refused,
    the connection dropped mid-conversation, or the liveness plane's
    pid+epoch verdict says the engine process is dead. TRANSIENT by
    design — the replica's death says nothing about the query, and the
    router's recovery (spill-over to a survivor, or journal-backed
    RESUME) is exactly a retry elsewhere. Only the router raises this;
    a client talking straight to one server keeps seeing
    ``RemoteEngineError``."""
    transient = True

    def __init__(self, *args, replica: Optional[str] = None,
                 reason: Optional[str] = None,
                 site: Optional[str] = None):
        super().__init__(*args, site=site)
        #: "host:port" of the replica that failed
        self.replica = replica
        #: connect | io | dead | hello
        self.reason = reason


# ---------------------------------------------------------------------------
# lifecycle classes — the query lifecycle control plane (PR 8)
# ---------------------------------------------------------------------------

class QueryCancelled(AuronError):
    """The query's CancelToken was flipped (host cancel, serving CANCEL
    frame, or session.cancel(query_id)): the task unwinds cooperatively
    with full resource cleanup. NOT transient — a cancelled query must
    never be silently recomputed; the retry driver surfaces it
    immediately (and the executor treats it as teardown, not failure,
    exactly like the legacy TaskCancelled)."""
    transient = False

    def __init__(self, *args, query_id: Optional[str] = None,
                 site: Optional[str] = None):
        super().__init__(*args, site=site)
        self.query_id = query_id


class DeadlineExceeded(QueryCancelled):
    """The query ran past its deadline (``df.collect(timeout_s=...)`` /
    ``auron.query.deadline_s`` / a serving-frame timeout): same
    cooperative unwind as QueryCancelled, but surfaced to the caller as
    a budget failure rather than swallowed as teardown."""


class TaskStalled(AuronError):
    """The stall watchdog flagged this task silent past
    ``auron.watchdog.stall_timeout_s`` (no heartbeat from the drive
    loop, shuffle frames, or spill consumers). Transient ONCE: the retry
    driver re-runs a stalled task a single time (a wedged external
    dependency may have healed), then surfaces it — an infinite
    stall-retry loop would hide a deterministic wedge forever."""
    transient = True


class MemoryExhausted(AuronError):
    """The memory-pressure degradation ladder ran out of rungs (shrink →
    force-spill → shed) or a per-query quota was breached: THIS query is
    shed with a classified error — never the process. Not transient: an
    immediate identical recompute meets the same pressure; admission
    control / the caller decides when to resubmit."""
    transient = False


class AdmissionRejected(AuronError):
    """The query scheduler refused to ADMIT this query (run queue full,
    queue-wait p99 past the admission threshold, memory used/budget
    ratio past its threshold, or an injected ``sched.admit`` fault):
    the query never started — no executor, no memmgr consumers, no
    durable-tier artifacts exist for it. TRANSIENT by design: this is
    load shedding, not failure — the same query resubmitted after
    ``retry_after_s`` can succeed once the backlog drains. The retry
    driver never sees it (admission happens before any task exists);
    the hint is for the CALLER's backoff."""
    transient = True

    def __init__(self, *args, reason: Optional[str] = None,
                 retry_after_s: Optional[float] = None,
                 site: Optional[str] = None):
        super().__init__(*args, site=site)
        #: queue_full | queue_wait | memory | injected
        self.reason = reason
        #: caller backoff hint (seconds); estimated from the observed
        #: queue-wait distribution when available
        self.retry_after_s = retry_after_s


# ---------------------------------------------------------------------------
# journal classes — the crash-safe query journal (runtime/journal.py)
# ---------------------------------------------------------------------------

class JournalError(AuronError):
    """Base of the query-journal verdicts. NOT transient: a journal
    problem is never recovered by blindly re-running the resume — the
    recovery is always explicit (fall back to a fresh run, or surface
    the structured reason to the caller)."""
    transient = False

    def __init__(self, *args, query_id: Optional[str] = None,
                 reason: Optional[str] = None,
                 site: Optional[str] = None):
        super().__init__(*args, site=site)
        self.query_id = query_id
        #: machine-readable verdict (no_journal | corrupt | ambiguous |
        #: fingerprint_mismatch | journaling_disabled | missing_source)
        self.reason = reason


class JournalCorrupt(JournalError):
    """A journal file failed its per-record CRC, carries an unknown
    format version, or cannot be parsed. The committed RSS data it
    described may be fine, but its inventory is not trustworthy — the
    ONLY safe recovery is a fresh run (which the reuse path performs
    automatically); resume() surfaces this classified verdict so the
    caller decides. Never a wrong answer: a corrupt journal is
    discarded, not believed."""


class JournalInvalidated(JournalError):
    """The journal's plan or source-snapshot fingerprints no longer
    match the live plan/sources (a source file was rewritten, a catalog
    table changed): the journaled shuffle outputs were computed from
    DIFFERENT data, so reusing them would return stale rows. The
    classified invalidation: journal + its RSS run directory are
    garbage-collected and the query must run fresh."""


class ResumeUnavailable(JournalError):
    """``Session.resume`` (or the serving RESUME frame) named a query
    id with no resumable journal behind it: unknown id, already
    completed (journals are deleted at completion), journaling
    disabled, or a plan whose sources this process cannot re-bind.
    Carries the machine-readable ``reason`` the serving tier puts on
    the structured ERROR frame's first line."""


class UnknownQuery(JournalError):
    """A by-id control operation (the serving CANCEL-by-id frame)
    named a query id that is not live on this server: unknown, or
    already finished (cancel-after-DONE is a no-op by contract, but a
    FIRST-frame CANCEL for an id the server never saw deserves a
    structured verdict, not a generic traceback)."""


# ---------------------------------------------------------------------------
# transient classes — a clean re-execution can succeed
# ---------------------------------------------------------------------------

class TransientError(AuronError):
    """Base of the retryable classes."""
    transient = True


class DeviceExecutionError(TransientError, RuntimeError):
    """A device/backend execution failure that is not a deterministic
    lowering defect (resource exhaustion, tunnel hiccup, injected
    device fault): an exact partition recompute can succeed."""


class MeshUnavailable(DeviceExecutionError):
    """A device of the SPMD mesh is lost (or the collective fabric
    failed) mid-exchange: the gang-scheduled ``all_to_all`` round holds
    every chip's in-flight shard in volatile HBM, so the round cannot
    complete on the mesh. Recovery is ROUTE DEMOTION, not a blind rerun
    of the same collective: the exchange re-routes its remaining rounds
    down the existing ladder (``all_to_all`` → host ``device_buffer`` →
    RSS) re-using the lost round's still-live map inputs (inputs are
    never donated into the exchange program by contract), and the plane
    quarantines the device so SUBSEQUENT exchanges rebuild a smaller
    submesh or route host-side (``parallel/exchange.py`` /
    ``parallel/mesh.py``). Transient by type — if it escapes the
    in-place demotion (e.g. the prior rounds' mesh-resident shards are
    unreadable too), a task-level recompute re-routes against the
    already-quarantined plane and succeeds host-side."""

    def __init__(self, *args, device: Optional[int] = None,
                 site: Optional[str] = None):
        super().__init__(*args, site=site)
        #: mesh device index the failure was attributed to (None when
        #: XLA's error carries no device identity)
        self.device = device


class StorageIOError(TransientError, OSError):
    """IO failure against a durable tier (shared-storage RSS root,
    spill directory): the storage substrate heals between attempts.
    OSError subclass so legacy ``except OSError`` sites keep working."""


class RssUnavailableError(StorageIOError):
    """The RSS service root failed a write/flush/commit/fetch."""


class SpillIOError(StorageIOError):
    """A spill-file write/read failed."""


class JournalIOError(StorageIOError):
    """A query-journal append/fsync/load failed at the IO layer. The
    journal plane SWALLOWS this on the write path (journaling degrades
    to off for that query — losing resumability, never the query); the
    load path converts it to the deterministic JournalCorrupt verdict."""


class SpillCorruption(TransientError):
    """A spill frame failed its checksum. Transient at TASK granularity:
    spill files are per-attempt artifacts, so a fresh attempt of the
    same partition rewrites them from source — the retry driver's
    normal recompute is the recovery."""


# ---------------------------------------------------------------------------
# boundary classification
# ---------------------------------------------------------------------------

#: RuntimeError message signatures of XLA's deterministic defect class.
#: Used ONLY by classify_runtime at the device-compute boundary — the
#: retry driver never sees these (formerly executor._NO_RETRY_RUNTIME_
#: PATTERNS, matched inside the retry loop itself).
_XLA_DETERMINISTIC_PATTERNS = (
    "lowering", "invalid argument", "invalid_argument", "mosaic",
    "incompatible shapes", "rank mismatch", "unimplemented",
)

#: RuntimeError signatures of DEVICE LOSS — the failure class where the
#: chip (or the collective fabric between chips) died under a running
#: program, as opposed to the program being wrong. Checked BEFORE the
#: deterministic split: these become ``MeshUnavailable`` so the SPMD
#: exchange's demotion handler (and the plane's quarantine) can route
#: around the dead device instead of retrying into it.
_DEVICE_LOSS_PATTERNS = (
    "device lost", "device unavailable", "device failure",
    "device halted", "device is in an invalid state", "slice health",
    "interconnect", "data transfer failure", "chip unreachable",
)


def classify_runtime(e: RuntimeError) -> BaseException:
    """Classify a bare RuntimeError crossing the device-compute boundary
    into the taxonomy. Deterministic lowering/shape signatures become
    KernelLoweringError (no retry); everything else — XLA wraps
    resource and external-service failures in plain RuntimeError — is
    DeviceExecutionError (retry).

    Taxonomy trap guarded FIRST: ``NotImplementedError`` IS-A
    RuntimeError (and jax raises TypeError-adjacent errors for trace/
    lowering defects), so the deterministic builtin types must be
    checked before the message split — otherwise the engine's
    deliberate unsupported-plan rejections would be re-wrapped as a
    *transient* DeviceExecutionError and retried ``retries+1`` times.
    They return UNCHANGED (``raise classify_runtime(e) from e`` keeps
    the original type) because callers catch them by type to reject
    unsupported plans; ``is_transient`` already routes them
    non-transient by NO_RETRY_TYPES membership."""
    if isinstance(e, NO_RETRY_TYPES):
        return e
    msg = str(e)
    low = msg.lower()
    # device loss outranks the deterministic split: "device lost during
    # lowering cleanup"-style messages are a dead chip, not a plan
    # defect, and must reach the mesh demotion/quarantine path
    if any(p in low for p in _DEVICE_LOSS_PATTERNS):
        return MeshUnavailable(msg)
    if any(p in low for p in _XLA_DETERMINISTIC_PATTERNS):
        return KernelLoweringError(msg)
    return DeviceExecutionError(msg)


#: exception classes that are deterministic plan/schema/engine defects
#: by TYPE: recomputing the partition cannot succeed (ValueError joined
#: in round 6 — shape mismatches, invalid kernel bounds and parse
#: failures are ValueErrors, and retrying them paid retries+1 full
#: computes with misleading "retrying" logs)
NO_RETRY_TYPES = (NotImplementedError, TypeError, AssertionError,
                  KeyError, IndexError, AttributeError, ValueError)


def is_transient(e: BaseException) -> bool:
    """The retry driver's routing function: True when a clean task-level
    recompute may succeed. Routes purely on types — classified errors
    carry their own ``transient`` verdict; bare builtins keep the
    legacy type-based split (NO_RETRY_TYPES fail fast, IO and unknown
    failures retry). No message inspection."""
    if isinstance(e, AuronError):
        return e.transient
    if isinstance(e, NO_RETRY_TYPES):
        return False
    # bare OSError/RuntimeError/Exception: the legacy default — retry
    # (boundaries classify their own errors before they get here; this
    # is the conservative fallback for third-party raises)
    return True
