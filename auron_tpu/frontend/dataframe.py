"""DataFrame DSL → proto plan builder.

Unresolved column names resolve against the child's schema at build time —
the same late binding the reference's converters do against the Spark
plan's output attributes (NativeConverters.scala:95+)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence, Union

import pyarrow as pa

from auron_tpu.columnar.schema import DataType, Field, Schema
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import infer_dtype
from auron_tpu.ir import pb, serde

# ---------------------------------------------------------------------------
# column expressions (unresolved)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Col:
    """Unresolved expression + optional alias."""

    node: Any             # _Ref | ir-builder tree of Cols
    name: Optional[str] = None

    # -- operators ----------------------------------------------------------
    def _bin(self, op, other) -> "Col":
        return Col(("bin", op, self, _wrap(other)))

    def __add__(self, o): return self._bin("+", o)
    def __radd__(self, o): return _wrap(o)._bin("+", self)
    def __sub__(self, o): return self._bin("-", o)
    def __rsub__(self, o): return _wrap(o)._bin("-", self)
    def __mul__(self, o): return self._bin("*", o)
    def __rmul__(self, o): return _wrap(o)._bin("*", self)
    def __truediv__(self, o): return self._bin("/", o)
    def __mod__(self, o): return self._bin("%", o)
    def __eq__(self, o): return self._bin("==", o)      # type: ignore
    def __ne__(self, o): return self._bin("!=", o)      # type: ignore
    def __lt__(self, o): return self._bin("<", o)
    def __le__(self, o): return self._bin("<=", o)
    def __gt__(self, o): return self._bin(">", o)
    def __ge__(self, o): return self._bin(">=", o)
    def __and__(self, o): return self._bin("and", o)
    def __or__(self, o): return self._bin("or", o)
    def __invert__(self): return Col(("not", self))

    def __hash__(self):
        return id(self)

    # -- builders -----------------------------------------------------------
    def alias(self, name: str) -> "Col":
        return Col(self.node, name)

    def cast(self, dtype: DataType, precision: int = 0,
             scale: int = 0) -> "Col":
        return Col(("cast", self, dtype, precision, scale), self.name)

    def is_null(self) -> "Col":
        return Col(("is_null", self))

    def is_not_null(self) -> "Col":
        return Col(("is_not_null", self))

    def isin(self, *values) -> "Col":
        vals = values[0] if len(values) == 1 and isinstance(
            values[0], (list, tuple)) else values
        return Col(("in", self, tuple(vals)))

    def like(self, pattern: str) -> "Col":
        return Col(("like", self, pattern))

    def startswith(self, prefix: str) -> "Col":
        return Col(("startswith", self, prefix))

    def endswith(self, suffix: str) -> "Col":
        return Col(("endswith", self, suffix))

    def contains(self, infix: str) -> "Col":
        return Col(("contains", self, infix))

    def getitem(self, ordinal: int) -> "Col":
        return Col(("index", self, ordinal))

    def asc(self, nulls_first: bool = True) -> "SortCol":
        return SortCol(self, True, nulls_first)

    def desc(self, nulls_first: bool = False) -> "SortCol":
        return SortCol(self, False, nulls_first)

    def out_name(self, default: str = "col") -> str:
        if self.name:
            return self.name
        if isinstance(self.node, str):
            return self.node
        return default


@dataclass(frozen=True)
class SortCol:
    col: Col
    ascending: bool = True
    nulls_first: bool = True


@dataclass(frozen=True)
class AggCol:
    fn: str
    arg: Optional[Col]
    name: Optional[str] = None
    distinct: bool = False

    def alias(self, name: str) -> "AggCol":
        return AggCol(self.fn, self.arg, name, self.distinct)

    def out_name(self, i: int) -> str:
        if self.name:
            return self.name
        argname = self.arg.out_name() if self.arg is not None else ""
        return f"{self.fn}({argname})" if argname else self.fn


@dataclass
class WinFn:
    """One window function spec for DataFrame.window() (the DSL face of
    WindowFunctionP / ops.window.WindowFunctionSpec)."""
    kind: str                   # rank_like | offset | agg
    fn: str
    arg: Optional["Col"] = None
    offset: int = 1
    default: Any = None
    name: Optional[str] = None
    #: ROWS BETWEEN (lo, hi) for agg functions; None = default frame
    frame: Optional[tuple] = None

    def alias(self, name: str) -> "WinFn":
        from dataclasses import replace as _replace
        return _replace(self, name=name)

    def out_name(self, i: int) -> str:
        return self.name or f"{self.fn}_{i}"


def col(name: str) -> Col:
    return Col(name)


def lit(value, dtype: Optional[DataType] = None) -> Col:
    return Col(("lit", value, dtype))


def _wrap(v) -> Col:
    return v if isinstance(v, Col) else lit(v)


def _py_dtype(v) -> DataType:
    if isinstance(v, bool):
        return DataType.BOOL
    if isinstance(v, int):
        return DataType.INT64
    if isinstance(v, float):
        return DataType.FLOAT64
    if isinstance(v, str):
        return DataType.STRING
    raise TypeError(f"cannot infer literal type for {type(v).__name__}")


def resolve(c: Col, schema: Schema) -> ir.Expr:
    """Resolve a Col tree to a bound ir.Expr against ``schema``."""
    n = c.node
    if isinstance(n, str):
        return ir.ColumnRef(schema.index_of(n), n)
    if isinstance(n, ir.Expr):
        return n
    tag = n[0]
    if tag == "lit":
        _, value, dtype = n
        if value is None:
            return ir.Literal(None, dtype or DataType.NULL)
        return ir.Literal(value, dtype or _py_dtype(value))
    if tag == "bin":
        _, op, l, r = n
        return ir.BinaryExpr(op, resolve(l, schema), resolve(r, schema))
    if tag == "not":
        return ir.Not(resolve(n[1], schema))
    if tag == "is_null":
        return ir.IsNull(resolve(n[1], schema))
    if tag == "is_not_null":
        return ir.IsNotNull(resolve(n[1], schema))
    if tag == "cast":
        _, child, dtype, p, s = n
        return ir.Cast(resolve(child, schema), dtype, p, s)
    if tag == "in":
        return ir.InList(resolve(n[1], schema), tuple(n[2]))
    if tag == "like":
        return ir.Like(resolve(n[1], schema), n[2])
    if tag == "startswith":
        return ir.StringStartsWith(resolve(n[1], schema), n[2])
    if tag == "endswith":
        return ir.StringEndsWith(resolve(n[1], schema), n[2])
    if tag == "contains":
        return ir.StringContains(resolve(n[1], schema), n[2])
    if tag == "index":
        return ir.GetIndexedField(resolve(n[1], schema), n[2])
    if tag == "fn":
        _, fname, args = n
        return ir.ScalarFunction(
            fname, tuple(resolve(a, schema) for a in args))
    if tag == "udf":
        _, registry_name, args, dtype = n
        from auron_tpu.exprs import udf as udf_registry
        fn, dt, p, s = udf_registry.lookup_udf(registry_name)
        return ir.HostUDF(fn, tuple(resolve(a, schema) for a in args),
                          dt, registry_name)
    if tag == "subquery":
        _, plan_bytes, dtype, p, s, sid = n
        return ir.ScalarSubquery(plan_bytes, dtype, p, s, sid)
    raise NotImplementedError(f"cannot resolve column node {tag!r}")


_SUBQUERY_IDS = iter(range(1, 1 << 30))


def scalar_subquery(df) -> Col:
    """An uncorrelated scalar subquery over a single-column DataFrame:
    the plan executes once per task and its one value becomes a literal
    (Spark's ScalarSubquery; 0 rows → NULL, >1 rows → runtime error).
    Correlated subqueries must still be rewritten as joins — exactly as
    Spark's own optimizer does before the physical plan exists."""
    if len(df.schema) != 1:
        raise ValueError(
            f"scalar subquery must produce exactly one column, got "
            f"{[f.name for f in df.schema]}")
    f = df.schema[0]
    return Col(("subquery", df.plan.SerializeToString(), f.dtype,
                f.precision, f.scale, next(_SUBQUERY_IDS)))


class _Functions:
    """`functions.upper(col) / functions.sum(col) / ...` — scalar function
    and aggregate builders (reference: datafusion-ext-functions registry +
    agg set)."""

    _AGGS = {"sum", "count", "avg", "min", "max", "first",
             "first_ignores_null", "collect_list", "collect_set"}

    def __getattr__(self, name: str) -> Callable[..., Any]:
        fname = name.rstrip("_")
        if fname in self._AGGS:
            def agg_builder(c: Optional[Col] = None, distinct=False):
                return AggCol(fname, _wrap(c) if c is not None else None,
                              distinct=distinct)
            return agg_builder

        def builder(*args):
            return Col(("fn", fname, tuple(_wrap(a) for a in args)))
        return builder

    def count_star(self) -> AggCol:
        return AggCol("count_star", None)

    # -- window function builders (DataFrame.window) ------------------------

    def row_number(self) -> WinFn:
        return WinFn("rank_like", "row_number")

    def rank(self) -> WinFn:
        return WinFn("rank_like", "rank")

    def dense_rank(self) -> WinFn:
        return WinFn("rank_like", "dense_rank")

    def percent_rank(self) -> WinFn:
        return WinFn("rank_like", "percent_rank")

    def cume_dist(self) -> WinFn:
        return WinFn("rank_like", "cume_dist")

    def ntile(self, n: int) -> WinFn:
        return WinFn("rank_like", "ntile", offset=n)

    def lead(self, c, offset: int = 1, default=None) -> WinFn:
        return WinFn("offset", "lead", _wrap(c), offset, default)

    def lag(self, c, offset: int = 1, default=None) -> WinFn:
        return WinFn("offset", "lag", _wrap(c), offset, default)

    def nth_value(self, c, n: int) -> WinFn:
        return WinFn("offset", "nth_value", _wrap(c), n)

    def first_value(self, c) -> WinFn:
        return WinFn("offset", "first_value", _wrap(c))

    def last_value(self, c) -> WinFn:
        return WinFn("offset", "last_value", _wrap(c))

    def win_agg(self, fn: str, c=None, frame=None) -> WinFn:
        """Running aggregate over the window frame (Spark default frame:
        UNBOUNDED PRECEDING..CURRENT ROW with ORDER BY, else whole
        partition): win_agg("sum", col) / win_agg("count_star").
        ``frame=(lo, hi)`` selects ROWS BETWEEN lo..hi (relative row
        offsets, negative = preceding), e.g. frame=(-1, 1) is the q47/
        q57-class centered moving window."""
        return WinFn("agg", fn, _wrap(c) if c is not None else None,
                     frame=tuple(frame) if frame is not None else None)

    def udf(self, registry_name: str, *args) -> Col:
        return Col(("udf", registry_name, tuple(_wrap(a) for a in args),
                    None))


functions = _Functions()


# ---------------------------------------------------------------------------
# DataFrame
# ---------------------------------------------------------------------------

class GroupedData:
    def __init__(self, df: "DataFrame", keys: Sequence[Col]):
        self.df = df
        self.keys = [_wrap(k) if not isinstance(k, Col) else k
                     for k in keys]

    def _rewrite_wide_distinct(self, aggs) -> Optional["DataFrame"]:
        """count/sum/avg DISTINCT over DECIMAL: plan distinct the way a
        vector engine wants it anyway — an inner regroup on (keys..., arg)
        dedupes the pairs (wide-decimal group keys are first-class since
        the limb-grouping work), then the plain decimal aggregate runs
        over the deduped rows with exact Spark result types
        (sum → decimal(p+10,s), avg → decimal(p+4,s+4) HALF_UP). The
        set-accumulator path cannot do either: its single int64 word
        cannot hold two-limb p>18 values, and its finalizers lose the
        decimal type (float avg). Distributed plans fall out for free:
        the inner agg exchanges on (keys, arg), the outer agg
        re-exchanges on keys. Reference models distinct as expand-to-set
        (agg/acc.rs); Spark similarly regroups distinct aggregates."""
        schema = self.df.schema

        def dec_info(a: AggCol):
            """(rewritable, needs): decimal distinct count/sum/avg can
            join the regroup; it is REQUIRED when the set path cannot
            serve the aggregate — two-limb p>18 values, or sum/avg whose
            set finalizers lose the Spark decimal result type. Narrow
            count-distinct alone stays on the set path (exact there), so
            mixed queries like (count(distinct d), count_star()) keep
            working."""
            if not (a.distinct and a.fn in ("count", "sum", "avg")
                    and a.arg is not None):
                return False, False
            dt, p, _s = infer_dtype(resolve(a.arg, schema), schema)
            if dt != DataType.DECIMAL:
                return False, False
            return True, (p > 18 or a.fn in ("sum", "avg"))

        infos = [dec_info(a) for a in aggs]
        if not any(needs for _r, needs in infos):
            return None
        dec = [a for a, (r, _n) in zip(aggs, infos) if r]
        if len(dec) != len(aggs):
            raise NotImplementedError(
                "DISTINCT over decimal cannot be mixed with other "
                "aggregates in one agg() call: the distinct regroup "
                "rewrite would dedupe the other aggregates' input rows. "
                "Split the decimal-distinct aggregates into their own "
                "agg().")
        arg_reprs = {repr(resolve(a.arg, schema)) for a in dec}
        if len(arg_reprs) > 1:
            raise NotImplementedError(
                "decimal DISTINCT aggregates in one agg() call must "
                "share one argument expression (one regroup dedupes one "
                "column); split differing arguments into separate agg()s.")

        dcol = dec[0].arg.alias("__wd_arg__")
        inner = GroupedData(self.df, list(self.keys) + [dcol]).agg()
        key_names = [k.out_name(f"k{i}") for i, k in enumerate(self.keys)]
        outer_aggs = [AggCol(a.fn, col("__wd_arg__"), name=a.out_name(i))
                      for i, a in enumerate(dec)]
        return GroupedData(inner, [col(n) for n in key_names]).agg(
            *outer_aggs)

    def agg(self, *aggs: AggCol) -> "DataFrame":
        rewritten = self._rewrite_wide_distinct(aggs)
        if rewritten is not None:
            return rewritten
        schema = self.df.schema
        group_exprs = [resolve(k, schema) for k in self.keys]
        group_names = [k.out_name(f"k{i}") for i, k in enumerate(self.keys)]
        agg_fns = [ir.AggFunction(
            a.fn, resolve(a.arg, schema) if a.arg is not None else None,
            a.distinct) for a in aggs]
        agg_names = [a.out_name(i) for i, a in enumerate(aggs)]
        n_keys = len(group_exprs)
        n_part = self.df.num_partitions

        out_partitions = n_part
        out_prov = self.df.partitioning
        if n_part > 1:
            # Spark-shaped two-phase plan: partial agg on every map
            # partition → exchange → final agg (the reference converts
            # HashAggregateExec pairs the same way,
            # AuronConverters.scala convertHashAggregateExec). Keyed aggs
            # hash-exchange on the group keys; a GLOBAL agg (no keys)
            # coalesces every partial row into one partition — without
            # that, each partition would emit its own "global" row.
            partial = pb.PlanNode(agg=pb.AggNode(
                child=self.df.plan,
                group_exprs=[serde.expr_to_proto(e) for e in group_exprs],
                aggs=[serde.agg_to_proto(a) for a in agg_fns],
                mode="partial", group_names=group_names,
                agg_names=agg_names))
            if n_keys > 0:
                part = pb.PartitioningP(
                    kind="hash", num_partitions=n_part,
                    hash_keys=[serde.expr_to_proto(ir.ColumnRef(i))
                               for i in range(n_keys)])
                out_prov = ("hash", tuple(group_names), n_part)
            else:
                part = pb.PartitioningP(kind="single", num_partitions=1)
                out_partitions = 1
                out_prov = ("single",)
            shuffle = pb.PlanNode(shuffle_writer=pb.ShuffleWriterNode(
                child=partial, partitioning=part, input_partitions=n_part))
            node = pb.PlanNode(agg=pb.AggNode(
                child=shuffle,
                group_exprs=[serde.expr_to_proto(ir.ColumnRef(i))
                             for i in range(n_keys)],
                aggs=[serde.agg_to_proto(
                    ir.AggFunction(a.fn, None, a.distinct))
                    for a in agg_fns],
                mode="final", group_names=group_names,
                agg_names=agg_names))
        else:
            node = pb.PlanNode(agg=pb.AggNode(
                child=self.df.plan,
                group_exprs=[serde.expr_to_proto(e) for e in group_exprs],
                aggs=[serde.agg_to_proto(a) for a in agg_fns],
                mode="complete", group_names=group_names,
                agg_names=agg_names))

        # schema via a throwaway op build is overkill; compute directly
        key_fields = []
        for e, nm in zip(group_exprs, group_names):
            dt, p, s = infer_dtype(e, schema)
            key_fields.append(Field(nm, dt, True, p, s))
        out_fields = list(key_fields)
        from auron_tpu.ops.agg import make_acc_spec
        for a, nm in zip(agg_fns, agg_names):
            spec = make_acc_spec(a, schema, "complete")
            out_fields.append(Field(nm, spec.result[0], True,
                                    spec.result[1], spec.result[2],
                                    elem=spec.elem))
        return DataFrame(self.df.session, node, Schema(tuple(out_fields)),
                         out_partitions, out_prov)


class DataFrame:
    def __init__(self, session, plan: pb.PlanNode, schema: Schema,
                 num_partitions: int = 1, partitioning=None):
        self.session = session
        self.plan = plan
        self.schema = schema
        self.num_partitions = num_partitions
        #: output-partitioning provenance, the EnsureRequirements signal:
        #: ("hash", (key names...), n) after repartition-by-key, ("single",)
        #: after a coalescing exchange, else None (unknown layout). Joins
        #: use it to recognize co-partitioned inputs; anything else gets a
        #: broadcast build side.
        self.partitioning = partitioning

    # -- transforms ---------------------------------------------------------

    def filter(self, cond: Col) -> "DataFrame":
        e = resolve(cond, self.schema)
        node = pb.PlanNode(filter=pb.FilterNode(
            child=self.plan, predicates=[serde.expr_to_proto(e)]))
        # row-preserving: the partition layout survives a filter
        return DataFrame(self.session, node, self.schema,
                         self.num_partitions, self.partitioning)

    where = filter

    def _project_provenance(self, exprs, names):
        """Hash-partitioning provenance survives a projection when every
        key column passes through untransformed — possibly renamed, in
        which case the provenance carries the NEW name (the data layout
        is unchanged; only the label moved)."""
        prov = self.partitioning
        if not prov or prov[0] != "hash":
            return None
        renames = {}
        for e, nm in zip(exprs, names):
            if isinstance(e, ir.ColumnRef):
                renames.setdefault(self.schema[e.index].name, nm)
        try:
            new_keys = tuple(renames[k] for k in prov[1])
        except KeyError:
            return None
        return ("hash", new_keys, prov[2])

    def select(self, *cols: Union[str, Col]) -> "DataFrame":
        cs = [col(c) if isinstance(c, str) else c for c in cols]
        exprs = [resolve(c, self.schema) for c in cs]
        names = [c.out_name(f"c{i}") for i, c in enumerate(cs)]
        node = pb.PlanNode(project=pb.ProjectNode(
            child=self.plan, exprs=[serde.expr_to_proto(e) for e in exprs],
            names=names))
        fields = []
        for e, nm in zip(exprs, names):
            dt, p, s = infer_dtype(e, self.schema)
            fields.append(Field(nm, dt, True, p, s))
        return DataFrame(self.session, node, Schema(tuple(fields)),
                         self.num_partitions,
                         self._project_provenance(exprs, names))

    def with_column(self, name: str, c: Col) -> "DataFrame":
        existing = [col(f.name) for f in self.schema]
        return self.select(*existing, c.alias(name))

    def expand(self, projections: Sequence[Sequence[Col]],
               names: Sequence[str]) -> "DataFrame":
        """Emit every projection once per input row (Spark ExpandExec —
        the engine of rollup/cube/grouping sets; reference:
        datafusion-ext-plans/src/expand_exec.rs). The FIRST projection
        determines the output types, so put the most-typed one first."""
        schema = self.schema
        projs = [[resolve(_wrap(c), schema) for c in p]
                 for p in projections]
        node = pb.PlanNode(expand=pb.ExpandNode(
            child=self.plan,
            projections=[pb.ExpandNode.Projection(
                exprs=[serde.expr_to_proto(e) for e in p])
                for p in projs],
            names=list(names)))
        fields = []
        for e, nm in zip(projs[0], names):
            dt, p, s = infer_dtype(e, schema)
            fields.append(Field(nm, dt, True, p, s))
        return DataFrame(self.session, node, Schema(tuple(fields)),
                         self.num_partitions, None)

    def grouping_sets(self, keys: Sequence[Union[str, Col]],
                      sets: Sequence[Sequence[int]]) -> "GroupedData":
        """GROUP BY GROUPING SETS: expand one copy of the input per set,
        null-filling grouped-out keys, and tag ``spark_grouping_id``
        (bit i set = key i rolled up, leftmost key = highest bit — Spark's
        encoding). The grouping id participates in the group keys so a
        natural NULL key stays distinct from a rolled-up one."""
        kcols = [col(k) if isinstance(k, str) else k for k in keys]
        schema = self.schema
        key_names = [k.out_name(f"k{i}") for i, k in enumerate(kcols)]
        n = len(kcols)
        pass_names = list(schema.names)
        out_names = pass_names + [f"{kn}#g" for kn in key_names] \
            + ["spark_grouping_id"]
        null_keys = []
        for k in kcols:
            dt, p, s = infer_dtype(resolve(k, schema), schema)
            null_keys.append(Col(ir.Literal(None, dt, p, s)))
        projections = []
        for st in sets:
            inc = set(st)
            gid = sum(1 << (n - 1 - i) for i in range(n) if i not in inc)
            projections.append(
                [col(c) for c in pass_names]
                + [kcols[i] if i in inc else null_keys[i]
                   for i in range(n)]
                + [lit(gid, DataType.INT32)])
        # the full set must come first: it types the expanded columns
        projections.sort(key=lambda p: sum(
            1 for c in p if isinstance(c.node, ir.Literal)
            and c.node.value is None))
        expanded = self.expand(projections, out_names)
        gkeys = [col(f"{kn}#g").alias(kn) for kn in key_names] \
            + [col("spark_grouping_id")]
        return GroupedData(expanded, gkeys)

    def rollup(self, *keys: Union[str, Col]) -> "GroupedData":
        """GROUP BY ROLLUP(k1..kn): the n+1 prefix grouping sets."""
        n = len(keys)
        return self.grouping_sets(
            keys, [list(range(i)) for i in range(n, -1, -1)])

    def cube(self, *keys: Union[str, Col]) -> "GroupedData":
        """GROUP BY CUBE(k1..kn): all 2^n grouping sets."""
        import itertools
        n = len(keys)
        sets = []
        for r in range(n, -1, -1):
            sets.extend(list(c) for c in
                        itertools.combinations(range(n), r))
        return self.grouping_sets(keys, sets)

    def group_by(self, *keys: Union[str, Col]) -> GroupedData:
        ks = [col(k) if isinstance(k, str) else k for k in keys]
        return GroupedData(self, ks)

    def _to_sort_orders(self, orders) -> list[ir.SortOrder]:
        """str/Col/SortCol → resolved ir.SortOrder (shared by sort and
        window)."""
        sos = []
        for o in orders:
            if isinstance(o, str):
                o = col(o).asc()
            elif isinstance(o, Col):
                o = o.asc()
            sos.append(ir.SortOrder(resolve(o.col, self.schema),
                                    o.ascending, o.nulls_first))
        return sos

    def window(self, funcs: list, partition_by=(), order_by=(),
               group_limit: Optional[int] = None) -> "DataFrame":
        """Append window-function columns (WindowNode → ops/window.py).
        Multi-partition frames hash-exchange on the partition keys first
        (Spark's required child distribution for window execs); an empty
        partition_by coalesces to a single partition."""
        if group_limit is not None and group_limit < 1:
            raise ValueError(f"group_limit must be >= 1, got {group_limit}")
        pbs = [col(k) if isinstance(k, str) else k for k in partition_by]
        sos = self._to_sort_orders(order_by)
        pb_exprs = [resolve(c, self.schema) for c in pbs]
        child = self.plan
        out_partitions = self.num_partitions
        prov = None
        if self.num_partitions > 1:
            if pb_exprs:
                part = pb.PartitioningP(
                    kind="hash", num_partitions=self.num_partitions,
                    hash_keys=[serde.expr_to_proto(e) for e in pb_exprs])
                prov = ("hash", tuple(c.out_name() for c in pbs),
                        self.num_partitions)
            else:
                part = pb.PartitioningP(kind="single", num_partitions=1)
                out_partitions = 1
                prov = ("single",)
            child = pb.PlanNode(shuffle_writer=pb.ShuffleWriterNode(
                child=child, partitioning=part,
                input_partitions=self.num_partitions))
        # ONE spec build; protos and schema both derive from it (keeps the
        # spec's own validation ahead of wire construction)
        from auron_tpu.ops.window import WindowFunctionSpec, _result_field
        names = [f.out_name(i) for i, f in enumerate(funcs)]
        specs = []
        for f in funcs:
            default = None
            if f.default is not None:
                lit_ir = resolve(_wrap(f.default), self.schema)
                if not isinstance(lit_ir, ir.Literal):
                    raise TypeError(
                        f"{f.fn} default must be a literal, got "
                        f"{type(lit_ir).__name__}")
                default = lit_ir
            specs.append((WindowFunctionSpec(
                kind=f.kind, fn=f.fn,
                arg=resolve(f.arg, self.schema) if f.arg is not None
                else None, offset=f.offset,
                default=None if default is None else default.value,
                frame=getattr(f, "frame", None)),
                default))
        fprotos = []
        for (spec, default) in specs:
            wp = pb.WindowFunctionP(kind=spec.kind, fn=spec.fn)
            if spec.arg is not None:
                wp.arg.CopyFrom(serde.expr_to_proto(spec.arg))
            wp.offset = spec.offset
            if default is not None:
                wp.default_value.CopyFrom(
                    serde.expr_to_proto(default).literal)
            if spec.frame is not None:
                wp.frame_lo, wp.frame_hi = spec.frame
            fprotos.append(wp)
        node = pb.PlanNode(window=pb.WindowNode(
            child=child,
            partition_by=[serde.expr_to_proto(e) for e in pb_exprs],
            order_by=[serde.sort_order_to_proto(s) for s in sos],
            functions=fprotos, output_names=names,
            group_limit=-1 if group_limit is None else group_limit))
        extra = [_result_field(spec, nm, self.schema)
                 for (spec, _d), nm in zip(specs, names)]
        out_schema = Schema(tuple(self.schema.fields) + tuple(extra))
        return DataFrame(self.session, node, out_schema, out_partitions,
                         prov)

    def sort(self, *orders: Union[str, Col, SortCol],
             limit: Optional[int] = None) -> "DataFrame":
        sos = self._to_sort_orders(orders)
        so_protos = [serde.sort_order_to_proto(s) for s in sos]
        child = self.plan
        out_partitions = self.num_partitions
        prov = None
        if self.num_partitions > 1:
            # a per-partition sort is not a global sort: top-k runs a
            # MAP-SIDE SortNode(fetch=k) per partition so only
            # n_part * k rows cross the coalescing exchange, then the
            # final top-k; a full sort range-exchanges so per-partition
            # runs concatenate globally ordered (the Spark global-sort /
            # TakeOrdered shape, reference: shuffle/mod.rs:204-279)
            if limit is not None:
                child = pb.PlanNode(sort=pb.SortNode(
                    child=child, sort_orders=so_protos, fetch=limit))
                part = pb.PartitioningP(kind="single", num_partitions=1)
                out_partitions = 1
                prov = ("single",)
            else:
                part = pb.PartitioningP(kind="range",
                                        num_partitions=self.num_partitions,
                                        range_orders=so_protos)
            child = pb.PlanNode(shuffle_writer=pb.ShuffleWriterNode(
                child=child, partitioning=part,
                input_partitions=self.num_partitions))
        node = pb.PlanNode(sort=pb.SortNode(
            child=child, sort_orders=so_protos,
            fetch=-1 if limit is None else limit))
        return DataFrame(self.session, node, self.schema,
                         out_partitions, prov)

    order_by = sort

    def limit(self, n: int) -> "DataFrame":
        child = self.plan
        out_partitions = self.num_partitions
        prov = self.partitioning
        if self.num_partitions > 1:
            # LIMIT is global: a map-side LocalLimit caps each partition
            # at n rows so at most n_part * n rows cross the coalescing
            # exchange, then the global limit truncates (the Spark
            # LocalLimit/GlobalLimit pair)
            child = pb.PlanNode(limit=pb.LimitNode(child=child, limit=n))
            child = pb.PlanNode(shuffle_writer=pb.ShuffleWriterNode(
                child=child,
                partitioning=pb.PartitioningP(kind="single",
                                              num_partitions=1),
                input_partitions=self.num_partitions))
            out_partitions = 1
            prov = ("single",)
        node = pb.PlanNode(limit=pb.LimitNode(child=child, limit=n))
        return DataFrame(self.session, node, self.schema,
                         out_partitions, prov)

    def union(self, other: "DataFrame") -> "DataFrame":
        if other.num_partitions != self.num_partitions:
            raise ValueError(
                "union requires equal partition counts "
                f"({self.num_partitions} vs {other.num_partitions}); "
                "repartition one side first")
        node = pb.PlanNode(union=pb.UnionNode(
            children=[self.plan, other.plan]))
        return DataFrame(self.session, node, self.schema,
                         self.num_partitions)

    def _co_partitioned_with(self, other: "DataFrame", keys: list) -> bool:
        """True when both sides are laid out so probe partition p only
        needs build partition p: both single-partition, or both
        hash-partitioned on exactly the join keys with equal counts."""
        if self.num_partitions == 1 and other.num_partitions == 1:
            return True
        a, b = self.partitioning, other.partitioning
        return (a is not None and b is not None
                and a[0] == "hash" and b[0] == "hash"
                and a[1] == b[1] == tuple(keys)
                and a[2] == b[2] == self.num_partitions
                == other.num_partitions)

    def join(self, other: "DataFrame", on: Union[str, Sequence[str]],
             how: str = "inner") -> "DataFrame":
        keys = [on] if isinstance(on, str) else list(on)
        pk = [serde.expr_to_proto(resolve(col(k), self.schema))
              for k in keys]
        bk = [serde.expr_to_proto(resolve(col(k), other.schema))
              for k in keys]
        build_plan = other.plan
        if not self._co_partitioned_with(other, keys):
            # sides are not provably co-partitioned: collect the build
            # side once and replay it to every probe partition (broadcast
            # join, reference: NativeBroadcastExchangeBase / SURVEY §3.4)
            # — without this, probe partition p silently only sees build
            # partition p
            build_plan = pb.PlanNode(
                broadcast_exchange=pb.BroadcastExchangeNode(
                    child=other.plan,
                    input_partitions=other.num_partitions))
        node = pb.PlanNode(hash_join=pb.HashJoinNode(
            probe=self.plan, build=build_plan, probe_keys=pk,
            build_keys=bk, join_type=how))
        if how in ("semi", "anti"):
            return DataFrame(self.session, node, self.schema,
                             self.num_partitions, self.partitioning)
        if how == "existence":
            out = Schema(tuple(self.schema.fields)
                         + (Field("exists", DataType.BOOL, False),))
            return DataFrame(self.session, node, out, self.num_partitions,
                             self.partitioning)
        # USING-style join: the build side's key columns are dropped
        # (Spark/SQL `JOIN ... USING` semantics)
        raw = Schema(tuple(self.schema.fields)
                     + tuple(other.schema.fields))
        p = len(self.schema)
        keep = list(range(p)) + [
            p + i for i, f in enumerate(other.schema)
            if f.name not in keys]
        joined = DataFrame(self.session, node, raw, self.num_partitions,
                           self.partitioning)
        return joined.select(*[Col(ir.ColumnRef(i, raw[i].name),
                                   raw[i].name) for i in keep])

    def explode(self, c: Union[str, Col], outer: bool = False,
                keep: Optional[Sequence[str]] = None) -> "DataFrame":
        cc = col(c) if isinstance(c, str) else c
        gen = resolve(cc, self.schema)
        keep_idx = ([self.schema.index_of(k) for k in keep]
                    if keep is not None else list(range(len(self.schema))))
        node = pb.PlanNode(generate=pb.GenerateNode(
            child=self.plan, kind="explode",
            generator=serde.expr_to_proto(gen),
            required_child_output=keep_idx, outer=outer))
        elem = (self.schema[gen.index].elem
                if isinstance(gen, ir.ColumnRef) else DataType.INT64)
        fields = tuple(self.schema[i] for i in keep_idx) + (
            Field("col", elem, True),)
        return DataFrame(self.session, node, Schema(fields),
                         self.num_partitions)

    def repartition(self, n: int,
                    *keys: Union[str, Col]) -> "DataFrame":
        if keys:
            ks = [col(k) if isinstance(k, str) else k for k in keys]
            part = pb.PartitioningP(
                kind="hash", num_partitions=n,
                hash_keys=[serde.expr_to_proto(resolve(k, self.schema))
                           for k in ks])
            prov = ("hash", tuple(k.out_name() for k in ks), n)
        else:
            part = pb.PartitioningP(kind="round_robin", num_partitions=n)
            prov = ("single",) if n == 1 else None
        node = pb.PlanNode(shuffle_writer=pb.ShuffleWriterNode(
            child=self.plan, partitioning=part,
            input_partitions=self.num_partitions))
        return DataFrame(self.session, node, self.schema, n, prov)

    def map_batches(self, fn: Callable[[pa.RecordBatch], pa.RecordBatch],
                    schema: Optional[Schema] = None) -> "DataFrame":
        """Host-fallback boundary: run an arbitrary Arrow-batch function on
        the host (the ConvertToNative / C2R transition of the reference)."""
        rid = self.session._register_host_fn(fn, self)
        node = pb.PlanNode(memory_scan=pb.MemoryScanNode(table_name=rid))
        return DataFrame(self.session, node, schema or self.schema,
                         self.num_partitions)

    # -- actions ------------------------------------------------------------

    def task_bytes(self, partition_id: int = 0) -> bytes:
        return pb.TaskDefinition(
            partition_id=partition_id, num_partitions=self.num_partitions,
            plan=self.plan).SerializeToString()

    def collect(self, timeout_s: Optional[float] = None) -> pa.Table:
        """Execute and materialize. ``timeout_s`` arms a per-query
        deadline: past it, every cooperative poll site unwinds with the
        classified ``errors.DeadlineExceeded`` and the query's resources
        (spill files, shuffle buffers, memmgr consumers) are released —
        the same token mechanism ``session.cancel(query_id)`` and the
        serving CANCEL frame flip."""
        return self.session.execute(self, timeout_s=timeout_s)

    def to_pandas(self):
        return self.collect().to_pandas()

    def explain(self, analyze: bool = False) -> str:
        """The plan tree; ``analyze=True`` EXECUTES the plan and
        annotates every node with its mirrored metrics
        (elapsed_compute, output_rows, spill/shuffle counters — the
        EXPLAIN ANALYZE of obs/metric_tree.py)."""
        if analyze:
            return self.session.explain_analyze(self)
        op = self.session.plan_physical(self)
        return op.tree_string()
