"""Session: catalog, config, and plan execution for the DataFrame DSL.

The driver-side runtime the Spark session plays for the reference
(AuronSparkSessionExtension.scala): owns the table catalog and the planner
context, serializes each DataFrame's plan to TaskDefinition bytes, and runs
the engine's physical plan per partition — including materializing
host-fallback boundaries before native planning (the ConvertToNative
transition, SURVEY.md §3.1)."""

from __future__ import annotations

import contextlib
import itertools
from typing import Callable, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from auron_tpu.columnar.arrow_bridge import schema_from_arrow
from auron_tpu.columnar.schema import Schema
from auron_tpu.frontend.dataframe import DataFrame
from auron_tpu.ir import pb, plan_from_bytes
from auron_tpu.ir.planner import PhysicalPlanner, PlannerContext
from auron_tpu.runtime.executor import collect as _collect

#: process-wide query-id sequence: ids key process-global ledgers (the
#: program cache's per-query attribution, the memmgr query ledger), so
#: two Sessions must never mint the same id
_QUERY_SEQ = itertools.count(1)


class Session:
    def __init__(self, batch_capacity: Optional[int] = None, mem_manager=None,
                 config=None):
        from auron_tpu.config import get_config
        self.config = config or get_config()
        self._bind_xla_cache()
        # backend watchdog (runtime/watchdog.py): bounded device init +
        # first compile with CPU fallback. Both probes default OFF
        # (deadline 0) so Session construction stays lazy unless the
        # auron.watchdog.* knobs arm them.
        from auron_tpu.runtime import watchdog
        watchdog.ensure_backend(self.config)
        watchdog.first_compile_probe(self.config)
        # SPMD mesh plane (parallel/mesh.py): resolved EAGERLY at Session
        # init so the device layout exists before the first plan. The
        # plane is process-global by the knob's contract — consumers
        # (annotate_mesh, ExecContext.mesh_plane, exchange routing) all
        # resolve mesh.current_plane() themselves, so nothing is stored
        # per Session.
        from auron_tpu.parallel import mesh as _mesh
        _mesh.current_plane()
        self.ctx = PlannerContext(batch_capacity=batch_capacity,
                                  config=self.config)
        self.mem_manager = mem_manager
        if mem_manager is not None \
                and getattr(mem_manager, "config", None) is None:
            # bind the session config as the manager's knob source so
            # the auto per-query quota divisor and the scheduler's
            # admission clamp read the SAME auron.sched.max_concurrent
            # (first binding wins for a shared manager)
            mem_manager.config = self.config
            if hasattr(mem_manager, "_quota_cache"):
                mem_manager._quota_cache = (-1, 0, 1)
        self._ids = itertools.count()
        #: host-fallback registrations: rid -> (child DataFrame, fn)
        self._host_fns: dict[str, tuple[DataFrame, Callable]] = {}
        #: live query lifecycles: query_id -> CancelToken (the
        #: session.cancel(query_id) registry); guarded by _queries_lock
        #: because serving/admin threads cancel while the driver runs
        import threading
        self._queries_lock = threading.Lock()
        self._active_queries: dict[str, object] = {}
        self._closed = False
        #: thread-local current token: nested executes (host-fn
        #: children, scalar subqueries) join the ENCLOSING query's
        #: lifecycle — one cancel/deadline covers the whole tree
        self._tls = threading.local()
        #: the concurrent-query control plane (runtime/scheduler.py):
        #: every top-level execute is admitted through it — bounded run
        #: queue, weighted-round-robin task fairness, overload shedding
        #: with the classified errors.AdmissionRejected. Nested executes
        #: inherit the enclosing query's slot and NEVER queue (queueing
        #: a child behind its slot-holding parent would deadlock both).
        from auron_tpu.runtime.scheduler import QueryScheduler
        self._scheduler = QueryScheduler(name="session",
                                         mem_manager=mem_manager,
                                         config=self.config)
        #: crash-safe query journals this Session opened (runtime/
        #: journal.py): completed queries delete their own; close()
        #: deletes the rest — in-process, a journal never outlives its
        #: Session (cross-process survival is exactly the crash case)
        self._journals: list = []
        from auron_tpu.runtime import journal as _jrn
        if _jrn.enabled(self.config):
            # startup orphan sweep: reclaim dead predecessors' torn
            # journals and unreferenced RSS run dirs (resumable
            # journals of dead processes are KEPT — they are the
            # resume inventory)
            _jrn.sweep_orphans(_jrn.journal_dir(self.config))
        #: warm-path serving plane (auron_tpu/cache): register the
        #: process-wide result cache as a sheddable consumer on this
        #: Session's manager (refcounted — detached in close(), so the
        #: consumer ledger stays balanced), then START the AOT warmer
        #: (auron.cache.aot_top_n; a no-op at the default 0, NEVER
        #: raises — a corrupt inventory must not fail construction).
        #: The warm runs on a background daemon thread overlapping the
        #: first user query's planning; close() joins it (aot.wait)
        from auron_tpu.cache import aot as _aot
        from auron_tpu.cache import result_cache as _rcache
        self._result_cache = _rcache.get_cache()
        self._cache_attached = self._result_cache.attach(mem_manager)
        _aot.warm(self)
        #: ops plane (obs/ops_server.py): acquire the process's live
        #: telemetry endpoint when auron.ops.enabled — refcounted, so
        #: several Sessions share one server and the LAST close stops
        #: it. ops_address is the bound (host, port) — the ephemeral-
        #: port (auron.ops.port=0) discovery surface. Acquired LAST:
        #: nothing after this can raise, so a failed __init__ (whose
        #: close() never runs) can never strand the refcount above
        #: zero and keep the port bound for the process lifetime.
        from auron_tpu.obs import ops_server as _ops
        self._ops = _ops.ensure_started(self.config)
        self.ops_address = (self._ops.address
                            if self._ops is not None else None)

    def _bind_xla_cache(self) -> None:
        """Bind jax's persistent compilation cache to
        ``auron.xla_cache_dir`` (default off). On the tunneled
        accelerator each program build costs seconds, so a warm
        cross-process cache is the first step of the compile-budget diet
        (VERDICT round 5). Best-effort: a cache failure must never fail
        session construction."""
        from auron_tpu import config as cfg
        cache_dir = self.config.get(cfg.XLA_CACHE_DIR)
        if not cache_dir:
            return
        try:
            import jax
            jax.config.update("jax_compilation_cache_dir", cache_dir)
        except Exception:   # pragma: no cover - jax-version dependent
            import logging
            logging.getLogger("auron_tpu").warning(
                "could not bind jax_compilation_cache_dir=%s", cache_dir)

    # -- sources ------------------------------------------------------------

    def register(self, name: str, table: pa.Table) -> None:
        self.ctx.catalog[name] = table

    def table(self, name: str) -> DataFrame:
        table = self.ctx.catalog[name]
        node = pb.PlanNode(memory_scan=pb.MemoryScanNode(table_name=name))
        return DataFrame(self, node, schema_from_arrow(table.schema))

    def from_arrow(self, table: pa.Table,
                   name: Optional[str] = None) -> DataFrame:
        name = name or f"__mem_{next(self._ids)}"
        self.register(name, table)
        return self.table(name)

    def read_parquet(self, files, columns=None,
                     partitions: Optional[int] = None) -> DataFrame:
        """``partitions`` sets the scan parallelism (files are split
        round-robin across partitions, like Spark input splits); default 1."""
        files = [files] if isinstance(files, str) else list(files)
        node = pb.PlanNode(parquet_scan=pb.ParquetScanNode(
            files=files, columns=columns or []))
        schema = schema_from_arrow(pq.read_schema(files[0]))
        if columns:
            # requested order, not file order: the scan op emits columns in
            # the order they were asked for
            schema = Schema(tuple(schema[schema.index_of(c)] for c in columns))
        return DataFrame(self, node, schema,
                         num_partitions=partitions or 1)

    def read_orc(self, files, columns=None) -> DataFrame:
        from pyarrow import orc
        files = [files] if isinstance(files, str) else list(files)
        node = pb.PlanNode(orc_scan=pb.OrcScanNode(
            files=files, columns=columns or []))
        schema = schema_from_arrow(orc.ORCFile(files[0]).schema)
        if columns:
            schema = Schema(tuple(schema[schema.index_of(c)] for c in columns))
        return DataFrame(self, node, schema)

    # -- host fallback ------------------------------------------------------

    def _register_host_fn(self, fn: Callable, child_df: DataFrame) -> str:
        rid = f"__hostfn_{next(self._ids)}"
        self._host_fns[rid] = (child_df, fn)
        return rid

    def _materialize_host_fns(self, plan: pb.PlanNode) -> None:
        """Execute host-fallback children referenced by this plan and put
        their transformed output into the catalog."""
        rids = []

        def walk(node: pb.PlanNode):
            kind = node.WhichOneof("node")
            if kind is None:
                return
            if kind == "memory_scan" and \
                    node.memory_scan.table_name.startswith("__hostfn_"):
                rids.append(node.memory_scan.table_name)
            inner = getattr(node, kind)
            for _f, sub in inner.ListFields():
                if isinstance(sub, pb.PlanNode):
                    walk(sub)
                elif hasattr(sub, "__iter__") and not isinstance(sub, (str, bytes)):
                    for item in sub:
                        if isinstance(item, pb.PlanNode):
                            walk(item)

        walk(plan)
        for rid in rids:
            if rid in self.ctx.catalog:
                continue
            child_df, fn = self._host_fns[rid]
            child_table = self.execute(child_df)
            out_batches = []
            for rb in child_table.to_batches():
                out = fn(rb)
                if out.num_rows:
                    out_batches.append(out)
            self.ctx.catalog[rid] = (
                pa.Table.from_batches(out_batches) if out_batches
                else child_table.schema.empty_table())

    # -- execution ----------------------------------------------------------

    def plan_physical(self, df: DataFrame):
        self._materialize_host_fns(df.plan)
        return plan_from_bytes(df.task_bytes(), self.ctx)

    # -- query lifecycle ----------------------------------------------------

    def _begin_query(self, timeout_s: Optional[float]):
        """Create + register one query's CancelToken. The deadline is
        the explicit ``timeout_s`` when given, else the session default
        ``auron.query.deadline_s`` (0 = none)."""
        from auron_tpu import config as cfg
        from auron_tpu.runtime.lifecycle import CancelToken
        if timeout_s is None:
            default = float(self.config.get(cfg.QUERY_DEADLINE_S))
            timeout_s = default if default > 0 else None
        qid = f"q{next(_QUERY_SEQ)}"
        token = CancelToken(query_id=qid, deadline_s=timeout_s)
        with self._queries_lock:
            self._active_queries[qid] = token
        return token

    def _end_query(self, token) -> None:
        with self._queries_lock:
            self._active_queries.pop(token.query_id, None)
        # drop the query's program-cache attribution ledger (bounded
        # memory; explain_analyze reads it BEFORE ending the query)
        from auron_tpu.runtime import programs
        programs.pop_query(token.query_id)

    @contextlib.contextmanager
    def _admitted_query(self, timeout_s: Optional[float]):
        """One top-level query's full admission choreography as a
        context manager: begin (token + registry entry) → scheduler
        acquire (admission control; the token's slot rides it) →
        lifecycle/thread-local binding; unwound in exact reverse on
        exit. execute() and explain_analyze() share this so the
        teardown ordering can never desynchronize between them.

        Doubles as the query's end-to-end OBSERVATION point (the ops
        plane): every exit — success, shed, cancel, failure — lands on
        the ``auron_query_duration_seconds{outcome}`` registry
        histogram, and a CLASSIFIED failure writes its post-mortem
        bundle here (obs/bundle.maybe_write — the unwind that still
        sees the scheduler, memmgr and the token's plan tree)."""
        import time as _time

        from auron_tpu import errors
        from auron_tpu.obs import bundle as _bundle
        from auron_tpu.obs import registry as _registry
        from auron_tpu.runtime import lifecycle
        t0 = _time.monotonic()
        token = self._begin_query(timeout_s)

        def observe(exc) -> None:
            try:
                _registry.observe_query(
                    _time.monotonic() - t0,
                    _registry.classify_outcome(exc),
                    served_from=getattr(token, "served_from", None))
            except Exception:   # pragma: no cover - telemetry only
                pass

        # admission BEFORE any planning/execution work: a shed query
        # costs nothing (AdmissionRejected / the token's own classified
        # error when cancelled while queued)
        try:
            slot = self._scheduler.acquire(token)
        except errors.QueryCancelled as e:
            # queue-phase cancels feed the same cancel-latency
            # histogram as mid-execution ones (every cancel class
            # counts toward the acceptance-gate metric)
            lifecycle.observe_unwind(token, kind=token.reason or "cancel")
            observe(e)
            self._end_query(token)
            raise
        except BaseException as e:
            observe(e)
            self._end_query(token)
            raise
        token.slot = slot
        self._tls.token = token
        prev_bind = lifecycle.bind_token(token)
        try:
            yield token
        except BaseException as e:
            # classified-failure post-mortem (shed/deadline/stall/mesh/
            # journal — obs/bundle.classify decides; plain cancels and
            # unclassified crashes write nothing). maybe_write never
            # raises: the query's own verdict always wins the unwind.
            _bundle.maybe_write(e, token=token, config=self.config,
                                scheduler=self._scheduler,
                                mem_manager=self.mem_manager)
            observe(e)
            raise
        else:
            observe(None)
        finally:
            self._tls.token = None
            lifecycle.bind_token(prev_bind)
            slot.release()
            self._end_query(token)

    def cancel(self, query_id: str) -> bool:
        """Cancel a running query by id (thread-safe; the API face of
        the serving CANCEL frame). Returns True when a live query was
        cancelled; False — the idempotent after-DONE no-op — when the
        id is unknown or already finished."""
        with self._queries_lock:
            token = self._active_queries.get(query_id)
        if token is None:
            return False
        token.cancel()
        return True

    def active_queries(self) -> dict:
        """{query_id: CancelToken} of the queries currently executing."""
        with self._queries_lock:
            return dict(self._active_queries)

    def close(self) -> None:
        """End the session: drain the scheduler DETERMINISTICALLY —
        queued queries are cancelled first (reason "session-closed";
        their waiting acquires dequeue without ever starting, so no
        executor or consumer/spill ledger entry is ever created for
        them), then the running tokens — and finally sweep the spill
        tier's orphaned files (the commit-time ``.part`` sweep's
        equivalent for per-attempt spill artifacts — a crashed or
        cancelled attempt must not leak storage past the session)."""
        if self._closed:
            return
        self._closed = True
        # the AOT warmer overlaps this session's first queries on a
        # background thread; join it FIRST (bounded) so the spill and
        # journal sweeps below never race a still-warming plan
        from auron_tpu.cache import aot as _aot
        _aot.wait(timeout=60.0)
        # queued-first through the scheduler's drain order...
        self._scheduler.drain("session-closed")
        # ...then any token the scheduler has not seen yet (admission
        # raced close): cancel idempotently, first reason wins
        with self._queries_lock:
            tokens = list(self._active_queries.values())
        for t in tokens:
            t.cancel("session-closed")
        # cancellation is COOPERATIVE: wait (bounded) for the driver
        # threads to unwind and unregister before sweeping, or the
        # sweep would unlink spill files a still-running task is about
        # to read — turning the classified QueryCancelled into an
        # unclassified FileNotFoundError
        if tokens:
            import time as _time
            deadline = _time.monotonic() + 5.0
            while _time.monotonic() < deadline:
                with self._queries_lock:
                    if not self._active_queries:
                        break
                _time.sleep(0.02)
        spill_mgr = getattr(self.mem_manager, "spill_manager", None)
        if spill_mgr is not None and hasattr(spill_mgr, "sweep_orphans"):
            spill_mgr.sweep_orphans()
        # a journal's in-process lifetime is bounded by its Session:
        # completed queries already deleted theirs; failed/cancelled
        # ones are reclaimed here (a journal that outlives its process
        # is exactly — and only — the crash-recovery inventory)
        for jr in self._journals:
            try:
                jr.complete()
            except Exception:   # pragma: no cover - cleanup best-effort
                pass
        self._journals = []
        # balance the warm-path cache's consumer registration (the
        # cache itself is process-wide and keeps its entries; only this
        # Session's memmgr attachment ends)
        if self._cache_attached:
            self._result_cache.detach(self.mem_manager)
            self._cache_attached = False
        # ops endpoint: drop this Session's acquisition — the LAST
        # release stops the server (clean shutdown, no dangling port)
        if self._ops is not None:
            from auron_tpu.obs import ops_server as _ops
            _ops.release()
            self._ops = None

    def __enter__(self) -> "Session":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def execute(self, df: DataFrame,
                timeout_s: Optional[float] = None) -> pa.Table:
        from auron_tpu.obs import trace
        # nested execute (a host-fn child or scalar subquery driven from
        # inside an enclosing query): join the enclosing lifecycle — the
        # outer token's cancel/deadline covers the whole tree, and the
        # enclosing query's scheduler SLOT travels with the token (a
        # nested query must never queue behind its own parent)
        enclosing = getattr(self._tls, "token", None)
        if enclosing is not None:
            with trace.query_scope(label=f"p{df.num_partitions}"):
                op = self.plan_physical(df)
                return _collect(op, num_partitions=df.num_partitions,
                                mem_manager=self.mem_manager,
                                config=self.config,
                                cancel_token=enclosing)
        # one trace per TOP-LEVEL query: nested executes (host-fn
        # children, scalar subqueries) join the enclosing trace, and the
        # outermost scope exports into auron.trace.dir when set
        with self._admitted_query(timeout_s) as token:
            with trace.query_scope(label=f"p{df.num_partitions}"):
                # warm-path lookup BEFORE journal/plan work: an exact
                # re-submission (same plan fp + source fps + trace
                # salt — cache/identity.py) is answered from the
                # process cache; the key embeds the live source
                # fingerprints, so a mutated source simply misses
                pb_bytes = df.task_bytes()
                cache_key = self._result_cache.result_key(
                    pb_bytes, self.ctx.catalog)
                if cache_key is not None:
                    cached = self._result_cache.get_result(cache_key)
                    if cached is not None:
                        token.served_from = "cache"
                        return cached
                jr = self._journal_begin(df, token, plan_bytes=pb_bytes)
                try:
                    op = self.plan_physical(df)
                    # with bundles armed, mirror task metrics onto a
                    # positional tree as the query runs: a failure
                    # bundle then carries the explain-with-metrics of
                    # every task that DID finish (obs/bundle.py)
                    mtree = None
                    from auron_tpu.obs import bundle as _bundle
                    if _bundle.armed(self.config):
                        from auron_tpu.obs import metric_tree as mt
                        mtree = mt.build_tree(op)
                        token.plan_tree = mtree
                    table = _collect(op, num_partitions=df.num_partitions,
                                     mem_manager=self.mem_manager,
                                     config=self.config,
                                     metric_tree=mtree,
                                     cancel_token=token)
                except BaseException:
                    if jr is not None:
                        # the query failed IN-PROCESS: flush and keep
                        # the journal — an identical re-submission
                        # under auron.journal.reuse (or a resume) can
                        # pick the committed stages up; close() deletes
                        # whatever is never reused
                        jr.suspend()
                    raise
                if jr is not None:
                    jr.complete(write_report=True)
                    self._journal_discard(jr)
                if cache_key is not None:
                    self._result_cache.put_result(cache_key, table)
                from auron_tpu.cache import aot as _aot
                _aot.record_plan(pb_bytes, self.ctx.catalog,
                                 df.num_partitions, self.config)
                return table

    def _journal_discard(self, jr) -> None:
        """Drop a COMPLETED journal from the Session ledger (its disk
        state is already gone) — only suspended journals stay tracked,
        for close() to reclaim.  Without this a long-lived Session
        retains one QueryJournal (plan bytes included) per executed
        query forever."""
        try:
            self._journals.remove(jr)
        except ValueError:
            pass

    def _journal_begin(self, df: DataFrame, token, plan_bytes=None):
        """Open (adopt or mint) the crash-safe journal for one
        top-level query; None when journaling is disarmed or this plan
        opted out (runtime/journal.begin). ``plan_bytes`` lets the
        caller reuse an already-serialized plan (execute() serializes
        once for the cache key and the journal)."""
        from auron_tpu.runtime import journal as jrn
        if not jrn.enabled(self.config):
            return None
        if plan_bytes is None:
            plan_bytes = df.task_bytes()
        jr = jrn.begin(token, plan_bytes, df.num_partitions,
                       self.ctx.catalog, self.config)
        if jr is not None:
            self._journals.append(jr)
        return jr

    def resume(self, query_id: str,
               timeout_s: Optional[float] = None) -> pa.Table:
        """Resume a crashed process's journaled query: load + validate
        its journal (classified ResumeUnavailable / JournalCorrupt /
        JournalInvalidated on every not-resumable shape — never a wrong
        answer), re-plan from the journaled plan bytes, and execute
        with the journal bound so every fully-committed exchange is
        satisfied (map side skipped, reducers fetch the journaled RSS
        files) and partially-committed hash/round-robin/single
        exchanges skip exactly their committed maps. The resumed
        result is bit-identical to a fresh run, group order included;
        the journal (and its RSS run directory) is deleted at
        completion, leaving a resume report for
        tools/journal_report.py."""
        from auron_tpu.obs import trace
        from auron_tpu.runtime import journal as jrn
        jr = jrn.load_for_resume(jrn.journal_dir(self.config), query_id,
                                 self.ctx.catalog, self.config)
        try:
            with self._admitted_query(timeout_s) as token:
                with trace.query_scope(label=f"resume:{query_id}"):
                    jrn.attach_resumed(token, jr)
                    self._journals.append(jr)
                    op = plan_from_bytes(jr.plan_bytes, self.ctx)
                    if jr.scope == "task":
                        # serving-journaled Spark task: the host engine
                        # owns the partition fan-out — replay exactly
                        # the journaled task's own partition, not the
                        # whole range (which would over-produce)
                        from auron_tpu.runtime.executor import \
                            run_task_with_retries
                        task = pb.TaskDefinition.FromString(
                            jr.plan_bytes)
                        table = run_task_with_retries(
                            op, task.partition_id, jr.num_partitions,
                            mem_manager=self.mem_manager,
                            config=self.config, cancel_token=token)
                    else:
                        table = _collect(op,
                                         num_partitions=jr.num_partitions,
                                         mem_manager=self.mem_manager,
                                         config=self.config,
                                         cancel_token=token)
        except BaseException:
            # covers admission shedding / cancel-while-queued too: the
            # load claimed the journal's open stem, so EVERY unwind
            # must release it or the query becomes unresumable with
            # reason='open' until process restart (suspend is
            # idempotent — a no-op when the run already completed)
            jr.suspend()
            raise
        jr.complete(write_report=True)
        self._journal_discard(jr)
        return table

    def explain_analyze(self, df: DataFrame) -> str:
        """EXPLAIN ANALYZE: run the plan with a positional metric tree
        mirrored at every task finalize (obs/metric_tree — the
        update_metric_node walk of the reference, rt.rs:302-308) and
        render the annotated plan, followed by the query's program-cache
        footer (per-QUERY builds/hits — under the concurrent scheduler
        the central cache is shared across queries, so the hit rate a
        query actually enjoyed is its ledger's, not the process's)."""
        from auron_tpu.obs import metric_tree as mt
        from auron_tpu.obs import trace
        from auron_tpu.runtime import programs

        def analyzed(token) -> str:
            with trace.query_scope(label="explain_analyze"):
                op = self.plan_physical(df)
                tree, _table = mt.explain_analyze(
                    op, num_partitions=df.num_partitions,
                    mem_manager=self.mem_manager, config=self.config,
                    cancel_token=token)
            snap = programs.query_totals(token.query_id)
            total = snap.builds + snap.hits
            footer = (f"[program cache] builds={snap.builds} "
                      f"hits={snap.hits} hit_rate="
                      f"{(snap.hits / total * 100.0) if total else 0.0:.1f}%"
                      f" (query {token.query_id})\n")
            # warm-path result cache: PROCESS totals (the cache is
            # shared across sessions/queries by design — explain runs
            # fresh for the metric tree, so its own lookup is not in
            # these numbers)
            rc = self._result_cache.stats()
            footer += (f"[result cache] enabled={rc['enabled']} "
                       f"hits={rc['hits']} misses={rc['misses']} "
                       f"evictions={rc['evictions']} "
                       f"entries={rc['entries']} bytes={rc['bytes']}\n")
            return mt.render(tree) + footer

        # nested (a host fn analyzing mid-query): inherit the enclosing
        # token and slot exactly like execute() — acquiring here would
        # queue this analysis behind its own slot-holding parent
        enclosing = getattr(self._tls, "token", None)
        if enclosing is not None:
            return analyzed(enclosing)
        with self._admitted_query(None) as token:
            return analyzed(token)
