"""Host-engine front-end: Session + DataFrame building proto plans.

The role the Spark extension plays for the reference (reference:
spark-extension/src/main/scala/.../AuronConverters.scala — host plans are
converted node by node into the protobuf IR, then executed natively). Here
the host engine is this DataFrame DSL: every transformation appends a
PlanNode, `collect()` serializes the tree and hands it to the engine's
physical planner. Anything the engine cannot run natively goes through the
host-fallback boundary (`map_batches` — the ConvertToNative/C2R analogue).
"""

from auron_tpu.frontend.dataframe import (DataFrame, col, lit,  # noqa: F401
                                          functions, scalar_subquery)
from auron_tpu.frontend.session import Session  # noqa: F401
