"""Typed configuration registry.

The reference runs a three-layer config system: typed ``ConfigOption``
declarations with defaults and docs (reference:
auron-core/src/main/java/org/apache/auron/configuration/ConfigOption.java),
a Spark binding exposing ~70 ``spark.auron.*`` options (reference:
spark-extension/src/main/java/org/apache/spark/sql/auron/
SparkAuronConfiguration.java:42-526), and a native mirror that reads
through JNI at use-site so the host config is the single source of truth
(reference: native-engine/auron-jni-bridge/src/conf.rs:20-63).

Here the same shape, TPU-side: declared options with defaults + docs,
resolved at use-site through ``AuronConfig.get`` with precedence

    session/programmatic override  >  env var  >  default

Env binding: ``auron.agg.partial_skip.ratio`` ←
``AURON_CONF_AGG_PARTIAL_SKIP_RATIO`` (prefix stripped, dots → ``_``,
upper-cased). ``generate_docs()`` emits the markdown config reference
(the reference generates docs the same way:
SparkAuronConfigurationDocGenerator.java).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Optional


@dataclass(frozen=True)
class ConfigOption:
    key: str
    dtype: type           # int | float | bool | str
    default: Any
    doc: str

    @property
    def env_var(self) -> str:
        return "AURON_CONF_" + self.key.replace("auron.", "", 1) \
            .replace(".", "_").upper()

    def parse(self, raw: str) -> Any:
        if self.dtype is bool:
            v = raw.strip().lower()
            if v in ("1", "true", "yes", "on"):
                return True
            if v in ("0", "false", "no", "off"):
                return False
            raise ValueError(f"{self.key}: invalid bool {raw!r}")
        return self.dtype(raw)


_REGISTRY: dict[str, ConfigOption] = {}


def _opt(key: str, dtype: type, default, doc: str) -> str:
    assert key not in _REGISTRY, f"duplicate config option {key}"
    _REGISTRY[key] = ConfigOption(key, dtype, default, doc)
    return key


# --------------------------------------------------------------------------
# option declarations (grouped like the reference's config sections)
# --------------------------------------------------------------------------

# batching / shapes
BATCH_CAPACITY = _opt(
    "auron.batch.capacity", int, 1 << 16,
    "Default rows per device batch (scan batch size and the planner's "
    "capacity bucketing target). Larger batches amortize kernel launches; "
    "smaller ones reduce padding waste on ragged inputs.")
PARQUET_BATCH_ROWS = _opt(
    "auron.io.parquet.batch_rows", int, 1 << 16,
    "Row-group read granularity for the parquet/ORC scans when the plan "
    "does not pin batch_rows explicitly and auron.scan.batch_rows is 0 "
    "on a non-CPU platform (legacy knob; auron.scan.batch_rows wins "
    "when set).")
SCAN_BATCH_ROWS = _opt(
    "auron.scan.batch_rows", int, 0,
    "Rows per file-scan device batch (parquet/ORC). 0 (default) = auto: "
    "2^17 on the CPU mesh — larger batches amortize the per-batch host "
    "glue that dominates CPU throughput (PERF.md 'Pipelined "
    "execution') — and auron.io.parquet.batch_rows (2^16) on "
    "accelerators. The scan clamps the conversion capacity to the "
    "partition's actual row count bucket, so small files never pad to "
    "the full batch size. One flag for batch-size experiments; "
    "tools/hotspot_report.py prints the achieved rows/batch per "
    "operator next to the attribution table.")

# pipelined async execution (runtime/pipeline.py)
PIPELINE_ENABLED = _opt(
    "auron.pipeline.enabled", bool, True,
    "Pipelined asynchronous execution (the [speed] overlap plane): the "
    "file scans decode row-group N+1 on a bounded background worker "
    "while the device computes batch N (auron.scan.prefetch_batches "
    "deep, decoded bytes registered with the memory manager so depth "
    "degrades under pressure), per-batch device syncs inside operator "
    "timers and the profiler's program wrapper are skipped — XLA's "
    "async dispatch queues batch N+1 while N's arrays are in flight — "
    "and execution synchronizes only at operator boundaries that "
    "semantically require it (sort collect, shuffle materialize, "
    "to_arrow), where the wait is attributed to elapsed_device. Off "
    "restores fully serial decode → dispatch → block per batch "
    "(the differential baseline: pipelined and serial results are "
    "bit-identical by construction — overlap never reorders batches). "
    "PROCESS-GLOBAL by contract (resolved from get_config(), the "
    "map-key-dedup precedent): the mode moves sync points across "
    "planes that cannot see a session config (the profiler's program "
    "wrapper), so per-Session overrides are not honored for it.")
SCAN_PREFETCH_BATCHES = _opt(
    "auron.scan.prefetch_batches", int, 2,
    "Decoded-batch lookahead of the prefetching file scan (bounded "
    "queue depth between the background decode worker and the drive "
    "loop) when auron.pipeline.enabled is on. The prefetcher registers "
    "its buffered decoded bytes with the memory manager and degrades "
    "to depth 1 while the pressure ladder's shrink rung is active. "
    "<= 1 keeps the decode worker but no lookahead beyond the batch "
    "in flight.")

# SPMD mesh execution plane (parallel/mesh.py)
MESH_ENABLED = _opt(
    "auron.mesh.enabled", bool, False,
    "SPMD execution plane (parallel/mesh.py): Session init builds a "
    "jax Mesh/NamedSharding layout over the visible devices and eligible "
    "hash-repartition exchanges lower to the on-device "
    "lax.all_to_all stage program (parallel/mesh_exchange.py) — the "
    "fused stage chain, the partition-id compute, the sort-by-pid split "
    "and the collective run as ONE shard_map program partition-parallel "
    "across all mesh devices, fencing once at the output boundary. "
    "Ineligible exchanges (range/round-robin/single partitioning, fan-in "
    "wider than the mesh) keep the host-orchestrated device-buffer path; "
    "RSS stays the durable/multihost tier. The route taken is recorded "
    "per exchange in the metric tree (exchange_route_* counters) and the "
    "trace ('mesh' category exchange.route events — "
    "tools/mesh_report.py). A device lost mid-exchange "
    "(errors.MeshUnavailable) demotes the remaining rounds to the host "
    "path and quarantines the chip (auron.mesh.quarantine) — the plane "
    "degrades, never the query. PROCESS-GLOBAL by contract (the device "
    "set is process state, like auron.pipeline.enabled): resolved from "
    "get_config(), per-Session overrides are not honored. Default off; "
    "tests/bench force a virtual CPU mesh via "
    "--xla_force_host_platform_device_count.")
MESH_DEVICES = _opt(
    "auron.mesh.devices", int, 0,
    "Devices in the SPMD mesh; 0 (default) = every device jax exposes. "
    "An exchange with num_partitions <= this width runs on the leading "
    "submesh of exactly num_partitions devices (one output partition "
    "per device — the all-to-all's square contract); wider exchanges "
    "fall back to the host device-buffer route, recorded per exchange.")
MESH_AXIS = _opt(
    "auron.mesh.axis", str, "data",
    "Name of the mesh's single batch-sharding axis (the PartitionSpec "
    "axis scan batches shard over; broadcast relations and hash-table "
    "build sides replicate — parallel/mesh.buffer_spec).")
MESH_STRAGGLER_FACTOR = _opt(
    "auron.mesh.straggler_factor", float, 4.0,
    "Straggler defense of the SPMD plane: an all-to-all round slower "
    "than this factor times the rolling per-round p50 (the plane's "
    "MeshRoundStats window, armed after a few observed rounds) emits a "
    "mesh.straggler trace event and counts on "
    "auron_mesh_stragglers_total — one slow chip becomes an observable "
    "signal instead of an invisible latency spike on every query in the "
    "gang queue. With auron.mesh.demote_on_straggler it also triggers "
    "the same mid-exchange route demotion a device loss does. "
    "<= 0 disables the detector.")
MESH_DEMOTE_ON_STRAGGLER = _opt(
    "auron.mesh.demote_on_straggler", bool, False,
    "Escalate a detected straggler round (auron.mesh.straggler_factor) "
    "from an observable event to the demotion path: the exchange's "
    "REMAINING rounds re-route through the host device-buffer tier — "
    "the completed slow round's received rows stay valid on the mesh — "
    "so one slow chip degrades throughput instead of latency-spiking "
    "the whole gang queue. Default off: stragglers are reported, not "
    "acted on (a transient OS hiccup would otherwise demote a healthy "
    "mesh).")
MESH_QUARANTINE = _opt(
    "auron.mesh.quarantine", bool, True,
    "On a device loss (errors.MeshUnavailable mid-exchange), record the "
    "failed device in the MeshPlane's quarantine set: subsequent "
    "exchanges rebuild a smaller submesh from the remaining healthy "
    "devices when the square contract (num_partitions == submesh "
    "width) still holds, and route host-side otherwise — the rest of "
    "the query keeps running without ever re-entering the dead chip. "
    "When XLA's error carries no device identity, the tail device of "
    "the failed submesh is retired (deterministic; a wrongly blamed "
    "healthy chip costs one device of capacity, never correctness). "
    "Off demotes the failing exchange but leaves the plane's device "
    "set intact (the next exchange will try the full mesh again).")

# concurrent query scheduler (runtime/scheduler.py)
SCHED_MAX_CONCURRENT = _opt(
    "auron.sched.max_concurrent", int, 4,
    "Queries one scheduler (one Session / one AuronServer) runs "
    "concurrently. Further admitted queries wait in the bounded run "
    "queue (auron.sched.queue_depth); nested executes (host-fn "
    "children, scalar subqueries) inherit the enclosing query's slot "
    "and NEVER queue — queueing a child while its parent holds a slot "
    "would deadlock the pair. Also the divisor of the automatic "
    "per-query memory quota (auron.memmgr.query_quota_bytes = 0).")
SCHED_QUEUE_DEPTH = _opt(
    "auron.sched.queue_depth", int, 16,
    "Bounded run-queue depth behind the concurrent slots: a query "
    "arriving past max_concurrent running + queue_depth queued is "
    "REJECTED fast with the classified errors.AdmissionRejected "
    "(transient, retry_after_s hint) instead of waiting unboundedly — "
    "the overload-shedding half of admission control. Queued queries "
    "honor their deadline/cancel token WHILE queued (dequeued without "
    "ever starting).")
SCHED_ADMIT_QUEUE_WAIT_P99_S = _opt(
    "auron.sched.admit.queue_wait_p99_s", float, 0.0,
    "Admission threshold on the observed queue-wait p99 (the "
    "auron_sched_queue_wait_seconds registry histogram): when queries "
    "admitted in the last 30 s waited longer than this, new queries "
    "are shed with AdmissionRejected(reason='queue_wait') even though "
    "the queue still has room — latency-based backpressure ahead of "
    "the hard depth bound. Age-windowed so one old burst cannot latch "
    "the signal shut after the backlog drains. 0 (default) disables "
    "the signal.")
SCHED_ADMIT_MEM_RATIO = _opt(
    "auron.sched.admit.mem_ratio", float, 0.0,
    "Admission threshold on the memory manager's used/budget ratio: "
    "past it new queries are shed with "
    "AdmissionRejected(reason='memory') instead of being admitted into "
    "a budget that is already spilling — rejecting at the door is "
    "cheaper than shedding mid-flight with MemoryExhausted. Read from "
    "the scheduler's attached MemManager at admission time — the "
    "Session's mem_manager; a scheduler with NO manager attached "
    "(Session() without one, the serving process) logs a one-time "
    "warning and leaves the signal disarmed. 0 (default) disables "
    "the signal.")

# memory / spill
MEMORY_FRACTION = _opt(
    "auron.memory.fraction", float, 0.6,
    "Fraction of device HBM the memory manager arbitrates across "
    "consumers (the reference's spark.auron.memoryFraction).")
HOST_SPILL_BUDGET = _opt(
    "auron.memory.host_spill_budget", int, 1 << 30,
    "Bytes of host DRAM the spill manager may hold before overflowing "
    "frames to disk (tier 2 of the HBM->DRAM->disk spill path).")
SPILL_DIR = _opt(
    "auron.memory.spill_dir", str, "",
    "Directory for disk spill files; empty = system temp dir.")
SPILL_FRAME_ROWS = _opt(
    "auron.spill.frame_rows", int, 1 << 16,
    "Rows per serialized spill frame (the unit of spill I/O and of the "
    "k-way merge restore).")
SPILL_CODEC_LEVEL = _opt(
    "auron.spill.codec_level", int, 1,
    "zstd compression level for spill/shuffle frames (the reference "
    "defaults its IPC compression to lz4/zstd level 1).")
MEMMGR_PRESSURE_POLICY = _opt(
    "auron.memmgr.pressure_policy", str, "degrade",
    "What the memory manager does when the spill loop exits still over "
    "budget (the old silent 'deny'): 'degrade' (default) walks the "
    "degradation ladder — shrink (advise smaller scan batches + ask the "
    "requester to shrink) -> force-spill (largest consumer, ignoring "
    "min_trigger) -> deny (survivable, counted) — so pressure degrades "
    "throughput before it fails anything; 'shed' ends the ladder by "
    "failing THIS query with the classified errors.MemoryExhausted "
    "(never the process) — the serving/admission-control posture; "
    "'legacy' restores the pre-ladder deny event only. A per-query "
    "quota breach (auron.memmgr.query_quota_bytes) sheds under every "
    "policy except 'legacy'. Each rung taken is counted on "
    "auron_memmgr_pressure_total{rung=...}.")
MEMMGR_QUERY_QUOTA_BYTES = _opt(
    "auron.memmgr.query_quota_bytes", int, 0,
    "Device-memory quota on ONE query's registered consumers (the "
    "manager keeps a per-query ledger — consumers are tagged with the "
    "lifecycle plane's current query id at registration): exceeded "
    "AFTER the spill loop and the degradation ladder ran, the "
    "requesting query is shed with errors.MemoryExhausted — never the "
    "process, never an innocent neighbor. 0 (default) = AUTO: "
    "budget / auron.sched.max_concurrent while more than one query is "
    "live on the manager (one query cannot starve the rest), no quota "
    "while a single query runs (a solo query may use the whole "
    "budget). Set negative to disable the quota entirely.")

# NOTE: options are declared only once a use-site exists — an option in
# CONFIG.md that nothing reads is a lie to the user. SMJ-fallback,
# exchange-spill, and dense-kernel-selection knobs land together with
# their features.

# compile-cache ceiling (default lives in utils/compile_stats so the
# mechanism and its documented value cannot drift)
from auron_tpu.utils.compile_stats import DEFAULT_MAX_LIVE_PROGRAMS

MAX_LIVE_PROGRAMS = _opt(
    "auron.max_live_programs", int, DEFAULT_MAX_LIVE_PROGRAMS,
    "Ceiling on live compiled programs per process, enforced through the "
    "central program-cache registry (runtime/programs.py): every kernel "
    "builder registers its cache there, and when either the registry's "
    "live-program count or the raw backend compiles since the last clear "
    "reach this value, utils/compile_stats.maybe_clear drops BOTH jax's "
    "compiled caches and the builder memos (the CPU backend's JIT can "
    "segfault once several hundred programs accumulate in one long-lived "
    "process). Checked only at quiescent boundaries (between serving "
    "tasks / runner queries); <= 0 disables.")

# compile-budget diet: persistent XLA compilation cache
XLA_CACHE_DIR = _opt(
    "auron.xla_cache_dir", str, "",
    "Directory for jax's persistent compilation cache "
    "(jax_compilation_cache_dir), bound at Session init. On the "
    "tunneled accelerator each program build costs seconds, so a warm "
    "cache across processes is the first step of the compile-budget "
    "diet; empty (the default) leaves the cache off.")

# warm-path serving plane (auron_tpu/cache): result/subplan cache + AOT
CACHE_ENABLED = _opt(
    "auron.cache.enabled", bool, False,
    "Master switch for the warm-path result/subplan cache "
    "(cache/result_cache.py). When on, exact re-submissions — same "
    "plan fingerprint, same source fingerprints, same trace salt "
    "(cache/identity.py, the journal's crash-tested identity) — are "
    "answered from a process-wide LRU of materialized Arrow results "
    "instead of re-executing; serving marks such answers with "
    "cache_hit and served_from=cache. Off by default: caching trades "
    "memory for latency and dashboards must opt in.")
CACHE_MAX_BYTES = _opt(
    "auron.cache.max_bytes", int, 256 << 20,
    "Capacity of the warm-path cache in bytes (LRU eviction on "
    "insert). Independent of auron.memmgr.*: the cache additionally "
    "registers as a sheddable memmgr consumer, so global pressure "
    "evicts it (ladder rung cache_evict) before any working state is "
    "force-spilled, whatever this cap says.")
CACHE_SUBPLAN = _opt(
    "auron.cache.subplan", bool, True,
    "Cache materialized SUBPLAN outputs (broadcast relations keyed by "
    "per-node fingerprints computed at planning time) in addition to "
    "full results, so queries that differ in their outer plan but "
    "share a broadcast subtree reuse the built relation. Only "
    "meaningful while auron.cache.enabled is on.")
CACHE_AOT_TOP_N = _opt(
    "auron.cache.aot_top_n", int, 0,
    "Ahead-of-time warming at Session init (cache/aot.py): execute the "
    "top-N plan signatures by submission count from the aot_plans "
    "inventory (recorded next to auron.xla_cache_dir) and resumable "
    "journals, driving their compiles through the central program "
    "registry and the persistent XLA cache before the first user "
    "query. 0 (the default) disables; the warmer never raises — "
    "failures surface in cache/aot.last_stats() and fail the "
    "perf_gate cache arm.")

# failure recovery
TASK_MAX_RETRIES = _opt(
    "auron.task.max_retries", int, 2,
    "Transient-failure retries per (plan, partition) task in the driver "
    "collect path. The engine is functional, so a retry is an exact "
    "partition-granularity recompute (the recovery unit the reference "
    "delegates to Spark's task scheduler, SURVEY §5.3); cancellation is "
    "never retried. 0 disables.")
TASK_RETRY_BACKOFF_S = _opt(
    "auron.task.retry_backoff_s", float, 0.0,
    "Base backoff before each task retry attempt. The driver sleeps a "
    "uniform random amount in [0, min(cap, base * 2^attempt)] — "
    "exponential backoff with FULL jitter, so concurrently failed "
    "partitions don't retry in lockstep against the same external "
    "system. Keep 0 for in-process transients; set >0 when retries "
    "wait out external systems (remote FS, RSS service).")
TASK_RETRY_BACKOFF_MAX_S = _opt(
    "auron.task.retry_backoff_max_s", float, 30.0,
    "Cap on the exponential retry backoff window (the 'min(cap, ...)' "
    "bound): attempt k draws its sleep from [0, min(cap, "
    "retry_backoff_s * 2^k)].")

# crash-safe query journal (runtime/journal.py)
JOURNAL_DIR = _opt(
    "auron.journal.dir", str, "",
    "Directory of the crash-safe query journal. When set, every "
    "top-level query writes a per-query journal (plan fingerprint, "
    "source-snapshot fingerprints, the exchange DAG, and an "
    "append-only log of committed RSS map outputs recorded at the "
    "durable tier's existing commit() boundary), and the planner "
    "routes the query's shuffle exchanges through the durable RSS tier "
    "under <dir>/rss/<journal stem> so shuffle stages survive the "
    "process. After a crash, Session.resume(query_id) — or "
    "re-submission of the identical plan with auron.journal.reuse on — "
    "re-plans, validates the fingerprints, skips the map side of every "
    "fully-committed exchange (reducers fetch straight from the "
    "journaled RSS files), skips individual committed map outputs of "
    "partially-committed hash/round-robin/single exchanges, and "
    "recomputes only what the durable tier never received — resumed "
    "results are bit-identical to a fresh run, group order included. "
    "Journals are deleted at query completion (and by Session.close); "
    "a startup sweep garbage-collects journals/RSS run directories "
    "whose owning process is dead and whose state is not resumable "
    "(utils/liveness.py pid+epoch check). Empty (default) disables "
    "journaling entirely: shuffles stay on the in-memory device-buffer "
    "tier and a crash loses in-flight queries (the pre-journal "
    "posture).")
JOURNAL_REUSE = _opt(
    "auron.journal.reuse", bool, True,
    "Allow Session.execute to ADOPT an existing resumable journal "
    "whose plan fingerprint AND source-snapshot fingerprints match the "
    "submitted query (the crashed-and-resubmitted dashboard case): the "
    "adopted journal's committed exchanges are skipped exactly like "
    "Session.resume. Only journals not currently open in a live "
    "process are adoptable; fingerprint mismatch or a corrupt journal "
    "falls back to a fresh run (classified handling, never a wrong "
    "answer). Off mints a fresh journal per submission.")
JOURNAL_RETENTION_S = _opt(
    "auron.journal.retention_s", float, 7 * 24 * 3600.0,
    "Age cap on the resume inventory: the startup sweep garbage-"
    "collects a DEAD process's resumable journal — and with it the "
    "journal's RSS run directory holding real shuffle bytes — once "
    "the journal file has not been touched for this many seconds. "
    "Without a cap, a long-lived deployment with a steady trickle of "
    "failed-and-never-resumed queries (suspended serving tasks, "
    "crashed dashboards nobody re-opens) accumulates journals and "
    "multi-MB RSS dirs until the disk fills. <= 0 keeps the inventory "
    "indefinitely.")
JOURNAL_FSYNC = _opt(
    "auron.journal.fsync", bool, True,
    "fsync the journal at its durability boundaries only: the header "
    "write and each shuffle-level commit record (map-output records "
    "ride the async appender and are made durable by the next commit "
    "fsync — the journal never claims more than the RSS tier holds, "
    "because records are appended AFTER the durable tier's atomic "
    "rename). Off skips the fsync (journal durability then depends on "
    "the OS page cache surviving the crash — fine for tests, not for "
    "production).")

# fault injection (runtime/faults.py) — the deterministic chaos plane
FAULTS_PLAN = _opt(
    "auron.faults.plan", str, "",
    "Seeded fault-injection plan: 'site:kind@prob;...' over the named "
    "sites rss.{write,flush,commit,fetch}, spill.{write,read}, "
    "device.compute, task.hang, cancel.race, program.build, "
    "backend.init, memmgr.deny, sched.admit, mesh.all_to_all (per "
    "sharded-exchange round: io_error/fatal simulate a device loss the "
    "demotion path must route around, hang a straggling chip), "
    "mesh.gang (kind cancel: a cancel racing the gang door) and "
    "journal.{write,commit,load} (the crash-safe query journal: write/"
    "commit faults degrade journaling to off for that query — the run "
    "completes identical, resumability is lost; load faults surface "
    "the classified JournalCorrupt / fresh-run fallback) and "
    "fleet.{route,forward} (the fleet router: route faults fail the "
    "routing decision before any replica is contacted, forward faults "
    "break/hang the router→replica conversation mid-stream — both "
    "exercise the spill-over and failover recovery paths) with kinds "
    "io_error | fatal | corrupt | "
    "hang | cancel | deny (prob defaults to 1.0). Injected hangs poll "
    "the task's cancel registry, 'cancel' fires the task's CancelToken "
    "mid-drive (the cancel-race site), 'deny' forces the memory "
    "manager's degradation ladder. Every injection "
    "decision is a pure function of (auron.faults.seed, site, kind, "
    "event index), so failing chaos runs replay exactly. Empty (the "
    "default) disarms every site at one cached epoch-compare of "
    "overhead; arm/disarm via AuronConfig.set/unset (a direct "
    "os.environ change after first use needs faults.reset()).")
FAULTS_SEED = _opt(
    "auron.faults.seed", int, 0,
    "Seed of the fault plane's deterministic Bernoulli sequences; "
    "chaos batteries sweep it to explore injection schedules.")
FAULTS_HANG_S = _opt(
    "auron.faults.hang_s", float, 2.0,
    "Sleep injected by the 'hang' fault kind (simulates a wedged "
    "backend init; pair with auron.watchdog.init_timeout_s below it "
    "to exercise the watchdog fallback).")

# durable-tier integrity (shuffle_service.py, memmgr/spill.py)
DURABILITY_CHECKSUM = _opt(
    "auron.durability.checksum", bool, True,
    "Frame checksums (CRC32C when the image provides it, zlib CRC-32 "
    "otherwise) on RSS map-output frames and spill frames: every fetch "
    "verifies before deserializing, so a flipped byte surfaces as "
    "ShuffleCorruption (map recompute) or SpillCorruption (task "
    "recompute), never as silently wrong rows. Off writes algo-id 0 "
    "frames (same format, no verification) — the A/B knob for the "
    "checksum-overhead microbench (tools/microbench_shuffle.py).")

# backend watchdog (runtime/watchdog.py)
WATCHDOG_INIT_TIMEOUT_S = _opt(
    "auron.watchdog.init_timeout_s", float, 0.0,
    "Deadline on device/backend init (jax.devices()): past it the "
    "watchdog logs a diagnostic, falls back to the CPU platform and "
    "counts a watchdog_fallback in the metrics snapshot — the wedged "
    "axon-init failure mode that ate four rounds of bench windows "
    "(VERDICT r5). 0 (default) disables the probe entirely (no eager "
    "backend init).")
WATCHDOG_COMPILE_TIMEOUT_S = _opt(
    "auron.watchdog.compile_timeout_s", float, 0.0,
    "Deadline on the watchdog's first-compile probe (a trivial jit "
    "program): a backend that initializes but cannot compile within "
    "the deadline triggers the same CPU fallback. 0 (default) skips "
    "the probe.")
WATCHDOG_STALL_TIMEOUT_S = _opt(
    "auron.watchdog.stall_timeout_s", float, 0.0,
    "Task-level stall watchdog: executor, shuffle and spill loops beat "
    "a per-attempt heartbeat (ExecContext.checkpoint); a monitor thread "
    "flags any task silent past this timeout, writes a structured "
    "StallReport (last heartbeat site, driving thread's stack) into "
    "auron.trace.dir, and raises the classified errors.TaskStalled at "
    "the task's next cooperative poll — which the retry driver retries "
    "exactly ONCE before surfacing. Detection latency is bounded by "
    "1.25x the timeout (the monitor polls at a quarter interval). "
    "0 (default) disarms the plane (no heartbeat registration, no "
    "monitor thread).")

# query lifecycle (runtime/lifecycle.py)
QUERY_DEADLINE_S = _opt(
    "auron.query.deadline_s", float, 0.0,
    "Default per-query deadline applied by Session.execute when the "
    "caller passes no explicit df.collect(timeout_s=...): past it, the "
    "query's CancelToken self-cancels with reason 'deadline' and every "
    "cooperative poll site unwinds with errors.DeadlineExceeded — full "
    "resource cleanup, task-level backoff sleeps clamped to the "
    "remaining budget. 0 (default) = no deadline.")

# profiling
PROFILE = _opt(
    "auron.profile", bool, False,
    "Wrap task execution in a jax.profiler trace and attach per-operator "
    "device-time attribution to the finalize metrics (the role of the "
    "reference's pprof flamegraph/heap HTTP endpoints, "
    "auron/src/http/mod.rs:25-108).")
PROFILE_DIR = _opt(
    "auron.profile.dir", str, "",
    "Directory for profiler trace output; empty = a per-task directory "
    "under the system temp dir. The trace is viewable with "
    "tensorboard/xprof.")
PROFILE_ENABLED = _opt(
    "auron.profile.enabled", bool, True,
    "Host/device time attribution (auron_tpu/obs/profile.py): every "
    "jitted-program invocation through the central registry "
    "(runtime/programs.py) is timed as dispatch (host python glue until "
    "the async call returns) + device (block_until_ready wait), and "
    "per-operator timers classify the remaining wall into named host "
    "buckets (elapsed_host_{dispatch,convert,serde,iter,other}) "
    "alongside elapsed_device in the metric tree / EXPLAIN ANALYZE. "
    "Feeds the per-batch dispatch-overhead registry histograms and the "
    "per-query profile_*.jsonl export into auron.trace.dir that "
    "tools/hotspot_report.py ranks. Measured overhead < 2% (bench A/B, "
    "PERF.md 'Performance forensics'); off reduces the hot-path cost to "
    "one cached epoch compare per timer. Attribution requires the "
    "per-call sync point, so auron.metrics.device_sync=false (the "
    "maximum-throughput knob) disables the profiler too — profiling "
    "never silently serializes a run that asked for async overlap.")
PERF_GATE_TOLERANCE_PCT = _opt(
    "auron.perf_gate.tolerance_pct", float, 50.0,
    "Allowed q01 rows/s shortfall vs the checked-in per-platform "
    "baseline (tools/perf_baseline.json) before tools/perf_gate.py "
    "fails the run. Sized to this container's measured wall-clock "
    "variance (single-rep swings of +/-10-50%): the BENCH_r03->r05 "
    "regression (276k -> 108k rows/s, a 61% drop) fails the default "
    "while honest noise passes. CLI --tolerance-pct overrides.")

# tracing plane (auron_tpu/obs/trace.py)
TRACE_ENABLED = _opt(
    "auron.trace.enabled", bool, False,
    "Record the query→stage→task→operator→event span timeline "
    "(auron_tpu/obs/trace.py): task attempts and retry backoffs, "
    "program builds per compile site, shuffle write/flush/commit/fetch, "
    "spill tier decisions, injected faults (site/kind attributes) and "
    "watchdog probes. Spans are buffered lock-free per thread; the "
    "disabled hot path costs one cached epoch compare. Export with "
    "auron.trace.dir (per-query Chrome-trace JSON + JSONL) or the "
    "trace API (tools/trace_report.py summarizes a trace dir).")
TRACE_DIR = _opt(
    "auron.trace.dir", str, "",
    "Directory the tracer exports each top-level query's spans into "
    "(trace_<id>.json Chrome/Perfetto trace + trace_<id>.jsonl event "
    "log), written when the outermost Session.execute finishes. Empty "
    "(the default) keeps spans in memory for the trace API only.")
TRACE_EVENTS = _opt(
    "auron.trace.events", str, "",
    "Comma-separated span-category allowlist (query, task, program, "
    "shuffle, spill, fault, watchdog, memory, sched, mesh, journal, "
    "cache, fleet); empty records every category. "
    "Narrowing the list bounds tracing overhead on hot paths — e.g. "
    "'task,shuffle,fault' drops the per-hit program events.")
TRACE_MAX_SPANS = _opt(
    "auron.trace.max_spans", int, 200_000,
    "Ceiling on buffered spans per process; past it new spans are "
    "dropped (counted — the Chrome export records dropped_spans) so an "
    "unbounded query can never turn the tracer into a memory leak. "
    "The cap is approximate: enforcement is lock-free like recording.")
TRACE_PROPAGATE = _opt(
    "auron.trace.propagate", bool, True,
    "Cross-process trace-context propagation over the serving wire "
    "protocol: when tracing is enabled and a trace is active, "
    "AuronClient prefixes SUBMIT/SUBMIT_PLAN/RESUME with a TRACE frame "
    "(trace id + parent span id), the fleet router adds a fleet.forward "
    "hop span and forwards the context, and the replica adopts the "
    "inbound id as its query-span parent — so exports from client, "
    "router, and every replica share ONE trace id and "
    "tools/trace_report.py --stitch renders a single cross-process "
    "timeline. With tracing off (or no active trace) nothing extra is "
    "sent on the wire; overhead with tracing on is gated < 2% by the "
    "perf-gate obs-fleet arm.")

# ops plane: live telemetry endpoint (auron_tpu/obs/ops_server.py)
OPS_ENABLED = _opt(
    "auron.ops.enabled", bool, False,
    "Run the in-process ops HTTP endpoint (auron_tpu/obs/ops_server.py, "
    "stdlib ThreadingHTTPServer — the role of the reference's runtime "
    "HTTP service, auron/src/http/mod.rs:25-108): /metrics serves the "
    "process registry's Prometheus exposition, /healthz the ok-vs-"
    "degraded probe/scheduler/memmgr/mesh verdict, /queries the live "
    "query table (state, wall, tasks done/total, per-query memory vs "
    "quota, program-cache hits), /flight the flight recorder's recent-"
    "event ring as JSONL. One server per process (refcounted across "
    "Sessions/AuronServers; the last close stops it). Default off.")
OPS_PORT = _opt(
    "auron.ops.port", int, 0,
    "TCP port of the ops HTTP endpoint; 0 (default) binds an ephemeral "
    "port, logged at startup and surfaced as Session.ops_address / the "
    "AuronServer stats 'ops_port' entry (and on the serving STATS "
    "frame), so a supervisor can discover it without parsing logs.")

# serving fleet (auron_tpu/fleet/: router in front of N AuronServers)
FLEET_REPLICAS = _opt(
    "auron.fleet.replicas", int, 2,
    "Replica count booted by the fleet tooling (tools/load_report.py "
    "--fleet, the perf-gate fleet arm, chaos fleet_failover). The "
    "router itself takes an explicit replica list and ignores this "
    "knob — it sizes HARNESSES, not the router.")
FLEET_POLL_S = _opt(
    "auron.fleet.poll_s", float, 0.5,
    "Bounded-staleness interval of the router's health poll loop: each "
    "tick scrapes every replica's /healthz + /queries (occupancy, "
    "memmgr pressure, watchdog state, warm plan fingerprints) into an "
    "immutable snapshot the pure routing functions decide over. A "
    "snapshot older than 4 poll intervals is treated as unreachable — "
    "routing never blocks on a scrape.")
FLEET_AFFINITY = _opt(
    "auron.fleet.affinity", bool, True,
    "Warm-affinity routing: a submission whose plan fingerprint (the "
    "cache/identity.py result-key fp) matches a replica's warm result-"
    "cache inventory — or that this router recently routed — lands on "
    "that replica so the plan-fingerprint cache's warm path survives "
    "going multi-process. Off routes purely by load.")
FLEET_FAILOVER = _opt(
    "auron.fleet.failover", bool, True,
    "Journal-backed failover: on replica death mid-query (connection "
    "loss confirmed by the liveness plane's pid+epoch verdict) the "
    "router RESUMEs the journaled query on a survivor from its "
    "committed shuffle stages (bit-identical), and re-executes non-"
    "journaled in-flight queries from scratch under a result-key "
    "idempotency guard. Off surfaces replica death to the client as a "
    "classified ReplicaUnavailable.")
FLEET_OPS_PORT = _opt(
    "auron.fleet.ops_port", int, -1,
    "TCP port of the ROUTER's own ops HTTP endpoint (federated "
    "/metrics merging every replica's scraped exposition re-labeled "
    "replica=\"rN\", /fleet/queries merging the live query tables, "
    "/healthz with per-replica up/down rows). 0 binds an ephemeral "
    "port (surfaced as FleetRouter.ops_address and on the router STATS "
    "frame); a negative value (default) disables the router endpoint.")
CLIENT_TIMEOUT_S = _opt(
    "auron.client.timeout_s", float, 30.0,
    "AuronClient socket budget: connect timeout per attempt and read "
    "timeout on every subsequent frame (a dead peer surfaces as a "
    "classified RemoteEngineError instead of hanging the client "
    "forever). Connection attempts retry with jittered backoff inside "
    "this same budget; <=0 disables (legacy block-forever behavior).")

# always-on flight recorder (auron_tpu/obs/flight_recorder.py)
FLIGHT_ENABLED = _opt(
    "auron.flight.enabled", bool, True,
    "Arm the always-on flight recorder: a bounded per-thread ring of "
    "the most recent structured events across ALL trace categories "
    "that records even while auron.trace.enabled is off (the trace "
    "plane tees into it at emit time), so the last seconds before any "
    "failure are reconstructable from /flight or a post-mortem bundle "
    "without having had tracing on. Overhead is measured by the bench "
    "three-arm A/B's 'norec' arm (flight_overhead_pct, gate <2% — "
    "PERF.md 'Ops plane'); off restores the bare cached-epoch-compare "
    "disabled path.")
FLIGHT_RING_EVENTS = _opt(
    "auron.flight.ring_events", int, 4096,
    "Events retained per THREAD by the flight recorder's ring (a "
    "collections.deque maxlen — O(1) memory, oldest evicted first). "
    "Sized so several seconds of control-plane history (retries, "
    "sheds, fault injections, admission decisions) survive on every "
    "thread without the ring ever becoming a leak.")

# post-mortem failure bundles (auron_tpu/obs/bundle.py)
BUNDLE_ENABLED = _opt(
    "auron.bundle.enabled", bool, False,
    "Write a self-contained post-mortem bundle directory "
    "(bundle_<query_id>/ under auron.bundle.dir) when a query ends in "
    "a CLASSIFIED failure — MemoryExhausted shed, DeadlineExceeded, "
    "TaskStalled exhaustion, unrecovered MeshUnavailable, "
    "JournalCorrupt/JournalInvalidated: flight-recorder dump, explain "
    "tree with metrics, scheduler/memmgr/mesh stats, probe + stall "
    "reports, journal state and a config snapshot with the trace "
    "salt. Plain cancels and admission sheds (no resources ever "
    "existed) write nothing. tools/ops_report.py renders a bundle "
    "into a human post-mortem. Default off.")
BUNDLE_DIR = _opt(
    "auron.bundle.dir", str, "",
    "Directory for post-mortem bundles; empty (default) places them "
    "under '<system temp>/auron-bundles'.")
BUNDLE_MAX_BUNDLES = _opt(
    "auron.bundle.max_bundles", int, 16,
    "Retention cap on bundle directories under auron.bundle.dir: past "
    "it the OLDEST bundles are evicted after each write, so a crash "
    "loop can never fill the disk with post-mortems. <= 0 keeps "
    "everything (tests only).")

# process metrics registry (auron_tpu/obs/registry.py)
METRICS_REGISTRY = _opt(
    "auron.metrics.registry", bool, True,
    "Aggregate per-task observations (task seconds histogram, retries, "
    "recovery/spill/program counters) into the process-wide metrics "
    "registry (auron_tpu/obs/registry.py), whose Prometheus text "
    "exposition (render_prometheus) is the scrape surface — the role "
    "of the reference's pprof HTTP endpoints. Off skips the per-task "
    "observation entirely.")

# per-query cost ledger (auron_tpu/obs/ledger.py)
LEDGER_ENABLED = _opt(
    "auron.ledger.enabled", bool, True,
    "Assemble a compact per-query cost ledger at query finalize "
    "(auron_tpu/obs/ledger.py): device seconds vs host-bucket splits, "
    "shuffle/spill/combine bytes and rows, cache hits, retries and "
    "recovery counts, replica hops. The record rides the serving DONE "
    "frame, lands in failure bundles (ledger.json), and surfaces in "
    "AuronClient.stats() and tools/load_report.py — the accounting "
    "unit for admission and capacity decisions at fleet scale. "
    "Overhead is gated < 2% by the perf-gate obs-fleet arm; off skips "
    "assembly entirely (no ledger on DONE, none retained).")

# metrics / sinks
METRICS_DEVICE_SYNC = _opt(
    "auron.metrics.device_sync", bool, True,
    "Block on kernel outputs inside per-operator timers so "
    "elapsed_compute measures device compute, not async dispatch. "
    "Costs pipelining overlap; disable for maximum throughput runs.")
SINK_BUFFER_ROWS = _opt(
    "auron.sink.buffer_rows", int, 1 << 17,
    "Rows a file sink buffers before flushing a row group / dataset "
    "fragment — bounds sink host memory for arbitrarily large "
    "partitions.")

# aggregation
AGG_INITIAL_CAPACITY = _opt(
    "auron.agg.initial_capacity", int, 4096,
    "Initial group-state capacity of the agg merge kernel; grows by "
    "power-of-two re-bucketing when exceeded.")
AGG_PARTIAL_SKIP_ENABLED = _opt(
    "auron.agg.partial_skip.enabled", bool, True,
    "Adaptive partial-agg skipping: when the observed group/input "
    "cardinality ratio stays high, the partial stage stops merging and "
    "passes rows through in state layout (the reference's "
    "spark.auron.partialAggSkipping.*, agg_ctx.rs:63-196).")
AGG_PARTIAL_SKIP_RATIO = _opt(
    "auron.agg.partial_skip.ratio", float, 0.8,
    "Cardinality ratio (distinct groups / input rows) at or above which "
    "the partial agg switches to pass-through.")
AGG_PARTIAL_SKIP_MIN_ROWS = _opt(
    "auron.agg.partial_skip.min_rows", int, 1 << 16,
    "Input rows to observe before the skip decision is made.")

# whole-stage fusion (ir/planner.fuse_stages + ops/fused.py)
FUSION_ENABLED = _opt(
    "auron.fusion.enabled", bool, True,
    "Whole-stage XLA fusion: the planner chains maximal runs of "
    "row-local operators (filter, project, expand, limit-within-batch, "
    "rename — plus the shuffle-split and hash-join-probe prologues) "
    "into one jit-compiled program per stage, so intermediates never "
    "materialize in HBM and the compile budget pays one program per "
    "chain instead of one per operator. Off executes every operator as "
    "its own program. The plan NORMALIZATION half of the pass (pre-agg "
    "key/value projection, pure-projection elision under aggs) applies "
    "under BOTH settings — that is what keeps on/off results "
    "bit-identical (eager vs jitted float arithmetic differs in the "
    "last ulp), so 'off' restores the per-operator program layout, not "
    "the exact pre-fusion plan shape.")
FUSION_MAX_STAGE_OPS = _opt(
    "auron.fusion.max_stage_ops", int, 8,
    "Longest operator chain a single fused stage may contain. Longer "
    "chains split into multiple stages — a bound on per-program trace "
    "size and compile time (an over-long chain compiles one huge XLA "
    "program whose build cost defeats the purpose on the tunneled "
    "chip).")
FUSION_COMBINE = _opt(
    "auron.fusion.combine", bool, True,
    "Map-side combine: a hash exchange fed by an eligible partial "
    "aggregation folds the agg's update/merge into the shuffle-split "
    "program itself, so groups are combined per map batch (host route) "
    "or per shard round (all_to_all route) BEFORE rows cross the "
    "exchange. Eligibility mirrors the hashtable dispatch rule: "
    "reassociation-exact accumulator kinds only (integer/decimal sums, "
    "min/max, first, count) — float sums and element-collecting kinds "
    "keep the unfolded partial-agg operator, so results stay "
    "bit-identical either way. Off makes the folded exchange pass "
    "state-layout rows through UNCOMBINED (the partial-skip "
    "pass-through shape) — the honest A/B for shuffle-byte accounting, "
    "and what the cost model picks per exchange when observed combine "
    "ratios say combining does not pay. TRACE-SEMANTIC knob: it "
    "changes what the compiled split program computes, so it is "
    "resolved from the PROCESS-GLOBAL config and rides every "
    "program-cache key (runtime/programs.py trace salt).")
FUSION_COST_MODEL = _opt(
    "auron.fusion.cost_model", bool, True,
    "Cost-based fusion plan selection (ir/cost.py): the planner "
    "enumerates candidate fusion decisions per site (combine vs "
    "pass-through at each foldable exchange, probe-into-consumer fold "
    "at each hash join) and scores them with a small cost model fed by "
    "recorded per-site statistics from prior runs of the same plan "
    "fingerprint (rows/batch, observed combine ratio), falling back to "
    "a safe static prior when no history exists. Off restores "
    "greedy-maximal fusion: always fold, always combine where "
    "eligible. TRACE-SEMANTIC knob: the selected plan decides which "
    "programs are built, so it rides every program-cache key "
    "(runtime/programs.py trace salt).")

# hand-written kernels (auron_tpu/kernels)
KERNELS_ENABLED = _opt(
    "auron.kernels.enabled", bool, True,
    "Allow the dense grouped-aggregation kernels (Pallas VMEM / one-hot "
    "matmul) when the planner bounds the group-key domain; off forces "
    "every aggregation through the general sort-based path "
    "(kernels/dispatch.py).")
KERNELS_MAX_KEY_DOMAIN = _opt(
    "auron.kernels.max_key_domain", int, 1 << 16,
    "Largest bounded key domain eligible for the dense grouped-agg "
    "kernels; plans with a larger (or unknown) bound fall back to the "
    "sort path. Hard-capped at 2^16 by the kernels' (hi, lo) byte grid "
    "decomposition.")
# device-resident hash table (auron_tpu/hashtable)
HASHTABLE_ENABLED = _opt(
    "auron.hashtable.enabled", bool, True,
    "Allow the device-resident open-addressing hash table "
    "(auron_tpu/hashtable) on the general (unbounded-key) aggregation "
    "path, distinct dedup, and the hash-join candidate search; off "
    "forces the sort-based formulations everywhere "
    "(kernels/dispatch.select_hash_agg).")
HASHTABLE_BACKEND = _opt(
    "auron.hashtable.backend", str, "auto",
    "General-agg grouping backend: 'auto' routes aggregations whose "
    "accumulators are reassociation-exact (integer/decimal sums, "
    "min/max, first, count) through the hash table and keeps float "
    "sums on the sort path so results stay bit-identical either way; "
    "'hash' forces the hash table wherever its kinds are structurally "
    "supported (float scatter-adds may differ from the sort path in "
    "the last ulp); 'sort' disables the hash path entirely.")
HASHTABLE_LOAD_FACTOR = _opt(
    "auron.hashtable.load_factor", float, 0.5,
    "Maximum occupancy of the device hash table before a power-of-two "
    "growth re-bucket (the auron.agg.initial_capacity growth "
    "discipline). Lower values buy shorter probe chains with more "
    "device memory.")
HASHTABLE_MAX_PROBE_ROUNDS = _opt(
    "auron.hashtable.max_probe_rounds", int, 64,
    "Probe rounds (double-hashed open addressing) the vectorized "
    "insert/probe loop runs before declaring overflow; an overflowing "
    "insert grows the table and retries, and pathological repeat "
    "overflow falls back to the sort path for the rest of the stream.")

# map semantics
MAP_KEY_DEDUP_POLICY = _opt(
    "auron.map.key_dedup_policy", str, "LAST_WIN",
    "Duplicate-key policy of the map constructors (map, create_map, "
    "map_from_arrays, map_from_entries, map_concat): 'LAST_WIN' keeps "
    "the last entry per key (Spark's legacy policy — this engine's "
    "default, because a jit-compiled kernel cannot raise data-dependent "
    "errors); 'EXCEPTION' (Spark's default) raises a deterministic "
    "ValueError when the construction is evaluated eagerly, and inside "
    "a jit-fused stage — where raising is impossible — nulls the "
    "offending rows instead. TRACE-SEMANTIC knob: it changes what a "
    "compiled kernel computes, so it is resolved from the PROCESS-GLOBAL "
    "config (AuronConfig.set on get_config(), or the env var) and rides "
    "every program-cache key (runtime/programs.py trace salt); "
    "per-ExecContext session overrides are not honored for it.")

KERNELS_BACKEND = _opt(
    "auron.kernels.backend", str, "auto",
    "Dense grouped-agg backend: 'auto' compiles the Pallas VMEM kernel "
    "natively on a real TPU and uses the one-hot matmul formulation "
    "elsewhere; 'pallas' forces the Pallas kernel (interpreter on "
    "non-TPU platforms — how the differential battery verifies it on "
    "CPU); 'dense' forces the matmul path; 'sort' disables the dense "
    "path entirely.")


# --------------------------------------------------------------------------
# resolution
# --------------------------------------------------------------------------

class AuronConfig:
    """One resolved configuration: programmatic overrides > env > default."""

    def __init__(self, overrides: Optional[dict] = None):
        self._overrides: dict[str, Any] = {}
        self._lock = threading.Lock()
        for k, v in (overrides or {}).items():
            self.set(k, v)

    def set(self, key: str, value) -> "AuronConfig":
        opt = _REGISTRY.get(key)
        if opt is None:
            raise KeyError(f"unknown config option {key!r}; "
                           f"known: {sorted(_REGISTRY)}")
        if isinstance(value, str) and opt.dtype is not str:
            value = opt.parse(value)
        if opt.dtype is float and isinstance(value, int):
            value = float(value)
        if not isinstance(value, opt.dtype):
            raise TypeError(f"{key} expects {opt.dtype.__name__}, "
                            f"got {type(value).__name__}")
        with self._lock:
            self._overrides[key] = value
        _bump_epoch()
        return self

    def unset(self, key: str) -> None:
        with self._lock:
            self._overrides.pop(key, None)
        _bump_epoch()

    def get(self, key: str):
        opt = _REGISTRY.get(key)
        if opt is None:
            raise KeyError(f"unknown config option {key!r}")
        with self._lock:
            if key in self._overrides:
                return self._overrides[key]
        raw = os.environ.get(opt.env_var)
        if raw is not None:
            return opt.parse(raw)
        return opt.default


#: monotonic count of set()/unset() calls across ALL AuronConfig
#: instances — a cheap change signal for hot-path caches (the fault
#: plane keys its armed/disarmed verdict on it so an unarmed site check
#: costs one int compare, not a lock + env lookup). Direct os.environ
#: mutation after the first resolution is NOT detected; knobs consulted
#: on hot paths change via set()/unset().
_MUTATION_EPOCH = 0


def _bump_epoch() -> None:
    global _MUTATION_EPOCH
    _MUTATION_EPOCH += 1


def config_epoch() -> int:
    """Current config-mutation epoch (any instance, any key)."""
    return _MUTATION_EPOCH


#: process-wide default config; ExecContext carries a per-execution one
#: that defaults to this (the "session" layer)
_GLOBAL = AuronConfig()

#: options whose value is read DURING kernel tracing and changes what
#: the compiled program computes (not just how the plan is shaped).
#: Their current values ride every program-cache key as the trace salt
#: (runtime/programs.py), so flipping one can never serve a stale trace.
TRACE_SEMANTIC_KEYS = (MAP_KEY_DEDUP_POLICY, FUSION_COMBINE, FUSION_COST_MODEL)


def trace_salt() -> tuple:
    """Current values of the trace-semantic options, resolved from the
    process-global config (these knobs are global by contract — see
    their docs)."""
    return tuple(_GLOBAL.get(k) for k in TRACE_SEMANTIC_KEYS)


def get_config() -> AuronConfig:
    return _GLOBAL


def options() -> list[ConfigOption]:
    return sorted(_REGISTRY.values(), key=lambda o: o.key)


def generate_docs() -> str:
    """Markdown config reference (the doc-generator analogue of the
    reference's SparkAuronConfigurationDocGenerator.java)."""
    lines = [
        "# Configuration reference",
        "",
        "Resolution order: session override (`AuronConfig.set`) > env var "
        "> default. Env binding: drop the `auron.` prefix, upper-case, "
        "dots to underscores, prepend `AURON_CONF_`.",
        "",
        "| Option | Type | Default | Env var | Description |",
        "|---|---|---|---|---|",
    ]
    for o in options():
        default = repr(o.default) if o.dtype is str else str(o.default)
        lines.append(f"| `{o.key}` | {o.dtype.__name__} | {default} "
                     f"| `{o.env_var}` | {o.doc} |")
    return "\n".join(lines) + "\n"
