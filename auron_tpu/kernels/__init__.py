"""Hand-written accelerator kernels + the planner's dispatch policy.

Layout:
- ``grouped_agg``  — dense grouped-aggregation kernels over a bounded
  key domain: the Pallas VMEM-accumulate sum/count kernel (with an
  interpret-mode path for CPU verification), the one-hot matmul
  formulation, and exact scatter reductions.
- ``dispatch``     — per-plan kernel selection (Pallas vs dense matmul
  vs the general sort path), keyed on key-domain bound, dtype set, and
  platform.
- ``registry``     — kernel capability registry + selection/fallback/
  interpret/bytes-moved counters.
"""

from auron_tpu.kernels import dispatch, grouped_agg, registry  # noqa: F401
