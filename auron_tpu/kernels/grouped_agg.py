"""Grouped aggregation over a bounded key domain — the dense kernels.

The engine's general aggregation is sort-based (ops/agg.py) and pays
O(n log n) VPU work per batch. When the planner can bound the group-key
domain (table stats, dictionary domains), the aggregation becomes a
dense accumulation problem with two much cheaper formulations:

``pallas_sum_count``
    The Pallas VMEM-accumulate kernel (promoted from
    tools/microbench_pallas.py): per 2048-row block the (hi, lo) one-hot
    tiles are built IN VMEM, the [hi, lo] sum/count grids accumulate IN
    VMEM across the whole grid, and HBM traffic collapses to the
    ~12 B/row inputs. The XLA one-hot formulation materializes
    [n, 256..1024] one-hot operands in HBM (~4 GB per 1M rows); this
    kernel is the route from that memory-bound 0.82x to the >=3x bar.
    ``interpret=True`` runs the same kernel through the Pallas
    interpreter, so it executes (and is differentially verified) under
    ``JAX_PLATFORMS=cpu``; real Mosaic compiles happen only when the
    dispatch policy sees a TPU platform (kernels/dispatch.py).

``dense_matmul_sum_count``
    The one-hot einsum formulation (the flagship ``_q01_kernel`` math),
    lax.map-tiled; compiles everywhere XLA runs.

``scatter_reduce``
    Exact dense-domain scatter (.at[k].add/min/max): the formulation for
    reductions the MXU can't express (min/max) and for integer sums,
    where bit-exactness vs the general path is part of the contract.

Accuracy contract (shared with __graft_entry__._q01_kernel): the f32
value operand is split into 3 additive bf16-exact terms via bit masking,
so a single DEFAULT-precision bf16 MXU pass reproduces f32-HIGHEST
quality (~1e-7 rel); counts are 0/1-exact. Sums accumulate in f32 —
exact whenever inputs are integer-valued and per-key totals stay below
2^24 (the differential battery exploits this for bit-exact checks);
callers wanting exact float-independent sums use ``scatter_reduce``.

Key contract: keys must already lie in [0, key_domain) — callers clip
(the engine additionally tracks the observed key range and fails the
task with a deterministic ValueError when the planner's bound was
wrong, ops/agg.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

#: lane width of the dense grids: keys decompose as (k >> 8, k & 255) so
#: the minor grid dimension matches the TPU's 256-wide key byte
_LANES = 256

#: second-minor tile granularity for f32 on TPU — the hi grid dimension
#: rounds up to this so Mosaic gets well-shaped blocks
_SUBLANES = 8

#: the hi/lo byte decomposition caps the supported domain
MAX_KEY_DOMAIN = _LANES * _LANES


def grid_dims(key_domain: int) -> tuple[int, int]:
    """(gh, gl) grid shape covering ``key_domain`` keys: gl is the
    256-wide lo byte, gh covers the hi byte rounded up to the f32
    sublane granularity."""
    if not 0 < key_domain <= MAX_KEY_DOMAIN:
        raise ValueError(
            f"key_domain {key_domain} outside (0, {MAX_KEY_DOMAIN}]")
    gh = -(-key_domain // _LANES)
    gh = -(-gh // _SUBLANES) * _SUBLANES
    return gh, _LANES


def _mask16(x):
    """Top-16-bit truncation of f32 via bit masking: exactly
    bf16-representable, and opaque to XLA's bf16-propagation pass (which
    folds convert-based f32->bf16->f32 pairs and would collapse a
    convert-based residual split)."""
    bits = lax.bitcast_convert_type(x, jnp.uint32)
    return lax.bitcast_convert_type(bits & jnp.uint32(0xFFFF0000),
                                    jnp.float32)


def _split3(v):
    """f32 -> 3 additive bf16-exact terms (v == v1 + v2 + v3)."""
    v1 = _mask16(v)
    r = v - v1
    v2 = _mask16(r)
    return v1, v2, r - v2


# ---------------------------------------------------------------------------
# Pallas VMEM-accumulate kernel
# ---------------------------------------------------------------------------

def _vmem_agg_kernel(gh, k_ref, v_ref, c_ref, sums_ref, cnts_ref):
    """One grid step: fold a [1, blk] row block into the VMEM-resident
    [gh, 256] sum/count grids. The one-hot tiles never leave VMEM."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        cnts_ref[:] = jnp.zeros_like(cnts_ref)

    k = k_ref[:]          # [1, blk] int32 in [0, gh * 256)
    v = v_ref[:]          # [1, blk] f32, nulls already zeroed
    c = c_ref[:]          # [1, blk] f32 0/1 valid mask
    blk = k.shape[1]

    v1, v2, v3 = _split3(v)

    iota_h = lax.broadcasted_iota(jnp.int32, (blk, gh), 1)
    iota_l = lax.broadcasted_iota(jnp.int32, (blk, _LANES), 1)
    hi = (k.reshape(blk, 1) >> 8) == iota_h
    lo = ((k.reshape(blk, 1) & 255) == iota_l).astype(jnp.bfloat16)

    def masked(vals):
        return jnp.where(hi, vals.reshape(blk, 1), 0.0).astype(jnp.bfloat16)

    lhs = jnp.concatenate(
        [masked(v1), masked(v2), masked(v3), masked(c)], axis=1)
    out = lax.dot_general(lhs, lo, (((0,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    sums_ref[:] += out[:gh] + out[gh:2 * gh] + out[2 * gh:3 * gh]
    cnts_ref[:] += out[3 * gh:]


@functools.partial(jax.jit,
                   static_argnames=("key_domain", "blk", "interpret"))
def pallas_sum_count(k, v, c, key_domain: int, blk: int = 2048,
                     interpret: bool = False):
    """Dense grouped (sum, count) over ``key_domain`` keys.

    k: int32[n] in [0, key_domain); v: f32[n] with nulls zeroed;
    c: f32[n] 0/1 valid mask. n must be a multiple of ``blk`` (batch
    capacities are power-of-two bucketed, so pass blk=min(blk, n)).
    Returns (sums f32[key_domain], counts f32[key_domain]).
    """
    n = k.shape[0]
    blk = min(blk, n)
    if n % blk:
        raise ValueError(f"rows {n} not a multiple of block {blk}")
    gh, gl = grid_dims(key_domain)
    grid = n // blk
    sums, cnts = pl.pallas_call(
        functools.partial(_vmem_agg_kernel, gh),
        out_shape=(jax.ShapeDtypeStruct((gh, gl), jnp.float32),
                   jax.ShapeDtypeStruct((gh, gl), jnp.float32)),
        grid=(grid,),
        in_specs=[pl.BlockSpec((1, blk), lambda i: (0, i)),
                  pl.BlockSpec((1, blk), lambda i: (0, i)),
                  pl.BlockSpec((1, blk), lambda i: (0, i))],
        out_specs=(pl.BlockSpec((gh, gl), lambda i: (0, 0)),
                   pl.BlockSpec((gh, gl), lambda i: (0, 0))),
        interpret=interpret,
    )(k.reshape(1, n), v.reshape(1, n), c.reshape(1, n))
    return sums.reshape(-1)[:key_domain], cnts.reshape(-1)[:key_domain]


# ---------------------------------------------------------------------------
# one-hot matmul formulation (XLA; compiles everywhere)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("key_domain", "block"))
def dense_matmul_sum_count(k, v, c, key_domain: int, block: int = 1 << 16):
    """Same contract as ``pallas_sum_count`` via the one-hot einsum
    formulation: lax.map tiles the one-hots so the HBM working set stays
    in tens of MB. This is the flagship ``_q01_kernel`` math, shared so
    the entry point and the engine dispatch one implementation."""
    n = k.shape[0]
    block = min(block, n)
    if n % block:
        raise ValueError(f"rows {n} not a multiple of block {block}")
    gh, gl = grid_dims(key_domain)
    nb = n // block
    kb = k.reshape(nb, block)
    vb = v.reshape(nb, block)
    cb = c.reshape(nb, block)

    def block_grids(inp):
        kk, vals, cnts = inp
        hi = jax.nn.one_hot(kk >> 8, gh, dtype=jnp.float32)
        lo = jax.nn.one_hot(kk & 255, gl, dtype=jnp.float32)
        v1, v2, v3 = _split3(vals)
        lhs = jnp.concatenate(
            [hi * v1[:, None], hi * v2[:, None], hi * v3[:, None],
             hi * cnts[:, None]], axis=1)
        out = jnp.einsum("nh,nl->hl", lhs, lo,
                         precision=lax.Precision.DEFAULT,
                         preferred_element_type=jnp.float32)
        sums = out[:gh] + out[gh:2 * gh] + out[2 * gh:3 * gh]
        return sums, out[3 * gh:]

    sum_blocks, cnt_blocks = lax.map(block_grids, (kb, vb, cb))
    sums = jnp.sum(sum_blocks, axis=0).reshape(-1)[:key_domain]
    cnts = jnp.sum(cnt_blocks, axis=0).reshape(-1)[:key_domain]
    return sums, cnts


def sum_count(k, v, c, key_domain: int, backend: str = "dense_matmul",
              interpret: bool = False, blk: int = 2048):
    """Backend-dispatched dense grouped (sum, count) — the single entry
    the engine and the flagship lowering call with a
    ``kernels.dispatch`` decision."""
    if backend == "pallas_vmem":
        return pallas_sum_count(k, v, c, key_domain, blk=blk,
                                interpret=interpret)
    if backend == "dense_matmul":
        return dense_matmul_sum_count(k, v, c, key_domain)
    raise ValueError(f"unknown dense grouped-agg backend {backend!r}")


# ---------------------------------------------------------------------------
# exact dense-domain scatter reductions
# ---------------------------------------------------------------------------

def scatter_reduce(kind: str, k, v, valid, key_domain: int, dtype):
    """Exact dense reduction via XLA scatter — the formulation for
    reduce kinds the MXU grids can't express (min/max) and for integer
    sums where bit-exactness is contractual. Traffic is the same
    ~12 B/row class as the VMEM kernel (inputs + a [domain] accumulator,
    no one-hot materialization).

    Invalid rows contribute the reduction's neutral; the caller masks
    group existence separately (a key whose rows are all invalid still
    returns the neutral here).
    """
    if kind == "sum":
        vals = jnp.where(valid, v.astype(dtype), jnp.asarray(0, dtype))
        return jnp.zeros(key_domain, dtype).at[k].add(vals, mode="drop")
    if kind == "count":
        ones = valid.astype(jnp.int64)
        return jnp.zeros(key_domain, jnp.int64).at[k].add(ones, mode="drop")
    if kind in ("min", "max"):
        if jnp.issubdtype(dtype, jnp.floating):
            neutral = jnp.asarray(jnp.inf if kind == "min" else -jnp.inf,
                                  dtype)
        else:
            info = jnp.iinfo(dtype)
            neutral = jnp.asarray(info.max if kind == "min" else info.min,
                                  dtype)
        vals = jnp.where(valid, v.astype(dtype), neutral)
        acc = jnp.full(key_domain, neutral, dtype)
        if kind == "min":
            return acc.at[k].min(vals, mode="drop")
        return acc.at[k].max(vals, mode="drop")
    raise ValueError(f"unknown scatter reduction {kind!r}")


# Pallas imports last so the module loads (and scatter/dense paths work)
# even if the installed jax lacks the experimental pallas package — the
# dispatch policy gates pallas selection on PALLAS_AVAILABLE.
try:  # pragma: no cover - environment probe
    from jax.experimental import pallas as pl  # noqa: E402
    PALLAS_AVAILABLE = True
except Exception:  # pragma: no cover
    pl = None
    PALLAS_AVAILABLE = False
