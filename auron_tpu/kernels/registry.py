"""Kernel registry + per-kernel counters.

The engine's hand-written accelerator kernels (the Pallas VMEM
grouped-agg, the one-hot matmul grids) register here with their
capability envelope so the dispatch policy (kernels/dispatch.py) can
reason over data instead of hard-coded if-chains, and so operational
introspection has one place to ask "which kernels exist, what can they
do, and how often did each get picked".

Two counter surfaces:

- a process-global ``KernelStats`` per kernel (selected / fallback /
  interpret counts, bytes-moved estimate) readable via ``snapshot()`` —
  the long-lived serving view;
- the per-task ``MetricsSet`` the dispatch call-site passes in, which
  rides the existing metrics snapshot (ExecutionRuntime.finalize) under
  the ``kernels`` operator key — the per-query view.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class KernelInfo:
    """One registered kernel implementation.

    ``name`` is the dispatch identifier; ``reductions`` the reduce kinds
    it accelerates; ``max_key_domain`` the dense key-domain ceiling its
    grid decomposition supports (the hi/lo byte split caps at 2^16);
    ``platforms`` where it compiles natively ('*' = anywhere XLA runs —
    interpretable kernels additionally run anywhere via interpret mode).
    """

    name: str
    description: str
    reductions: tuple
    max_key_domain: int
    platforms: tuple
    interpretable: bool = False


class KernelStats:
    """Monotonic per-kernel counters (thread-safe adds)."""

    __slots__ = ("selected", "fallback", "interpret", "bytes_moved_est",
                 "_lock")

    def __init__(self):
        self.selected = 0
        self.fallback = 0
        self.interpret = 0
        self.bytes_moved_est = 0
        self._lock = threading.Lock()

    def add(self, name: str, v: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + v)

    def snapshot(self) -> dict:
        with self._lock:
            return {"selected": self.selected, "fallback": self.fallback,
                    "interpret": self.interpret,
                    "bytes_moved_est": self.bytes_moved_est}


_REGISTRY: dict[str, KernelInfo] = {}
_STATS: dict[str, KernelStats] = {}
_LOCK = threading.Lock()


def register(info: KernelInfo) -> KernelInfo:
    with _LOCK:
        assert info.name not in _REGISTRY, f"duplicate kernel {info.name}"
        _REGISTRY[info.name] = info
        _STATS[info.name] = KernelStats()
    return info


def lookup(name: str) -> Optional[KernelInfo]:
    return _REGISTRY.get(name)


def kernels() -> list[KernelInfo]:
    return sorted(_REGISTRY.values(), key=lambda k: k.name)


def stats(name: str) -> KernelStats:
    with _LOCK:
        if name not in _STATS:
            # fallback pseudo-kernels (e.g. "sort") get counters without
            # requiring a capability registration
            _STATS[name] = KernelStats()
        return _STATS[name]


def snapshot() -> dict:
    """{kernel name: counter dict} — the process-global view."""
    with _LOCK:
        items = list(_STATS.items())
    return {k: s.snapshot() for k, s in items}


# ---------------------------------------------------------------------------
# built-in kernels
# ---------------------------------------------------------------------------

PALLAS_VMEM = register(KernelInfo(
    name="pallas_vmem",
    description=(
        "Pallas VMEM-accumulate grouped sum/count: one-hot tiles built in "
        "VMEM per row block, [hi, lo] grids accumulated in VMEM across the "
        "whole grid — HBM traffic collapses to the ~12 B/row inputs "
        "(vs ~4 GB/1M rows of one-hot operands in the XLA formulation)."),
    reductions=("sum", "count"),
    max_key_domain=1 << 16,
    platforms=("tpu",),
    interpretable=True,
))

DENSE_MATMUL = register(KernelInfo(
    name="dense_matmul",
    description=(
        "One-hot matmul grouped sum/count (einsum('nh,nl->hl') on the "
        "MXU), lax.map-tiled so the one-hot working set stays in tens of "
        "MB; the XLA formulation the flagship q01 kernel shipped with."),
    reductions=("sum", "count"),
    max_key_domain=1 << 16,
    platforms=("*",),
))

HASHTABLE = register(KernelInfo(
    name="hashtable",
    description=(
        "Device-resident open-addressing hash table "
        "(auron_tpu/hashtable): claim-owner probe rounds (one "
        "scatter-min + gathers per round, compacted tail) build the "
        "group table in one fused program per batch; accumulators "
        "scatter into their slots. Unbounded key domains, "
        "primitive/string/decimal128 keys, reassociation-exact reduce "
        "kinds (sum/min/max/or/first); the general-agg replacement for "
        "sort + segment-reduce."),
    reductions=("sum", "count", "min", "max", "or", "first"),
    max_key_domain=0,            # unbounded
    platforms=("*",),
))

SORT_GENERAL = register(KernelInfo(
    name="sort",
    description=(
        "General sort-based grouping (xxhash64 -> stable sort -> segment "
        "reduce): unbounded key domains, every dtype — the AggOp merge "
        "kernel (ops/agg.py). The dispatch fallback."),
    reductions=("sum", "count", "min", "max", "or", "first"),
    max_key_domain=0,            # unbounded
    platforms=("*",),
))
