"""Kernel-selection policy for grouped aggregation.

The planner-facing decision point: given what the plan knows about an
aggregation (key-domain bound, key/value dtypes, aggregate set) and
what the environment provides (platform, config), pick one of

- ``pallas_vmem``   — the VMEM-accumulate Pallas kernel
                      (kernels/grouped_agg.pallas_sum_count); native
                      Mosaic compilation only on a real TPU — real-chip
                      compiles stay behind bench.py's healthy-window
                      probe (the TPU-tunnel pitfall: a Mosaic compile
                      against a wedged client can re-wedge it) — and
                      the interpreter elsewhere;
- ``dense_matmul``  — the one-hot einsum formulation (compiles on any
                      XLA backend);
- ``sort``          — the general sort-based AggOp path (unbounded
                      domains, every dtype): the fallback.

Every decision is counted (kernels/registry.py + the per-task
MetricsSet under the ``kernels`` key) so "which kernel ran and why"
is answerable from the existing metrics snapshot.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from auron_tpu.columnar.schema import DataType
from auron_tpu.kernels import grouped_agg, registry

#: aggregate functions the dense-domain path finalizes (ops/agg.py
#: _DenseDomainState); first/collect/distinct/bloom/udaf stay sort-based
DENSE_AGG_FNS = frozenset(
    {"count", "count_star", "sum", "avg", "min", "max"})

#: integer-class key dtypes the (hi, lo) byte decomposition accepts
DENSE_KEY_DTYPES = frozenset(
    {DataType.INT8, DataType.INT16, DataType.INT32, DataType.INT64})

#: value dtypes with a dense accumulator formulation (floats via the
#: MXU grids, integers/dates via exact scatter)
DENSE_VALUE_DTYPES = frozenset(
    {DataType.INT8, DataType.INT16, DataType.INT32, DataType.INT64,
     DataType.FLOAT32, DataType.FLOAT64, DataType.DATE32})

#: rough HBM-traffic estimates, bytes per input row (the VMEM kernel
#: reads k/v/c once: 12 B/row; the matmul path materializes one-hot +
#: lhs operands in HBM: ~(5*gh + gl)*4 at the full 256x256 grid; the
#: sort path re-reads rows across hash/sort/segment passes)
BYTES_PER_ROW = {"pallas_vmem": 12, "dense_matmul": 6144, "sort": 48}


@dataclass(frozen=True)
class KernelDecision:
    kernel: str              # pallas_vmem | dense_matmul | sort
    interpret: bool          # pallas interpreter (non-TPU platforms)
    reason: str              # why this kernel (or why the fallback)
    bytes_per_row: int       # HBM-traffic estimate for metrics

    @property
    def is_dense(self) -> bool:
        return self.kernel != "sort"


def _platform(platform: Optional[str]) -> str:
    if platform is not None:
        return platform
    import jax
    return jax.default_backend()


def backend_for_platform(conf=None, platform: Optional[str] = None
                         ) -> tuple[str, bool]:
    """(backend, interpret) honoring ``auron.kernels.backend``.

    ``auto`` picks the Pallas kernel natively on a real TPU and the
    one-hot matmul formulation elsewhere; ``pallas`` forces the Pallas
    kernel, through the interpreter on non-TPU platforms (how the
    differential battery runs it under JAX_PLATFORMS=cpu)."""
    from auron_tpu import config as cfg
    conf = conf or cfg.get_config()
    choice = conf.get(cfg.KERNELS_BACKEND)
    plat = _platform(platform)
    if choice == "pallas":
        if not grouped_agg.PALLAS_AVAILABLE:
            # jax without the experimental pallas package: honor the
            # intent as closely as possible instead of dispatching to a
            # kernel whose module handle is None
            return "dense_matmul", False
        return "pallas_vmem", plat != "tpu"
    if choice == "dense":
        return "dense_matmul", False
    if choice == "sort":
        return "sort", False
    if choice != "auto":
        raise ValueError(
            f"auron.kernels.backend: unknown backend {choice!r} "
            "(auto|pallas|dense|sort)")
    if plat == "tpu" and grouped_agg.PALLAS_AVAILABLE:
        return "pallas_vmem", False
    return "dense_matmul", False


def _count(metrics, name: str, v: int = 1) -> None:
    if metrics is not None:
        metrics.counter(name).add(v)


def record_rows(decision: KernelDecision, rows: int, metrics=None) -> None:
    """Accumulate the bytes-moved estimate for ``rows`` input rows
    against the decision's kernel (registry + per-task metrics)."""
    est = rows * decision.bytes_per_row
    registry.stats(decision.kernel).add("bytes_moved_est", est)
    _count(metrics, "bytes_moved_est", est)


@dataclass(frozen=True)
class HashAggDecision:
    """The general-path grouping decision: hashtable vs sort."""
    backend: str             # hashtable | sort
    reason: str
    load_factor: float = 0.5
    max_probe_rounds: int = 64

    @property
    def is_hash(self) -> bool:
        return self.backend == "hashtable"


#: key column dtypes with a hashtable word encoding; nested types
#: (STRUCT/LIST/MAP) stay on the sort path
HASH_KEY_DTYPES = frozenset(
    {DataType.BOOL, DataType.INT8, DataType.INT16, DataType.INT32,
     DataType.INT64, DataType.FLOAT32, DataType.FLOAT64,
     DataType.DATE32, DataType.TIMESTAMP_US, DataType.STRING,
     DataType.DECIMAL})


def record_operator_choice(metrics, backend: str) -> None:
    """Mirror the chosen grouping backend into the OPERATOR's metrics
    (not just the shared ``kernels`` set), so the finalize snapshot
    shows which backend each operator actually ran."""
    _count(metrics, f"dispatch_{backend}")


def select_hash_agg(*, key_dtypes, acc_kinds, has_float_sum: bool,
                    conf=None, metrics=None,
                    record: bool = True) -> HashAggDecision:
    """The general (unbounded-key) grouping decision: the device hash
    table (auron_tpu/hashtable) or the sort + segment-reduce path.

    key_dtypes: DataType per group key (nested types fall back).
    acc_kinds: flat device reduce kinds (ops/agg._device_kinds).
    has_float_sum: any float-dtype 'sum' accumulator — reassociation
    changes last-ulp results, so 'auto' keeps those on the sort path and
    only auron.hashtable.backend=hash forces them through the table.
    """
    from auron_tpu import config as cfg
    from auron_tpu.hashtable import SUPPORTED_KINDS
    conf = conf or cfg.get_config()

    def decide(backend: str, reason: str) -> HashAggDecision:
        if record:
            event = "selected" if backend == "hashtable" else "fallback"
            registry.stats("hashtable").add(event)
            _count(metrics, f"hashtable_{event}")
        return HashAggDecision(
            backend, reason,
            load_factor=conf.get(cfg.HASHTABLE_LOAD_FACTOR),
            max_probe_rounds=max(1, conf.get(
                cfg.HASHTABLE_MAX_PROBE_ROUNDS)))

    if not conf.get(cfg.HASHTABLE_ENABLED):
        return decide("sort", "disabled")
    choice = conf.get(cfg.HASHTABLE_BACKEND)
    if choice == "sort":
        return decide("sort", "backend_config")
    if choice not in ("auto", "hash"):
        raise ValueError(
            f"auron.hashtable.backend: unknown backend {choice!r} "
            "(auto|hash|sort)")
    kds = tuple(key_dtypes)
    if not kds:
        return decide("sort", "no_keys")
    bad = [d for d in kds if d not in HASH_KEY_DTYPES]
    if bad:
        return decide("sort", f"key_dtype:{bad[0].value}")
    for kind in acc_kinds:
        if kind not in SUPPORTED_KINDS:
            return decide("sort", f"acc_kind:{kind}")
    if has_float_sum and choice != "hash":
        # scatter-add reassociates float sums; 'auto' keeps results
        # bit-identical to the sort path by falling back
        return decide("sort", "float_sum_inexact")
    return decide("hashtable", "eligible")


def select_grouped_agg(*, key_domain: Optional[int], key_dtypes,
                       agg_fns, value_dtypes, conf=None, metrics=None,
                       platform: Optional[str] = None,
                       record: bool = True) -> KernelDecision:
    """The grouped-agg kernel decision.

    key_domain: exclusive upper bound on the (non-negative) group keys,
    or None when unbounded. key_dtypes/value_dtypes: DataType per group
    key / aggregate argument. agg_fns: AccSpec.fn per aggregate.
    ``metrics``: a MetricsSet (usually ctx.metrics_for("kernels")) that
    receives selected/fallback/interpret counters alongside the
    process-global registry stats. ``record=False`` returns the pure
    policy decision without touching any counter — for callers that
    override the fallback and account for the kernel they actually run
    themselves (the flagship lowering)."""
    from auron_tpu import config as cfg
    conf = conf or cfg.get_config()

    def fallback(reason: str) -> KernelDecision:
        if record:
            registry.stats("sort").add("selected")
            registry.stats("sort").add("fallback")
            _count(metrics, "sort_selected")
            _count(metrics, "fallback")
        return KernelDecision("sort", False, reason,
                              BYTES_PER_ROW["sort"])

    if not conf.get(cfg.KERNELS_ENABLED):
        return fallback("disabled")
    if key_domain is None:
        return fallback("unbounded_key_domain")
    if key_domain <= 0:
        return fallback("empty_key_domain")
    if key_domain > min(conf.get(cfg.KERNELS_MAX_KEY_DOMAIN),
                        grouped_agg.MAX_KEY_DOMAIN):
        return fallback("key_domain_too_large")
    kds = tuple(key_dtypes)
    if len(kds) != 1:
        # the dense grids decompose ONE integer key as (hi, lo) bytes;
        # composite keys stay on the sort path
        return fallback("multi_key" if kds else "no_key")
    bad = [d for d in kds if d not in DENSE_KEY_DTYPES]
    if bad:
        return fallback(f"key_dtype:{bad[0].value}")
    for fn in agg_fns:
        if fn not in DENSE_AGG_FNS:
            return fallback(f"agg_fn:{fn}")
    for d in value_dtypes:
        if d not in DENSE_VALUE_DTYPES:
            return fallback(f"value_dtype:{d.value}")

    backend, interpret = backend_for_platform(conf, platform)
    if backend == "sort":
        return fallback("backend_config")
    if record:
        registry.stats(backend).add("selected")
        _count(metrics, f"{backend}_selected")
        if interpret:
            registry.stats(backend).add("interpret")
            _count(metrics, "interpret")
    return KernelDecision(backend, interpret, "eligible",
                          BYTES_PER_ROW[backend])
