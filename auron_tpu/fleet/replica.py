"""Subprocess replica harness: real AuronServer processes for the
fleet tooling.

Everything that exercises cross-process failover — ``tools/
load_report.py --fleet``, the perf-gate fleet arm, the chaos
``fleet_failover`` scenario, tests/test_zz_fleet_battery.py — boots
replicas through this ONE harness, because the property under test
(a SIGKILLed engine's journal claim becomes winnable by a survivor)
only exists across real process boundaries: an in-process "kill"
leaves the claim owner's pid alive and the liveness plane would
correctly refuse the steal.

Each replica is ``python -m auron_tpu.runtime.serving --port 0`` with
its knobs injected through the ``AURON_CONF_*`` environment mapping
(ops endpoint on, shared journal dir, CPU platform) and discovered
through the ``AURON_SERVING host:port`` stdout line — the same
contract the serving CLI prints for any supervisor.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time


class ReplicaProc:
    """One spawned AuronServer subprocess (host, port, Popen)."""

    def __init__(self, proc: subprocess.Popen, host: str, port: int):
        self.proc = proc
        self.host = host
        self.port = port
        self.name = f"{host}:{port}"

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        """SIGKILL — no shutdown courtesy, the failover test surface."""
        if self.alive():
            self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=30)

    def stop(self) -> None:
        if self.alive():
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)


def spawn_replica(journal_dir: str, *, window: int = 4,
                  env_extra: dict | None = None,
                  boot_timeout_s: float = 60.0) -> ReplicaProc:
    """Boot one serving subprocess and wait for its bound address.

    The child runs on the CPU platform (fleet tests are host-side),
    with the ops endpoint enabled on an ephemeral port (the router
    scrapes it; HELLO reveals the port) and ``journal_dir`` as the
    SHARED journal directory every replica of the fleet writes —
    failover's resume path exists only because the survivors see the
    dead owner's stems there.
    """
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "AURON_CONF_OPS_ENABLED": "1",
        "AURON_CONF_OPS_PORT": "0",
        "AURON_CONF_JOURNAL_DIR": journal_dir,
    })
    if env_extra:
        env.update({k: str(v) for k, v in env_extra.items()})
    proc = subprocess.Popen(
        [sys.executable, "-m", "auron_tpu.runtime.serving",
         "--port", "0", "--window", str(window)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=env, text=True)
    deadline = time.monotonic() + boot_timeout_s
    line = ""
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                from auron_tpu import errors
                raise errors.ReplicaUnavailable(
                    f"replica exited rc={proc.returncode} before "
                    "announcing its address", reason="boot")
            time.sleep(0.05)
            continue
        if line.startswith("AURON_SERVING "):
            break
    if not line.startswith("AURON_SERVING "):
        proc.kill()
        from auron_tpu import errors
        raise errors.ReplicaUnavailable(
            "replica never printed AURON_SERVING", reason="boot")
    host, _, port = line.split()[1].rpartition(":")
    return ReplicaProc(proc, host, int(port))


class FleetHarness:
    """N subprocess replicas + an in-process FleetRouter, as a context
    manager.  The router runs inside the caller's process (its decision
    counters and failover latencies are directly inspectable via
    ``router.stats_dict()``); the replicas are real processes so
    SIGKILL is a real death."""

    def __init__(self, n: int | None = None, *,
                 journal_dir: str | None = None,
                 window: int = 4, env_extra: dict | None = None,
                 config=None):
        if n is None:
            from auron_tpu import config as cfg
            n = int((config or cfg.get_config()).get(cfg.FLEET_REPLICAS))
        self.n = n
        self._own_journal = journal_dir is None
        self.journal_dir = journal_dir or tempfile.mkdtemp(
            prefix="auron_fleet_journal_")
        self.window = window
        self.env_extra = env_extra
        self._config = config
        self.replicas: list = []
        self.router = None

    def __enter__(self) -> "FleetHarness":
        from auron_tpu.fleet.router import FleetRouter
        try:
            for _ in range(self.n):
                self.replicas.append(spawn_replica(
                    self.journal_dir, window=self.window,
                    env_extra=self.env_extra))
            self.router = FleetRouter(
                [(r.host, r.port) for r in self.replicas],
                config=self._config).start()
        except BaseException:
            self.__exit__(None, None, None)
            raise
        return self

    def __exit__(self, *exc) -> None:
        if self.router is not None:
            try:
                self.router.close()
            except Exception:   # graft: disable=GL004 -- teardown must reach every replica even if the router is wedged
                pass
            self.router = None
        for rep in self.replicas:
            rep.stop()
        self.replicas = []

    @property
    def address(self) -> tuple:
        return self.router.address

    def client(self, **kw):
        """An AuronClient pointed at the ROUTER — the fleet looks like
        one server."""
        from auron_tpu.runtime import serving
        host, port = self.router.address
        return serving.AuronClient(host, port, **kw)

    def kill_replica(self, index: int) -> ReplicaProc:
        """SIGKILL replica ``index`` (failover drill)."""
        rep = self.replicas[index]
        rep.kill()
        return rep
