"""Pure routing decisions over replica snapshots.

Every function here is a pure function of its arguments — no clocks, no
sockets, no config reads, no randomness that is not injected — so the
router's entire decision surface (admission routing, warm affinity,
spill-over ordering, backoff clamping, failover action) is exhaustively
unit-testable from literal snapshots (tests/test_fleet.py).  The
``FleetRouter`` in ``fleet/router.py`` owns all the I/O and calls down
into these; it never second-guesses them.
"""

from __future__ import annotations

from typing import Optional

from auron_tpu.fleet.snapshot import ReplicaSnapshot


def load_score(snap: ReplicaSnapshot) -> tuple:
    """Deterministic least-loaded ordering key: occupancy first (live +
    queued queries — the admission plane's real queue), then memory
    pressure, then degraded-after-ok, then name for a total order (two
    idle replicas must sort the same way on every router)."""
    return (snap.occupancy, round(snap.mem_frac, 3),
            0 if snap.status == "ok" else 1, snap.name)


def usable(snapshots, now: float, staleness_s: float) -> list:
    """The routable subset: scraped ok and fresh. Degraded replicas
    stay usable (degraded means serving with a caveat — shedding them
    entirely would turn one bad probe into an outage); unreachable and
    stale ones do not."""
    return [s for s in snapshots if s.fresh(now, staleness_s)]


def route_order(snapshots, *, plan_fp: Optional[str] = None,
                sticky: Optional[str] = None, affinity: bool = True,
                now: float = 0.0,
                staleness_s: float = 2.0) -> list:
    """Full preference order for one submission: every usable replica,
    best first — the head is the admission target, the tail is the
    spill-over sequence.

    Affinity first: replicas whose warm result-cache inventory holds
    ``plan_fp`` — or the ``sticky`` replica this router last routed the
    same fingerprint to (the router's own memory covers SUBMIT_PLAN
    payloads whose server-side task identity it cannot compute) — rank
    ahead of cold ones, each group least-loaded first.  The warm path's
    173x is worth far more than perfect load spreading; ties inside a
    group still spread by load."""
    cands = usable(snapshots, now, staleness_s)
    if not (affinity and (plan_fp or sticky)):
        return sorted(cands, key=load_score)
    warm, cold = [], []
    for s in cands:
        if (plan_fp is not None and plan_fp in s.warm_fps) \
                or (sticky is not None and s.name == sticky):
            warm.append(s)
        else:
            cold.append(s)
    return sorted(warm, key=load_score) + sorted(cold, key=load_score)


def resume_target(snapshots, stem: str, *, now: float,
                  staleness_s: float) -> Optional[ReplicaSnapshot]:
    """Where to RESUME a dead replica's journaled query: prefer a
    survivor that already sees the stem in its resume inventory (same
    shared journal dir, inventory confirmed by its own scrape), else
    the least-loaded usable survivor (the shared dir means any of them
    can claim it — inventory lag must not block failover).  None when
    the fleet has no usable survivor."""
    order = route_order(snapshots, affinity=False, now=now,
                        staleness_s=staleness_s)
    for s in order:
        if stem in s.resume_stems:
            return s
    return order[0] if order else None


def spillover_delay(retry_after_s: Optional[float], attempt: int,
                    rand: float, remaining_s: Optional[float],
                    *, floor_s: float = 0.02,
                    cap_s: float = 2.0) -> float:
    """Jittered, deadline-clamped backoff before retrying a shed at the
    next replica (the PR 7 token discipline, fleet edition).

    ``rand`` is an injected uniform [0,1) sample — determinism belongs
    to the caller. The server's ``retry_after_s`` hint anchors the
    delay; without a hint, exponential from ``floor_s`` by attempt.
    Clamped to ``remaining_s`` (the submission's deadline budget) so a
    backoff never outlives the query it serves; never negative."""
    base = retry_after_s if retry_after_s and retry_after_s > 0 \
        else floor_s * (2 ** attempt)
    delay = min(base, cap_s) * (0.5 + rand / 2)   # full jitter, >=50%
    if remaining_s is not None:
        delay = min(delay, max(0.0, remaining_s))
    return max(0.0, delay)


def failover_action(*, query_id: Optional[str], pid: Optional[int],
                    journal_shared: bool, failover_enabled: bool,
                    survivors: int) -> str:
    """The failover state machine's single decision: what to do about a
    query that was mid-flight on a replica that died.

    - ``resume``     — the router knows the server-assigned query id
                       and pid (the early ACK echo), the fleet shares a
                       journal dir, and a survivor exists: RESUME the
                       journal stem ``<query_id>_<pid>`` there.
    - ``reexecute``  — survivors exist but the query has no reachable
                       journal identity: run it again from scratch
                       (under the idempotency guard).
    - ``error``      — failover is off, or nobody is left: surface the
                       classified ReplicaUnavailable verdict.
    """
    if not failover_enabled or survivors <= 0:
        return "error"
    if query_id and pid and journal_shared:
        return "resume"
    return "reexecute"


def shed_verdict(sheds: list) -> tuple[str, Optional[float]]:
    """Collapse per-replica sheds into the fleet-wide verdict the
    client sees: reason ``fleet_saturated`` and the LARGEST retry hint
    (the fleet is ready when its slowest-draining member is).
    ``sheds`` holds (reason, retry_after_s) tuples from
    ``serving.parse_shed``."""
    hints = [r for _, r in sheds if r is not None]
    return "fleet_saturated", (max(hints) if hints else None)
