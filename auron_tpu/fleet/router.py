"""The fleet router: one process in front of N AuronServer replicas.

Speaks the ``runtime/serving.py`` wire protocol on BOTH sides — to a
client it looks exactly like an AuronServer (the wire protocol is
unchanged; ``AuronClient`` connects to the router with no code
changes), to each replica it looks like a driving client.  Three
behaviors compose the availability story:

- **Routed admission**: a poll thread scrapes every replica's /healthz
  + /queries into immutable snapshots (``fleet/snapshot.py``) and each
  submission is routed by the pure preference order in
  ``fleet/routing.py`` — least-loaded first, warm-affinity (result-
  cache plan fingerprints + the router's own sticky memory) ahead of
  cold.
- **Spill-over retry**: an ``AdmissionRejected`` shed at one replica is
  retried at the next candidate after a jittered, deadline-clamped
  sleep honoring the shed's ``retry_after_s`` hint; only a fleet-wide
  shed reaches the client, as a structured verdict the same parser
  understands.
- **Journal-backed failover**: the router buffers a replica's BATCH
  frames and forwards them only after DONE (store-and-forward), so a
  replica death mid-query never leaves a client stream half-written.
  On death (connection loss, confirmed by the liveness plane's
  pid+epoch verdict) a query whose id the router learned through the
  ``router_tag`` early-ACK echo RESUMEs on a survivor under its
  journal stem ``<query_id>_<pid>`` — bit-identical from committed
  shuffle stages — and a non-journaled one re-executes from scratch
  under a single-flight idempotency guard keyed on its plan
  fingerprint.

Fault sites ``fleet.route`` (the routing decision) and
``fleet.forward`` (the router→replica conversation) extend the
deterministic fault plane to this tier.

Observability rides the same paths: an inbound TRACE prefix frame is
adopted (``trace.wire_scope`` with role "router") so routing decisions
(``fleet.route`` events), forwarding conversations (``fleet.forward``
spans) and failovers land in the CLIENT's trace, and a fresh context is
forwarded to the replica so all three processes share one trace id.
``auron.fleet.ops_port`` ≥ 0 additionally opens the router's own ops
endpoint: /metrics federates every replica's last-scraped exposition
re-labeled ``replica="rN"`` alongside the router's registry, and
/fleet/queries merges the live query tables (dead replicas labeled
``down``). A liveness-confirmed death writes a fleet failure bundle
(routing timeline + the dead replica's last scraped state), and the
DONE-frame cost ledger is augmented with fleet facts before replay.
"""

from __future__ import annotations

import json
import logging
import random
import socket
import socketserver
import threading
import time

from auron_tpu import errors
from auron_tpu.obs import ops_server as _ops
from auron_tpu.obs import trace as _trace
from auron_tpu.runtime import serving

logger = logging.getLogger("auron_tpu.fleet")


class _Flight:
    """Single-flight slot of the re-execution idempotency guard."""

    __slots__ = ("event", "result")

    def __init__(self):
        self.event = threading.Event()
        self.result = None


class _Replica:
    """Mutable per-replica runtime state (snapshot + identity)."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self.name = f"{host}:{port}"
        self.hello: dict = {}
        self.dead = False
        #: last successfully scraped bodies, stashed by the POLL loop —
        #: the router ops endpoint serves ONLY these (a handler never
        #: scrapes inline, so a wedged replica cannot wedge a scrape of
        #: the router, and a dead replica's last state survives for the
        #: fleet failure bundle)
        self.last_health: dict = {}
        self.last_queries: dict = {}
        self.last_metrics: str = ""
        from auron_tpu.fleet import snapshot as snap_mod
        self.snapshot = snap_mod.unreachable(self.name, host, port, 0.0)

    @property
    def pid(self):
        return self.hello.get("pid")

    @property
    def tag(self):
        return self.hello.get("tag", "")

    @property
    def ops_port(self):
        return self.hello.get("ops_port")

    @property
    def journal_dir(self) -> str:
        return self.hello.get("journal_dir") or ""


class _RouterHandler(socketserver.BaseRequestHandler):
    def handle(self):
        self.server.router._handle_conn(self.request)


class _RouterServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _FleetOpsHandler(_ops._OpsHandler):
    """The router's ops endpoint: fleet-scope views assembled from
    the poll loop's stashed scrapes — a handler NEVER touches a
    replica's network, so a wedged or dead replica cannot wedge a
    scrape of the router."""

    _KNOWN_PATHS = frozenset(
        ("/metrics", "/healthz", "/fleet/queries", "/"))

    def _route(self, path: str, q: dict) -> None:
        self._count(path)
        router = self.server.context
        if path == "/metrics":
            self._reply(200, router.federated_metrics().encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            self._reply_json(router.fleet_health())
        elif path == "/fleet/queries":
            self._reply_json(router.fleet_queries())
        elif path == "/":
            self._reply_json({
                "service": "auron fleet ops endpoint",
                "endpoints": ["/metrics", "/healthz",
                              "/fleet/queries"]})
        else:
            self._reply(404, f"no such endpoint {path!r}\n".encode(),
                        "text/plain; charset=utf-8")


class FleetRouter:
    """Router/coordinator over ``replicas`` = [(host, port), ...]."""

    def __init__(self, replicas, host: str = "127.0.0.1", port: int = 0,
                 config=None):
        from auron_tpu import config as cfg
        conf = config or cfg.get_config()
        self.poll_s = float(conf.get(cfg.FLEET_POLL_S))
        #: a snapshot older than 4 poll intervals is unroutable
        self.staleness_s = max(self.poll_s * 4, 0.5)
        self.affinity = bool(conf.get(cfg.FLEET_AFFINITY))
        self.failover = bool(conf.get(cfg.FLEET_FAILOVER))
        io_t = conf.get(cfg.CLIENT_TIMEOUT_S)
        #: per-operation socket budget for replica conversations
        self.io_timeout_s = io_t if io_t and io_t > 0 else None
        #: -1 = no router ops endpoint; 0 = ephemeral; >0 = fixed
        self.ops_port_conf = int(conf.get(cfg.FLEET_OPS_PORT))
        self._replicas = [_Replica(h, p) for h, p in replicas]
        if not self._replicas:
            raise ValueError("a fleet needs at least one replica")
        self._lock = threading.Lock()
        self._sticky: dict = {}     # affinity fp -> replica name
        self._inflight: dict = {}   # idempotency guard: fp -> _Flight
        self.stats = {"routed": 0, "spillovers": 0, "fleet_sheds": 0,
                      "failovers_resume": 0, "failovers_reexecute": 0,
                      "replica_deaths": 0, "guard_shared": 0,
                      "errors_forwarded": 0}
        #: detect→recovered failover latencies (seconds) — the perf
        #: gate and PERF.md read p50/p99 from here via stats()
        self._failover_lat: list = []
        self._srv = _RouterServer((host, port), _RouterHandler)
        self._srv.router = self
        self._poll_stop = threading.Event()
        self._poll_thread = None
        self._ops_srv = None
        #: most recent fleet death bundle path — _observe_failover
        #: appends the survivor's recovery record (failover.json) there
        self._last_death_bundle = None

    # -- lifecycle -----------------------------------------------------------

    @property
    def address(self) -> tuple:
        return self._srv.server_address

    def start(self) -> "FleetRouter":
        """HELLO every replica (identity + ops port + journal dir),
        take one synchronous scrape so the first submission routes on
        real data, then start the poll loop and the listener."""
        for rep in self._replicas:
            self._hello(rep)
        if all(rep.dead for rep in self._replicas):
            raise errors.ReplicaUnavailable(
                "no replica answered HELLO at fleet startup",
                reason="hello")
        self._poll_once()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, daemon=True)
        self._poll_thread.start()
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()
        if self.ops_port_conf >= 0:
            self._start_ops()
        return self

    def _start_ops(self) -> None:
        """Bind the router's own ops endpoint (auron.fleet.ops_port ≥
        0). Observability, never availability: a taken port logs and
        the fleet serves on."""
        from auron_tpu.obs import ops_server as _ops
        try:
            self._ops_srv = _ops.OpsServer(
                port=self.ops_port_conf,
                handler_cls=_FleetOpsHandler, context=self).start()
        except OSError:
            logger.exception("could not bind the fleet ops endpoint")
            self._ops_srv = None

    @property
    def ops_address(self):
        """(host, port) of the router ops endpoint, or None."""
        return self._ops_srv.address if self._ops_srv else None

    def close(self) -> None:
        self._poll_stop.set()
        if self._ops_srv is not None:
            self._ops_srv.stop()
            self._ops_srv = None
        self._srv.shutdown()
        self._srv.server_close()

    def stats_dict(self) -> dict:
        """Router counters + per-replica snapshots + failover latency
        samples (the STATS frame body and the tooling's gate input)."""
        with self._lock:
            lat = sorted(self._failover_lat)
            body = {"router": dict(self.stats),
                    "failover_latency_s": lat,
                    "replicas": {}}
        ops = self.ops_address
        body["ops_port"] = ops[1] if ops else None
        for rep in self._replicas:
            s = rep.snapshot
            body["replicas"][rep.name] = {
                "status": s.status, "ok": s.ok, "dead": rep.dead,
                "running": s.running, "queued": s.queued,
                "admitted": s.admitted, "rejected": s.rejected,
                "mem_frac": round(s.mem_frac, 4),
                "warm_fps": len(s.warm_fps),
                "resume_stems": list(s.resume_stems),
                "pid": rep.pid, "ops_port": rep.ops_port}
        return body

    # -- fleet ops views (served by _FleetOpsHandler) -------------------------

    def federated_metrics(self) -> str:
        """The router /metrics body: this process's registry exposition
        merged with every live replica's last-scraped exposition,
        replica samples re-labeled ``replica="rN"`` (strict round-trip
        through ``registry.parse_prometheus`` on both ends). Dead
        replicas' stale expositions are dropped — their reachability
        survives as the ``auron_fleet_replica_up`` gauge."""
        from auron_tpu.obs import registry as _reg
        local = _reg.get_registry().render_prometheus()
        texts = [(f"r{i}", rep.last_metrics)
                 for i, rep in enumerate(self._replicas)
                 if not rep.dead and rep.last_metrics]
        return _reg.render_federated(local, texts)

    def fleet_queries(self) -> dict:
        """The /fleet/queries body: every replica's live query table
        merged, each row tagged with its replica label; dead or
        unreachable replicas stay in the replica table labeled
        ``down`` (the scrape-under-failover contract)."""
        merged: list = []
        replicas: dict = {}
        for i, rep in enumerate(self._replicas):
            label = f"r{i}"
            s = rep.snapshot
            replicas[label] = {
                "name": rep.name,
                "status": ("down" if (rep.dead or not s.ok)
                           else s.status),
                "dead": rep.dead,
                "running": s.running, "queued": s.queued,
                "pid": rep.pid, "ops_port": rep.ops_port}
            if rep.dead:
                continue
            for row in (rep.last_queries or {}).get("queries") or []:
                if isinstance(row, dict):
                    merged.append(dict(row, replica=label,
                                       replica_name=rep.name))
        return {"role": "router", "replicas": replicas,
                "queries": merged}

    def fleet_health(self) -> dict:
        """The router /healthz body: a fleet-level verdict (``ok``
        while at least one replica is routable) plus the router's own
        counters and per-replica reachability."""
        with self._lock:
            stats = dict(self.stats)
        live = sum(1 for rep in self._replicas if not rep.dead)
        return {
            "status": "ok" if live else "degraded",
            "role": "router",
            "replicas_total": len(self._replicas),
            "replicas_live": live,
            "router": stats,
            "replicas": {
                rep.name: ("down" if (rep.dead or not rep.snapshot.ok)
                           else rep.snapshot.status)
                for rep in self._replicas}}

    # -- replica registration + polling --------------------------------------

    def _hello(self, rep: _Replica) -> None:
        try:
            client = serving.AuronClient(rep.host, rep.port,
                                         timeout_s=self.io_timeout_s
                                         or 10.0)
            rep.hello = client.hello()
            rep.dead = False
        except (OSError, errors.RemoteEngineError):
            rep.dead = True

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(self.poll_s):
            try:
                self._poll_once()
            except Exception:   # graft: disable=GL004 -- the poll loop must survive any scrape surprise; stale snapshots already classify as unreachable
                pass

    def _poll_once(self) -> None:
        from auron_tpu.fleet import snapshot as snap_mod
        now = time.monotonic()
        for rep in self._replicas:
            if not rep.hello:
                self._hello(rep)
            snap = None
            if rep.ops_port:
                try:
                    health, queries = snap_mod.scrape_replica(
                        rep.host, rep.ops_port,
                        timeout_s=max(self.poll_s, 0.5))
                    snap = snap_mod.snapshot_from_bodies(
                        rep.name, rep.host, rep.port, health, queries,
                        now)
                    rep.dead = False
                    rep.last_health, rep.last_queries = health, queries
                    try:
                        rep.last_metrics = snap_mod.scrape_text(
                            rep.host, rep.ops_port, "/metrics",
                            timeout_s=max(self.poll_s, 0.5))
                    except OSError:
                        rep.last_metrics = ""
                except (OSError, ValueError):
                    snap = None
            if snap is None:
                snap = snap_mod.unreachable(rep.name, rep.host,
                                            rep.port, now)
            rep.snapshot = snap
            self._gauge("auron_fleet_replica_up",
                        0.0 if (rep.dead or not snap.ok) else 1.0,
                        replica=rep.name)

    def _snapshots(self) -> list:
        return [rep.snapshot for rep in self._replicas if not rep.dead]

    def _by_name(self, name: str):
        for rep in self._replicas:
            if rep.name == name:
                return rep
        return None

    def _mark_dead(self, rep: _Replica) -> bool:
        """Record a replica death after the connection-loss signal,
        CONFIRMED by the liveness plane where possible: a same-host
        pid+epoch that is provably alive keeps the replica routable
        (the conversation broke, not the process) — journal claim
        arbitration protects the resume path either way."""
        from auron_tpu.fleet import snapshot as snap_mod
        from auron_tpu.utils import liveness
        if rep.dead:
            return True   # another conversation already confirmed it
        confirmed = True
        parsed = liveness.parse_tag(rep.tag) if rep.tag else None
        if parsed is not None:
            host, pid, epoch = parsed
            if host == socket.gethostname():
                confirmed = liveness.owner_dead(pid, epoch)
                if not confirmed:
                    # a SIGKILLed child lingers as a zombie until its
                    # parent reaps it — one beat closes that window
                    time.sleep(0.05)
                    confirmed = liveness.owner_dead(pid, epoch)
        if confirmed:
            first = False
            with self._lock:
                if not rep.dead:   # N broken conversations, ONE death
                    rep.dead = True
                    rep.snapshot = snap_mod.unreachable(
                        rep.name, rep.host, rep.port, time.monotonic())
                    self.stats["replica_deaths"] += 1
                    first = True
            if first:
                _trace.event("fleet", "fleet.death", replica=rep.name,
                             pid=rep.pid or 0)
                self._count("auron_fleet_replica_deaths_total",
                            replica=rep.name)
                self._gauge("auron_fleet_replica_up", 0.0,
                            replica=rep.name)
                self._write_death_bundle(rep)
        return confirmed

    def _write_death_bundle(self, rep: _Replica) -> None:
        """Fleet failure bundle on the FIRST confirmation of a death:
        the router's routing/failover timeline (its flight ring), the
        dead replica's LAST scraped health + query table, and the
        router counters. The survivor's recovery record
        (``failover.json``) is appended by ``_observe_failover`` once
        recovery lands."""
        try:
            from auron_tpu.obs import bundle as _bundle
            from auron_tpu.obs import flight_recorder as _flight
            path = _bundle.write_fleet_death(
                rep.name, rep.last_health, rep.last_queries,
                self.stats_dict(), _flight.recorder().dump_jsonl())
            if path:
                with self._lock:
                    self._last_death_bundle = path
        except Exception:   # graft: disable=GL004 -- diagnostics must never block failover
            logger.exception("fleet death bundle failed")

    # -- metrics -------------------------------------------------------------

    def _count(self, name: str, **labels) -> None:
        try:
            from auron_tpu.obs import registry as _reg
            if _reg.enabled():
                _reg.get_registry().counter(name, **labels).inc()
        except Exception:   # graft: disable=GL004 -- metric emission is best-effort by contract
            pass

    def _gauge(self, name: str, value: float, **labels) -> None:
        try:
            from auron_tpu.obs import registry as _reg
            if _reg.enabled():
                _reg.get_registry().gauge(name, **labels).set(value)
        except Exception:   # graft: disable=GL004 -- metric emission is best-effort by contract
            pass

    def _observe_failover(self, seconds: float, replica: str,
                          action: str) -> None:
        with self._lock:
            self._failover_lat.append(seconds)
            self.stats["failovers_resume" if action == "resume"
                       else "failovers_reexecute"] += 1
        self._count("auron_fleet_failover_total", replica=replica,
                    action=action)
        try:
            from auron_tpu.obs import registry as _reg
            if _reg.enabled():
                _reg.get_registry().histogram(
                    "auron_fleet_failover_seconds").observe(seconds)
        except Exception:   # graft: disable=GL004 -- metric emission is best-effort by contract
            pass
        _trace.event("fleet", "fleet.failover", survivor=replica,
                     action=action, latency_s=round(seconds, 6))
        with self._lock:
            bundle_path = self._last_death_bundle
        if bundle_path:
            from auron_tpu.obs import bundle as _bundle
            _bundle.add_artifact(bundle_path, "failover.json",
                                 json.dumps({"survivor": replica,
                                             "action": action,
                                             "latency_s":
                                                 round(seconds, 6),
                                             "wall": time.time()},
                                            indent=2))

    # -- connection dispatch -------------------------------------------------

    def _handle_conn(self, sock) -> None:
        try:
            kind, payload = serving.read_frame(sock)
        except (OSError, ConnectionError):
            return
        wire_ctx = None
        if kind == serving.KIND_TRACE:
            # optional trace-context prefix frame: adopt the client's
            # trace id so every routing decision, forward and failover
            # this conversation makes lands in the client's trace
            try:
                ctx = json.loads(payload.decode() or "{}")
                if isinstance(ctx, dict):
                    wire_ctx = ctx
            except (ValueError, UnicodeDecodeError):
                pass
            try:
                kind, payload = serving.read_frame(sock)
            except (OSError, ConnectionError):
                return
        with _trace.wire_scope(wire_ctx, role="router"):
            self._dispatch(sock, kind, payload)

    def _dispatch(self, sock, kind: int, payload: bytes) -> None:
        try:
            if kind == serving.KIND_SHUTDOWN:
                self._shutdown_fleet()
                return
            if kind == serving.KIND_HELLO:
                self._send_router_hello(sock)
                return
            if kind == serving.KIND_STATS:
                serving.write_frame(
                    sock, serving.KIND_DONE,
                    json.dumps(self.stats_dict(), default=str).encode())
                return
            if kind == serving.KIND_CANCEL:
                self._broadcast_cancel(sock, payload)
                return
            if kind == serving.KIND_RESUME:
                self._serve_resume(sock, payload)
                return
            if kind in (serving.KIND_SUBMIT, serving.KIND_SUBMIT_PLAN):
                self._serve_submit(sock, kind, payload)
                return
            serving.write_frame(sock, serving.KIND_ERROR,
                                f"expected SUBMIT, got kind={kind}"
                                .encode())
        except errors.AuronError as e:
            # classified router-tier verdict (injected fleet.route
            # faults, exhausted fleets): structured first line, the
            # serving ERROR convention
            try:
                serving.write_frame(
                    sock, serving.KIND_ERROR,
                    (f"{type(e).__name__} "
                     f"reason={getattr(e, 'reason', None) or 'error'}"
                     f"\n{e}").encode())
            except OSError:
                pass
        except (OSError, ConnectionError):
            pass   # client went away mid-reply: nothing to tell it

    def _send_router_hello(self, sock) -> None:
        import os
        from auron_tpu.utils import liveness
        ops = self.ops_address
        body = {"pid": os.getpid(), "tag": liveness.own_tag(),
                "role": "router",
                "host": self.address[0], "port": self.address[1],
                "ops_port": ops[1] if ops else None,
                "replicas": [rep.name for rep in self._replicas]}
        serving.write_frame(sock, serving.KIND_DONE,
                            json.dumps(body).encode())

    def _shutdown_fleet(self) -> None:
        for rep in self._replicas:
            if rep.dead:
                continue
            try:
                serving.AuronClient(rep.host, rep.port,
                                    timeout_s=5.0).shutdown()
            except (OSError, errors.RemoteEngineError):
                pass
        threading.Thread(target=self.close, daemon=True).start()

    def _broadcast_cancel(self, sock, payload: bytes) -> None:
        """First-frame CANCEL-by-id: the router does not know which
        replica owns the id (ids are per-replica), so ask each live one
        in turn; the first success wins, otherwise the last structured
        verdict is forwarded."""
        last_error = b"UnknownQuery reason=unknown_query_id \nno replica"
        for rep in self._replicas:
            if rep.dead:
                continue
            try:
                with socket.create_connection(
                        (rep.host, rep.port),
                        timeout=self.io_timeout_s) as rsock:
                    serving.write_frame(rsock, serving.KIND_CANCEL,
                                        payload)
                    fkind, fpayload = serving.read_frame(rsock)
            except (OSError, ConnectionError):
                continue
            if fkind == serving.KIND_DONE:
                serving.write_frame(sock, serving.KIND_DONE, fpayload)
                return
            last_error = fpayload
        serving.write_frame(sock, serving.KIND_ERROR, last_error)

    # -- submission path -----------------------------------------------------

    def _affinity_fp(self, kind: int, payload: bytes):
        """The submission's affinity fingerprint. A SUBMIT payload IS
        the TaskDefinition bytes the replica's cache identity
        fingerprints, so the router computes the SAME fp and can match
        a replica's warm inventory exactly; a SUBMIT_PLAN's task bytes
        only exist after server-side conversion, so its fp is a local
        digest that rides the router's sticky memory instead."""
        from auron_tpu.runtime.journal import plan_fingerprint
        if kind == serving.KIND_SUBMIT:
            return plan_fingerprint(payload)
        import hashlib
        return "plan:" + hashlib.sha256(payload).hexdigest()[:32]

    def _deadline_of(self, kind: int, payload: bytes):
        if kind != serving.KIND_SUBMIT_PLAN:
            return None
        try:
            t = json.loads(payload.decode()).get("timeout_s")
            return time.monotonic() + float(t) if t else None
        except (ValueError, UnicodeDecodeError):
            return None

    def _tagged_payload(self, kind: int, payload: bytes) -> bytes:
        """Inject ``router_tag`` into a SUBMIT_PLAN request so the
        replica echoes its query id + pid (the journal stem) on an
        early ACK. SUBMIT payloads are raw protobuf — no tag channel —
        so their failover is re-execution, never resume."""
        if kind != serving.KIND_SUBMIT_PLAN:
            return payload
        try:
            req = json.loads(payload.decode())
            req["router_tag"] = True
            return json.dumps(req).encode()
        except (ValueError, UnicodeDecodeError):
            return payload

    def _serve_submit(self, client, kind: int, payload: bytes) -> None:
        from auron_tpu.fleet import routing
        from auron_tpu.runtime import faults
        fp = self._affinity_fp(kind, payload)
        deadline = self._deadline_of(kind, payload)
        fwd = self._tagged_payload(kind, payload)
        tried: set = set()
        sheds: list = []
        attempt = 0
        max_attempts = 2 * len(self._replicas) + 2
        while attempt < max_attempts:
            attempt += 1
            faults.maybe_fail("fleet.route", errors.ReplicaUnavailable)
            faults.maybe_hang("fleet.route")
            with self._lock:
                sticky = self._sticky.get(fp)
            order = routing.route_order(
                self._snapshots(), plan_fp=fp, sticky=sticky,
                affinity=self.affinity, now=time.monotonic(),
                staleness_s=self.staleness_s)
            cands = [s for s in order if s.name not in tried]
            if not cands:
                break
            target = self._by_name(cands[0].name)
            if target is None or target.dead:
                tried.add(cands[0].name)
                continue
            reason = ("warm" if self.affinity
                      and (fp in cands[0].warm_fps
                           or cands[0].name == sticky) else "load")
            _trace.event("fleet", "fleet.route", replica=target.name,
                         reason=reason, attempt=attempt)
            res = self._drive_replica(target, kind, fwd, client)
            rkind = res["kind"]
            if rkind == "done":
                with self._lock:
                    self.stats["routed"] += 1
                    if self.affinity:
                        self._sticky[fp] = target.name
                self._count("auron_fleet_routed_total",
                            replica=target.name, reason=reason)
                self._replay(client, res["batches"],
                             self._augment_done(
                                 res["done"], hops=attempt,
                                 spillovers=len(sheds),
                                 replica=target.name))
                return
            if rkind == "client_gone":
                return
            if rkind == "error":
                with self._lock:
                    self.stats["errors_forwarded"] += 1
                self._count("auron_fleet_errors_forwarded_total",
                            replica=target.name)
                serving.write_frame(client, serving.KIND_ERROR,
                                    res["payload"])
                return
            if rkind == "shed":
                tried.add(target.name)
                sheds.append((res["reason"], res["retry_after_s"]))
                with self._lock:
                    self.stats["spillovers"] += 1
                self._count("auron_fleet_spillover_total",
                            replica=target.name)
                remaining = (deadline - time.monotonic()
                             if deadline is not None else None)
                delay = routing.spillover_delay(
                    res["retry_after_s"], len(sheds) - 1,
                    random.random(), remaining)
                if delay:
                    time.sleep(delay)
                continue
            # rkind == "died": replica conversation broke mid-query
            tried.add(target.name)
            self._mark_dead(target)
            t_detect = time.monotonic()
            if self._failover(client, kind, payload, fp, target,
                              res.get("query_id"), res.get("pid"),
                              t_detect):
                return
            # failover exhausted its own candidates: fall out to the
            # fleet-wide verdict below
            break
        if sheds:
            with self._lock:
                self.stats["fleet_sheds"] += 1
            self._count("auron_fleet_shed_total")
            from auron_tpu.fleet.routing import shed_verdict
            reason, hint = shed_verdict(sheds)
            serving.write_frame(
                client, serving.KIND_ERROR,
                (f"AdmissionRejected reason={reason} "
                 f"retry_after_s={hint}\nevery replica shed this "
                 f"submission ({len(sheds)} sheds); resubmit after "
                 "the hint").encode())
            return
        serving.write_frame(
            client, serving.KIND_ERROR,
            (b"ReplicaUnavailable reason=no_replicas\nno usable "
             b"replica in the fleet (all dead or unreachable)"))

    # -- failover ------------------------------------------------------------

    def _failover(self, client, kind: int, payload: bytes, fp: str,
                  dead_rep: _Replica, query_id, pid,
                  t_detect: float) -> bool:
        """Recover a query that was mid-flight on a dead replica.
        True when the client received a full reply (success or a
        classified error); False to let the caller surface the
        fleet-wide verdict."""
        from auron_tpu.fleet import routing
        # exclusion is DEATH-only: a replica that merely shed this
        # submission earlier was full at that instant, not unusable —
        # the patient re-execution below must be allowed back there
        excluded = {dead_rep.name}
        survivors = [rep for rep in self._replicas
                     if not rep.dead and rep.name != dead_rep.name]
        action = routing.failover_action(
            query_id=query_id, pid=pid,
            journal_shared=bool(dead_rep.journal_dir),
            failover_enabled=self.failover,
            survivors=len(survivors))
        if action == "error":
            if not self.failover:
                serving.write_frame(
                    client, serving.KIND_ERROR,
                    (f"ReplicaUnavailable reason=dead "
                     f"replica={dead_rep.name}\nreplica died "
                     "mid-query and auron.fleet.failover is off"
                     ).encode())
                return True
            return False
        stem = f"{query_id}_{pid}" if action == "resume" else None
        if stem is None:
            # a raw SUBMIT has no router_tag channel, but its affinity
            # fp IS the journal's plan fingerprint (both hash the
            # TaskDefinition bytes): find the dead owner's stem in the
            # shared journal dir so the query RESUMEs (completing a
            # resume deletes the journal — re-execution would leave it
            # as a permanent orphan the sweep deliberately keeps)
            stem = self._orphan_stem(dead_rep, fp)
        if stem is not None:
            # RESUME rides the survivor's admission door like any
            # query, so a momentarily full survivor SHEDS it — and a
            # failed-over query already earned its slot once, so shed
            # means wait-and-retry (hint-paced, bounded), never an
            # instant downgrade to re-execution (which would strand
            # the dead owner's journal as a permanent orphan)
            resume_payload = json.dumps({"query_id": stem}).encode()
            deadline = time.monotonic() + 20.0
            round_ = 0
            while stem is not None:
                hint = None
                sheds_only = False
                for rep in self._replicas:
                    if rep.dead or rep.name in excluded:
                        continue
                    res = self._drive_replica(
                        rep, serving.KIND_RESUME, resume_payload,
                        client)
                    if res["kind"] == "done":
                        self._observe_failover(
                            time.monotonic() - t_detect, rep.name,
                            "resume")
                        self._replay(client, res["batches"],
                                     self._augment_done(
                                         res["done"],
                                         hops=len(excluded) + 1,
                                         failover="resume",
                                         replica=rep.name))
                        return True
                    if res["kind"] == "client_gone":
                        return True
                    if res["kind"] == "died":
                        excluded.add(rep.name)
                        self._mark_dead(rep)
                        continue
                    if res["kind"] == "shed":
                        sheds_only = True
                        if res["retry_after_s"]:
                            hint = max(hint or 0.0,
                                       res["retry_after_s"])
                        continue
                    # a structured resume refusal (no journal for
                    # the stem, claim raced, corrupt): re-execution
                    # is the classified fallback
                    stem = None
                    break
                if stem is None or not sheds_only:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                time.sleep(routing.spillover_delay(
                    hint, round_, random.random(), remaining))
                round_ += 1
        status = self._reexecute_guarded(client, kind, payload, fp,
                                         excluded)
        if status == "served":
            self._observe_failover(time.monotonic() - t_detect,
                                   "fleet", "reexecute")
            return True
        if status == "gone":
            return True
        if status == "failed_shed":
            # the survivors are FULL, not gone: the classified verdict
            # is a fleet-wide shed, same as the spill-over path's
            with self._lock:
                self.stats["fleet_sheds"] += 1
            self._count("auron_fleet_shed_total")
            serving.write_frame(
                client, serving.KIND_ERROR,
                (b"AdmissionRejected reason=fleet_saturated "
                 b"retry_after_s=1.0\nreplica died mid-query and "
                 b"every survivor shed the re-execution"))
            return True
        return False

    def _orphan_stem(self, dead_rep: _Replica, fp):
        """The dead replica's resumable journal stem for this
        submission, found by plan-fingerprint match in the SHARED
        journal dir (same-host deployments; a remote dir is simply not
        visible and failover re-executes)."""
        import os
        jdir = dead_rep.journal_dir
        if not fp or not jdir or not os.path.isdir(jdir):
            return None
        try:
            from auron_tpu.runtime import journal as jrn
            for ent in jrn.resume_inventory(jdir):
                if ent.get("owner_alive") or ent.get("claimed"):
                    continue
                if ent.get("plan_fp") == fp:
                    return ent.get("stem")
        except Exception:   # graft: disable=GL004 -- inventory probing is an optimization; re-execution stays correct without it
            pass
        return None

    def _reexecute_guarded(self, client, kind: int, payload: bytes,
                           fp: str, excluded: set,
                           budget_s: float = 20.0) -> str:
        """Re-execute a non-resumable in-flight query on a survivor
        under the single-flight idempotency guard: two concurrent
        failovers of the SAME submission (same result-key fingerprint)
        must produce exactly one replica execution — the second waits
        and replays the first's buffered frames.

        A failed-over query was ALREADY admitted once, so a shed here
        means a momentarily full survivor, not a rejection verdict:
        keep coming around (hint-paced) until ``budget_s`` runs out.
        Returns ``served`` / ``gone`` (client vanished) /
        ``failed_shed`` (survivors kept shedding all budget long) /
        ``failed_dead`` (no survivor left at all)."""
        owner = False
        with self._lock:
            fl = self._inflight.get(fp)
            if fl is None:
                fl = _Flight()
                self._inflight[fp] = fl
                owner = True
        if not owner:
            fl.event.wait(timeout=(self.io_timeout_s or 30.0) * 2)
            if fl.result is not None:
                with self._lock:
                    self.stats["guard_shared"] += 1
                self._count("auron_fleet_guard_shared_total")
                self._replay(client, fl.result["batches"],
                             fl.result["done"])
                return "served"
            # the owner failed; this waiter recovers on its own
        from auron_tpu.fleet import routing
        deadline = time.monotonic() + budget_s
        shed_seen = False
        try:
            round_ = 0
            while True:
                hint = None
                progressed = False
                for rep in self._replicas:
                    if rep.dead or rep.name in excluded:
                        continue
                    res = self._drive_replica(
                        rep, kind,
                        self._tagged_payload(kind, payload), client)
                    if res["kind"] == "done":
                        if owner:
                            fl.result = res
                        self._replay(client, res["batches"],
                                     self._augment_done(
                                         res["done"],
                                         hops=len(excluded) + 1,
                                         failover="reexecute",
                                         replica=rep.name))
                        return "served"
                    if res["kind"] == "client_gone":
                        return "gone"
                    if res["kind"] == "error":
                        serving.write_frame(client,
                                            serving.KIND_ERROR,
                                            res["payload"])
                        return "served"
                    if res["kind"] == "died":
                        excluded.add(rep.name)
                        self._mark_dead(rep)
                        continue
                    # shed: the survivor is merely FULL, not gone
                    progressed = True
                    shed_seen = True
                    if res["retry_after_s"]:
                        hint = max(hint or 0.0, res["retry_after_s"])
                if not progressed:
                    return ("failed_shed" if shed_seen
                            else "failed_dead")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return "failed_shed"
                time.sleep(routing.spillover_delay(
                    hint, round_, random.random(), remaining))
                round_ += 1
        finally:
            if owner:
                with self._lock:
                    self._inflight.pop(fp, None)
                fl.event.set()

    # -- resume path (client-driven) -----------------------------------------

    def _serve_resume(self, client, payload: bytes) -> None:
        """First-frame RESUME from a client: route to the survivor
        whose scraped resume inventory holds the stem (shared journal
        dir), else least-loaded, and forward verbatim."""
        from auron_tpu.fleet import routing
        qid = serving._TaskHandler._parse_query_id(payload)
        last_error = (b"ResumeUnavailable reason=no_replicas "
                      b"query_id=\nno usable replica")
        tried: set = set()
        while True:
            snap = routing.resume_target(
                [s for s in self._snapshots()
                 if s.name not in tried], qid,
                now=time.monotonic(), staleness_s=self.staleness_s)
            if snap is None:
                serving.write_frame(client, serving.KIND_ERROR,
                                    last_error)
                return
            rep = self._by_name(snap.name)
            if rep is None or rep.dead:
                tried.add(snap.name)
                continue
            res = self._drive_replica(rep, serving.KIND_RESUME,
                                      payload, client)
            if res["kind"] == "done":
                with self._lock:
                    self.stats["routed"] += 1
                self._replay(client, res["batches"],
                             self._augment_done(res["done"], hops=1,
                                                replica=rep.name))
                return
            if res["kind"] == "client_gone":
                return
            if res["kind"] in ("error", "shed"):
                serving.write_frame(client, serving.KIND_ERROR,
                                    res["payload"])
                return
            tried.add(rep.name)
            self._mark_dead(rep)
            last_error = (f"ReplicaUnavailable reason=dead "
                          f"replica={rep.name}\nreplica died during "
                          "RESUME").encode()

    # -- the store-and-forward pump ------------------------------------------

    def _drive_replica(self, rep: _Replica, kind: int, payload: bytes,
                       client) -> dict:
        """Drive one conversation with one replica, buffering BATCH
        frames (forwarded to the client only after DONE — a death
        mid-stream must leave the client stream untouched so failover
        can restart it cleanly).  NEED_TABLES/TABLE exchanges relay
        through live, they are client-owned state.

        Returns a dict with ``kind`` one of: ``done`` (with buffered
        ``batches`` + ``done`` payload + echoed ``query_id``/``pid``),
        ``shed`` (structured AdmissionRejected, parsed), ``error``
        (any other ERROR payload, forwarded verbatim), ``died``
        (connection broke — with whatever identity the early ACK
        echoed), ``client_gone`` (the CLIENT side broke mid-relay)."""
        from auron_tpu.runtime import faults
        batches: list = []
        query_id = pid = None
        try:
            rsock = socket.create_connection(
                (rep.host, rep.port), timeout=self.io_timeout_s)
        except OSError:
            return {"kind": "died", "query_id": None, "pid": None}
        with rsock, _trace.span("fleet", "fleet.forward",
                                replica=rep.name, kind=kind):
            try:
                faults.maybe_fail("fleet.forward",
                                  errors.ReplicaUnavailable)
                # forward the adopted trace context so the replica's
                # spans join the same trace (parent = this forward
                # span); None when tracing/propagation is off — the
                # replica-side wire is then byte-identical to before
                fctx = _trace.wire_context()
                if fctx is not None:
                    serving.write_frame(rsock, serving.KIND_TRACE,
                                        json.dumps(fctx).encode())
                serving.write_frame(rsock, kind, payload)
                while True:
                    faults.maybe_hang("fleet.forward")
                    fkind, fpayload = serving.read_frame(rsock)
                    if fkind == serving.KIND_ACK:
                        # the router_tag echo: query id + pid = the
                        # journal stem failover resumes under
                        try:
                            meta = json.loads(fpayload.decode())
                            query_id = meta.get("query_id")
                            pid = meta.get("pid")
                        except (ValueError, UnicodeDecodeError):
                            pass
                        continue
                    if fkind == serving.KIND_BATCH:
                        batches.append(fpayload)
                        serving.write_frame(rsock, serving.KIND_ACK,
                                            b"")
                    elif fkind == serving.KIND_NEED_TABLES:
                        if not self._relay_tables(rsock, client,
                                                  fpayload):
                            return {"kind": "client_gone"}
                    elif fkind == serving.KIND_ERROR:
                        text = fpayload.decode("utf-8", "replace")
                        shed = serving.parse_shed(text)
                        if shed is not None:
                            return {"kind": "shed",
                                    "reason": shed[0],
                                    "retry_after_s": shed[1],
                                    "payload": fpayload}
                        return {"kind": "error", "payload": fpayload}
                    elif fkind == serving.KIND_DONE:
                        return {"kind": "done", "batches": batches,
                                "done": fpayload,
                                "query_id": query_id, "pid": pid}
                    else:
                        return {"kind": "died", "query_id": query_id,
                                "pid": pid}
            except (errors.ReplicaUnavailable, OSError,
                    ConnectionError, TimeoutError):
                return {"kind": "died", "query_id": query_id,
                        "pid": pid}

    def _relay_tables(self, rsock, client, need_payload: bytes) -> bool:
        """Relay a NEED_TABLES round: forward the request to the
        client, stream its TABLE frames back to the replica. False
        when the client broke the protocol or vanished."""
        try:
            serving.write_frame(client, serving.KIND_NEED_TABLES,
                                need_payload)
            need = json.loads(need_payload.decode())
            for _ in range(len(need)):
                ck, cp = serving.read_frame(client)
                if ck != serving.KIND_TABLE:
                    return False
                serving.write_frame(rsock, serving.KIND_TABLE, cp)
            return True
        except (OSError, ConnectionError, ValueError):
            return False

    def _augment_done(self, done_payload: bytes, **fleet) -> bytes:
        """Stamp fleet facts (hops, spillovers, failover action, the
        serving replica) into the DONE frame's cost ledger before the
        replay to the client — tolerant of a ledger-less or non-JSON
        payload (ledger disabled, an older replica): the payload then
        passes through untouched."""
        try:
            done = json.loads(done_payload.decode())
        except (ValueError, UnicodeDecodeError):
            return done_payload
        if not isinstance(done, dict) or "cost_ledger" not in done:
            return done_payload
        from auron_tpu.obs import ledger as _ledger
        _ledger.augment_fleet(done["cost_ledger"], **fleet)
        try:
            return json.dumps(done, default=str).encode()
        except (TypeError, ValueError):   # pragma: no cover
            return done_payload

    def _replay(self, client, batches: list, done_payload: bytes) -> bool:
        """Forward the buffered result to the client under its ACK
        flow control (one un-ACKed frame in flight — the router is the
        server now)."""
        try:
            for b in batches:
                serving.write_frame(client, serving.KIND_BATCH, b)
                ck, _ = serving.read_frame(client)
                if ck != serving.KIND_ACK:
                    return False
            serving.write_frame(client, serving.KIND_DONE,
                                done_payload)
            return True
        except (OSError, ConnectionError):
            return False


def main(argv=None) -> int:
    """``python -m auron_tpu.fleet.router --replica host:port ...`` —
    run a router process (prints the bound address for the parent)."""
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--replica", action="append", required=True,
                    help="host:port of an AuronServer replica "
                         "(repeatable)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)
    from auron_tpu.obs import flight_recorder as _flight
    _flight.set_role("router")
    replicas = []
    for spec in args.replica:
        host, _, port = spec.rpartition(":")
        replicas.append((host or "127.0.0.1", int(port)))
    router = FleetRouter(replicas, host=args.host, port=args.port)
    router.start()
    print(f"AURON_FLEET {router.address[0]}:{router.address[1]}",
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        router.close()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
