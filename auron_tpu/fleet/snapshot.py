"""Replica health snapshots: the router's view of one engine process.

The router never holds live references into a replica — it decides over
immutable ``ReplicaSnapshot`` values assembled from the PR 14 ops
surfaces (/healthz + /queries) on a bounded-staleness poll loop.  The
split here is deliberate and test-facing:

- ``scrape_replica`` is the ONLY function that touches the network
  (stdlib urllib against the replica's ops port);
- ``snapshot_from_bodies`` / ``unreachable`` are pure functions from
  scraped JSON bodies to a snapshot, so every routing decision in
  ``fleet/routing.py`` is unit-testable from literal dicts without a
  single socket (tests/test_fleet.py).
"""

from __future__ import annotations

import json
import urllib.request
from dataclasses import dataclass, field


@dataclass(frozen=True)
class ReplicaSnapshot:
    """One replica's scraped state at one poll instant (immutable)."""

    #: "host:port" of the replica's SERVING socket — the routing key
    name: str
    host: str
    port: int
    #: did the scrape succeed at all?
    ok: bool
    #: the /healthz verdict: ok | degraded | unreachable
    status: str
    #: live query occupancy from the /queries table
    running: int = 0
    queued: int = 0
    #: admission counters (cumulative) — the shed history
    admitted: int = 0
    rejected: int = 0
    #: worst memmgr used/total ratio across the replica's managers
    mem_frac: float = 0.0
    #: watchdog CPU fallbacks taken (a degraded-but-alive signal)
    watchdog_fallbacks: int = 0
    #: warm plan fingerprints (result-cache inventory) — affinity keys
    warm_fps: frozenset = field(default_factory=frozenset)
    #: resumable journal stems visible to this replica (dead owners)
    resume_stems: tuple = ()
    #: time.monotonic() of the scrape (staleness accounting)
    scraped_at: float = 0.0

    @property
    def occupancy(self) -> int:
        return self.running + self.queued

    def fresh(self, now: float, staleness_s: float) -> bool:
        """Is this snapshot recent enough to route on?"""
        return self.ok and (now - self.scraped_at) <= staleness_s


def unreachable(name: str, host: str, port: int,
                scraped_at: float) -> ReplicaSnapshot:
    """The snapshot of a replica whose scrape failed: never routed to,
    but kept in the table so staleness/recovery is observable."""
    return ReplicaSnapshot(name=name, host=host, port=port, ok=False,
                           status="unreachable", scraped_at=scraped_at)


def snapshot_from_bodies(name: str, host: str, port: int,
                         health: dict, queries: dict,
                         scraped_at: float) -> ReplicaSnapshot:
    """Pure assembly of a snapshot from the two scraped JSON bodies.

    Tolerant by construction: every field degrades to a neutral value
    when absent (an older replica, a partially-failed collector) — a
    routing decision must never crash on a scrape-shape surprise."""
    running = queued = 0
    for row in queries.get("queries") or []:
        state = row.get("state")
        if state == "running":
            running += 1
        elif state == "queued":
            queued += 1
    admitted = rejected = 0
    for ent in (queries.get("admission") or {}).values():
        if isinstance(ent, dict):
            admitted += int(ent.get("admitted", 0))
            rejected += int(ent.get("rejected", 0))
    mem_frac = 0.0
    for st in health.get("memmgr") or []:
        total = st.get("total") or 0
        if total > 0:
            mem_frac = max(mem_frac, st.get("used", 0) / total)
    wd = health.get("watchdog") or {}
    stems = tuple(
        ent["stem"] for ent in queries.get("resume_inventory") or []
        if not ent.get("owner_alive") and not ent.get("claimed")
        and "stem" in ent)
    return ReplicaSnapshot(
        name=name, host=host, port=port, ok=True,
        status=health.get("status", "ok"),
        running=running, queued=queued,
        admitted=admitted, rejected=rejected,
        mem_frac=mem_frac,
        watchdog_fallbacks=int(wd.get("fallbacks", 0) or 0),
        warm_fps=frozenset(queries.get("warm_plan_fps") or ()),
        resume_stems=stems,
        scraped_at=scraped_at)


def scrape_text(host: str, ops_port: int, path: str = "/metrics",
                timeout_s: float = 2.0) -> str:
    """Fetch one ops endpoint body as raw text — the router federates
    each replica's /metrics exposition verbatim (re-labeling happens at
    render time, ``registry.render_federated``). Raises OSError on an
    unreachable endpoint; the poll loop treats that as a missed scrape,
    not a death."""
    with urllib.request.urlopen(
            f"http://{host}:{ops_port}{path}",
            timeout=timeout_s) as resp:
        return resp.read().decode("utf-8", "replace")


def scrape_replica(host: str, ops_port: int,
                   timeout_s: float = 2.0) -> tuple[dict, dict]:
    """Fetch (/healthz body, /queries body) from a replica's ops
    endpoint.  Raises OSError/ValueError on an unreachable or
    malformed endpoint — the poll loop maps that to ``unreachable``."""
    bodies = []
    for path in ("/healthz", "/queries"):
        with urllib.request.urlopen(
                f"http://{host}:{ops_port}{path}",
                timeout=timeout_s) as resp:
            bodies.append(json.loads(resp.read().decode()))
    return bodies[0], bodies[1]
