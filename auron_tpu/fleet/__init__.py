"""Serving fleet: a router/coordinator over replicated AuronServers.

- ``fleet.snapshot`` — immutable replica health snapshots (scrape +
  pure parsers over the PR 14 ops bodies);
- ``fleet.routing``  — pure decisions: least-loaded order, warm
  affinity, spill-over backoff, failover action, shed verdicts;
- ``fleet.router``   — the I/O: a wire-compatible front that routes,
  spills sheds over, and fails dead replicas' queries over to
  survivors (journal RESUME or guarded re-execution);
- ``fleet.replica``  — the subprocess harness the fleet tooling boots
  real replicas with.

A plain ``AuronClient`` pointed at the router sees one server; the
wire protocol is unchanged.
"""

from auron_tpu.fleet.router import FleetRouter
from auron_tpu.fleet.replica import FleetHarness, ReplicaProc, \
    spawn_replica
from auron_tpu.fleet.snapshot import ReplicaSnapshot, \
    snapshot_from_bodies, unreachable
from auron_tpu.fleet import routing

__all__ = [
    "FleetRouter", "FleetHarness", "ReplicaProc", "spawn_replica",
    "ReplicaSnapshot", "snapshot_from_bodies", "unreachable",
    "routing",
]
