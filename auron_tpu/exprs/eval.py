"""Expression evaluation: lowering expr trees onto DeviceBatches.

The analogue of the reference's PhysicalExpr evaluation (reference:
datafusion-ext-exprs/, datafusion-ext-functions/), except nothing is
interpreted at runtime: ``evaluate`` runs inside a traced jax function, so
the whole expression tree flattens into one fused XLA computation per
operator — XLA's fusion pass is the CachedExprsEvaluator (reference:
datafusion-ext-plans/src/common/cached_exprs_evaluator.rs) of this design.

Null semantics follow Spark/SQL: arithmetic/comparison propagate null;
AND/OR are three-valued; casts are non-ANSI (overflow wraps / saturates like
the JVM, invalid parses give null).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from auron_tpu.columnar.batch import DeviceBatch, PrimitiveColumn, StringColumn
from auron_tpu.columnar.schema import DataType, Field, Schema
from auron_tpu.exprs import ir
from auron_tpu.ops import hashing
from auron_tpu.ops import strings as S
from auron_tpu.utils.shapes import bucket_string_width

# ---------------------------------------------------------------------------
# typed values
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TypedValue:
    col: object            # PrimitiveColumn | StringColumn
    dtype: DataType
    precision: int = 0
    scale: int = 0

    @property
    def data(self):
        return self.col.data

    @property
    def validity(self):
        return self.col.validity


@dataclass(frozen=True)
class EvalContext:
    """Ambient scalars available to expressions.

    ``memo``: when a kernel passes a fresh dict, evaluate() caches each
    subexpression's TypedValue per (batch, expr) — the common-subexpression
    evaluator (reference: datafusion-ext-plans/src/common/
    cached_exprs_evaluator.rs). Safe only for a dict created INSIDE the
    traced kernel (tracer lifetimes match the trace); the default None
    disables caching, so the shared default context can never leak
    tracers across traces."""
    partition_id: object = 0          # device or python int32
    row_num_offset: object = 0        # rows produced before this batch
    num_partitions: int = 1
    memo: object = None               # dict | None; see docstring


_JNP = {
    DataType.BOOL: jnp.bool_,
    DataType.INT8: jnp.int8,
    DataType.INT16: jnp.int16,
    DataType.INT32: jnp.int32,
    DataType.INT64: jnp.int64,
    DataType.FLOAT32: jnp.float32,
    DataType.FLOAT64: jnp.float64,
    DataType.DATE32: jnp.int32,
    DataType.TIMESTAMP_US: jnp.int64,
    DataType.DECIMAL: jnp.int64,
    DataType.NULL: jnp.bool_,
}

def null_column_for_field(field, cap: int):
    """All-null device column shaped for ``field`` (outer-join padding)."""
    from auron_tpu.columnar.batch import PrimitiveColumn, StringColumn
    if field.dtype == DataType.STRING:
        return StringColumn(jnp.zeros((cap, 8), jnp.uint8),
                            jnp.zeros(cap, jnp.int32), jnp.zeros(cap, bool))
    if field.dtype == DataType.DECIMAL and field.precision > 18:
        from auron_tpu.columnar.decimal128 import Decimal128Column
        return Decimal128Column(jnp.zeros(cap, jnp.int64),
                                jnp.zeros(cap, jnp.int64),
                                jnp.zeros(cap, bool))
    if field.dtype == DataType.MAP and field.key == DataType.STRING:
        from auron_tpu.columnar.batch import StringMapColumn
        return StringMapColumn(jnp.zeros((cap, 1, 8), jnp.uint8),
                               jnp.zeros((cap, 1), jnp.int32),
                               jnp.zeros((cap, 1, 8), jnp.uint8),
                               jnp.zeros((cap, 1), jnp.int32),
                               jnp.zeros((cap, 1), bool),
                               jnp.zeros(cap, jnp.int32),
                               jnp.zeros(cap, bool))
    if field.dtype == DataType.LIST and field.elem == DataType.STRING:
        from auron_tpu.columnar.batch import StringListColumn
        return StringListColumn(jnp.zeros((cap, 1, 8), jnp.uint8),
                                jnp.zeros((cap, 1), jnp.int32),
                                jnp.zeros((cap, 1), bool),
                                jnp.zeros(cap, jnp.int32),
                                jnp.zeros(cap, bool))
    if field.dtype == DataType.LIST:
        from auron_tpu.columnar.batch import ListColumn
        return ListColumn(jnp.zeros((cap, 1), _JNP[field.elem]),
                          jnp.zeros((cap, 1), bool),
                          jnp.zeros(cap, jnp.int32), jnp.zeros(cap, bool))
    if field.dtype == DataType.MAP:
        from auron_tpu.columnar.batch import MapColumn
        return MapColumn(jnp.zeros((cap, 1), _JNP[field.key]),
                         jnp.zeros((cap, 1), _JNP[field.elem]),
                         jnp.zeros((cap, 1), bool),
                         jnp.zeros(cap, jnp.int32), jnp.zeros(cap, bool))
    if field.dtype == DataType.STRUCT:
        from auron_tpu.columnar.batch import StructColumn
        return StructColumn(
            tuple(null_column_for_field(cf, cap) for cf in field.children),
            jnp.zeros(cap, bool))
    return PrimitiveColumn(jnp.zeros(cap, _JNP[field.dtype]),
                           jnp.zeros(cap, bool))


_RANK = [DataType.INT8, DataType.INT16, DataType.INT32, DataType.INT64,
         DataType.FLOAT32, DataType.FLOAT64]


def decimal_result_type(op: str, lp: int, ls: int, rp: int,
                        rs: int) -> tuple[int, int, int]:
    """Spark decimal binary result type with allowPrecisionLoss scale
    adjustment (Spark's DecimalPrecision.adjustPrecisionScale): returns
    (precision, scale, full_scale) where full_scale is the scale the raw
    limb computation produces before any precision-loss rescale. ONE
    definition shared by infer_dtype and evaluation so declared schemas
    and evaluated columns can't drift."""
    if op == "*":
        p, s = lp + rp + 1, ls + rs
        full_s = ls + rs
    else:   # + - and comparisons share add/sub typing
        s = max(ls, rs)
        p = max(lp - ls, rp - rs) + s + 1
        full_s = s
    if p <= 38:
        return p, s, full_s
    digits_int = p - s
    min_scale = min(s, 6)
    adj_s = max(38 - digits_int, min_scale)
    return 38, adj_s, full_s


def common_type(a: DataType, b: DataType) -> DataType:
    if a == b:
        return a
    if a == DataType.NULL:
        return b
    if b == DataType.NULL:
        return a
    if a == DataType.DECIMAL or b == DataType.DECIMAL:
        # decimal vs float → float64; decimal vs int → decimal handled upstream
        if b.is_floating or a.is_floating:
            return DataType.FLOAT64
        return DataType.DECIMAL
    if a in _RANK and b in _RANK:
        return _RANK[max(_RANK.index(a), _RANK.index(b))]
    if {a, b} <= {DataType.DATE32, DataType.STRING}:
        return DataType.DATE32
    if {a, b} <= {DataType.TIMESTAMP_US, DataType.STRING}:
        return DataType.TIMESTAMP_US
    raise TypeError(f"no common type for {a} and {b}")


def _const_column(value, dtype: DataType, capacity: int, width_hint: int = 8):
    """Materialize a literal as a broadcast column."""
    if dtype == DataType.STRING:
        b = value.encode() if isinstance(value, str) else (value or b"")
        w = bucket_string_width(max(len(b), 1))
        row, _ = S.literal_to_device(b, w)
        chars = jnp.broadcast_to(jnp.asarray(row)[None, :], (capacity, w))
        lens = jnp.full(capacity, len(b), jnp.int32)
        validity = jnp.full(capacity, value is not None, bool)
        return StringColumn(chars, lens, validity)
    jdt = _JNP[dtype]
    if value is None:
        return PrimitiveColumn(jnp.zeros(capacity, jdt),
                               jnp.zeros(capacity, bool))
    return PrimitiveColumn(jnp.full(capacity, value, jdt),
                           jnp.ones(capacity, bool))


# ---------------------------------------------------------------------------
# main entry
# ---------------------------------------------------------------------------

def evaluate(expr: ir.Expr, batch: DeviceBatch, schema: Schema,
             ctx: EvalContext = EvalContext()) -> TypedValue:
    """Evaluate ``expr`` against ``batch``; with ctx.memo set, each
    distinct subexpression evaluates once per batch (CSE — expr trees are
    frozen/hashable, so structural duplicates share one result; host
    callbacks like string parsing benefit most since XLA cannot CSE
    those)."""
    memo = ctx.memo
    if memo is None or isinstance(expr, (ir.ColumnRef, ir.Literal)):
        return _evaluate(expr, batch, schema, ctx)
    key = (id(batch), expr)
    hit = memo.get(key)
    if hit is None:
        hit = _evaluate(expr, batch, schema, ctx)
        memo[key] = hit
    return hit


def _evaluate(expr: ir.Expr, batch: DeviceBatch, schema: Schema,
              ctx: EvalContext) -> TypedValue:
    cap = batch.capacity
    if isinstance(expr, ir.ColumnRef):
        f = schema[expr.index]
        return TypedValue(batch.columns[expr.index], f.dtype, f.precision, f.scale)

    if isinstance(expr, ir.ScalarSubquery):
        raise RuntimeError(
            "unresolved scalar subquery reached evaluation — plans with "
            "subqueries must go through plan_from_bytes / the DataFrame "
            "API (ScalarSubqueryBinderOp substitutes the value)")

    if isinstance(expr, ir.Literal):
        if expr.dtype == DataType.DECIMAL and expr.precision > 18:
            from auron_tpu.columnar.decimal128 import (Decimal128Column,
                                                       limbs_from_ints)
            vals = [None if expr.value is None else int(expr.value)] * cap
            hi, lo, valid = limbs_from_ints(vals, cap)
            return TypedValue(
                Decimal128Column(jnp.asarray(hi), jnp.asarray(lo),
                                 jnp.asarray(valid)),
                DataType.DECIMAL, expr.precision, expr.scale)
        return TypedValue(_const_column(expr.value, expr.dtype, cap),
                          expr.dtype, expr.precision, expr.scale)

    if isinstance(expr, ir.BinaryExpr):
        return _eval_binary(expr, batch, schema, ctx)

    if isinstance(expr, ir.Not):
        v = evaluate(expr.child, batch, schema, ctx)
        return TypedValue(PrimitiveColumn(~v.data.astype(bool), v.validity),
                          DataType.BOOL)

    if isinstance(expr, ir.IsNull):
        v = evaluate(expr.child, batch, schema, ctx)
        return TypedValue(PrimitiveColumn(~v.validity & batch.row_mask(),
                                          jnp.ones(cap, bool)), DataType.BOOL)

    if isinstance(expr, ir.IsNotNull):
        v = evaluate(expr.child, batch, schema, ctx)
        return TypedValue(PrimitiveColumn(v.validity & batch.row_mask(),
                                          jnp.ones(cap, bool)), DataType.BOOL)

    if isinstance(expr, ir.Negative):
        v = evaluate(expr.child, batch, schema, ctx)
        return TypedValue(PrimitiveColumn(-v.data, v.validity),
                          v.dtype, v.precision, v.scale)

    if isinstance(expr, ir.Cast):
        v = evaluate(expr.child, batch, schema, ctx)
        return cast_value(v, expr.dtype, expr.precision, expr.scale,
                          safe=expr.safe)

    if isinstance(expr, ir.CaseWhen):
        return _eval_case(expr, batch, schema, ctx)

    if isinstance(expr, ir.InList):
        return _eval_in_list(expr, batch, schema, ctx)

    if isinstance(expr, (ir.Like, ir.StringStartsWith, ir.StringEndsWith,
                         ir.StringContains)):
        return _eval_like(expr, batch, schema, ctx)

    if isinstance(expr, ir.ScalarFunction):
        from auron_tpu.exprs.functions import dispatch_function
        return dispatch_function(expr, batch, schema, ctx)

    if isinstance(expr, ir.RowNum):
        rn = jnp.arange(cap, dtype=jnp.int64) + jnp.asarray(ctx.row_num_offset, jnp.int64)
        return TypedValue(PrimitiveColumn(rn, jnp.ones(cap, bool)), DataType.INT64)

    if isinstance(expr, ir.SparkPartitionId):
        pid = jnp.full(cap, 0, jnp.int32) + jnp.asarray(ctx.partition_id, jnp.int32)
        return TypedValue(PrimitiveColumn(pid, jnp.ones(cap, bool)), DataType.INT32)

    if isinstance(expr, ir.MonotonicallyIncreasingId):
        # Spark: partition_id << 33 | row index
        base = jnp.asarray(ctx.partition_id, jnp.int64) << 33
        mid = base + jnp.arange(cap, dtype=jnp.int64) + jnp.asarray(
            ctx.row_num_offset, jnp.int64)
        return TypedValue(PrimitiveColumn(mid, jnp.ones(cap, bool)), DataType.INT64)

    if isinstance(expr, ir.BloomFilterMightContain):
        from auron_tpu.exprs.bloom import might_contain_device
        v = evaluate(expr.value, batch, schema, ctx)
        vals = v.data.astype(jnp.int64)
        hit = might_contain_device(expr.serialized, vals)
        return TypedValue(PrimitiveColumn(hit, v.validity), DataType.BOOL)

    if isinstance(expr, ir.GetIndexedField):
        from auron_tpu.columnar.batch import ListColumn
        if isinstance(expr.child, ir.ScalarFunction) \
                and expr.child.name == "split":
            # split(...)[i] fused — string lists are never materialized
            from auron_tpu.exprs.fn_strings import split_index
            return split_index(expr.child.args, expr.ordinal, batch,
                               schema, ctx)
        v = evaluate(expr.child, batch, schema, ctx)
        from auron_tpu.columnar.batch import StringListColumn
        assert isinstance(v.col, (ListColumn, StringListColumn)), \
            "GetIndexedField needs a list"
        i = expr.ordinal
        in_range = (i >= 0) & (i < v.col.lens)
        idx = min(max(i, 0), v.col.max_elems - 1)
        if isinstance(v.col, StringListColumn):
            valid = v.col.validity & in_range & v.col.elem_valid[:, idx]
            return TypedValue(
                StringColumn(v.col.chars[:, idx],
                             jnp.where(valid, v.col.slens[:, idx], 0),
                             valid), DataType.STRING)
        elem_dt, _, _ = infer_dtype(expr, schema)
        return TypedValue(
            PrimitiveColumn(v.col.values[:, idx],
                            v.col.validity & in_range
                            & v.col.elem_valid[:, idx]),
            elem_dt)

    if isinstance(expr, ir.GetStructField):
        from auron_tpu.columnar.batch import StructColumn
        v = evaluate(expr.child, batch, schema, ctx)
        assert isinstance(v.col, StructColumn), "GetStructField needs struct"
        child = v.col.children[expr.ordinal]
        cf = infer_field(expr.child, schema).children[expr.ordinal]
        return TypedValue(
            child.with_validity(child.validity & v.validity),
            cf.dtype, cf.precision, cf.scale)

    if isinstance(expr, ir.HostUDF):
        return _eval_host_udf(expr, batch, schema, ctx)

    raise NotImplementedError(f"expression {type(expr).__name__}")


def infer_dtype(expr: ir.Expr, schema: Schema) -> tuple[DataType, int, int]:
    """Static result type of an expression (dtype, precision, scale)."""
    if isinstance(expr, ir.ColumnRef):
        f = schema[expr.index]
        return f.dtype, f.precision, f.scale
    if isinstance(expr, ir.Literal):
        return expr.dtype, expr.precision, expr.scale
    if isinstance(expr, ir.BinaryExpr):
        if expr.op in ("==", "!=", "<", "<=", ">", ">=", "and", "or"):
            return DataType.BOOL, 0, 0
        lt, lp, ls = infer_dtype(expr.left, schema)
        rt, rp, rs = infer_dtype(expr.right, schema)
        if lt == DataType.DECIMAL and rt == DataType.DECIMAL:
            # Spark decimal result types (precision 19..38 runs on the
            # two-limb kernels, columnar/decimal128.py)
            if expr.op == "/":
                return DataType.FLOAT64, 0, 0
            p, s, _fs = decimal_result_type(expr.op, lp, ls, rp, rs)
            return DataType.DECIMAL, p, s
        out = common_type(lt, rt)
        if expr.op == "/" and out in _RANK and not out.is_floating:
            # integer '/' keeps integer semantics here; Spark's true divide
            # is expressed by the host converter as cast-to-double first.
            return out, 0, 0
        return out, 0, 0
    if isinstance(expr, (ir.Not, ir.IsNull, ir.IsNotNull, ir.Like,
                         ir.StringStartsWith, ir.StringEndsWith,
                         ir.StringContains, ir.InList,
                         ir.BloomFilterMightContain)):
        return DataType.BOOL, 0, 0
    if isinstance(expr, ir.Negative):
        return infer_dtype(expr.child, schema)
    if isinstance(expr, ir.Cast):
        return expr.dtype, expr.precision, expr.scale
    if isinstance(expr, ir.ScalarSubquery):
        return expr.dtype, expr.precision, expr.scale
    if isinstance(expr, ir.CaseWhen):
        if expr.when_then:
            return infer_dtype(expr.when_then[0][1], schema)
        return infer_dtype(expr.otherwise, schema)
    if isinstance(expr, ir.ScalarFunction):
        from auron_tpu.exprs.functions import function_result_type
        return function_result_type(expr, schema)
    if isinstance(expr, ir.RowNum) or isinstance(expr, ir.MonotonicallyIncreasingId):
        return DataType.INT64, 0, 0
    if isinstance(expr, ir.SparkPartitionId):
        return DataType.INT32, 0, 0
    if isinstance(expr, ir.HostUDF):
        return expr.dtype, 0, 0
    if isinstance(expr, ir.GetIndexedField):
        if isinstance(expr.child, ir.ScalarFunction) \
                and expr.child.name == "split":
            return DataType.STRING, 0, 0
        child_dt = infer_dtype(expr.child, schema)
        if child_dt[0] == DataType.LIST:
            # element type rides in the field's elem slot / array expr
            if isinstance(expr.child, ir.ColumnRef):
                return schema[expr.child.index].elem, 0, 0
            from auron_tpu.exprs.fn_arrays import elem_dtype_of
            return elem_dtype_of(expr.child, schema), 0, 0
        raise NotImplementedError("GetIndexedField on non-column list")
    if isinstance(expr, ir.GetStructField):
        cf = infer_field(expr.child, schema).children[expr.ordinal]
        return cf.dtype, cf.precision, cf.scale
    raise NotImplementedError(f"infer_dtype for {type(expr).__name__}")


def infer_field(expr: ir.Expr, schema: Schema, name: str = "c") -> Field:
    """Nested-aware result field of an expression — like infer_dtype but
    keeping list/map element types and struct children (the 3-tuple
    (dtype, p, s) cannot describe nested results)."""
    if isinstance(expr, ir.ColumnRef):
        return schema[expr.index].with_name(name)
    if isinstance(expr, ir.ScalarFunction):
        from auron_tpu.exprs.functions import function_result_field
        f = function_result_field(expr, schema)
        if f is not None:
            return f.with_name(name)
    if isinstance(expr, ir.GetStructField):
        return infer_field(expr.child, schema).children[expr.ordinal] \
            .with_name(name)
    if isinstance(expr, ir.CaseWhen) and expr.otherwise is not None:
        f = infer_field(expr.otherwise, schema)
        if f.dtype in (DataType.MAP, DataType.STRUCT, DataType.LIST):
            return f.with_name(name)
    dt, p, s = infer_dtype(expr, schema)
    if dt in (DataType.MAP, DataType.STRUCT):
        # no nested-aware arm matched above: a Field without key/children
        # metadata would crash schema_to_arrow/serde far downstream — fail
        # at plan time instead (e.g. CaseWhen over maps with no otherwise)
        raise NotImplementedError(
            f"cannot infer nested ({dt.value}) result metadata for "
            f"{type(expr).__name__}; add an explicit typed branch "
            "(e.g. an 'otherwise' arm) or project the nested column "
            "directly")
    elem = None
    if dt == DataType.LIST:
        if isinstance(expr, ir.ScalarFunction):
            from auron_tpu.exprs.fn_arrays import elem_dtype_of
            elem = elem_dtype_of(expr, schema)
    return Field(name, dt, True, p, s, elem=elem)


# ---------------------------------------------------------------------------
# binary ops
# ---------------------------------------------------------------------------

def _numeric_promote(v: TypedValue, target: DataType, cap: int) -> TypedValue:
    if v.dtype == target:
        return v
    return cast_value(v, target)


def _eval_binary(expr: ir.BinaryExpr, batch, schema, ctx) -> TypedValue:
    op = expr.op
    l = evaluate(expr.left, batch, schema, ctx)
    r = evaluate(expr.right, batch, schema, ctx)
    cap = batch.capacity

    if op in ("and", "or"):
        ld, rd = l.data.astype(bool), r.data.astype(bool)
        lv, rv = l.validity, r.validity
        if op == "and":
            data = (ld & lv) & (rd & rv)
            # null unless any FALSE or both valid
            validity = (lv & rv) | (lv & ~ld) | (rv & ~rd)
        else:
            data = (ld & lv) | (rd & rv)
            validity = (lv & rv) | (lv & ld) | (rv & rd)
        return TypedValue(PrimitiveColumn(data, validity), DataType.BOOL)

    # string comparisons
    if isinstance(l.col, StringColumn) or isinstance(r.col, StringColumn):
        if not (isinstance(l.col, StringColumn) and isinstance(r.col, StringColumn)):
            raise TypeError(f"cannot {op} string with non-string")
        lt, eq = S.compare(l.col.chars, l.col.lens, r.col.chars, r.col.lens)
        validity = l.validity & r.validity
        table = {"==": eq, "!=": ~eq, "<": lt, "<=": lt | eq,
                 ">": ~(lt | eq), ">=": ~lt}
        if op not in table:
            raise TypeError(f"unsupported string op {op}")
        return TypedValue(PrimitiveColumn(table[op], validity), DataType.BOOL)

    # decimal alignment
    if l.dtype == DataType.DECIMAL or r.dtype == DataType.DECIMAL:
        return _eval_decimal_binary(op, l, r, cap)

    target = common_type(l.dtype, r.dtype)
    l = _numeric_promote(l, target, cap)
    r = _numeric_promote(r, target, cap)
    ld, rd = l.data, r.data
    validity = l.validity & r.validity

    if op in ("==", "!=", "<", "<=", ">", ">="):
        fn = {"==": jnp.equal, "!=": jnp.not_equal, "<": jnp.less,
              "<=": jnp.less_equal, ">": jnp.greater, ">=": jnp.greater_equal}[op]
        return TypedValue(PrimitiveColumn(fn(ld, rd), validity), DataType.BOOL)

    if op == "+":
        data = ld + rd
    elif op == "-":
        data = ld - rd
    elif op == "*":
        data = ld * rd
    elif op == "/":
        if target.is_floating:
            # Spark double semantics: x/0 → null (non-ANSI divide)
            safe = jnp.where(rd == 0, 1.0, rd)
            data = ld / safe
            validity = validity & (rd != 0)
        else:
            # Java-style truncating division; x/0 → null
            safe = jnp.where(rd == 0, 1, rd)
            q = jnp.sign(ld) * jnp.sign(safe) * (jnp.abs(ld) // jnp.abs(safe))
            data = q.astype(ld.dtype)
            validity = validity & (rd != 0)
    elif op == "%":
        if target.is_floating:
            safe = jnp.where(rd == 0, 1, rd)
            data = jnp.where(rd == 0, jnp.nan, ld - jnp.trunc(ld / safe) * safe)
        else:
            safe = jnp.where(rd == 0, 1, rd)
            data = (ld - (jnp.sign(ld) * jnp.sign(safe)
                          * (jnp.abs(ld) // jnp.abs(safe))).astype(ld.dtype) * safe)
            validity = validity & (rd != 0)
    else:
        raise NotImplementedError(f"binary op {op}")
    return TypedValue(PrimitiveColumn(data, validity), target)


def _eval_decimal_binary(op, l: TypedValue, r: TypedValue, cap: int) -> TypedValue:
    """Decimal arithmetic on unscaled int64 (reference decimal semantics live
    in spark-extension NativeConverters decimal arith + check_overflow;
    precision capped at 18 here)."""
    # promote ints to decimal scale 0
    if l.dtype != DataType.DECIMAL:
        l = TypedValue(PrimitiveColumn(l.data.astype(jnp.int64), l.validity),
                       DataType.DECIMAL, 18, 0) if not l.dtype.is_floating else l
    if r.dtype != DataType.DECIMAL:
        r = TypedValue(PrimitiveColumn(r.data.astype(jnp.int64), r.validity),
                       DataType.DECIMAL, 18, 0) if not r.dtype.is_floating else r
    if l.dtype.is_floating or r.dtype.is_floating or op == "/":
        lf = _decimal_to_f64(l)
        rf = _decimal_to_f64(r)
        return _eval_binary_simple(op, lf, rf)
    from auron_tpu.columnar.decimal128 import Decimal128Column
    s = max(l.scale, r.scale)
    # route to the two-limb path when either side is wide or the Spark
    # result type exceeds 18 digits (the int64 payload would wrap)
    rp, rs, full_s = decimal_result_type(op, l.precision, l.scale,
                                         r.precision, r.scale)
    wide = (isinstance(l.col, Decimal128Column)
            or isinstance(r.col, Decimal128Column) or rp > 18
            or full_s > rs)
    if wide:
        return _eval_decimal128_binary(op, l, r, rp, rs, full_s)
    ld = l.data * (10 ** (s - l.scale))
    rd = r.data * (10 ** (s - r.scale))
    validity = l.validity & r.validity
    if op in ("==", "!=", "<", "<=", ">", ">="):
        fn = {"==": jnp.equal, "!=": jnp.not_equal, "<": jnp.less,
              "<=": jnp.less_equal, ">": jnp.greater, ">=": jnp.greater_equal}[op]
        return TypedValue(PrimitiveColumn(fn(ld, rd), validity), DataType.BOOL)
    if op == "+":
        return TypedValue(PrimitiveColumn(ld + rd, validity), DataType.DECIMAL,
                          rp, s)
    if op == "-":
        return TypedValue(PrimitiveColumn(ld - rd, validity), DataType.DECIMAL,
                          rp, s)
    if op == "*":
        return TypedValue(PrimitiveColumn(l.data * r.data, validity),
                          DataType.DECIMAL, rp, l.scale + r.scale)
    raise NotImplementedError(f"decimal op {op}")


def _limbs_of(v: TypedValue):
    """(hi, lo) limbs of a decimal TypedValue of either representation."""
    from auron_tpu.columnar import decimal128 as D
    if isinstance(v.col, D.Decimal128Column):
        return v.col.hi, v.col.lo
    return D.from_int64(v.data.astype(jnp.int64))


def _mk_decimal(hi, lo, validity, precision: int, scale: int) -> TypedValue:
    """Wrap limb results in the narrowest faithful column class."""
    from auron_tpu.columnar import decimal128 as D
    if precision <= 18:
        v64, _fits = D.to_int64(hi, lo)   # |x| < 10^18 always fits
        return TypedValue(PrimitiveColumn(v64, validity), DataType.DECIMAL,
                          precision, scale)
    return TypedValue(D.Decimal128Column(hi, lo, validity),
                      DataType.DECIMAL, precision, scale)


def _eval_decimal128_binary(op, l: TypedValue, r: TypedValue, rp: int,
                            rs: int, full_s: int) -> TypedValue:
    """Two-limb decimal arithmetic/comparison for precision 19..38
    (reference computes these in Rust i128; columnar/decimal128.py is the
    limb kernel library). Declared-precision overflow nulls the row
    (Spark non-ANSI check_overflow semantics); when adjustPrecisionScale
    reduced the scale (full_s > rs), the raw result rescales HALF_UP.

    Rescaling by 10^ds can push a 38-digit value past 2^127 and wrap, so
    every rescale is guarded by a fits_precision(38 - ds) pre-check: for
    arithmetic an unsafe rescale implies result overflow (null); for
    comparisons those rows fall back to float64 ordering."""
    from auron_tpu.columnar import decimal128 as D
    s = max(l.scale, r.scale)
    lh, ll_ = _limbs_of(l)
    rh, rl = _limbs_of(r)
    validity = l.validity & r.validity

    def rescale_safe(h, lo, ds):
        if ds == 0:
            return h, lo, jnp.ones_like(validity)
        ok = D.fits_precision(h, lo, 38 - ds)
        h2, l2 = D.mul_pow10(h, lo, ds)
        return h2, l2, ok

    if op in ("==", "!=", "<", "<=", ">", ">="):
        ah, al, oka = rescale_safe(lh, ll_, s - l.scale)
        bh, bl, okb = rescale_safe(rh, rl, s - r.scale)
        lt, eq = D.cmp128(ah, al, bh, bl)
        # At most ONE side can be rescale-unsafe (only the smaller-scale
        # side has ds > 0), and an unsafe side's magnitude at the common
        # scale is >= 10^38 while a safe side's is < 10^38 — so the
        # unsafe side strictly dominates and its SIGN decides the order.
        a_unsafe = ~oka
        b_unsafe = ~okb
        a_neg = D.is_negative(lh, ll_)
        b_neg = D.is_negative(rh, rl)
        lt = jnp.where(a_unsafe, a_neg,
                       jnp.where(b_unsafe, ~b_neg, lt))
        eq = jnp.where(a_unsafe | b_unsafe, False, eq)
        out = {"==": eq, "!=": ~eq, "<": lt, "<=": lt | eq,
               ">": ~(lt | eq), ">=": ~lt}[op]
        return TypedValue(PrimitiveColumn(out, validity), DataType.BOOL)
    if op in ("+", "-"):
        ah, al, oka = rescale_safe(lh, ll_, s - l.scale)
        bh, bl, okb = rescale_safe(rh, rl, s - r.scale)
        if op == "+":
            oh, ol = D.add128(ah, al, bh, bl)
            bsign = D.is_negative(bh, bl)
        else:
            oh, ol = D.sub128(ah, al, bh, bl)
            bsign = ~D.is_negative(bh, bl) & ~((bh == 0) & (bl == 0))
        # 128-bit wrap detection: same-sign operands whose result flips
        # sign overflowed 2^127 (would otherwise slip past the
        # post-rescale precision check as a plausible wrong value)
        asign = D.is_negative(ah, al)
        osign = D.is_negative(oh, ol)
        no_wrap = ~((asign == bsign) & (osign != asign))
        ok = oka & okb & no_wrap
    elif op == "*":
        oh, ol = D.mul128(lh, ll_, rh, rl)
        # a RAW product beyond 2^127 wraps silently in the low-128
        # multiply; guard with a float magnitude check at the
        # representability bound (2^127 ~ 1.70e38, margin for float
        # error). Known limitation vs Spark's unbounded BigDecimal
        # intermediates: a product whose raw (pre-precision-loss-rescale)
        # value exceeds 2^127 nulls even if the rescaled result would fit.
        mag = jnp.abs(D.to_float64(lh, ll_) * D.to_float64(rh, rl))
        ok = mag < 1.6e38
    else:
        raise NotImplementedError(f"decimal128 op {op}")
    if full_s > rs:
        # precision-loss rescale (Spark adjustPrecisionScale, HALF_UP)
        oh, ol = D.div_pow10_half_up(oh, ol, full_s - rs)
    ok = ok & D.fits_precision(oh, ol, rp)
    return _mk_decimal(oh, ol, validity & ok, rp, rs)


def _decimal_to_f64(v: TypedValue) -> TypedValue:
    from auron_tpu.columnar import decimal128 as D
    if isinstance(v.col, D.Decimal128Column):
        f = D.to_float64(v.col.hi, v.col.lo) / (10.0 ** v.scale)
        return TypedValue(PrimitiveColumn(f, v.validity), DataType.FLOAT64)
    if v.dtype == DataType.DECIMAL:
        return TypedValue(
            PrimitiveColumn(v.data.astype(jnp.float64) / (10.0 ** v.scale),
                            v.validity), DataType.FLOAT64)
    if v.dtype != DataType.FLOAT64:
        return TypedValue(PrimitiveColumn(v.data.astype(jnp.float64), v.validity),
                          DataType.FLOAT64)
    return v


def _eval_binary_simple(op, l: TypedValue, r: TypedValue) -> TypedValue:
    validity = l.validity & r.validity
    fn = {"+": jnp.add, "-": jnp.subtract, "*": jnp.multiply,
          "/": jnp.divide,
          "==": jnp.equal, "!=": jnp.not_equal, "<": jnp.less,
          "<=": jnp.less_equal, ">": jnp.greater, ">=": jnp.greater_equal}[op]
    data = fn(l.data, r.data)
    out_t = DataType.BOOL if op in ("==", "!=", "<", "<=", ">", ">=") else DataType.FLOAT64
    return TypedValue(PrimitiveColumn(data, validity), out_t)


# ---------------------------------------------------------------------------
# case / in-list / like
# ---------------------------------------------------------------------------

def _eval_case(expr: ir.CaseWhen, batch, schema, ctx) -> TypedValue:
    branches = [(evaluate(w, batch, schema, ctx), evaluate(t, batch, schema, ctx))
                for w, t in expr.when_then]
    if expr.otherwise is not None:
        otherwise = evaluate(expr.otherwise, batch, schema, ctx)
    else:
        t0 = branches[0][1]
        if isinstance(t0.col, StringColumn):
            otherwise = TypedValue(
                StringColumn(jnp.zeros_like(t0.col.chars),
                             jnp.zeros_like(t0.col.lens),
                             jnp.zeros(batch.capacity, bool)),
                t0.dtype, t0.precision, t0.scale)
        else:
            otherwise = TypedValue(
                PrimitiveColumn(jnp.zeros_like(t0.data),
                                jnp.zeros(batch.capacity, bool)),
                t0.dtype, t0.precision, t0.scale)

    result = otherwise
    for cond, val in reversed(branches):
        take = cond.data.astype(bool) & cond.validity
        if isinstance(val.col, StringColumn):
            rw = max(val.col.width, result.col.width)
            vc = _widen_string(val.col, rw)
            rc = _widen_string(result.col, rw)
            col = StringColumn(
                jnp.where(take[:, None], vc.chars, rc.chars),
                jnp.where(take, vc.lens, rc.lens),
                jnp.where(take, vc.validity, rc.validity))
        else:
            col = PrimitiveColumn(
                jnp.where(take, val.data, result.data),
                jnp.where(take, val.validity, result.validity))
        result = TypedValue(col, val.dtype, val.precision, val.scale)
    return result


def _widen_string(col: StringColumn, width: int) -> StringColumn:
    if col.width == width:
        return col
    return StringColumn(jnp.pad(col.chars, ((0, 0), (0, width - col.width))),
                        col.lens, col.validity)


def _eval_in_list(expr: ir.InList, batch, schema, ctx) -> TypedValue:
    v = evaluate(expr.child, batch, schema, ctx)
    cap = batch.capacity
    if isinstance(v.col, StringColumn):
        hit = jnp.zeros(cap, bool)
        for s in expr.values:
            b = s.encode() if isinstance(s, str) else s
            lit_row, lit_len = S.literal_to_device(b, v.col.width)
            if lit_len > v.col.width:
                continue
            eq = jnp.all(v.col.chars == jnp.asarray(lit_row)[None, :], axis=1) \
                & (v.col.lens == lit_len)
            hit = hit | eq
    else:
        hit = jnp.zeros(cap, bool)
        for s in expr.values:
            hit = hit | (v.data == jnp.asarray(s, v.data.dtype))
    if expr.negated:
        hit = ~hit
    return TypedValue(PrimitiveColumn(hit, v.validity), DataType.BOOL)


def _eval_like(expr, batch, schema, ctx) -> TypedValue:
    v = evaluate(expr.child, batch, schema, ctx)
    if not isinstance(v.col, StringColumn):
        raise TypeError("LIKE on non-string")
    chars, lens = v.col.chars, v.col.lens

    if isinstance(expr, ir.StringStartsWith):
        hit = S.starts_with(chars, lens, expr.prefix.encode())
    elif isinstance(expr, ir.StringEndsWith):
        hit = S.ends_with(chars, lens, expr.suffix.encode())
    elif isinstance(expr, ir.StringContains):
        hit = S.contains(chars, lens, expr.infix.encode())
    else:
        pat = expr.pattern
        body = pat.strip("%")
        if "%" not in pat and "_" not in pat:
            row, ln = S.literal_to_device(pat.encode(), v.col.width)
            hit = (jnp.all(chars == jnp.asarray(row)[None, :], axis=1)
                   & (lens == ln)) if ln <= v.col.width else jnp.zeros(batch.capacity, bool)
        elif "_" not in body and "%" not in body:
            starts = not pat.startswith("%")
            ends = not pat.endswith("%")
            if starts and ends:
                # 'a%b' pattern
                parts = pat.split("%")
                hit = S.starts_with(chars, lens, parts[0].encode())
                for p in parts[1:-1]:
                    if p:
                        hit = hit & S.contains(chars, lens, p.encode())
                hit = hit & S.ends_with(chars, lens, parts[-1].encode())
                minlen = sum(len(p) for p in parts)
                hit = hit & (lens >= minlen)
            elif starts:
                hit = S.starts_with(chars, lens, body.encode())
            elif ends:
                hit = S.ends_with(chars, lens, body.encode())
            else:
                hit = S.contains(chars, lens, body.encode())
        else:
            # general pattern: host regex fallback
            import re
            rx = re.compile("^" + re.escape(pat).replace("%", ".*").replace("_", ".") + "$",
                            re.S)
            def host_like(chars_np, lens_np):
                out = np.zeros(chars_np.shape[0], bool)
                for i in range(chars_np.shape[0]):
                    s = bytes(chars_np[i, :lens_np[i]]).decode("utf-8", "replace")
                    out[i] = rx.match(s) is not None
                return out
            hit = jax.pure_callback(
                host_like, jax.ShapeDtypeStruct((batch.capacity,), jnp.bool_),
                chars, lens, vmap_method="sequential")
    if getattr(expr, "negated", False):
        hit = ~hit
    return TypedValue(PrimitiveColumn(hit, v.validity), DataType.BOOL)


# ---------------------------------------------------------------------------
# cast
# ---------------------------------------------------------------------------

_INT_BITS = {DataType.INT8: 8, DataType.INT16: 16, DataType.INT32: 32,
             DataType.INT64: 64}


def cast_value(v: TypedValue, dtype: DataType, precision: int = 0,
               scale: int = 0, safe: bool = True) -> TypedValue:
    """Spark cast semantics (checklist: reference
    datafusion-ext-commons/src/arrow/cast.rs). safe=True is the default
    null-on-failure mode (Spark non-ANSI / TryCast); safe=False raises on
    unparseable strings (ANSI), checked at the host boundary."""
    if v.dtype == dtype and (dtype != DataType.DECIMAL
                             or (v.scale == scale
                                 and v.precision <= precision)):
        return v
    validity = v.validity
    cap = validity.shape[0]

    if isinstance(v.col, StringColumn):
        return _cast_from_string(v, dtype, precision, scale, safe)

    from auron_tpu.columnar import decimal128 as _D128
    if isinstance(v.col, _D128.Decimal128Column) or (
            v.dtype == DataType.DECIMAL and dtype == DataType.DECIMAL
            and precision > 18):
        return _cast_decimal128(v, dtype, precision, scale)

    if dtype == DataType.STRING:
        return _cast_to_string(v)

    d = v.data

    if v.dtype == DataType.DECIMAL:
        if dtype == DataType.DECIMAL:
            # rescale with integer math: round half-up like Spark
            ds = scale - v.scale
            limit = 10 ** min(precision, 18)
            if ds >= 0:
                # overflow-check BEFORE multiplying (int64 wrap would
                # otherwise slip past the bound)
                pre_limit = limit // (10 ** ds) if ds <= 18 else 0
                ok = jnp.abs(d) < max(pre_limit, 1)
                unscaled = jnp.where(ok, d, 0) * (10 ** min(ds, 18))
            else:
                div = 10 ** (-ds)
                # round half away from zero (Spark HALF_UP)
                q_abs = (jnp.abs(d) + div // 2) // div
                unscaled = jnp.where(d >= 0, q_abs, -q_abs)
                ok = jnp.abs(unscaled) < limit
            return TypedValue(
                PrimitiveColumn(jnp.where(ok, unscaled, 0).astype(jnp.int64),
                                validity & ok),
                DataType.DECIMAL, precision, scale)
        if dtype.is_integer:
            # truncate toward zero on the decimal value (Spark)
            div = 10 ** v.scale
            q = jnp.where(d >= 0, d // div, -((-d) // div))
            target = _JNP[dtype]
            return TypedValue(PrimitiveColumn(q.astype(target), validity),
                              dtype)
        f = d.astype(jnp.float64) / (10.0 ** v.scale)
        return cast_value(TypedValue(PrimitiveColumn(f, validity),
                                     DataType.FLOAT64), dtype, precision, scale)

    if dtype == DataType.DECIMAL:
        if precision > 18 and v.dtype.is_floating:
            # double → wide decimal: build limbs from the float magnitude
            # (doubles carry 53 bits — digits beyond ~17 are already
            # approximation in Spark too, which rounds BigDecimal(double))
            from auron_tpu.columnar import decimal128 as D
            mag = jnp.abs(jnp.round(d.astype(jnp.float64) * (10.0 ** scale)))
            ok = mag < float(10 ** precision)
            magc = jnp.where(ok, mag, 0.0)
            hi_f = jnp.floor(magc / (2.0 ** 64))
            lo_f = magc - hi_f * (2.0 ** 64)
            hi = hi_f.astype(jnp.int64)
            lo = jnp.where(lo_f >= 2.0 ** 63,
                           (lo_f - 2.0 ** 64).astype(jnp.int64),
                           lo_f.astype(jnp.int64))
            neg = d < 0
            nh, nl = D.neg128(hi, lo)
            hi = jnp.where(neg, nh, hi)
            lo = jnp.where(neg, nl, lo)
            return TypedValue(D.Decimal128Column(hi, lo, validity & ok),
                              DataType.DECIMAL, precision, scale)
        if precision > 18 and not v.dtype.is_floating:
            # int → wide decimal: exact limb promotion + scale-up
            from auron_tpu.columnar import decimal128 as D
            hi, lo = D.from_int64(d.astype(jnp.int64))
            hi, lo = D.mul_pow10(hi, lo, scale)
            ok = D.fits_precision(hi, lo, precision)
            return TypedValue(D.Decimal128Column(hi, lo, validity & ok),
                              DataType.DECIMAL, precision, scale)
        if v.dtype.is_floating:
            unscaled = jnp.round(d.astype(jnp.float64) * (10.0 ** scale))
            ok = jnp.abs(unscaled) < float(10 ** min(precision, 18))
            out = jnp.where(ok, unscaled, 0).astype(jnp.int64)
            return TypedValue(PrimitiveColumn(out, validity & ok),
                              DataType.DECIMAL, precision, scale)
        unscaled = d.astype(jnp.int64) * (10 ** scale)
        ok = jnp.abs(unscaled) < (10 ** min(precision, 18))
        return TypedValue(PrimitiveColumn(jnp.where(ok, unscaled, 0), validity & ok),
                          DataType.DECIMAL, precision, scale)

    if dtype == DataType.BOOL:
        return TypedValue(PrimitiveColumn(d != 0, validity), DataType.BOOL)

    if v.dtype == DataType.BOOL:
        d = d.astype(jnp.int32)

    if dtype in _INT_BITS:
        target = _JNP[dtype]
        if v.dtype.is_floating:
            # Spark non-ANSI Cast: truncate toward zero; NaN, ±inf and
            # values outside the target range become NULL (not the JVM
            # d2i saturate — cast(2.5e9 as int) is NULL, not MaxValue).
            # The range check mirrors Spark's, where Long.MaxValue
            # promotes to double 2^63: the input exactly 2^63 is ADMITTED
            # and d2l-saturates to MaxValue, while anything above nulls.
            bits = _INT_BITS[dtype]
            t = jnp.trunc(d.astype(jnp.float64))
            lo_f = -(2.0 ** (bits - 1))
            hi_adm = float(2 ** (bits - 1) - 1)   # int64: rounds to 2^63
            ok = (t >= lo_f) & (t <= hi_adm)      # False for NaN/±inf too
            at_top = t >= 2.0 ** (bits - 1)       # the admitted boundary
            out = jnp.where(ok & ~at_top, t, 0.0).astype(target)
            out = jnp.where(at_top, jnp.asarray(2 ** (bits - 1) - 1, target),
                            out)
            return TypedValue(PrimitiveColumn(out, validity & ok), dtype)
        # int→int narrowing wraps (Java semantics)
        return TypedValue(PrimitiveColumn(d.astype(target), validity), dtype)

    if dtype in (DataType.FLOAT32, DataType.FLOAT64):
        return TypedValue(PrimitiveColumn(d.astype(_JNP[dtype]), validity), dtype)

    if dtype == DataType.DATE32:
        if v.dtype == DataType.TIMESTAMP_US:
            days = jnp.floor_divide(d, 86_400_000_000)
            return TypedValue(PrimitiveColumn(days.astype(jnp.int32), validity),
                              DataType.DATE32)
        return TypedValue(PrimitiveColumn(d.astype(jnp.int32), validity),
                          DataType.DATE32)

    if dtype == DataType.TIMESTAMP_US:
        if v.dtype == DataType.DATE32:
            us = d.astype(jnp.int64) * 86_400_000_000
            return TypedValue(PrimitiveColumn(us, validity), DataType.TIMESTAMP_US)
        return TypedValue(PrimitiveColumn(d.astype(jnp.int64), validity),
                          DataType.TIMESTAMP_US)

    raise NotImplementedError(f"cast {v.dtype} -> {dtype}")


def _cast_decimal128(v: TypedValue, dtype: DataType, precision: int,
                     scale: int) -> TypedValue:
    """Casts touching the two-limb representation: rescale between wide
    and narrow decimals (HALF_UP, overflow→null), to float, to ints, and
    to string via the host (reference: arrow/cast.rs decimal arms)."""
    from auron_tpu.columnar import decimal128 as D
    validity = v.validity
    hi, lo = _limbs_of(v)
    if dtype == DataType.DECIMAL:
        ds = scale - v.scale
        if ds >= 0:
            hi2, lo2 = D.mul_pow10(hi, lo, ds)
        else:
            hi2, lo2 = D.div_pow10_half_up(hi, lo, -ds)
        ok = D.fits_precision(hi2, lo2, precision)
        return _mk_decimal(hi2, lo2, validity & ok, precision, scale)
    if dtype.is_floating or dtype == DataType.FLOAT64:
        f = D.to_float64(hi, lo) / (10.0 ** v.scale)
        return cast_value(TypedValue(PrimitiveColumn(f, validity),
                                     DataType.FLOAT64), dtype)
    if dtype.is_integer:
        # truncate toward zero, then int64 range check (Spark)
        qh, ql = D.div_pow10_trunc(hi, lo, v.scale)
        v64, fits = D.to_int64(qh, ql)
        target = _JNP[dtype]
        return TypedValue(PrimitiveColumn(v64.astype(target),
                                          validity & fits), dtype)
    if dtype == DataType.STRING:
        import jax
        import numpy as np
        cap = validity.shape[0]
        width = 48  # 38 digits + sign + point + margin

        def host(hi_np, lo_np, valid_np):
            import decimal
            ints = D.ints_from_limbs(hi_np, lo_np, valid_np)
            chars = np.zeros((cap, width), np.uint8)
            lens = np.zeros(cap, np.int32)
            with decimal.localcontext() as dctx:
                dctx.prec = 60
                for i, x in enumerate(ints):
                    if x is None:
                        continue
                    d = decimal.Decimal(x).scaleb(-v.scale)
                    # plain notation, never scientific (Spark CAST output)
                    b = format(d, "f").encode()[:width]
                    chars[i, :len(b)] = np.frombuffer(b, np.uint8)
                    lens[i] = len(b)
            return chars, lens

        chars, lens = jax.pure_callback(
            host,
            (jax.ShapeDtypeStruct((cap, width), jnp.uint8),
             jax.ShapeDtypeStruct((cap,), jnp.int32)),
            hi, lo, validity, vmap_method="sequential")
        return TypedValue(StringColumn(chars, lens, validity),
                          DataType.STRING)
    raise NotImplementedError(f"decimal128 cast to {dtype}")


def _cast_to_string(v: TypedValue) -> TypedValue:
    """Numeric→string via host callback (cold path, like the reference's JVM
    UDF fallback)."""
    cap = v.data.shape[0]
    if v.dtype == DataType.BOOL:
        fmt = lambda x: str(bool(x)).lower()
        width = 8
    elif v.dtype.is_integer:
        fmt = lambda x: str(int(x))
        width = 24
    elif v.dtype == DataType.DECIMAL:
        scale = v.scale
        def fmt(x):
            from decimal import Decimal
            return str(Decimal(int(x)).scaleb(-scale))
        width = 24
    elif v.dtype == DataType.DATE32:
        import datetime
        fmt = lambda x: (datetime.date(1970, 1, 1)
                         + datetime.timedelta(days=int(x))).isoformat()
        width = 16
    elif v.dtype == DataType.TIMESTAMP_US:
        import datetime
        def fmt(x):
            ts = (datetime.datetime(1970, 1, 1)
                  + datetime.timedelta(microseconds=int(x)))
            s = ts.strftime("%Y-%m-%d %H:%M:%S")
            if ts.microsecond:
                s += f".{ts.microsecond:06d}".rstrip("0")
            return s
        width = 32
    else:
        is_f32 = v.dtype == DataType.FLOAT32
        def fmt(x):
            f = float(x)
            if f != f:
                return "NaN"
            if f == float("inf"):
                return "Infinity"
            if f == float("-inf"):
                return "-Infinity"
            a = abs(f)
            if a != 0 and (a >= 1e7 or a < 1e-3):
                # Java Float/Double.toString switches to scientific
                # notation outside [1e-3, 1e7): '1.0E30'
                s = np.format_float_scientific(
                    np.float32(x) if is_f32 else f, unique=True,
                    trim="0", exp_digits=1)
                mant, exp = s.split("e")
                if "." not in mant:
                    mant += ".0"
                return f"{mant}E{int(exp)}"
            if f == int(f):
                return f"{f:.1f}"
            if is_f32:
                # shortest round-trip at f32 precision: '0.1', not the
                # widened double representation '0.10000000149...'
                return np.format_float_positional(
                    np.float32(x), unique=True, trim="0")
            return repr(f)
        width = 32

    def host_fmt(data_np):
        chars = np.zeros((cap, width), np.uint8)
        lens = np.zeros(cap, np.int32)
        for i, x in enumerate(data_np):
            b = fmt(x).encode()[:width]
            chars[i, : len(b)] = np.frombuffer(b, np.uint8)
            lens[i] = len(b)
        return chars, lens

    chars, lens = jax.pure_callback(
        host_fmt,
        (jax.ShapeDtypeStruct((cap, width), jnp.uint8),
         jax.ShapeDtypeStruct((cap,), jnp.int32)),
        v.data, vmap_method="sequential")
    return TypedValue(StringColumn(chars, lens, v.validity), DataType.STRING)


def _cast_from_string(v: TypedValue, dtype: DataType, precision: int,
                      scale: int, safe: bool = True) -> TypedValue:
    """string→numeric parse on host; invalid → null when safe (TryCast /
    non-ANSI), raise when not (ANSI) (reference:
    datafusion-ext-exprs/src/cast.rs)."""
    col: StringColumn = v.col
    cap = col.capacity

    if dtype == DataType.BOOL:
        parse = lambda s: {"true": True, "t": True, "1": True, "yes": True, "y": True,
                           "false": False, "f": False, "0": False, "no": False,
                           "n": False}.get(s.strip().lower())
        np_t = np.bool_
    elif dtype.is_integer or dtype == DataType.DATE32:
        if dtype == DataType.DATE32:
            import datetime
            import re
            # Spark accepts non-zero-padded fields: yyyy-[m]m-[d]d
            # (DateTimeUtils.stringToDate); fromisoformat would reject
            # "2020-1-2"
            date_re = re.compile(r"^(\d{1,4})-(\d{1,2})-(\d{1,2})$")
            def parse(s):
                m = date_re.match(s.strip())
                if not m:
                    return None
                try:
                    d = datetime.date(int(m.group(1)), int(m.group(2)),
                                      int(m.group(3)))
                except ValueError:
                    return None
                return (d - datetime.date(1970, 1, 1)).days
            np_t = np.int32
        else:
            bits = _INT_BITS[dtype]
            lo, hi = -(2 ** (bits - 1)), 2 ** (bits - 1) - 1
            def parse(s):
                # Spark UTF8String.toInt/toLong: trimmed, optional sign,
                # digits with an optional '.' + digit fraction that
                # TRUNCATES toward zero ('4.5'→4, '.5'→0); scientific
                # notation ('1e2') stays NULL. Exact int parsing keeps
                # Long.MaxValue-class strings lossless.
                s = s.strip()
                if not s:
                    return None
                sign = -1 if s[0] == "-" else 1
                body = s[1:] if s[0] in "+-" else s
                if not (body.isascii() and body.isdigit()):
                    intpart, dot, frac = body.partition(".")
                    if not dot or not (frac == "" or (frac.isascii()
                                                     and frac.isdigit())):
                        return None
                    if intpart and not (intpart.isascii()
                                        and intpart.isdigit()):
                        return None
                    if not intpart and not frac:
                        return None      # bare '.' / '+.'
                    body = intpart or "0"
                r = sign * int(body)
                return r if lo <= r <= hi else None
            np_t = _JNP[dtype]
    elif dtype == DataType.DECIMAL:
        from decimal import ROUND_HALF_UP, Decimal, InvalidOperation
        def parse(s):
            try:
                # Spark Decimal.changePrecision rescales HALF_UP:
                # cast('1.005' as decimal(10,2)) → 1.01, not banker's 1.00.
                # OverflowError: 'Infinity' parses as a Decimal but cannot
                # convert to int — NULL, not a crash
                r = int(Decimal(s.strip()).scaleb(scale)
                        .to_integral_value(rounding=ROUND_HALF_UP))
            except (InvalidOperation, ValueError, OverflowError):
                return None
            # beyond the declared precision → null (Spark
            # Decimal.changePrecision failure)
            if precision and abs(r) >= 10 ** precision:
                return None
            return r
        np_t = np.int64
    elif dtype == DataType.TIMESTAMP_US:
        import datetime
        def parse(s):
            try:
                ts = datetime.datetime.fromisoformat(s.strip())
            except ValueError:
                return None
            if ts.tzinfo is None:
                ts = ts.replace(tzinfo=datetime.timezone.utc)
            else:
                ts = ts.astimezone(datetime.timezone.utc)
            return int(ts.timestamp() * 1e6)
        np_t = np.int64
    else:
        def parse(s):
            try:
                return float(s.strip())
            except ValueError:
                return None
        np_t = _JNP[dtype]

    def host_parse(chars_np, lens_np, valid_np):
        data = np.zeros(cap, np_t)
        ok = np.zeros(cap, bool)
        for i in range(cap):
            s = bytes(chars_np[i, : lens_np[i]]).decode("utf-8", "replace")
            try:
                r = parse(s)
            except (ValueError, OverflowError):
                r = None
            if r is not None:
                try:
                    data[i] = r
                    ok[i] = True
                except (OverflowError, ValueError):
                    # parsed but does not fit the target width → null
                    data[i] = 0
                    ok[i] = False
            if not ok[i] and not safe and valid_np[i]:
                raise ValueError(
                    f"[CAST_INVALID_INPUT] cannot cast {s!r} to "
                    f"{dtype.value} (ANSI mode)")
        return data, ok

    data, ok = jax.pure_callback(
        host_parse,
        (jax.ShapeDtypeStruct((cap,), np_t),
         jax.ShapeDtypeStruct((cap,), jnp.bool_)),
        col.chars, col.lens, v.validity, vmap_method="sequential")
    return TypedValue(PrimitiveColumn(data, v.validity & ok), dtype,
                      precision, scale)


# ---------------------------------------------------------------------------
# host UDF escape hatch
# ---------------------------------------------------------------------------

def _eval_host_udf(expr: ir.HostUDF, batch, schema, ctx) -> TypedValue:
    import pyarrow as pa
    args = [evaluate(a, batch, schema, ctx) for a in expr.args]
    cap = batch.capacity

    # argument wire: primitives as (data, validity); strings via the
    # (chars, lens, validity) protocol (the Arrow-FFI round trip of the
    # reference's SparkUDFWrapperContext, spark_udf_wrapper.rs:43-230)
    leaves: list = []
    layout: list[str] = []
    for a in args:
        if isinstance(a.col, StringColumn):
            layout.append("s")
            leaves += [a.col.chars, a.col.lens, a.validity]
        else:
            layout.append("p")
            leaves += [a.data, a.validity]

    string_result = expr.dtype == DataType.STRING
    out_np = None if string_result else _JNP[expr.dtype]
    # result width bound: adaptive to the string inputs (a concat-style
    # UDF fits), floored at 256; truncation happens on UTF-8 codepoint
    # boundaries so an overflow can never corrupt the column
    out_w = 0
    if string_result:
        in_w = sum(a.col.width for a in args
                   if isinstance(a.col, StringColumn))
        out_w = bucket_string_width(max(2 * in_w + 64, 256))

    def host(*cols):
        arrays = []
        pos = 0
        for kind in layout:
            if kind == "s":
                chars, lens, ok = cols[pos:pos + 3]
                pos += 3
                vals = [bytes(chars[i, :lens[i]]).decode("utf-8", "replace")
                        if ok[i] else None for i in range(cap)]
                arrays.append(pa.array(vals, pa.string()))
            else:
                d, ok = cols[pos:pos + 2]
                pos += 2
                arrays.append(pa.array(
                    np.where(ok, d, None).tolist() if not ok.all() else d))
        result = expr.fn(arrays)
        ok = ~np.asarray(result.is_null()) if result.null_count \
            else np.ones(cap, bool)
        if string_result:
            chars = np.zeros((cap, out_w), np.uint8)
            lens = np.zeros(cap, np.int32)
            for i, v in enumerate(result.to_pylist()):
                if v is None:
                    continue
                b = v.encode()
                if len(b) > out_w:
                    b = b[:out_w]
                    # back off to a codepoint boundary (0b10xxxxxx bytes
                    # are continuations)
                    while b and (b[-1] & 0xC0) == 0x80:
                        b = b[:-1]
                chars[i, :len(b)] = np.frombuffer(b, np.uint8)
                lens[i] = len(b)
            return chars, lens, ok
        res_np = np.asarray(result.fill_null(0).to_numpy(
            zero_copy_only=False), dtype=out_np)
        return res_np.astype(out_np), ok

    if string_result:
        chars, lens, ok = jax.pure_callback(
            host,
            (jax.ShapeDtypeStruct((cap, out_w), jnp.uint8),
             jax.ShapeDtypeStruct((cap,), jnp.int32),
             jax.ShapeDtypeStruct((cap,), jnp.bool_)),
            *leaves, vmap_method="sequential")
        return TypedValue(StringColumn(chars, lens, ok), DataType.STRING)
    data, ok = jax.pure_callback(
        host,
        (jax.ShapeDtypeStruct((cap,), out_np),
         jax.ShapeDtypeStruct((cap,), jnp.bool_)),
        *leaves, vmap_method="sequential")
    return TypedValue(PrimitiveColumn(data, ok), expr.dtype)
