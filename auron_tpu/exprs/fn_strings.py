"""Spark string functions as device kernels.

The reference implements these as Rust row loops over Arrow string arrays
(reference: datafusion-ext-functions/src/spark_strings.rs). On TPU the
fixed-width (chars[n, w], lens[n]) layout turns every one of them into
masked gathers/scatters over the char matrix — no per-row host work. Ops
whose output length is data-dependent (translate with deletions,
substring_index) compute a per-row keep mask and compact it with one
argsort, the same trick the filter operator uses for rows.

Registered into the shared scalar-function registry (exprs/functions.py).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from auron_tpu.columnar.batch import PrimitiveColumn, StringColumn
from auron_tpu.columnar.schema import DataType
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import TypedValue, cast_value
from auron_tpu.exprs.functions import register
from auron_tpu.ops import strings as S
from auron_tpu.utils.shapes import bucket_string_width


def _string_result(expr, schema):
    return DataType.STRING, 0, 0


def _lit(expr: ir.ScalarFunction, k: int, default=None):
    """Literal argument value at position k, or default when absent."""
    if k >= len(expr.args):
        return default
    a = expr.args[k]
    if not isinstance(a, ir.Literal):
        raise NotImplementedError(
            f"{expr.name}: argument {k} must be a literal")
    return a.value


def _pos(w: int):
    return jnp.arange(w, dtype=jnp.int32)[None, :]


# ---------------------------------------------------------------------------
# concat_ws / initcap / repeat / reverse / pads
# ---------------------------------------------------------------------------

@register("concat_ws", _string_result)
def _concat_ws(args, expr, batch, schema, ctx):
    """concat_ws(sep, s1, s2, ...): null args are SKIPPED (unlike concat);
    result is null only when sep is null (Spark semantics)."""
    sep, parts = args[0], args[1:]
    if not parts:
        n = batch.capacity
        return TypedValue(StringColumn(jnp.zeros((n, 8), jnp.uint8),
                                       jnp.zeros(n, jnp.int32),
                                       sep.validity), DataType.STRING)
    n = parts[0].col.capacity
    sep_c: StringColumn = sep.col
    total_w = sum(p.col.width for p in parts) + \
        sep_c.width * max(len(parts) - 1, 0)
    out_w = bucket_string_width(max(total_w, 1))
    out = jnp.zeros((n, out_w), jnp.uint8)
    pos = jnp.zeros(n, jnp.int32)
    written_any = jnp.zeros(n, bool)
    rows = jnp.arange(n)

    def scatter(out, pos, chars, lens, include):
        w = chars.shape[1]
        tgt = pos[:, None] + _pos(w)
        valid = (_pos(w) < lens[:, None]) & include[:, None]
        tgt = jnp.where(valid, tgt, out_w)
        r = jnp.broadcast_to(rows[:, None], (n, w))
        out = out.at[r.reshape(-1),
                     jnp.clip(tgt, 0, out_w).reshape(-1)].max(
            jnp.where(valid, chars, 0).reshape(-1), mode="drop")
        return out, pos + jnp.where(include, lens, 0)

    for p in parts:
        inc = p.validity
        # separator before this part if something was already written
        sep_inc = inc & written_any
        out, pos = scatter(out, pos, sep_c.chars, sep_c.lens, sep_inc)
        out, pos = scatter(out, pos, p.col.chars, p.col.lens, inc)
        written_any = written_any | inc
    return TypedValue(StringColumn(out, jnp.where(sep.validity, pos, 0),
                                   sep.validity), DataType.STRING)


@register("initcap", _string_result)
def _initcap(args, expr, batch, schema, ctx):
    """Uppercase the first letter of each space-separated word, lowercase
    the rest (Spark initcap, ASCII)."""
    c = args[0].col.chars
    lo = jnp.where((c >= ord("A")) & (c <= ord("Z")), c + 32, c)
    # word start: position 0, or previous char is a space
    prev = jnp.concatenate(
        [jnp.full((c.shape[0], 1), ord(" "), jnp.uint8), lo[:, :-1]], axis=1)
    start = prev == ord(" ")
    up = jnp.where(start & (lo >= ord("a")) & (lo <= ord("z")), lo - 32, lo)
    return TypedValue(StringColumn(up.astype(jnp.uint8), args[0].col.lens,
                                   args[0].validity), DataType.STRING)


@register("repeat", _string_result)
def _repeat(args, expr, batch, schema, ctx):
    v = args[0]
    times = int(_lit(expr, 1, 1) or 0)
    w = v.col.width
    if times <= 0:
        n = v.col.capacity
        return TypedValue(StringColumn(jnp.zeros((n, 8), jnp.uint8),
                                       jnp.zeros(n, jnp.int32), v.validity),
                          DataType.STRING)
    out_w = bucket_string_width(w * times)
    n = v.col.capacity
    # tile positions: out[j] = chars[j mod len] for j < len*times
    out_pos = jnp.arange(out_w, dtype=jnp.int32)[None, :]
    lens = jnp.maximum(v.col.lens, 1)[:, None]  # avoid mod 0
    src = jnp.mod(out_pos, lens)
    gathered = jnp.take_along_axis(
        jnp.pad(v.col.chars, ((0, 0), (0, max(out_w - w, 0)))),
        jnp.clip(src, 0, max(out_w - 1, 0)), axis=1)
    out_len = v.col.lens * times
    mask = out_pos < out_len[:, None]
    return TypedValue(StringColumn(
        jnp.where(mask, gathered, 0).astype(jnp.uint8),
        out_len, v.validity), DataType.STRING)


@register("reverse", _string_result)
def _reverse(args, expr, batch, schema, ctx):
    v = args[0]
    chars, lens = v.col.chars, v.col.lens
    n, w = chars.shape
    idx = lens[:, None] - 1 - _pos(w)
    out = jnp.take_along_axis(chars, jnp.clip(idx, 0, w - 1), axis=1)
    mask = _pos(w) < lens[:, None]
    return TypedValue(StringColumn(jnp.where(mask, out, 0).astype(jnp.uint8),
                                   lens, v.validity), DataType.STRING)


def _pad(args, expr, batch, schema, ctx, left: bool):
    v = args[0]
    target = int(_lit(expr, 1, 0) or 0)
    pad_s = _lit(expr, 2, " ")
    pad_b = (pad_s if isinstance(pad_s, bytes) else str(pad_s).encode()) or b" "
    n, w = v.col.chars.shape
    out_w = bucket_string_width(max(target, 1))
    lens = v.col.lens
    out_len = jnp.minimum(jnp.maximum(lens, target), target)
    pad_arr = jnp.asarray(np.frombuffer(pad_b, np.uint8))
    plen = len(pad_b)
    pos = jnp.arange(out_w, dtype=jnp.int32)[None, :]
    src = jnp.pad(v.col.chars, ((0, 0), (0, max(out_w - w, 0))))[:, :out_w]
    if left:
        npad = jnp.maximum(target - lens, 0)[:, None]
        from_pad = pos < npad
        pad_chars = pad_arr[jnp.mod(pos, plen)]
        str_idx = pos - npad
        str_chars = jnp.take_along_axis(
            src, jnp.clip(str_idx, 0, out_w - 1), axis=1)
        out = jnp.where(from_pad, pad_chars, str_chars)
    else:
        in_str = pos < lens[:, None]
        pad_chars = pad_arr[jnp.mod(pos - lens[:, None], plen)]
        out = jnp.where(in_str, src, pad_chars)
    mask = pos < out_len[:, None]
    return TypedValue(StringColumn(jnp.where(mask, out, 0).astype(jnp.uint8),
                                   out_len, v.validity), DataType.STRING)


@register("lpad", _string_result)
def _lpad(args, expr, batch, schema, ctx):
    return _pad(args, expr, batch, schema, ctx, left=True)


@register("rpad", _string_result)
def _rpad(args, expr, batch, schema, ctx):
    return _pad(args, expr, batch, schema, ctx, left=False)


@register("left", _string_result)
def _left(args, expr, batch, schema, ctx):
    v = args[0]
    ln = cast_value(args[1], DataType.INT32).data
    return TypedValue(S.substring(v.col, jnp.ones_like(ln),
                                  jnp.maximum(ln, 0)), DataType.STRING)


@register("right", _string_result)
def _right(args, expr, batch, schema, ctx):
    v = args[0]
    ln = jnp.maximum(cast_value(args[1], DataType.INT32).data, 0)
    start = jnp.where(ln == 0, v.col.lens + 1, -ln)
    return TypedValue(S.substring(v.col, start, jnp.full_like(ln, 2 ** 30)),
                      DataType.STRING)


@register("space", _string_result)
def _space(args, expr, batch, schema, ctx):
    # literal-only: the output width must be static (a silent cap on a
    # column argument would truncate data)
    nsp = jnp.maximum(cast_value(args[0], DataType.INT32).data, 0)
    cap_n = int(_lit(expr, 0, 0) or 0)
    out_w = bucket_string_width(max(cap_n, 1))
    n = args[0].col.capacity
    nsp = jnp.minimum(nsp, out_w)
    mask = _pos(out_w) < nsp[:, None]
    chars = jnp.where(mask, ord(" "), 0).astype(jnp.uint8)
    return TypedValue(StringColumn(
        jnp.broadcast_to(chars, (n, out_w)), nsp, args[0].validity),
        DataType.STRING)


@register("ascii", DataType.INT32)
def _ascii(args, expr, batch, schema, ctx):
    v = args[0]
    first = jnp.where(v.col.lens > 0, v.col.chars[:, 0].astype(jnp.int32), 0)
    return TypedValue(PrimitiveColumn(first, v.validity), DataType.INT32)


@register("chr", _string_result)
@register("char", _string_result)
def _chr(args, expr, batch, schema, ctx):
    code = jnp.mod(cast_value(args[0], DataType.INT64).data, 256)
    n = args[0].col.capacity
    chars = jnp.zeros((n, 8), jnp.uint8).at[:, 0].set(
        code.astype(jnp.uint8))
    lens = jnp.where(code > 0, 1, 0).astype(jnp.int32)
    return TypedValue(StringColumn(chars, lens, args[0].validity),
                      DataType.STRING)


# ---------------------------------------------------------------------------
# search: instr / locate / substring_index / translate
# ---------------------------------------------------------------------------

def _first_occurrence(chars, lens, needle: bytes, from_pos):
    """1-based position of the first occurrence of ``needle`` at or after
    0-based ``from_pos``; 0 when absent. Vectorized window scan."""
    n, w = chars.shape
    m = len(needle)
    if m == 0:
        return jnp.minimum(from_pos + 1, lens + 1)
    if m > w:
        return jnp.zeros(n, jnp.int32)
    lit = jnp.asarray(np.frombuffer(needle, np.uint8))
    best = jnp.full(n, w + 1, jnp.int32)
    for s in range(w - m + 1):
        ok = jnp.all(chars[:, s:s + m] == lit[None, :], axis=1) \
            & (s + m <= lens) & (s >= from_pos)
        best = jnp.where(ok & (s < best), s, best)
    return jnp.where(best <= w, best + 1, 0).astype(jnp.int32)


@register("instr", DataType.INT32)
def _instr(args, expr, batch, schema, ctx):
    v = args[0]
    needle = _lit(expr, 1, "")
    needle_b = needle.encode() if isinstance(needle, str) else (needle or b"")
    p = _first_occurrence(v.col.chars, v.col.lens, needle_b,
                          jnp.zeros(v.col.capacity, jnp.int32))
    return TypedValue(PrimitiveColumn(p, v.validity & args[1].validity),
                      DataType.INT32)


@register("locate", DataType.INT32)
@register("position", DataType.INT32)
def _locate(args, expr, batch, schema, ctx):
    # locate(substr, str[, pos])
    needle = _lit(expr, 0, "")
    needle_b = needle.encode() if isinstance(needle, str) else (needle or b"")
    v = args[1]
    start = (cast_value(args[2], DataType.INT32).data - 1
             if len(args) > 2 else jnp.zeros(v.col.capacity, jnp.int32))
    p = _first_occurrence(v.col.chars, v.col.lens, needle_b,
                          jnp.maximum(start, 0))
    return TypedValue(PrimitiveColumn(p, v.validity), DataType.INT32)


@register("substring_index", _string_result)
def _substring_index(args, expr, batch, schema, ctx):
    """substring_index(str, delim, count): everything before the count-th
    delimiter (count > 0, from the left) or after it (count < 0, from the
    right) — Spark semantics incl. whole-string when too few delimiters."""
    v = args[0]
    delim = _lit(expr, 1, "")
    delim_b = delim.encode() if isinstance(delim, str) else (delim or b"")
    count = int(_lit(expr, 2, 0) or 0)
    chars, lens = v.col.chars, v.col.lens
    n, w = chars.shape
    m = len(delim_b)
    if m == 0 or count == 0:
        return TypedValue(StringColumn(jnp.zeros_like(chars),
                                       jnp.zeros(n, jnp.int32), v.validity),
                          DataType.STRING)
    lit = jnp.asarray(np.frombuffer(delim_b, np.uint8))
    # occurrence matrix (non-overlapping, left to right, like Java indexOf
    # stepping by the delimiter length)
    occ = jnp.zeros((n, w), bool)
    blocked_until = jnp.zeros(n, jnp.int32)
    for s in range(w - m + 1):
        hit = jnp.all(chars[:, s:s + m] == lit[None, :], axis=1) \
            & (s + m <= lens) & (s >= blocked_until)
        occ = occ.at[:, s].set(hit)
        blocked_until = jnp.where(hit, s + m, blocked_until)
    cum = jnp.cumsum(occ.astype(jnp.int32), axis=1)
    total = cum[:, -1] if w else jnp.zeros(n, jnp.int32)
    if count > 0:
        # cut before the count-th occurrence
        kth = jnp.where(occ & (cum == count), _pos(w), w)
        cut = jnp.min(kth, axis=1)
        new_len = jnp.where(total >= count, jnp.minimum(cut, lens), lens)
        mask = _pos(w) < new_len[:, None]
        return TypedValue(StringColumn(
            jnp.where(mask, chars, 0).astype(jnp.uint8),
            new_len.astype(jnp.int32), v.validity), DataType.STRING)
    k = -count
    # start after the (total-k+1)-th occurrence from the left
    target = total - k + 1
    kth = jnp.where(occ & (cum == target[:, None]), _pos(w), -1)
    start_at = jnp.max(kth, axis=1) + m
    start = jnp.where(total >= k, start_at, 0)
    new_len = lens - start
    idx = start[:, None] + _pos(w)
    out = jnp.take_along_axis(chars, jnp.clip(idx, 0, w - 1), axis=1)
    mask = _pos(w) < new_len[:, None]
    return TypedValue(StringColumn(
        jnp.where(mask, out, 0).astype(jnp.uint8),
        jnp.maximum(new_len, 0).astype(jnp.int32), v.validity),
        DataType.STRING)


@register("translate", _string_result)
def _translate(args, expr, batch, schema, ctx):
    """translate(str, from, to): per-char mapping via a 256-entry LUT;
    chars beyond len(to) are DELETED (per-row compaction by one argsort)."""
    v = args[0]
    from_s = str(_lit(expr, 1, ""))
    to_s = str(_lit(expr, 2, ""))
    lut = np.arange(256, dtype=np.int32)        # identity
    delete = np.zeros(256, bool)
    for i, ch in enumerate(from_s.encode()):
        if lut[ch] != ch or delete[ch]:
            continue  # first occurrence wins (Java semantics)
        if i < len(to_s.encode()):
            lut[ch] = to_s.encode()[i]
        else:
            delete[ch] = True
    chars, lens = v.col.chars, v.col.lens
    n, w = chars.shape
    mapped = jnp.asarray(lut)[chars.astype(jnp.int32)].astype(jnp.uint8)
    drop = jnp.asarray(delete)[chars.astype(jnp.int32)] \
        | (_pos(w) >= lens[:, None])
    # stable compact per row: sort by (dropped, position)
    key = jnp.where(drop, w + _pos(w), _pos(w))
    order = jnp.argsort(key, axis=1)
    out = jnp.take_along_axis(mapped, order, axis=1)
    new_len = jnp.sum(~drop, axis=1).astype(jnp.int32)
    mask = _pos(w) < new_len[:, None]
    return TypedValue(StringColumn(jnp.where(mask, out, 0).astype(jnp.uint8),
                                   new_len, v.validity), DataType.STRING)


# ---------------------------------------------------------------------------
# split (host) + fused element access
# ---------------------------------------------------------------------------

def split_index(child_args, ordinal: int, batch, schema, ctx):
    """GetIndexedField(split(str, regex), i) fused into one host kernel —
    the dominant use of split in query plans. Returns the i-th piece or
    null when out of range (reference: spark_strings.rs string_split +
    list extract)."""
    import re
    import jax
    from auron_tpu.exprs.eval import evaluate
    v = evaluate(child_args[0], batch, schema, ctx)
    pat = child_args[1]
    assert isinstance(pat, ir.Literal), "split pattern must be literal"
    rx = re.compile(str(pat.value))
    col: StringColumn = v.col
    cap, w = col.chars.shape
    out_w = col.chars.shape[1]

    def host(chars_np, lens_np, valid_np):
        chars = np.zeros((cap, out_w), np.uint8)
        lens = np.zeros(cap, np.int32)
        ok = np.zeros(cap, bool)
        for i in range(cap):
            if not valid_np[i]:
                continue
            s = bytes(chars_np[i, : lens_np[i]]).decode("utf-8", "replace")
            parts = rx.split(s)
            if 0 <= ordinal < len(parts):
                b = parts[ordinal].encode()[:out_w]
                chars[i, : len(b)] = np.frombuffer(b, np.uint8)
                lens[i] = len(b)
                ok[i] = True
        return chars, lens, ok

    chars, lens, ok = jax.pure_callback(
        host,
        (jax.ShapeDtypeStruct((cap, out_w), jnp.uint8),
         jax.ShapeDtypeStruct((cap,), jnp.int32),
         jax.ShapeDtypeStruct((cap,), jnp.bool_)),
        col.chars, col.lens, v.validity, vmap_method="sequential")
    return TypedValue(StringColumn(chars, lens, ok), DataType.STRING)
