"""Array and map constructors/accessors.

The reference's spark_map.rs (1,516 LoC) builds Arrow List/Map arrays row
by row. Here arrays are the engine's padded ListColumn ([cap, max_elems]
matrix + lens), so constructors are one stack and accessors are one
gather. Maps have no columnar materialization yet (the batch layer has no
MapColumn); a map built inside a projection lives as an eval-internal
``MapValue`` (parallel key/value ListColumns) that the map accessors
consume in the same expression tree — the common `map(...)[k]` /
element_at pattern. Materializing a map into an output batch raises.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax.numpy as jnp

from auron_tpu.columnar.batch import ListColumn, PrimitiveColumn, StringColumn
from auron_tpu.columnar.schema import DataType
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import TypedValue, cast_value, infer_dtype
from auron_tpu.exprs.functions import register
from auron_tpu.ops import strings as S


def _list_result(expr, schema):
    return DataType.LIST, 0, 0


def elem_dtype_of(a: ir.Expr, schema) -> DataType:
    """Static element dtype of an array/map-valued expression."""
    if isinstance(a, ir.ScalarFunction):
        if a.name in ("array", "array_repeat") and a.args:
            return infer_dtype(a.args[0], schema)[0]
        if a.name == "sort_array":
            return elem_dtype_of(a.args[0], schema)
        if a.name == "map_keys":
            m = a.args[0]
            if isinstance(m, ir.ScalarFunction) and m.name == "map" and m.args:
                return infer_dtype(m.args[0], schema)[0]
            if isinstance(m, ir.ScalarFunction) and m.name == "map_from_arrays":
                return elem_dtype_of(m.args[0], schema)
        if a.name == "map_values":
            m = a.args[0]
            if isinstance(m, ir.ScalarFunction) and m.name == "map" and m.args:
                return infer_dtype(m.args[1], schema)[0]
            if isinstance(m, ir.ScalarFunction) and m.name == "map_from_arrays":
                return elem_dtype_of(m.args[1], schema)
    if isinstance(a, ir.ColumnRef):
        return schema[a.index].elem
    return DataType.INT64


def _elem_dtype(expr, schema):
    """Element dtype of the function's first (array) argument."""
    return elem_dtype_of(expr.args[0], schema)


def _elem_result(expr, schema):
    return _elem_dtype(expr, schema), 0, 0


def _element_at_result(expr, schema):
    a = expr.args[0]
    if isinstance(a, ir.ScalarFunction) and a.name == "map" and len(a.args) > 1:
        return infer_dtype(a.args[1], schema)
    if isinstance(a, ir.ScalarFunction) and a.name == "map_from_arrays":
        return elem_dtype_of(a.args[1], schema), 0, 0
    return _elem_dtype(expr, schema), 0, 0


# ---------------------------------------------------------------------------
# arrays
# ---------------------------------------------------------------------------

@register("array", _list_result)
def _array(args, expr, batch, schema, ctx):
    """array(e1, ..., ek): ListColumn with max_elems = k."""
    if not args:
        n = batch.capacity
        return TypedValue(ListColumn(jnp.zeros((n, 1), jnp.int64),
                                     jnp.zeros((n, 1), bool),
                                     jnp.zeros(n, jnp.int32),
                                     jnp.ones(n, bool)), DataType.LIST)
    if any(isinstance(a.col, StringColumn) for a in args):
        raise NotImplementedError(
            "array() over STRING elements: string lists have no columnar "
            "materialization yet")
    target = args[0].dtype
    vals = [cast_value(a, target) if a.dtype != target else a for a in args]
    values = jnp.stack([v.data for v in vals], axis=1)
    elem_valid = jnp.stack([v.validity for v in vals], axis=1)
    n = batch.capacity
    k = len(args)
    return TypedValue(ListColumn(values, elem_valid,
                                 jnp.full(n, k, jnp.int32),
                                 jnp.ones(n, bool)), DataType.LIST)


@register("size", DataType.INT32)
@register("cardinality", DataType.INT32)
def _size(args, expr, batch, schema, ctx):
    v = args[0]
    if isinstance(v.col, MapValue):
        lens = v.col.keys.lens
        valid = v.col.validity
    else:
        assert isinstance(v.col, ListColumn), "size() needs an array/map"
        lens, valid = v.col.lens, v.col.validity
    # Spark legacy sizeOfNull: null input → -1
    out = jnp.where(valid, lens, -1).astype(jnp.int32)
    return TypedValue(PrimitiveColumn(out, jnp.ones_like(valid)),
                      DataType.INT32)


@register("array_contains", DataType.BOOL)
def _array_contains(args, expr, batch, schema, ctx):
    arr, needle = args
    if isinstance(needle.col, StringColumn):
        raise NotImplementedError("array_contains with STRING needle")
    col: ListColumn = arr.col
    hit = jnp.any((col.values == needle.data[:, None]) & col.elem_valid
                  & (jnp.arange(col.max_elems)[None, :] < col.lens[:, None]),
                  axis=1)
    return TypedValue(PrimitiveColumn(hit, arr.validity & needle.validity),
                      DataType.BOOL)


@register("array_position", DataType.INT64)
def _array_position(args, expr, batch, schema, ctx):
    arr, needle = args
    col: ListColumn = arr.col
    in_list = jnp.arange(col.max_elems)[None, :] < col.lens[:, None]
    eq = (col.values == needle.data[:, None]) & col.elem_valid & in_list
    first = jnp.argmax(eq, axis=1)
    any_hit = jnp.any(eq, axis=1)
    pos = jnp.where(any_hit, first + 1, 0).astype(jnp.int64)
    return TypedValue(PrimitiveColumn(pos, arr.validity & needle.validity),
                      DataType.INT64)


@register("element_at", _element_at_result)
def _element_at(args, expr, batch, schema, ctx):
    v = args[0]
    if isinstance(v.col, MapValue):
        return _map_get(v, args[1])
    col: ListColumn = v.col
    idx = cast_value(args[1], DataType.INT32).data
    # 1-based; negative counts from the end; out of range → null
    zero = jnp.where(idx > 0, idx - 1, col.lens + idx)
    in_range = (zero >= 0) & (zero < col.lens)
    zi = jnp.clip(zero, 0, col.max_elems - 1)
    data = jnp.take_along_axis(col.values, zi[:, None], axis=1)[:, 0]
    ev = jnp.take_along_axis(col.elem_valid, zi[:, None], axis=1)[:, 0]
    dt = _elem_dtype(expr, schema)
    return TypedValue(PrimitiveColumn(data, v.validity & in_range & ev), dt)


def _array_minmax(args, expr, schema, largest: bool):
    v = args[0]
    col: ListColumn = v.col
    in_list = (jnp.arange(col.max_elems)[None, :] < col.lens[:, None]) \
        & col.elem_valid
    if largest:
        neutral = jnp.asarray(np.iinfo(np.int64).min, col.values.dtype) \
            if jnp.issubdtype(col.values.dtype, jnp.integer) \
            else jnp.asarray(-np.inf, col.values.dtype)
        data = jnp.max(jnp.where(in_list, col.values, neutral), axis=1)
    else:
        neutral = jnp.asarray(np.iinfo(np.int64).max, col.values.dtype) \
            if jnp.issubdtype(col.values.dtype, jnp.integer) \
            else jnp.asarray(np.inf, col.values.dtype)
        data = jnp.min(jnp.where(in_list, col.values, neutral), axis=1)
    has = jnp.any(in_list, axis=1)
    dt = _elem_dtype(expr, schema)
    return TypedValue(PrimitiveColumn(data, v.validity & has), dt)


@register("array_max", _elem_result)
def _array_max(args, expr, batch, schema, ctx):
    return _array_minmax(args, expr, schema, largest=True)


@register("array_min", _elem_result)
def _array_min(args, expr, batch, schema, ctx):
    return _array_minmax(args, expr, schema, largest=False)


@register("sort_array", _list_result)
def _sort_array(args, expr, batch, schema, ctx):
    v = args[0]
    asc = True
    if len(expr.args) > 1 and isinstance(expr.args[1], ir.Literal):
        asc = bool(expr.args[1].value)
    col: ListColumn = v.col
    pos = jnp.arange(col.max_elems)[None, :]
    in_list = pos < col.lens[:, None]
    valid = in_list & col.elem_valid
    # two stable argsorts: value order first, then the class key
    # (asc: nulls < values < padding; desc: values < nulls < padding —
    # Spark sort_array null placement), so padding never leaks into the
    # live prefix regardless of direction
    valkey = col.values if asc else -col.values
    order = jnp.argsort(valkey, axis=1, stable=True)
    cls = jnp.where(in_list & ~col.elem_valid, 0 if asc else 1,
                    jnp.where(valid, 1 if asc else 0, 2))
    cls_sorted = jnp.take_along_axis(cls, order, axis=1)
    order = jnp.take_along_axis(order,
                                jnp.argsort(cls_sorted, axis=1, stable=True),
                                axis=1)
    values = jnp.take_along_axis(col.values, order, axis=1)
    ev = jnp.take_along_axis(col.elem_valid, order, axis=1)
    return TypedValue(ListColumn(values, ev, col.lens, col.validity),
                      DataType.LIST)


@register("array_repeat", _list_result)
def _array_repeat(args, expr, batch, schema, ctx):
    v = args[0]
    times = int(expr.args[1].value) if isinstance(expr.args[1], ir.Literal) \
        else 1
    times = max(times, 0)
    n = batch.capacity
    k = max(times, 1)
    values = jnp.broadcast_to(v.data[:, None], (n, k))
    ev = jnp.broadcast_to(v.validity[:, None], (n, k))
    return TypedValue(ListColumn(values, ev, jnp.full(n, times, jnp.int32),
                                 jnp.ones(n, bool)), DataType.LIST)


# ---------------------------------------------------------------------------
# maps (eval-internal composite)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class MapValue:
    """Parallel key/value lists; exists only inside expression evaluation
    (consumed by element_at / map_keys / map_values / size before any
    batch materialization)."""
    keys: ListColumn
    values: ListColumn
    validity: object

    @property
    def capacity(self):
        return self.keys.capacity


def _map_result(expr, schema):
    return DataType.LIST, 0, 0   # only observable through accessors


@register("map", _map_result)
@register("map_from_arrays", _map_result)
def _map(args, expr, batch, schema, ctx):
    if expr.name == "map_from_arrays":
        karr, varr = args
        return TypedValue(MapValue(karr.col, varr.col,
                                   karr.validity & varr.validity),
                          DataType.LIST)
    assert len(args) % 2 == 0, "map() needs key/value pairs"
    if any(isinstance(a.col, StringColumn) for a in args):
        raise NotImplementedError(
            "map() over STRING keys/values: string lists have no columnar "
            "materialization yet")
    keys = args[0::2]
    vals = args[1::2]
    n = batch.capacity
    k = len(keys)

    def mklist(items):
        values = jnp.stack([x.data for x in items], axis=1)
        ev = jnp.stack([x.validity for x in items], axis=1)
        return ListColumn(values, ev, jnp.full(n, k, jnp.int32),
                          jnp.ones(n, bool))

    return TypedValue(MapValue(mklist(keys), mklist(vals),
                               jnp.ones(n, bool)), DataType.LIST)


@register("map_keys", _list_result)
def _map_keys(args, expr, batch, schema, ctx):
    m: MapValue = args[0].col
    return TypedValue(m.keys.with_validity(args[0].validity), DataType.LIST)


@register("map_values", _list_result)
def _map_values(args, expr, batch, schema, ctx):
    m: MapValue = args[0].col
    return TypedValue(m.values.with_validity(args[0].validity), DataType.LIST)


def _map_get(v: TypedValue, key: TypedValue) -> TypedValue:
    """map[key]: last matching key wins (Spark map semantics)."""
    if isinstance(key.col, StringColumn):
        raise NotImplementedError("map lookup with STRING key")
    m: MapValue = v.col
    kcol, vcol = m.keys, m.values
    in_map = jnp.arange(kcol.max_elems)[None, :] < kcol.lens[:, None]
    eq = (kcol.values == key.data[:, None]) & kcol.elem_valid & in_map
    # last match: flip, argmax, flip back
    rev = eq[:, ::-1]
    last = kcol.max_elems - 1 - jnp.argmax(rev, axis=1)
    hit = jnp.any(eq, axis=1)
    li = jnp.clip(last, 0, vcol.max_elems - 1)
    data = jnp.take_along_axis(vcol.values, li[:, None], axis=1)[:, 0]
    ev = jnp.take_along_axis(vcol.elem_valid, li[:, None], axis=1)[:, 0]
    return TypedValue(PrimitiveColumn(data, v.validity & hit & ev),
                      DataType.INT64 if jnp.issubdtype(
                          vcol.values.dtype, jnp.integer) else DataType.FLOAT64)
