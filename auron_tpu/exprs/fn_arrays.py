"""Array and map constructors/accessors.

The reference's spark_map.rs (1,516 LoC) builds Arrow List/Map arrays row
by row. Here arrays are the engine's padded ListColumn ([cap, max_elems]
matrix + lens), so constructors are one stack and accessors are one
gather; maps are the batch layer's MapColumn (parallel key/value matrices
sharing a length column), fully batch-materializable — they flow through
scans, projections, shuffles, spill serde and the Arrow bridge like any
other column. See the maps section below for the Spark-semantics notes
(null keys, LAST_WINS dedup).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from auron_tpu.columnar.batch import ListColumn, PrimitiveColumn, StringColumn
from auron_tpu.columnar.schema import DataType
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import TypedValue, cast_value, infer_dtype
from auron_tpu.exprs.functions import register
from auron_tpu.ops import strings as S


def _list_result(expr, schema):
    return DataType.LIST, 0, 0


def elem_dtype_of(a: ir.Expr, schema) -> DataType:
    """Static element dtype of an array/map-valued expression."""
    if isinstance(a, ir.ScalarFunction):
        if a.name in ("array", "array_repeat") and a.args:
            return infer_dtype(a.args[0], schema)[0]
        if a.name in ("sort_array", "array_distinct", "array_union",
                      "array_intersect", "array_except"):
            return elem_dtype_of(a.args[0], schema)
        if a.name == "split":
            return DataType.STRING
        if a.name == "map_keys":
            m = a.args[0]
            if isinstance(m, ir.ScalarFunction) and m.name == "map" and m.args:
                return infer_dtype(m.args[0], schema)[0]
            if isinstance(m, ir.ScalarFunction) and m.name == "map_from_arrays":
                return elem_dtype_of(m.args[0], schema)
        if a.name == "map_values":
            m = a.args[0]
            if isinstance(m, ir.ScalarFunction) and m.name == "map" and m.args:
                return infer_dtype(m.args[1], schema)[0]
            if isinstance(m, ir.ScalarFunction) and m.name == "map_from_arrays":
                return elem_dtype_of(m.args[1], schema)
    if isinstance(a, ir.ColumnRef):
        return schema[a.index].elem
    return DataType.INT64


def _elem_dtype(expr, schema):
    """Element dtype of the function's first (array) argument."""
    return elem_dtype_of(expr.args[0], schema)


def _elem_result(expr, schema):
    return _elem_dtype(expr, schema), 0, 0


def _element_at_result(expr, schema):
    a = expr.args[0]
    dt, _p, _s = infer_dtype(a, schema)
    if dt == DataType.MAP:
        # _map_field resolves the value dtype for ANY map-valued
        # expression (column ref, constructor, map_concat, ...)
        mf = _map_field(a, schema)
        if mf.elem is not None:
            return mf.elem, 0, 0
    return _elem_dtype(expr, schema), 0, 0


# ---------------------------------------------------------------------------
# arrays
# ---------------------------------------------------------------------------

@register("array", _list_result)
def _array(args, expr, batch, schema, ctx):
    """array(e1, ..., ek): ListColumn with max_elems = k."""
    if not args:
        n = batch.capacity
        return TypedValue(ListColumn(jnp.zeros((n, 1), jnp.int64),
                                     jnp.zeros((n, 1), bool),
                                     jnp.zeros(n, jnp.int32),
                                     jnp.ones(n, bool)), DataType.LIST)
    if any(isinstance(a.col, StringColumn) for a in args):
        from auron_tpu.columnar.batch import StringListColumn
        if not all(isinstance(a.col, StringColumn) for a in args):
            raise NotImplementedError("array() mixing STRING and non-"
                                      "STRING elements")
        scols = [a.col for a in args]
        w = max(c.width for c in scols)
        n = batch.capacity

        def widen(c):
            if c.width == w:
                return c.chars
            return jnp.pad(c.chars, ((0, 0), (0, w - c.width)))

        chars = jnp.stack([widen(c) for c in scols], axis=1)
        slens = jnp.stack([c.lens for c in scols], axis=1)
        ev = jnp.stack([a.validity for a in args], axis=1)
        return TypedValue(StringListColumn(
            chars, slens, ev, jnp.full(n, len(args), jnp.int32),
            jnp.ones(n, bool)), DataType.LIST)
    target = args[0].dtype
    vals = [cast_value(a, target) if a.dtype != target else a for a in args]
    values = jnp.stack([v.data for v in vals], axis=1)
    elem_valid = jnp.stack([v.validity for v in vals], axis=1)
    n = batch.capacity
    k = len(args)
    return TypedValue(ListColumn(values, elem_valid,
                                 jnp.full(n, k, jnp.int32),
                                 jnp.ones(n, bool)), DataType.LIST)


@register("size", DataType.INT32)
@register("cardinality", DataType.INT32)
def _size(args, expr, batch, schema, ctx):
    from auron_tpu.columnar.batch import (MapColumn, StringListColumn,
                                          StringMapColumn)
    v = args[0]
    if isinstance(v.col, (MapColumn, StringMapColumn)):
        lens, valid = v.col.lens, v.validity
    else:
        assert isinstance(v.col, (ListColumn, StringListColumn)), \
            "size() needs an array/map"
        lens, valid = v.col.lens, v.col.validity
    # Spark legacy sizeOfNull: null input → -1
    out = jnp.where(valid, lens, -1).astype(jnp.int32)
    return TypedValue(PrimitiveColumn(out, jnp.ones_like(valid)),
                      DataType.INT32)


@register("array_contains", DataType.BOOL)
def _array_contains(args, expr, batch, schema, ctx):
    from auron_tpu.columnar.batch import StringListColumn
    arr, needle = args
    if isinstance(arr.col, StringListColumn):
        if not isinstance(needle.col, StringColumn):
            raise NotImplementedError(
                "array_contains over array<string> needs a STRING needle")
        col = arr.col
        nc = needle.col
        w = max(col.width, nc.width)
        ch = jnp.pad(col.chars,
                     ((0, 0), (0, 0), (0, w - col.width)))
        nh = jnp.pad(nc.chars, ((0, 0), (0, w - nc.width)))
        same = jnp.all(ch == nh[:, None, :], axis=2) \
            & (col.slens == nc.lens[:, None])
        in_list = jnp.arange(col.max_elems)[None, :] < col.lens[:, None]
        hit = jnp.any(same & col.elem_valid & in_list, axis=1)
        has_null_elem = jnp.any(~col.elem_valid & in_list, axis=1)
        return TypedValue(
            PrimitiveColumn(hit, arr.validity & needle.validity
                            & (hit | ~has_null_elem)), DataType.BOOL)
    if isinstance(needle.col, StringColumn):
        raise NotImplementedError("array_contains with STRING needle")
    col: ListColumn = arr.col
    in_list = jnp.arange(col.max_elems)[None, :] < col.lens[:, None]
    # Spark compares with SQLOrderingUtil semantics: NaN matches NaN
    from auron_tpu.ops.hashing import nan_aware_eq
    hit = jnp.any(nan_aware_eq(col.values, needle.data[:, None])
                  & col.elem_valid & in_list, axis=1)
    # Spark three-valued semantics: no match but a null element present →
    # NULL (the null "might have been" the needle), not false
    has_null_elem = jnp.any(~col.elem_valid & in_list, axis=1)
    return TypedValue(
        PrimitiveColumn(hit, arr.validity & needle.validity
                        & (hit | ~has_null_elem)), DataType.BOOL)


@register("array_position", DataType.INT64)
def _array_position(args, expr, batch, schema, ctx):
    arr, needle = args
    col: ListColumn = arr.col
    in_list = jnp.arange(col.max_elems)[None, :] < col.lens[:, None]
    from auron_tpu.ops.hashing import nan_aware_eq
    eq = nan_aware_eq(col.values, needle.data[:, None]) \
        & col.elem_valid & in_list
    first = jnp.argmax(eq, axis=1)
    any_hit = jnp.any(eq, axis=1)
    pos = jnp.where(any_hit, first + 1, 0).astype(jnp.int64)
    return TypedValue(PrimitiveColumn(pos, arr.validity & needle.validity),
                      DataType.INT64)


@register("element_at", _element_at_result)
@register("get_map_value", _element_at_result)
def _element_at(args, expr, batch, schema, ctx):
    from auron_tpu.columnar.batch import (MapColumn, StringListColumn,
                                          StringMapColumn)
    v = args[0]
    if isinstance(v.col, (MapColumn, StringMapColumn)):
        return _map_get(v, args[1], expr, schema)
    if isinstance(v.col, StringListColumn):
        col = v.col
        idx = cast_value(args[1], DataType.INT32).data
        zero = jnp.where(idx > 0, idx - 1, col.lens + idx)
        in_range = (zero >= 0) & (zero < col.lens)
        zi = jnp.clip(zero, 0, col.max_elems - 1)
        chars = jnp.take_along_axis(
            col.chars, zi[:, None, None], axis=1)[:, 0]
        slens = jnp.take_along_axis(col.slens, zi[:, None], axis=1)[:, 0]
        ev = jnp.take_along_axis(col.elem_valid, zi[:, None],
                                 axis=1)[:, 0]
        valid = v.validity & in_range & ev
        return TypedValue(StringColumn(chars, jnp.where(valid, slens, 0),
                                       valid), DataType.STRING)
    col: ListColumn = v.col
    idx = cast_value(args[1], DataType.INT32).data
    # 1-based; negative counts from the end; out of range → null
    zero = jnp.where(idx > 0, idx - 1, col.lens + idx)
    in_range = (zero >= 0) & (zero < col.lens)
    zi = jnp.clip(zero, 0, col.max_elems - 1)
    data = jnp.take_along_axis(col.values, zi[:, None], axis=1)[:, 0]
    ev = jnp.take_along_axis(col.elem_valid, zi[:, None], axis=1)[:, 0]
    dt = _elem_dtype(expr, schema)
    return TypedValue(PrimitiveColumn(data, v.validity & in_range & ev), dt)


def _array_minmax(args, expr, schema, largest: bool):
    v = args[0]
    col: ListColumn = v.col
    in_list = (jnp.arange(col.max_elems)[None, :] < col.lens[:, None]) \
        & col.elem_valid
    if largest:
        neutral = jnp.asarray(np.iinfo(np.int64).min, col.values.dtype) \
            if jnp.issubdtype(col.values.dtype, jnp.integer) \
            else jnp.asarray(-np.inf, col.values.dtype)
        data = jnp.max(jnp.where(in_list, col.values, neutral), axis=1)
    else:
        neutral = jnp.asarray(np.iinfo(np.int64).max, col.values.dtype) \
            if jnp.issubdtype(col.values.dtype, jnp.integer) \
            else jnp.asarray(np.inf, col.values.dtype)
        data = jnp.min(jnp.where(in_list, col.values, neutral), axis=1)
    has = jnp.any(in_list, axis=1)
    dt = _elem_dtype(expr, schema)
    return TypedValue(PrimitiveColumn(data, v.validity & has), dt)


@register("array_max", _elem_result)
def _array_max(args, expr, batch, schema, ctx):
    return _array_minmax(args, expr, schema, largest=True)


@register("array_min", _elem_result)
def _array_min(args, expr, batch, schema, ctx):
    return _array_minmax(args, expr, schema, largest=False)


@register("sort_array", _list_result)
def _sort_array(args, expr, batch, schema, ctx):
    v = args[0]
    asc = True
    if len(expr.args) > 1 and isinstance(expr.args[1], ir.Literal):
        asc = bool(expr.args[1].value)
    from auron_tpu.columnar.batch import StringListColumn
    if isinstance(v.col, StringListColumn):
        return _sort_string_array(v, asc)
    col: ListColumn = v.col
    pos = jnp.arange(col.max_elems)[None, :]
    in_list = pos < col.lens[:, None]
    valid = in_list & col.elem_valid
    # two stable argsorts: value order first, then the class key
    # (asc: nulls < values < padding; desc: values < nulls < padding —
    # Spark sort_array null placement), so padding never leaks into the
    # live prefix regardless of direction
    valkey = col.values if asc else -col.values
    order = jnp.argsort(valkey, axis=1, stable=True)
    cls = jnp.where(in_list & ~col.elem_valid, 0 if asc else 1,
                    jnp.where(valid, 1 if asc else 0, 2))
    cls_sorted = jnp.take_along_axis(cls, order, axis=1)
    order = jnp.take_along_axis(order,
                                jnp.argsort(cls_sorted, axis=1, stable=True),
                                axis=1)
    values = jnp.take_along_axis(col.values, order, axis=1)
    ev = jnp.take_along_axis(col.elem_valid, order, axis=1)
    return TypedValue(ListColumn(values, ev, col.lens, col.validity),
                      DataType.LIST)


def _sort_string_array(v: TypedValue, asc: bool) -> TypedValue:
    """Row-wise lexicographic sort of string-list elements: pack each
    element's bytes into big-endian uint64 words, then a stable argsort
    chain along the element axis (least-significant word first), like
    ops/sort.py order_words but per row."""
    from auron_tpu.columnar.batch import StringListColumn
    from auron_tpu.ops.sort import string_be_words
    col: StringListColumn = v.col
    cap, e, w = col.chars.shape
    words = string_be_words(
        col.chars.reshape(cap * e, w)).reshape(cap, e, -1)  # [cap,e,k]
    if not asc:
        words = ~words
    in_list = jnp.arange(e)[None, :] < col.lens[:, None]
    # class: asc nulls < values < padding; desc values < nulls < padding
    cls = jnp.where(in_list & ~col.elem_valid, 0 if asc else 1,
                    jnp.where(in_list & col.elem_valid, 1 if asc else 0,
                              2)).astype(jnp.uint64)
    order = jnp.arange(e, dtype=jnp.int32)[None, :].repeat(cap, axis=0)
    for k in range(words.shape[2] - 1, -1, -1):
        kk = jnp.take_along_axis(words[:, :, k], order, axis=1)
        order = jnp.take_along_axis(order, jnp.argsort(kk, axis=1,
                                                       stable=True), axis=1)
    ck = jnp.take_along_axis(cls, order, axis=1)
    order = jnp.take_along_axis(order, jnp.argsort(ck, axis=1,
                                                   stable=True), axis=1)
    return TypedValue(StringListColumn(
        jnp.take_along_axis(col.chars, order[:, :, None], axis=1),
        jnp.take_along_axis(col.slens, order, axis=1),
        jnp.take_along_axis(col.elem_valid, order, axis=1),
        col.lens, col.validity), DataType.LIST)


@register("array_repeat", _list_result)
def _array_repeat(args, expr, batch, schema, ctx):
    v = args[0]
    times = int(expr.args[1].value) if isinstance(expr.args[1], ir.Literal) \
        else 1
    times = max(times, 0)
    n = batch.capacity
    k = max(times, 1)
    values = jnp.broadcast_to(v.data[:, None], (n, k))
    ev = jnp.broadcast_to(v.validity[:, None], (n, k))
    return TypedValue(ListColumn(values, ev, jnp.full(n, times, jnp.int32),
                                 jnp.ones(n, bool)), DataType.LIST)


# ---------------------------------------------------------------------------
# maps — columnar MapColumn (batch-materializable)
# ---------------------------------------------------------------------------
#
# reference: datafusion-ext-functions/src/spark_map.rs (map constructors /
# accessors over Arrow MapArray) + get_map_value.rs. Here a map is the
# engine's MapColumn: parallel [cap, max_elems] key/value matrices sharing
# one length column (columnar/batch.py). Spark semantics notes:
#   - map keys cannot be null: a row constructing one nulls instead of
#     raising (jit kernels cannot throw data-dependent errors);
#   - duplicate keys resolve LAST_WINS (Spark's legacy/LAST_WIN dedup
#     policy; the default EXCEPTION policy cannot raise from a kernel).

from auron_tpu.columnar.batch import MapColumn
from auron_tpu.columnar.schema import Field


def _in_len(col):
    return jnp.arange(col.max_elems)[None, :] < col.lens[:, None]


def _map_field(expr, schema):
    """Result Field of a map-valued expression (key/value dtypes)."""
    from auron_tpu.exprs.eval import infer_field
    if isinstance(expr, ir.ColumnRef):
        return schema[expr.index]
    assert isinstance(expr, ir.ScalarFunction), expr
    if expr.name in ("map", "create_map"):
        from functools import reduce
        from auron_tpu.exprs.eval import common_type
        k = reduce(common_type, [infer_dtype(e, schema)[0]
                                 for e in expr.args[0::2]])
        v = reduce(common_type, [infer_dtype(e, schema)[0]
                                 for e in expr.args[1::2]])
        return Field("m", DataType.MAP, True, key=k, elem=v)
    if expr.name == "map_from_arrays":
        return Field("m", DataType.MAP, True,
                     key=elem_dtype_of(expr.args[0], schema),
                     elem=elem_dtype_of(expr.args[1], schema))
    if expr.name == "map_concat":
        return _map_field(expr.args[0], schema)
    if expr.name == "map_from_entries":
        return _map_from_entries_field(expr, schema)
    return infer_field(expr, schema)


def _map_result_field(expr, schema):
    return _map_field(expr, schema)


def _map_result(expr, schema):
    return DataType.MAP, 0, 0


def _key_dedup_policy() -> str:
    from auron_tpu import config as cfg
    policy = cfg.get_config().get(cfg.MAP_KEY_DEDUP_POLICY)
    if policy not in ("LAST_WIN", "EXCEPTION"):
        raise ValueError(
            f"auron.map.key_dedup_policy: unknown policy {policy!r} "
            "(LAST_WIN|EXCEPTION)")
    return policy


def _dedupe_last_wins(keys, values, vev, lens, row_valid=None):
    """Resolve duplicate map keys per ``auron.map.key_dedup_policy``:

    - LAST_WIN (this engine's default): drop entry i when a later
      in-range entry has the same key and compact survivors left —
      Spark's legacy policy;
    - EXCEPTION (Spark's default): raise a deterministic ValueError when
      any valid row constructs a map with duplicate keys. Inside a
      jit-fused stage the check value is a tracer — a kernel cannot
      raise data-dependent errors — so offending ROWS null out instead
      (returned via the row-validity mask), the same degradation the
      null-map-key rule uses.

    Maps are small, so the per-row M^2 compare stays tiny. Returns
    (keys, values, value_valid, lens, row_valid)."""
    M = keys.shape[1]
    jj = jnp.arange(M)
    in_rng = jj[None, :] < lens[:, None]
    same = keys[:, :, None] == keys[:, None, :]
    later = jj[None, None, :] > jj[None, :, None]
    dup = jnp.any(same & later & in_rng[:, None, :], axis=2)
    if row_valid is None:
        row_valid = jnp.ones(keys.shape[0], bool)
    if _key_dedup_policy() == "EXCEPTION":
        dup_row = jnp.any(dup & in_rng, axis=1) & row_valid
        has_dup = jnp.any(dup_row)
        if not isinstance(has_dup, jax.core.Tracer):
            if bool(has_dup):
                raise ValueError(
                    "duplicate map key (auron.map.key_dedup_policy="
                    "EXCEPTION; set LAST_WIN to keep the last entry)")
        row_valid = row_valid & ~dup_row
    keep = in_rng & ~dup
    order = jnp.argsort(jnp.where(keep, 0, 1), axis=1, stable=True)
    keys = jnp.take_along_axis(keys, order, axis=1)
    values = jnp.take_along_axis(values, order, axis=1)
    vev = jnp.take_along_axis(vev & keep, order, axis=1)
    lens = jnp.sum(keep, axis=1).astype(jnp.int32)
    return keys, values, vev, jnp.where(row_valid, lens, 0), row_valid


def _reject_unsupported_map_args(name, args, expr, schema):
    if any(isinstance(a.col, StringColumn) for a in args):
        raise NotImplementedError(
            f"{name}() over STRING keys/values: no string map "
            "materialization")
    for a in expr.args:
        dt, _p, _s = infer_dtype(a, schema)
        if dt == DataType.DECIMAL:
            # Field.key/elem are bare DataTypes: a decimal's (p, s) would
            # be lost and the scaled int64 payload would leak out raw
            raise NotImplementedError(
                f"{name}() over DECIMAL keys/values: map element types "
                "carry no precision/scale; cast to double first")



@register("map", _map_result, result_field=_map_result_field)
@register("create_map", _map_result, result_field=_map_result_field)
@register("map_from_arrays", _map_result, result_field=_map_result_field)
def _map(args, expr, batch, schema, ctx):
    if expr.name == "map_from_arrays":
        karr, varr = args
        for a in expr.args:
            if elem_dtype_of(a, schema) == DataType.DECIMAL:
                raise NotImplementedError(
                    "map_from_arrays over DECIMAL elements: map element "
                    "types carry no precision/scale; cast to double first")
        kcol, vcol = karr.col, varr.col
        m = max(kcol.max_elems, vcol.max_elems)
        from auron_tpu.columnar.batch import pad_list_elems
        kcol = pad_list_elems(kcol, m)
        vcol = pad_list_elems(vcol, m)
        # Spark: null map keys are illegal and lengths must match; a jit
        # kernel cannot raise, so offending rows null out
        k_in = jnp.arange(m)[None, :] < kcol.lens[:, None]
        ok = (karr.validity & varr.validity
              & (kcol.lens == vcol.lens)
              & ~jnp.any(k_in & ~kcol.elem_valid, axis=1))
        kv, vv, vev, lens, ok = _dedupe_last_wins(
            kcol.values, vcol.values, vcol.elem_valid,
            jnp.where(ok, kcol.lens, 0), row_valid=ok)
        return TypedValue(MapColumn(kv, vv, vev, lens, ok), DataType.MAP)
    assert len(args) % 2 == 0 and args, "map() needs key/value pairs"
    _reject_unsupported_map_args("map", args, expr, schema)
    from functools import reduce
    from auron_tpu.exprs.eval import common_type
    keys = args[0::2]
    vals = args[1::2]
    # coerce to the declared common key/value types (like array())
    kt = reduce(common_type, [a.dtype for a in keys])
    vt = reduce(common_type, [a.dtype for a in vals])
    keys = [cast_value(a, kt) if a.dtype != kt else a for a in keys]
    vals = [cast_value(a, vt) if a.dtype != vt else a for a in vals]
    k = len(keys)
    kv = jnp.stack([x.data for x in keys], axis=1)
    vv = jnp.stack([x.data for x in vals], axis=1)
    vev = jnp.stack([x.validity for x in vals], axis=1)
    ok = ~jnp.any(jnp.stack([~x.validity for x in keys], axis=1), axis=1)
    kv, vv, vev, lens, ok = _dedupe_last_wins(
        kv, vv, vev, jnp.where(ok, k, 0).astype(jnp.int32),
        row_valid=ok)
    return TypedValue(MapColumn(kv, vv, vev, lens, ok), DataType.MAP)


def _map_keys_field(expr, schema):
    mf = _map_field(expr.args[0], schema)
    return Field("c", DataType.LIST, True, elem=mf.key)


def _map_values_field(expr, schema):
    mf = _map_field(expr.args[0], schema)
    return Field("c", DataType.LIST, True, elem=mf.elem)


@register("map_keys", _list_result, result_field=_map_keys_field)
def _map_keys(args, expr, batch, schema, ctx):
    from auron_tpu.columnar.batch import StringListColumn, StringMapColumn
    m = args[0].col
    if isinstance(m, StringMapColumn):
        return TypedValue(StringListColumn(
            m.kchars, m.kslens, _in_len(m), m.lens, args[0].validity),
            DataType.LIST)
    return TypedValue(ListColumn(m.keys, _in_len(m), m.lens,
                                 args[0].validity), DataType.LIST)


@register("map_values", _list_result, result_field=_map_values_field)
def _map_values(args, expr, batch, schema, ctx):
    from auron_tpu.columnar.batch import StringListColumn, StringMapColumn
    m = args[0].col
    if isinstance(m, StringMapColumn):
        return TypedValue(StringListColumn(
            m.vchars, m.vslens, m.val_valid & _in_len(m), m.lens,
            args[0].validity), DataType.LIST)
    return TypedValue(ListColumn(m.values, m.val_valid & _in_len(m),
                                 m.lens, args[0].validity), DataType.LIST)


def _map_entries_field(expr, schema):
    mf = _map_field(expr.args[0], schema)
    if DataType.STRING in (mf.key, mf.elem):
        # fail at plan time: the entry-list carrier (MapColumn) has no
        # char-tensor slot, so a string entry schema could never egress
        raise NotImplementedError(
            "map_entries over map<string,...>: no string entry-struct "
            "materialization")
    return Field("c", DataType.LIST, True, elem=DataType.STRUCT,
                 children=(Field("key", mf.key, False),
                           Field("value", mf.elem, True)))


@register("map_entries", _list_result, result_field=_map_entries_field)
def _map_entries(args, expr, batch, schema, ctx):
    """map → array<struct<key,value>> in entry order (reference:
    spark_map.rs map_entries). The MapColumn layout — parallel key/value
    matrices over shared lens — IS the list-of-entry-structs layout, so
    the kernel is an identity re-type of the carrier."""
    from auron_tpu.columnar.batch import StringMapColumn
    m = args[0].col
    if isinstance(m, StringMapColumn):
        raise NotImplementedError(
            "map_entries over map<string,string>: no string entry-struct "
            "materialization")
    return TypedValue(MapColumn(m.keys, m.values, m.val_valid, m.lens,
                                args[0].validity), DataType.LIST)


def _map_from_entries_field(expr, schema):
    from auron_tpu.exprs.eval import infer_field
    ef = infer_field(expr.args[0], schema)
    if ef.dtype != DataType.LIST or ef.elem != DataType.STRUCT \
            or len(ef.children) != 2:
        raise NotImplementedError(
            f"map_from_entries over {ef.dtype.value}: needs "
            "array<struct<key,value>>")
    kf, vf = ef.children
    if DataType.DECIMAL in (kf.dtype, vf.dtype):
        raise NotImplementedError(
            "map_from_entries over DECIMAL entry children: map element "
            "types carry no precision/scale; cast to double first")
    return Field("m", DataType.MAP, True, key=kf.dtype, elem=vf.dtype)


@register("map_from_entries", _map_result,
          result_field=_map_from_entries_field)
def _map_from_entries(args, expr, batch, schema, ctx):
    """array<struct<key,value>> → map with LAST_WINS key dedup, matching
    the map()/map_from_arrays family (reference: spark_map.rs:553
    MapFromEntries; null entries/keys are rejected at the ingest
    boundary — the entry-list carrier cannot hold them)."""
    _map_from_entries_field(expr, schema)   # re-raise the typed guards
    m = args[0].col
    if not isinstance(m, MapColumn):
        raise NotImplementedError(
            "map_from_entries needs an array<struct<key,value>> entry "
            "list")
    kv, vv, vev, lens, ok = _dedupe_last_wins(
        m.keys, m.values, m.val_valid,
        jnp.where(args[0].validity, m.lens, 0),
        row_valid=args[0].validity)
    return TypedValue(MapColumn(kv, vv, vev, lens, ok), DataType.MAP)


@register("map_contains_key", DataType.BOOL)
def _map_contains_key(args, expr, batch, schema, ctx):
    from auron_tpu.columnar.batch import StringMapColumn
    v, key = args
    m = v.col
    if isinstance(m, StringMapColumn):
        kc = key.col
        if not isinstance(kc, StringColumn):
            raise NotImplementedError(
                "map_contains_key over map<string,..> needs a STRING key")
        kw = max(m.kchars.shape[2], kc.width)
        mk = jnp.pad(m.kchars, ((0, 0), (0, 0),
                                (0, kw - m.kchars.shape[2])))
        nk = jnp.pad(kc.chars, ((0, 0), (0, kw - kc.width)))
        same = jnp.all(mk == nk[:, None, :], axis=2) \
            & (m.kslens == kc.lens[:, None])
        hit = jnp.any(same & _in_len(m), axis=1)
        return TypedValue(PrimitiveColumn(hit, v.validity & key.validity),
                          DataType.BOOL)
    hit = jnp.any((m.keys == key.data[:, None]) & _in_len(m), axis=1)
    return TypedValue(PrimitiveColumn(hit, v.validity & key.validity),
                      DataType.BOOL)


@register("map_concat", _map_result, result_field=_map_result_field)
def _map_concat(args, expr, batch, schema, ctx):
    """Entry-concatenate maps, duplicate keys LAST_WINS (later argument,
    later entry)."""
    out = args[0]
    for nxt in args[1:]:
        a: MapColumn = out.col
        b: MapColumn = nxt.col
        cap = a.capacity
        M = a.max_elems + b.max_elems
        rows = jnp.arange(cap)[:, None]

        def splice(xa, xb, fill=0):
            buf = jnp.full((cap, M), fill, xa.dtype)
            buf = buf.at[rows, jnp.arange(a.max_elems)[None, :]].set(
                jnp.where(_in_len(a), xa, fill))
            jb = jnp.arange(b.max_elems)[None, :]
            tgt = jnp.clip(a.lens[:, None] + jb, 0, M - 1)
            return buf.at[rows, tgt].set(
                jnp.where(_in_len(b), xb, buf[rows, tgt]))

        keys = splice(a.keys, b.keys)
        values = splice(a.values, b.values)
        vev = splice(a.val_valid, b.val_valid, fill=False)
        ok = out.validity & nxt.validity
        keys, values, vev, lens, ok = _dedupe_last_wins(
            keys, values, vev, jnp.where(ok, a.lens + b.lens, 0),
            row_valid=ok)
        out = TypedValue(MapColumn(keys, values, vev, lens, ok),
                         DataType.MAP)
    return out


def _map_get(v: TypedValue, key: TypedValue, expr, schema) -> TypedValue:
    """map[key]: last matching key wins (Spark map semantics)."""
    from auron_tpu.columnar.batch import StringMapColumn
    if isinstance(v.col, StringMapColumn):
        return _string_map_get(v, key)
    if isinstance(key.col, StringColumn):
        raise NotImplementedError("map lookup with STRING key")
    m: MapColumn = v.col
    eq = (m.keys == key.data[:, None]) & _in_len(m)
    rev = eq[:, ::-1]
    last = m.max_elems - 1 - jnp.argmax(rev, axis=1)
    hit = jnp.any(eq, axis=1)
    li = jnp.clip(last, 0, m.max_elems - 1)
    data = jnp.take_along_axis(m.values, li[:, None], axis=1)[:, 0]
    ev = jnp.take_along_axis(m.val_valid, li[:, None], axis=1)[:, 0]
    mf = _map_field(expr.args[0], schema) if expr is not None else None
    dt = mf.elem if mf is not None and mf.elem is not None else (
        DataType.INT64 if jnp.issubdtype(m.values.dtype, jnp.integer)
        else DataType.FLOAT64)
    return TypedValue(PrimitiveColumn(
        data, v.validity & key.validity & hit & ev), dt)


# ---------------------------------------------------------------------------
# array set operations (reference: datafusion-ext-functions/src/brickhouse/
# array_union.rs + Spark's ArrayDistinct/ArrayUnion/ArrayIntersect/
# ArrayExcept/ArraysOverlap)
# ---------------------------------------------------------------------------

def _elem_eq_cross(av, ae, bv, be):
    """[cap, Ea, Eb] structural element equality: both valid & NaN-aware
    equal, or both null."""
    from auron_tpu.ops.hashing import nan_aware_eq
    eq = nan_aware_eq(av[:, :, None], bv[:, None, :])
    both_valid = ae[:, :, None] & be[:, None, :]
    both_null = ~ae[:, :, None] & ~be[:, None, :]
    return (both_valid & eq) | both_null


def _first_occurrence(values, ev, in_list):
    """bool[cap, E]: element is in-list AND no equal element precedes it."""
    e = values.shape[1]
    eq = _elem_eq_cross(values, ev, values, ev)
    lower = jnp.tril(jnp.ones((e, e), bool), k=-1)   # j < i
    dup = jnp.any(eq & in_list[:, None, :] & lower[None, :, :], axis=2)
    return in_list & ~dup


def _member_of(av, ae, a_in, bv, be, b_in):
    """bool[cap, Ea]: a's element occurs among b's in-list elements."""
    eq = _elem_eq_cross(av, ae, bv, be)
    return jnp.any(eq & b_in[:, None, :], axis=2)


def _compact(values, ev, keep):
    """Left-compact kept elements preserving order."""
    cap, e = values.shape
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    tgt = jnp.where(keep, pos, e)          # e = out of range → dropped
    rows = jnp.broadcast_to(jnp.arange(cap)[:, None], (cap, e))
    out_v = jnp.zeros_like(values).at[rows, tgt].set(values, mode="drop")
    out_e = jnp.zeros_like(ev).at[rows, tgt].set(ev & keep, mode="drop")
    return out_v, out_e, keep.sum(axis=1).astype(jnp.int32)


def _in_list_mask(col: ListColumn):
    return jnp.arange(col.max_elems)[None, :] < col.lens[:, None]


@register("array_distinct", _list_result)
def _array_distinct(args, expr, batch, schema, ctx):
    col: ListColumn = args[0].col
    keep = _first_occurrence(col.values, col.elem_valid,
                             _in_list_mask(col))
    v, ev, lens = _compact(col.values, col.elem_valid, keep)
    return TypedValue(ListColumn(v, ev, lens, col.validity),
                      DataType.LIST)


def _concat_lists(a: ListColumn, b: ListColumn):
    values = jnp.concatenate([a.values, b.values], axis=1)
    ev = jnp.concatenate([a.elem_valid, b.elem_valid], axis=1)
    in_list = jnp.concatenate(
        [_in_list_mask(a),
         _in_list_mask(b)], axis=1)
    # order: all of a's elements first, then b's — matches Spark's
    # first-occurrence union order
    return values, ev, in_list


@register("array_union", _list_result)
def _array_union(args, expr, batch, schema, ctx):
    a: ListColumn = args[0].col
    b: ListColumn = args[1].col
    values, ev, in_list = _concat_lists(a, b)
    keep = _first_occurrence(values, ev, in_list)
    v, e2, lens = _compact(values, ev, keep)
    return TypedValue(ListColumn(v, e2, lens,
                                 a.validity & b.validity), DataType.LIST)


@register("array_intersect", _list_result)
def _array_intersect(args, expr, batch, schema, ctx):
    a: ListColumn = args[0].col
    b: ListColumn = args[1].col
    a_in = _in_list_mask(a)
    keep = _first_occurrence(a.values, a.elem_valid, a_in) \
        & _member_of(a.values, a.elem_valid, a_in,
                     b.values, b.elem_valid, _in_list_mask(b))
    v, ev, lens = _compact(a.values, a.elem_valid, keep)
    return TypedValue(ListColumn(v, ev, lens,
                                 a.validity & b.validity), DataType.LIST)


@register("array_except", _list_result)
def _array_except(args, expr, batch, schema, ctx):
    a: ListColumn = args[0].col
    b: ListColumn = args[1].col
    a_in = _in_list_mask(a)
    keep = _first_occurrence(a.values, a.elem_valid, a_in) \
        & ~_member_of(a.values, a.elem_valid, a_in,
                      b.values, b.elem_valid, _in_list_mask(b))
    v, ev, lens = _compact(a.values, a.elem_valid, keep)
    return TypedValue(ListColumn(v, ev, lens,
                                 a.validity & b.validity), DataType.LIST)


@register("arrays_overlap", DataType.BOOL)
def _arrays_overlap(args, expr, batch, schema, ctx):
    # Spark three-valued: any common NON-NULL element → true; otherwise
    # if both non-empty and either side holds a null element → NULL;
    # else false
    a: ListColumn = args[0].col
    b: ListColumn = args[1].col
    a_in, b_in = _in_list_mask(a), _in_list_mask(b)
    from auron_tpu.ops.hashing import nan_aware_eq
    eq = nan_aware_eq(a.values[:, :, None], b.values[:, None, :]) \
        & a.elem_valid[:, :, None] & b.elem_valid[:, None, :] \
        & a_in[:, :, None] & b_in[:, None, :]
    hit = jnp.any(eq, axis=(1, 2))
    has_null = jnp.any(~a.elem_valid & a_in, axis=1) \
        | jnp.any(~b.elem_valid & b_in, axis=1)
    both_nonempty = (a.lens > 0) & (b.lens > 0)
    unknown = ~hit & both_nonempty & has_null
    return TypedValue(
        PrimitiveColumn(hit, args[0].validity & args[1].validity
                        & ~unknown), DataType.BOOL)


# ---------------------------------------------------------------------------
# string lists: split / array_join + accessor arms (reference:
# spark_strings.rs string_split + Spark's ArrayJoin; the padded
# StringListColumn is columnar/batch.py's list-of-string layout)
# ---------------------------------------------------------------------------

def _split_limit(expr) -> int:
    if len(expr.args) > 2 and isinstance(expr.args[2], ir.Literal) \
            and expr.args[2].value is not None:
        return int(expr.args[2].value)
    return -1


@register("split", _list_result)
def _split(args, expr, batch, schema, ctx):
    """split(str, regex[, limit]) → array<string> (Spark semantics:
    java-regex split; limit<=0 keeps trailing empties EXCEPT the
    java default of dropping them when limit==0... Spark uses limit=-1
    as 'no limit', which KEEPS every part)."""
    import re as _re

    import jax

    from auron_tpu.columnar.batch import StringListColumn
    from auron_tpu.utils.shapes import bucket_string_width
    v = args[0]
    col = v.col
    if not isinstance(col, StringColumn):
        raise NotImplementedError("split() needs a STRING input")
    pat = expr.args[1]
    if not isinstance(pat, ir.Literal) or pat.value is None:
        raise NotImplementedError("split(): the regex must be a literal")
    pattern = _re.compile(str(pat.value))
    zero_width = pattern.match("") is not None
    limit = _split_limit(expr)
    cap, w = col.chars.shape
    # static bound: a W-byte string splits into at most W+1 parts; cap
    # the element budget so wide strings don't explode the tensor, and
    # fail loudly (not truncate) if a row exceeds it
    max_e = min(w + 1, 64) if limit <= 0 else min(limit, w + 1)
    out_w = bucket_string_width(max(w, 1))

    def host(chars_np, lens_np, valid_np):
        chars = np.zeros((cap, max_e, out_w), np.uint8)
        slens = np.zeros((cap, max_e), np.int32)
        ev = np.zeros((cap, max_e), bool)
        lens = np.zeros(cap, np.int32)
        for i in range(cap):
            if not valid_np[i]:
                continue
            s = bytes(chars_np[i, :lens_np[i]]).decode("utf-8", "replace")
            parts = pattern.split(s) if limit <= 0 \
                else pattern.split(s, maxsplit=limit - 1)
            if zero_width and parts and parts[0] == "":
                # Java/Spark: a zero-width match at position 0 never
                # produces an empty leading substring (re.split does)
                parts = parts[1:]
            if zero_width and parts and parts[-1] == "":
                # Spark 3.4+ (SPARK-40194): an empty regex also drops
                # the trailing empty string
                parts = parts[:-1]
            if len(parts) > max_e:
                raise ValueError(
                    f"split() produced {len(parts)} parts; the static "
                    f"element budget is {max_e} — pass an explicit limit")
            lens[i] = len(parts)
            for j, p in enumerate(parts):
                b = p.encode()[:out_w]
                chars[i, j, :len(b)] = np.frombuffer(b, np.uint8)
                slens[i, j] = len(b)
                ev[i, j] = True
        return chars, slens, ev, lens

    chars, slens, ev, lens = jax.pure_callback(
        host,
        (jax.ShapeDtypeStruct((cap, max_e, out_w), jnp.uint8),
         jax.ShapeDtypeStruct((cap, max_e), jnp.int32),
         jax.ShapeDtypeStruct((cap, max_e), jnp.bool_),
         jax.ShapeDtypeStruct((cap,), jnp.int32)),
        col.chars, col.lens, v.validity, vmap_method="sequential")
    return TypedValue(StringListColumn(chars, slens, ev, lens,
                                       v.validity), DataType.LIST)


@register("array_join", DataType.STRING)
def _array_join(args, expr, batch, schema, ctx):
    """array_join(arr, sep[, null_replacement]): concatenate string
    elements; null elements are skipped unless a replacement is given
    (Spark ArrayJoin)."""
    import jax

    from auron_tpu.columnar.batch import StringListColumn
    from auron_tpu.utils.shapes import bucket_string_width
    v = args[0]
    col = v.col
    if not isinstance(col, StringListColumn):
        raise NotImplementedError("array_join() needs an array<string>")
    sep = expr.args[1]
    if not isinstance(sep, ir.Literal):
        raise NotImplementedError("array_join(): separator must be literal")
    if sep.value is None:
        # Spark: NULL separator → NULL result
        cap = col.capacity
        return TypedValue(
            StringColumn(jnp.zeros((cap, 8), jnp.uint8),
                         jnp.zeros(cap, jnp.int32),
                         jnp.zeros(cap, bool)), DataType.STRING)
    sep_s = str(sep.value)
    repl = None
    if len(expr.args) > 2 and isinstance(expr.args[2], ir.Literal) \
            and expr.args[2].value is not None:
        repl = str(expr.args[2].value)
    cap, m, w = col.chars.shape
    repl_w = len(repl.encode()) if repl is not None else 0
    out_w = bucket_string_width(
        min(m * (max(w, repl_w) + len(sep_s.encode())) + 8, 4096))

    def host(chars_np, slens_np, ev_np, lens_np, valid_np):
        chars = np.zeros((cap, out_w), np.uint8)
        lens = np.zeros(cap, np.int32)
        for i in range(cap):
            if not valid_np[i]:
                continue
            parts = []
            for j in range(lens_np[i]):
                if ev_np[i, j]:
                    parts.append(bytes(chars_np[i, j, :slens_np[i, j]])
                                 .decode("utf-8", "replace"))
                elif repl is not None:
                    parts.append(repl)
            b = sep_s.join(parts).encode()
            if len(b) > out_w:
                # fail loudly like split(): silent truncation would be a
                # wrong query result
                raise ValueError(
                    f"array_join() produced {len(b)} bytes; the static "
                    f"width budget is {out_w}")
            chars[i, :len(b)] = np.frombuffer(b, np.uint8)
            lens[i] = len(b)
        return chars, lens

    chars, lens = jax.pure_callback(
        host,
        (jax.ShapeDtypeStruct((cap, out_w), jnp.uint8),
         jax.ShapeDtypeStruct((cap,), jnp.int32)),
        col.chars, col.slens, col.elem_valid, col.lens, v.validity,
        vmap_method="sequential")
    return TypedValue(StringColumn(chars, lens, v.validity),
                      DataType.STRING)


# ---------------------------------------------------------------------------
# string-keyed maps: str_to_map + accessor arms over StringMapColumn
# (reference: spark_map.rs:417 str_to_map)
# ---------------------------------------------------------------------------

def _str_to_map_field(expr, schema):
    return Field("m", DataType.MAP, True, key=DataType.STRING,
                 elem=DataType.STRING)


@register("str_to_map", DataType.MAP, result_field=_str_to_map_field)
def _str_to_map(args, expr, batch, schema, ctx):
    """str_to_map(text[, pairDelim[, keyValueDelim]]): split text into
    pairs then key/value (regex delimiters, Spark defaults ',' and ':');
    duplicate keys resolve LAST_WINS like the map constructors; a pair
    without the kv delimiter maps the whole pair to NULL."""
    import re as _re

    import jax

    from auron_tpu.columnar.batch import StringMapColumn
    from auron_tpu.utils.shapes import bucket_string_width
    v = args[0]
    col = v.col
    if not isinstance(col, StringColumn):
        raise NotImplementedError("str_to_map() needs a STRING input")

    def _delim(k, default):
        if len(expr.args) > k:
            a = expr.args[k]
            if not isinstance(a, ir.Literal) or a.value is None:
                raise NotImplementedError(
                    "str_to_map(): delimiters must be literals")
            return str(a.value)
        return default

    pair_re = _re.compile(_delim(1, ","))
    kv_re = _re.compile(_delim(2, ":"))
    cap, w = col.chars.shape
    max_e = min(w + 1, 64)
    out_w = bucket_string_width(max(w, 1))

    def host(chars_np, lens_np, valid_np):
        kchars = np.zeros((cap, max_e, out_w), np.uint8)
        kslens = np.zeros((cap, max_e), np.int32)
        vchars = np.zeros((cap, max_e, out_w), np.uint8)
        vslens = np.zeros((cap, max_e), np.int32)
        vv = np.zeros((cap, max_e), bool)
        lens = np.zeros(cap, np.int32)
        for i in range(cap):
            if not valid_np[i]:
                continue
            s = bytes(chars_np[i, :lens_np[i]]).decode("utf-8", "replace")
            entries = {}
            for pair in pair_re.split(s):
                kv = kv_re.split(pair, maxsplit=1)
                entries[kv[0]] = kv[1] if len(kv) > 1 else None
            if len(entries) > max_e:
                raise ValueError(
                    f"str_to_map() produced {len(entries)} entries; the "
                    f"static budget is {max_e}")
            lens[i] = len(entries)
            for j, (k, val) in enumerate(entries.items()):
                kb = k.encode()[:out_w]
                kchars[i, j, :len(kb)] = np.frombuffer(kb, np.uint8)
                kslens[i, j] = len(kb)
                if val is not None:
                    vb = val.encode()[:out_w]
                    vchars[i, j, :len(vb)] = np.frombuffer(vb, np.uint8)
                    vslens[i, j] = len(vb)
                    vv[i, j] = True
        return kchars, kslens, vchars, vslens, vv, lens

    kchars, kslens, vchars, vslens, vv, lens = jax.pure_callback(
        host,
        (jax.ShapeDtypeStruct((cap, max_e, out_w), jnp.uint8),
         jax.ShapeDtypeStruct((cap, max_e), jnp.int32),
         jax.ShapeDtypeStruct((cap, max_e, out_w), jnp.uint8),
         jax.ShapeDtypeStruct((cap, max_e), jnp.int32),
         jax.ShapeDtypeStruct((cap, max_e), jnp.bool_),
         jax.ShapeDtypeStruct((cap,), jnp.int32)),
        col.chars, col.lens, v.validity, vmap_method="sequential")
    return TypedValue(StringMapColumn(kchars, kslens, vchars, vslens, vv,
                                      lens, v.validity), DataType.MAP)


def _string_map_get(v: TypedValue, key: TypedValue) -> TypedValue:
    """map<string,string> lookup by string key → StringColumn."""
    from auron_tpu.columnar.batch import StringMapColumn
    col: StringMapColumn = v.col
    kc = key.col
    if not isinstance(kc, StringColumn):
        raise NotImplementedError("string-map lookup needs a STRING key")
    kw = max(col.kchars.shape[2], kc.width)
    mk = jnp.pad(col.kchars,
                 ((0, 0), (0, 0), (0, kw - col.kchars.shape[2])))
    nk = jnp.pad(kc.chars, ((0, 0), (0, kw - kc.width)))
    same = jnp.all(mk == nk[:, None, :], axis=2) \
        & (col.kslens == kc.lens[:, None])
    in_map = jnp.arange(col.max_elems)[None, :] < col.lens[:, None]
    hit = same & in_map
    any_hit = jnp.any(hit, axis=1)
    # LAST matching key wins, like the numeric _map_get and Spark
    last = col.max_elems - 1 - jnp.argmax(hit[:, ::-1], axis=1)
    li = jnp.clip(last, 0, col.max_elems - 1)
    chars = jnp.take_along_axis(col.vchars, li[:, None, None],
                                axis=1)[:, 0]
    slens = jnp.take_along_axis(col.vslens, li[:, None], axis=1)[:, 0]
    vvalid = jnp.take_along_axis(col.val_valid, li[:, None],
                                 axis=1)[:, 0]
    valid = v.validity & key.validity & any_hit & vvalid
    return TypedValue(StringColumn(chars, jnp.where(valid, slens, 0),
                                   valid), DataType.STRING)
