"""Physical expression IR.

The in-memory analogue of the reference's ``PhysicalExprNode`` protobuf
(reference: native-engine/auron-planner/proto/auron.proto:60-127). The
protobuf layer (auron_tpu.ir) deserializes into these nodes; the evaluator
(auron_tpu.exprs.eval) lowers them onto device batches as jax ops.

Expressions are frozen dataclass trees so they can be hashed/compared and
used as jit static arguments — one compiled kernel per (expression tree,
shape bucket) pair.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from auron_tpu.columnar.schema import DataType


class Expr:
    """Base class; subclasses are frozen dataclasses."""

    def children(self) -> tuple["Expr", ...]:
        return ()


@dataclass(frozen=True)
class ColumnRef(Expr):
    """Bound reference to input column by ordinal (the reference binds by
    index too, auron.proto BoundReference)."""
    index: int
    # optional name for debugging only
    name: str = ""


@dataclass(frozen=True)
class Literal(Expr):
    value: Any           # python scalar; None for typed null
    dtype: DataType
    precision: int = 0   # decimal only
    scale: int = 0


@dataclass(frozen=True)
class BinaryExpr(Expr):
    """op in {+,-,*,/,%, ==,!=,<,<=,>,>=, and,or}."""
    op: str
    left: Expr
    right: Expr

    def children(self):
        return (self.left, self.right)


@dataclass(frozen=True)
class Not(Expr):
    child: Expr

    def children(self):
        return (self.child,)


@dataclass(frozen=True)
class IsNull(Expr):
    child: Expr

    def children(self):
        return (self.child,)


@dataclass(frozen=True)
class IsNotNull(Expr):
    child: Expr

    def children(self):
        return (self.child,)


@dataclass(frozen=True)
class Negative(Expr):
    child: Expr

    def children(self):
        return (self.child,)


@dataclass(frozen=True)
class Cast(Expr):
    child: Expr
    dtype: DataType
    precision: int = 0
    scale: int = 0
    # try_cast: null on failure instead of error (reference: TryCast,
    # datafusion-ext-exprs/src/cast.rs)
    safe: bool = True

    def children(self):
        return (self.child,)


@dataclass(frozen=True)
class CaseWhen(Expr):
    """CASE WHEN c1 THEN v1 ... ELSE e END; when_then pairs, else optional."""
    when_then: tuple[tuple[Expr, Expr], ...]
    otherwise: Optional[Expr] = None

    def children(self):
        out = []
        for w, t in self.when_then:
            out += [w, t]
        if self.otherwise is not None:
            out.append(self.otherwise)
        return tuple(out)


@dataclass(frozen=True)
class InList(Expr):
    child: Expr
    values: tuple[Any, ...]   # python scalars (non-null)
    negated: bool = False

    def children(self):
        return (self.child,)


@dataclass(frozen=True)
class Like(Expr):
    """LIKE with a constant pattern; lowered to starts/ends/contains/regex."""
    child: Expr
    pattern: str
    negated: bool = False

    def children(self):
        return (self.child,)


@dataclass(frozen=True)
class StringStartsWith(Expr):
    child: Expr
    prefix: str

    def children(self):
        return (self.child,)


@dataclass(frozen=True)
class StringEndsWith(Expr):
    child: Expr
    suffix: str

    def children(self):
        return (self.child,)


@dataclass(frozen=True)
class StringContains(Expr):
    child: Expr
    infix: str

    def children(self):
        return (self.child,)


@dataclass(frozen=True)
class ScalarFunction(Expr):
    """Named scalar function from the registry (reference:
    datafusion-ext-functions/src/lib.rs)."""
    name: str
    args: tuple[Expr, ...]
    # some functions need a target type/scale (e.g. make_decimal)
    dtype: Optional[DataType] = None
    precision: int = 0
    scale: int = 0

    def children(self):
        return self.args


@dataclass(frozen=True)
class BloomFilterMightContain(Expr):
    """Membership probe against a serialized Spark bloom filter (reference:
    datafusion-ext-exprs/src/bloom_filter_might_contain.rs). The filter
    bytes travel in the expression, as in Spark's runtime filter pushdown."""
    value: Expr
    serialized: bytes

    def children(self):
        return (self.value,)


@dataclass(frozen=True)
class GetIndexedField(Expr):
    """list[ordinal] element access, 0-based (reference:
    datafusion-ext-exprs/src/get_indexed_field.rs)."""
    child: Expr
    ordinal: int

    def children(self):
        return (self.child,)


@dataclass(frozen=True)
class GetStructField(Expr):
    """struct.field access by child ordinal (reference:
    datafusion-ext-exprs/src/get_indexed_field.rs struct arm +
    Spark GetStructField)."""
    child: Expr
    ordinal: int

    def children(self):
        return (self.child,)


@dataclass(frozen=True)
class ScalarSubquery(Expr):
    """Uncorrelated scalar subquery: ``plan_bytes`` is a serialized
    PlanNode executed ONCE at task start; its single value is substituted
    as a Literal before any kernel builds (reference:
    datafusion-ext-exprs/src/spark_scalar_subquery_wrapper.rs — there the
    value comes back from the JVM, here the engine runs the child plan).
    Held as bytes so the expr tree stays hashable for kernel caching; it
    has NO expr children (the plan is opaque at this level)."""
    plan_bytes: bytes
    dtype: DataType
    precision: int = 0
    scale: int = 0
    sid: int = 0


@dataclass(frozen=True)
class RowNum(Expr):
    """Monotonic row number within the partition stream (reference:
    datafusion-ext-exprs/src/row_num.rs)."""


@dataclass(frozen=True)
class SparkPartitionId(Expr):
    pass


@dataclass(frozen=True)
class MonotonicallyIncreasingId(Expr):
    pass


@dataclass(frozen=True)
class HostUDF(Expr):
    """Escape hatch: evaluate an arbitrary host (python/pyarrow) function on
    the host via jax.pure_callback — the analogue of the reference's
    SparkUDFWrapperExpr JVM round-trip (reference:
    datafusion-ext-exprs/src/spark_udf_wrapper.rs:43-230)."""
    fn: Any                 # callable: list[pa.Array] -> pa.Array
    args: tuple[Expr, ...]
    dtype: DataType
    name: str = "udf"

    def children(self):
        return self.args

    def __hash__(self):
        return hash((id(self.fn), self.args, self.dtype, self.name))


# ---------------------------------------------------------------------------
# sort / aggregate helper nodes (used by operators, not standalone exprs)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SortOrder:
    expr: Expr
    ascending: bool = True
    nulls_first: bool = True


@dataclass(frozen=True)
class AggFunction:
    """One aggregate: fn in {sum,count,avg,min,max,first,first_ignores_null,
    count_star, bloom_filter, collect_list, collect_set}."""
    fn: str
    arg: Optional[Expr] = None     # None for count(*)
    distinct: bool = False
    # bloom_filter sizing; 0 = engine defaults
    expected_items: int = 0
    fpp: float = 0.0
