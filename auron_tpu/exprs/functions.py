"""Scalar function registry.

The TPU counterpart of the reference's Spark-exact function library
(reference: datafusion-ext-functions/src/lib.rs registry; spark_dates.rs,
spark_strings.rs, spark_bround.rs, ...). Functions take evaluated TypedValue
args and return a TypedValue; everything traces into the enclosing jit.
"""

from __future__ import annotations

import jax.numpy as jnp

from auron_tpu.columnar.batch import PrimitiveColumn, StringColumn
from auron_tpu.columnar.schema import DataType, Schema
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import TypedValue, cast_value, evaluate, infer_dtype
from auron_tpu.ops import hashing
from auron_tpu.ops import strings as S
from auron_tpu.utils.shapes import bucket_string_width

_REGISTRY = {}
_RESULT_TYPE = {}
#: name → callable(expr, schema) -> Field, for functions whose result is
#: nested (map/struct/list) and cannot be described by a (dtype, p, s)
_RESULT_FIELD = {}


def register(name, result_type=None, result_field=None):
    def deco(fn):
        _REGISTRY[name] = fn
        if result_type is not None:
            _RESULT_TYPE[name] = result_type
        if result_field is not None:
            _RESULT_FIELD[name] = result_field
        return fn
    return deco


def function_result_field(expr: ir.ScalarFunction, schema: Schema):
    """Full result Field for nested-returning functions; None when the
    (dtype, p, s) 3-tuple from function_result_type is the whole story."""
    rf = _RESULT_FIELD.get(expr.name)
    return rf(expr, schema) if rf is not None else None


def dispatch_function(expr: ir.ScalarFunction, batch, schema, ctx) -> TypedValue:
    fn = _REGISTRY.get(expr.name)
    if fn is None:
        raise NotImplementedError(f"scalar function {expr.name!r}")
    args = [evaluate(a, batch, schema, ctx) for a in expr.args]
    return fn(args, expr, batch, schema, ctx)


def function_result_type(expr: ir.ScalarFunction, schema: Schema):
    if expr.dtype is not None:
        return expr.dtype, expr.precision, expr.scale
    rt = _RESULT_TYPE.get(expr.name)
    if rt is None:
        # default: same as first arg
        return infer_dtype(expr.args[0], schema)
    if callable(rt):
        return rt(expr, schema)
    return rt, 0, 0


# ---------------------------------------------------------------------------
# date/time (civil-from-days, Hinnant algorithm — pure integer ops)
# ---------------------------------------------------------------------------

def _civil_from_days(days):
    """days since 1970-01-01 → (year, month, day), vectorized int32."""
    z = days.astype(jnp.int32) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(
        doe - jnp.floor_divide(doe, 1460) + jnp.floor_divide(doe, 36524)
        - jnp.floor_divide(doe, 146096), 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + jnp.floor_divide(yoe, 4) - jnp.floor_divide(yoe, 100))
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    year = y + (m <= 2)
    return year.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def _days_arg(v: TypedValue):
    if v.dtype == DataType.TIMESTAMP_US:
        return jnp.floor_divide(v.data, 86_400_000_000).astype(jnp.int32)
    return v.data.astype(jnp.int32)


@register("year", DataType.INT32)
def _year(args, expr, batch, schema, ctx):
    y, _, _ = _civil_from_days(_days_arg(args[0]))
    return TypedValue(PrimitiveColumn(y, args[0].validity), DataType.INT32)


@register("month", DataType.INT32)
def _month(args, expr, batch, schema, ctx):
    _, m, _ = _civil_from_days(_days_arg(args[0]))
    return TypedValue(PrimitiveColumn(m, args[0].validity), DataType.INT32)


@register("day", DataType.INT32)
@register("dayofmonth", DataType.INT32)
def _day(args, expr, batch, schema, ctx):
    _, _, d = _civil_from_days(_days_arg(args[0]))
    return TypedValue(PrimitiveColumn(d, args[0].validity), DataType.INT32)


@register("quarter", DataType.INT32)
def _quarter(args, expr, batch, schema, ctx):
    _, m, _ = _civil_from_days(_days_arg(args[0]))
    return TypedValue(PrimitiveColumn((m - 1) // 3 + 1, args[0].validity),
                      DataType.INT32)


@register("dayofweek", DataType.INT32)
def _dayofweek(args, expr, batch, schema, ctx):
    # Spark: 1 = Sunday. 1970-01-01 was a Thursday (=5).
    days = _days_arg(args[0])
    dow = jnp.mod(days + 4, 7) + 1
    return TypedValue(PrimitiveColumn(dow.astype(jnp.int32), args[0].validity),
                      DataType.INT32)


@register("dayofyear", DataType.INT32)
def _dayofyear(args, expr, batch, schema, ctx):
    days = _days_arg(args[0])
    y, _, _ = _civil_from_days(days)
    # days since Jan 1 of the same year
    jan1 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    return TypedValue(PrimitiveColumn((days - jan1 + 1).astype(jnp.int32),
                                      args[0].validity), DataType.INT32)


def _days_from_civil(y, m, d):
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.mod(m + 9, 12)
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + jnp.floor_divide(yoe, 4) - jnp.floor_divide(yoe, 100) + doy
    return era * 146097 + doe - 719468


@register("date_add", DataType.DATE32)
def _date_add(args, expr, batch, schema, ctx):
    d = args[0].data.astype(jnp.int32) + args[1].data.astype(jnp.int32)
    return TypedValue(PrimitiveColumn(d, args[0].validity & args[1].validity),
                      DataType.DATE32)


@register("date_sub", DataType.DATE32)
def _date_sub(args, expr, batch, schema, ctx):
    d = args[0].data.astype(jnp.int32) - args[1].data.astype(jnp.int32)
    return TypedValue(PrimitiveColumn(d, args[0].validity & args[1].validity),
                      DataType.DATE32)


@register("datediff", DataType.INT32)
def _datediff(args, expr, batch, schema, ctx):
    d = args[0].data.astype(jnp.int32) - args[1].data.astype(jnp.int32)
    return TypedValue(PrimitiveColumn(d, args[0].validity & args[1].validity),
                      DataType.INT32)


# ---------------------------------------------------------------------------
# math
# ---------------------------------------------------------------------------

@register("abs")
def _abs(args, expr, batch, schema, ctx):
    v = args[0]
    return TypedValue(PrimitiveColumn(jnp.abs(v.data), v.validity),
                      v.dtype, v.precision, v.scale)


@register("sqrt", DataType.FLOAT64)
def _sqrt(args, expr, batch, schema, ctx):
    v = cast_value(args[0], DataType.FLOAT64)
    return TypedValue(PrimitiveColumn(jnp.sqrt(v.data), v.validity),
                      DataType.FLOAT64)


@register("floor", DataType.INT64)
def _floor(args, expr, batch, schema, ctx):
    v = args[0]
    if v.dtype.is_integer:
        return TypedValue(PrimitiveColumn(v.data.astype(jnp.int64), v.validity),
                          DataType.INT64)
    return TypedValue(PrimitiveColumn(jnp.floor(v.data).astype(jnp.int64),
                                      v.validity), DataType.INT64)


@register("ceil", DataType.INT64)
def _ceil(args, expr, batch, schema, ctx):
    v = args[0]
    if v.dtype.is_integer:
        return TypedValue(PrimitiveColumn(v.data.astype(jnp.int64), v.validity),
                          DataType.INT64)
    return TypedValue(PrimitiveColumn(jnp.ceil(v.data).astype(jnp.int64),
                                      v.validity), DataType.INT64)


def _round_half_up(x, digits):
    factor = 10.0 ** digits
    scaled = x * factor
    # Spark ROUND = HALF_UP (away from zero on .5)
    return jnp.sign(scaled) * jnp.floor(jnp.abs(scaled) + 0.5) / factor


def _round_digits(expr):
    """Static digits argument of round/bround, read from the EXPRESSION
    (Spark requires a foldable scale): reading the evaluated arg would
    trace a device value and crash under jit. A typed-NULL scale returns
    None (round(x, NULL) is NULL in Spark, not an error)."""
    if len(expr.args) <= 1:
        return 0
    a = expr.args[1]
    if not isinstance(a, ir.Literal):
        raise NotImplementedError(
            f"{expr.name}: the scale argument must be a literal")
    return None if a.value is None else int(a.value)


@register("round")
def _round(args, expr, batch, schema, ctx):
    """Spark round: HALF_UP (reference: spark_bround.rs / spark_round)."""
    v = args[0]
    digits = _round_digits(expr)
    if digits is None:
        # all-null result of the input's own column type (wide decimals
        # are limb pairs, not .data columns)
        return TypedValue(v.col.with_validity(jnp.zeros_like(v.validity)),
                          v.dtype, v.precision, v.scale)
    if v.dtype == DataType.DECIMAL:
        shift = v.scale - digits
        if shift <= 0:
            return v
        p = 10 ** shift
        half = p // 2
        d = v.data
        rounded = jnp.sign(d) * ((jnp.abs(d) + half) // p)
        return TypedValue(PrimitiveColumn(rounded, v.validity),
                          DataType.DECIMAL, v.precision, digits)
    if v.dtype.is_integer:
        return v
    return TypedValue(PrimitiveColumn(_round_half_up(v.data, digits), v.validity),
                      v.dtype)


@register("bround")
def _bround(args, expr, batch, schema, ctx):
    """Spark bround: HALF_EVEN (banker's rounding)."""
    v = args[0]
    digits = _round_digits(expr)
    if digits is None:
        return TypedValue(v.col.with_validity(jnp.zeros_like(v.validity)),
                          v.dtype, v.precision, v.scale)
    if v.dtype.is_integer:
        return v
    factor = 10.0 ** digits
    data = jnp.round(v.data * factor) / factor  # jnp.round is half-even
    return TypedValue(PrimitiveColumn(data, v.validity), v.dtype)


@register("pow", DataType.FLOAT64)
@register("power", DataType.FLOAT64)
def _pow(args, expr, batch, schema, ctx):
    a = cast_value(args[0], DataType.FLOAT64)
    b = cast_value(args[1], DataType.FLOAT64)
    return TypedValue(PrimitiveColumn(jnp.power(a.data, b.data),
                                      a.validity & b.validity), DataType.FLOAT64)


@register("exp", DataType.FLOAT64)
def _exp(args, expr, batch, schema, ctx):
    v = cast_value(args[0], DataType.FLOAT64)
    return TypedValue(PrimitiveColumn(jnp.exp(v.data), v.validity), DataType.FLOAT64)


@register("log", DataType.FLOAT64)
@register("ln", DataType.FLOAT64)
def _log(args, expr, batch, schema, ctx):
    v = cast_value(args[0], DataType.FLOAT64)
    ok = v.data > 0
    safe = jnp.where(ok, v.data, 1.0)
    return TypedValue(PrimitiveColumn(jnp.log(safe), v.validity & ok),
                      DataType.FLOAT64)


@register("isnan", DataType.BOOL)
def _isnan(args, expr, batch, schema, ctx):
    v = args[0]
    if not v.dtype.is_floating:
        return TypedValue(PrimitiveColumn(jnp.zeros_like(v.validity),
                                          jnp.ones_like(v.validity)), DataType.BOOL)
    return TypedValue(PrimitiveColumn(jnp.isnan(v.data) & v.validity,
                                      jnp.ones_like(v.validity)), DataType.BOOL)


@register("nanvl")
def _nanvl(args, expr, batch, schema, ctx):
    a, b = args
    take_b = jnp.isnan(a.data)
    return TypedValue(
        PrimitiveColumn(jnp.where(take_b, b.data, a.data),
                        jnp.where(take_b, b.validity, a.validity)),
        a.dtype)


@register("normalize_nan_and_zero")
def _normalize(args, expr, batch, schema, ctx):
    """reference: spark_normalize_nan_and_zero — canonical NaN, -0.0 → 0.0."""
    v = args[0]
    d = jnp.where(jnp.isnan(v.data), jnp.asarray(float("nan"), v.data.dtype), v.data)
    d = jnp.where(d == 0.0, jnp.asarray(0.0, v.data.dtype), d)
    return TypedValue(PrimitiveColumn(d, v.validity), v.dtype)


def _nan_gt(a, b):
    """Spark ordering '>': NaN is the greatest value (a != a means NaN;
    no-op for ints)."""
    return (a > b) | ((a != a) & (b == b))


@register("greatest")
def _greatest(args, expr, batch, schema, ctx):
    out = args[0]
    for v in args[1:]:
        take = (~out.validity) | (v.validity & _nan_gt(v.data, out.data))
        out = TypedValue(PrimitiveColumn(jnp.where(take, v.data, out.data),
                                         out.validity | v.validity), out.dtype,
                         out.precision, out.scale)
    return out


@register("least")
def _least(args, expr, batch, schema, ctx):
    out = args[0]
    for v in args[1:]:
        take = (~out.validity) | (v.validity & _nan_gt(out.data, v.data))
        out = TypedValue(PrimitiveColumn(jnp.where(take, v.data, out.data),
                                         out.validity | v.validity), out.dtype,
                         out.precision, out.scale)
    return out


_UNARY_F64 = {
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "log10": jnp.log10, "log2": jnp.log2, "log1p": jnp.log1p,
    "expm1": jnp.expm1, "cbrt": jnp.cbrt,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "rint": jnp.round,
}


def _register_unary_f64():
    for fname, jfn in _UNARY_F64.items():
        def make(jf):
            def impl(args, expr, batch, schema, ctx):
                v = cast_value(args[0], DataType.FLOAT64)
                return TypedValue(PrimitiveColumn(jf(v.data), v.validity),
                                  DataType.FLOAT64)
            return impl
        register(fname, DataType.FLOAT64)(make(jfn))


_register_unary_f64()


@register("signum", DataType.FLOAT64)
@register("sign", DataType.FLOAT64)
def _signum(args, expr, batch, schema, ctx):
    v = cast_value(args[0], DataType.FLOAT64)
    return TypedValue(PrimitiveColumn(jnp.sign(v.data), v.validity),
                      DataType.FLOAT64)


@register("atan2", DataType.FLOAT64)
def _atan2(args, expr, batch, schema, ctx):
    a = cast_value(args[0], DataType.FLOAT64)
    b = cast_value(args[1], DataType.FLOAT64)
    return TypedValue(PrimitiveColumn(jnp.arctan2(a.data, b.data),
                                      a.validity & b.validity),
                      DataType.FLOAT64)


@register("hypot", DataType.FLOAT64)
def _hypot(args, expr, batch, schema, ctx):
    a = cast_value(args[0], DataType.FLOAT64)
    b = cast_value(args[1], DataType.FLOAT64)
    return TypedValue(PrimitiveColumn(jnp.hypot(a.data, b.data),
                                      a.validity & b.validity),
                      DataType.FLOAT64)


@register("pi", DataType.FLOAT64)
def _pi(args, expr, batch, schema, ctx):
    import math
    return TypedValue(PrimitiveColumn(
        jnp.full(batch.capacity, math.pi, jnp.float64),
        jnp.ones(batch.capacity, bool)), DataType.FLOAT64)


@register("e", DataType.FLOAT64)
def _e(args, expr, batch, schema, ctx):
    import math
    return TypedValue(PrimitiveColumn(
        jnp.full(batch.capacity, math.e, jnp.float64),
        jnp.ones(batch.capacity, bool)), DataType.FLOAT64)


def _pmod_result(expr, schema):
    lt, _, _ = infer_dtype(expr.args[0], schema)
    rt, _, _ = infer_dtype(expr.args[1], schema)
    if lt.is_floating or rt.is_floating:
        return DataType.FLOAT64, 0, 0
    return DataType.INT64, 0, 0


@register("pmod", _pmod_result)
def _pmod(args, expr, batch, schema, ctx):
    """Spark pmod(a, n) = ((a % n) + n) % n with Java remainder — which
    is exactly floor-mod for every sign combination (verified: (-7,3)->2,
    (7,-3)->-2, (-7,-3)->-1). Null on zero divisor."""
    a, b = args
    target = DataType.FLOAT64 if (a.dtype.is_floating
                                  or b.dtype.is_floating) else DataType.INT64
    av = cast_value(a, target)
    bv = cast_value(b, target)
    nz = bv.data != 0
    safe_b = jnp.where(nz, bv.data, 1)
    r = jnp.mod(av.data, safe_b)            # jnp.mod is floor-mod
    return TypedValue(PrimitiveColumn(r, av.validity & bv.validity & nz),
                      target)


@register("factorial", DataType.INT64)
def _factorial(args, expr, batch, schema, ctx):
    """Spark factorial: defined for 0..20, null outside."""
    import math
    table = jnp.asarray([math.factorial(i) for i in range(21)], jnp.int64)
    v = cast_value(args[0], DataType.INT64)
    ok = (v.data >= 0) & (v.data <= 20)
    idx = jnp.clip(v.data, 0, 20)
    return TypedValue(PrimitiveColumn(table[idx], v.validity & ok),
                      DataType.INT64)


# ---------------------------------------------------------------------------
# conditional / null
# ---------------------------------------------------------------------------

@register("coalesce")
def _coalesce(args, expr, batch, schema, ctx):
    out = args[0]
    for v in args[1:]:
        take = ~out.validity
        if isinstance(out.col, StringColumn):
            w = max(out.col.width, v.col.width)
            oc = _widen(out.col, w)
            vc = _widen(v.col, w)
            col = StringColumn(jnp.where(take[:, None], vc.chars, oc.chars),
                               jnp.where(take, vc.lens, oc.lens),
                               oc.validity | vc.validity)
        else:
            col = PrimitiveColumn(jnp.where(take, v.data, out.data),
                                  out.validity | v.validity)
        out = TypedValue(col, out.dtype, out.precision, out.scale)
    return out


def _widen(col: StringColumn, width: int) -> StringColumn:
    if col.width == width:
        return col
    return StringColumn(jnp.pad(col.chars, ((0, 0), (0, width - col.width))),
                        col.lens, col.validity)


@register("nullif")
@register("null_if")
def _nullif(args, expr, batch, schema, ctx):
    a, b = args
    if isinstance(a.col, StringColumn):
        _, eq = S.compare(a.col.chars, a.col.lens, b.col.chars, b.col.lens)
    else:
        eq = a.data == b.data
    eq = eq & a.validity & b.validity
    return TypedValue(a.col.with_validity(a.validity & ~eq),
                      a.dtype, a.precision, a.scale)


def _if_result(expr, schema):
    # the result type is the THEN branch's (args[1]), not the condition's
    return infer_dtype(expr.args[1], schema)


@register("if", _if_result)
def _if(args, expr, batch, schema, ctx):
    c, t, f = args
    take = c.data.astype(bool) & c.validity
    if isinstance(t.col, StringColumn):
        w = max(t.col.width, f.col.width)
        tc, fc = _widen(t.col, w), _widen(f.col, w)
        col = StringColumn(jnp.where(take[:, None], tc.chars, fc.chars),
                           jnp.where(take, tc.lens, fc.lens),
                           jnp.where(take, tc.validity, fc.validity))
    else:
        col = PrimitiveColumn(jnp.where(take, t.data, f.data),
                              jnp.where(take, t.validity, f.validity))
    return TypedValue(col, t.dtype, t.precision, t.scale)


# ---------------------------------------------------------------------------
# strings
# ---------------------------------------------------------------------------

def _string_result(expr, schema):
    return DataType.STRING, 0, 0


@register("length", DataType.INT32)
@register("char_length", DataType.INT32)
def _length(args, expr, batch, schema, ctx):
    v = args[0]
    assert isinstance(v.col, StringColumn)
    # NOTE: byte length == char length only for ASCII; UTF-8 aware length
    # subtracts continuation bytes (0b10xxxxxx).
    cont = ((v.col.chars & 0xC0) == 0x80) & (
        jnp.arange(v.col.width)[None, :] < v.col.lens[:, None])
    chars_len = v.col.lens - jnp.sum(cont, axis=1).astype(jnp.int32)
    return TypedValue(PrimitiveColumn(chars_len, v.validity), DataType.INT32)


@register("upper", _string_result)
def _upper(args, expr, batch, schema, ctx):
    return TypedValue(S.upper(args[0].col), DataType.STRING)


@register("lower", _string_result)
def _lower(args, expr, batch, schema, ctx):
    return TypedValue(S.lower(args[0].col), DataType.STRING)


@register("trim", _string_result)
def _trim(args, expr, batch, schema, ctx):
    return TypedValue(S.trim(args[0].col), DataType.STRING)


@register("ltrim", _string_result)
def _ltrim(args, expr, batch, schema, ctx):
    return TypedValue(S.trim(args[0].col, right=False), DataType.STRING)


@register("rtrim", _string_result)
def _rtrim(args, expr, batch, schema, ctx):
    return TypedValue(S.trim(args[0].col, left=False), DataType.STRING)


@register("substring", _string_result)
@register("substr", _string_result)
def _substring(args, expr, batch, schema, ctx):
    v = args[0]
    start = args[1].data.astype(jnp.int32)
    length = (args[2].data.astype(jnp.int32) if len(args) > 2
              else jnp.full_like(start, 2**30))
    return TypedValue(S.substring(v.col, start, length), DataType.STRING)


@register("concat", _string_result)
def _concat(args, expr, batch, schema, ctx):
    cols = [a.col for a in args]
    out_w = bucket_string_width(sum(c.width for c in cols))
    return TypedValue(S.concat(cols, out_w), DataType.STRING)


# ---------------------------------------------------------------------------
# hashes
# ---------------------------------------------------------------------------

@register("hash", DataType.INT32)
@register("murmur3_hash", DataType.INT32)
def _hash(args, expr, batch, schema, ctx):
    h = hashing.murmur3_columns([a.col for a in args], batch.capacity, 42)
    return TypedValue(PrimitiveColumn(h, jnp.ones(batch.capacity, bool)),
                      DataType.INT32)


@register("xxhash64", DataType.INT64)
def _xxhash64(args, expr, batch, schema, ctx):
    h = hashing.xxhash64_columns([a.col for a in args], batch.capacity, 42)
    return TypedValue(PrimitiveColumn(h, jnp.ones(batch.capacity, bool)),
                      DataType.INT64)


# ---------------------------------------------------------------------------
# extended surface — importing these modules populates the registry
# (strings/dates on device; json/regex as host callbacks; md5/sha256 as
# vectorized device kernels)
# ---------------------------------------------------------------------------

from auron_tpu.exprs import fn_arrays   # noqa: E402,F401
from auron_tpu.exprs import fn_structs  # noqa: E402,F401
from auron_tpu.exprs import fn_crypto   # noqa: E402,F401
from auron_tpu.exprs import fn_dates    # noqa: E402,F401
from auron_tpu.exprs import fn_json     # noqa: E402,F401
from auron_tpu.exprs import fn_strings  # noqa: E402,F401
