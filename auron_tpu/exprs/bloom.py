"""Spark-compatible bloom filter + bit array.

Byte-compatible with org.apache.spark.util.sketch.BloomFilterImpl V1 (the
reference re-implements the same: datafusion-ext-commons/src/
spark_bloom_filter.rs, spark_bit_array.rs): big-endian i32 version(=1),
i32 numHashFunctions, i32 word count, i64 words; double hashing
h1 = murmur3(item, 0), h2 = murmur3(item, h1), bit_i = (h1 + i*h2) with a
sign flip, i in 1..=k.

TPU split: *building* is a vectorized numpy pass on the host (build sides
are small and scatter-OR is host-friendly); *probing* — the hot path, a
semi-join filter inside scans — is a device kernel over the words array.
"""

from __future__ import annotations

import math
import struct
from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp

from auron_tpu.ops import hashing
from auron_tpu.runtime.programs import program_cache

_M1 = np.uint32(0xCC9E2D51)
_M2 = np.uint32(0x1B873593)


def _np_murmur3_long(values: np.ndarray, seed: np.ndarray) -> np.ndarray:
    """Vectorized Spark murmur3 hashLong (two 32-bit rounds), numpy mirror
    of ops.hashing.murmur3_int64 — build side runs on host."""
    def mix_k1(k1):
        k1 = (k1 * _M1).astype(np.uint32)
        k1 = (k1 << np.uint32(15)) | (k1 >> np.uint32(17))
        return (k1 * _M2).astype(np.uint32)

    def mix_h1(h1, k1):
        h1 = h1 ^ k1
        h1 = (h1 << np.uint32(13)) | (h1 >> np.uint32(19))
        return (h1 * np.uint32(5) + np.uint32(0xE6546B64)).astype(np.uint32)

    v = values.astype(np.int64).view(np.uint64)
    low = (v & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    high = (v >> np.uint64(32)).astype(np.uint32)
    h1 = seed.astype(np.uint32)
    h1 = mix_h1(h1, mix_k1(low))
    h1 = mix_h1(h1, mix_k1(high))
    h1 = h1 ^ np.uint32(8)
    h1 = h1 ^ (h1 >> np.uint32(16))
    h1 = (h1 * np.uint32(0x85EBCA6B)).astype(np.uint32)
    h1 = h1 ^ (h1 >> np.uint32(13))
    h1 = (h1 * np.uint32(0xC2B2AE35)).astype(np.uint32)
    h1 = h1 ^ (h1 >> np.uint32(16))
    return h1.astype(np.int32)


class SparkBloomFilter:
    def __init__(self, num_hash_functions: int, num_bits: int):
        num_bits = max((num_bits + 63) // 64, 1) * 64
        self.num_hash_functions = num_hash_functions
        self.words = np.zeros(num_bits // 64, np.uint64)

    # -- sizing (Spark BloomFilter.optimalNumOf*) ---------------------------

    @staticmethod
    def optimal_num_bits(expected_items: int, fpp: float) -> int:
        # Spark BloomFilter.optimalNumOfBits — no word rounding here; k is
        # derived from this raw count, the bit array rounds up separately
        return max(int(-expected_items * math.log(fpp)
                       / (math.log(2) ** 2)), 1)

    @classmethod
    def create(cls, expected_items: int,
               fpp: float = 0.03) -> "SparkBloomFilter":
        m = cls.optimal_num_bits(expected_items, fpp)
        k = max(round(m / expected_items * math.log(2)), 1)
        return cls(k, m)

    @property
    def bit_size(self) -> int:
        return len(self.words) * 64

    # -- build (host, vectorized) ------------------------------------------

    def _indices(self, items: np.ndarray) -> np.ndarray:
        """[n, k] bit indices for int64 items."""
        h1 = _np_murmur3_long(items, np.int32(0))
        h2 = _np_murmur3_long(items, h1)
        k = self.num_hash_functions
        i = np.arange(1, k + 1, dtype=np.int32)[None, :]
        combined = (h1[:, None].astype(np.int32)
                    + (i * h2[:, None].astype(np.int32)).astype(np.int32))
        combined = np.where(combined < 0, ~combined, combined)
        # Spark: int hash % long bitSize — keep the modulo in 64 bits so
        # filters past 2^31 bits work
        return combined.astype(np.int64) % np.int64(self.bit_size)

    def put_longs(self, items: np.ndarray) -> None:
        items = np.asarray(items, np.int64)
        if items.size == 0:
            return
        idx = self._indices(items).reshape(-1).astype(np.uint64)
        np.bitwise_or.at(self.words, (idx >> np.uint64(6)).astype(np.int64),
                         np.uint64(1) << (idx & np.uint64(63)))

    def might_contain_longs_host(self, items: np.ndarray) -> np.ndarray:
        items = np.asarray(items, np.int64)
        idx = self._indices(items).astype(np.uint64)
        bits = (self.words[(idx >> np.uint64(6)).astype(np.int64)]
                >> (idx & np.uint64(63))) & np.uint64(1)
        return bits.all(axis=1)

    def merge(self, other: "SparkBloomFilter") -> None:
        assert (self.bit_size == other.bit_size
                and self.num_hash_functions == other.num_hash_functions), \
            "cannot merge bloom filters with different layouts"
        self.words |= other.words

    # -- Spark V1 serde -----------------------------------------------------

    def serialize(self) -> bytes:
        out = struct.pack(">iii", 1, self.num_hash_functions, len(self.words))
        return out + self.words.view(np.int64).astype(">i8").tobytes()

    @classmethod
    def deserialize(cls, data: bytes) -> "SparkBloomFilter":
        if len(data) < 12:
            raise ValueError(
                f"bad bloom filter bytes: {len(data)} < 12-byte header")
        version, k, n_words = struct.unpack(">iii", data[:12])
        if version != 1:
            raise ValueError(f"unsupported bloom filter version {version}")
        if n_words <= 0:
            raise ValueError(f"bad bloom filter bytes: word count {n_words}")
        if len(data) < 12 + n_words * 8:
            raise ValueError(
                f"bad bloom filter bytes: truncated word array "
                f"({len(data) - 12} of {n_words * 8} bytes)")
        f = cls(k, n_words * 64)
        f.words = np.frombuffer(data[12:12 + n_words * 8],
                                dtype=">i8").astype(np.int64).view(np.uint64)
        return f


# ---------------------------------------------------------------------------
# device probe kernel
# ---------------------------------------------------------------------------

@program_cache("exprs.bloom.probe", maxsize=64)
def _probe_kernel(num_hash_functions: int, bit_size: int):
    k = num_hash_functions

    @jax.jit
    def kernel(words: jax.Array, values: jax.Array):
        h1 = hashing.murmur3_int64(values, jnp.uint32(0)).astype(jnp.int32)
        h2 = hashing.murmur3_int64(values, h1.view(jnp.uint32)) \
            .astype(jnp.int32)
        i = jnp.arange(1, k + 1, dtype=jnp.int32)[None, :]
        combined = h1[:, None] + i * h2[:, None]
        combined = jnp.where(combined < 0, ~combined, combined)
        idx = (combined.astype(jnp.int64)
               % jnp.int64(bit_size)).astype(jnp.uint64)
        bits = (words[idx >> jnp.uint64(6)]
                >> (idx & jnp.uint64(63))) & jnp.uint64(1)
        return jnp.all(bits == 1, axis=1)

    return kernel


def might_contain_device(filter_bytes: bytes, values: jax.Array) -> jax.Array:
    """bool[capacity]: device-side membership probe against a serialized
    Spark bloom filter."""
    f = _cached_filter(filter_bytes)
    words = _cached_words(filter_bytes)
    kern = _probe_kernel(f.num_hash_functions, f.bit_size)
    return kern(words, values)


@lru_cache(maxsize=32)
def _cached_filter(filter_bytes: bytes) -> SparkBloomFilter:
    return SparkBloomFilter.deserialize(filter_bytes)


@lru_cache(maxsize=32)
def _cached_words(filter_bytes: bytes):
    return jnp.asarray(_cached_filter(filter_bytes).words)
