"""Spark date/time functions as device kernels.

The reference's spark_dates.rs (1,177 LoC) does per-row chrono math; here
every function is Hinnant civil-calendar integer arithmetic over whole
columns (the same _civil_from_days/_days_from_civil pair the core date
extractors use, exprs/functions.py), so they trace into the enclosing jit.
String parsing (unix_timestamp(str, fmt), to_date(str, fmt)) is host-side
— data-dependent scalar parsing has no MXU mapping; the host callback
mirrors the reference's JVM-fallback escape hatch.

Formats use Java SimpleDateFormat tokens (yyyy, MM, dd, HH, mm, ss) as
Spark does; date_format builds fixed-width segments entirely on device.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from auron_tpu.columnar.batch import PrimitiveColumn, StringColumn
from auron_tpu.columnar.schema import DataType
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import TypedValue, cast_value
from auron_tpu.exprs.functions import (_civil_from_days, _days_arg,
                                       _days_from_civil, register)

US_PER_DAY = 86_400_000_000
US_PER_HOUR = 3_600_000_000
US_PER_MIN = 60_000_000
US_PER_SEC = 1_000_000


def _string_result(expr, schema):
    return DataType.STRING, 0, 0


def _lit(expr, k, default=None):
    if k >= len(expr.args):
        return default
    a = expr.args[k]
    if not isinstance(a, ir.Literal):
        raise NotImplementedError(f"{expr.name}: arg {k} must be a literal")
    return a.value


def _ts_us(v: TypedValue):
    """Any date/timestamp input → microseconds since epoch (int64)."""
    if v.dtype == DataType.TIMESTAMP_US:
        return v.data.astype(jnp.int64)
    return v.data.astype(jnp.int64) * US_PER_DAY


def _time_of_day_us(ts):
    return jnp.mod(ts, US_PER_DAY)  # floor-mod: correct for pre-epoch


@register("hour", DataType.INT32)
def _hour(args, expr, batch, schema, ctx):
    t = _time_of_day_us(_ts_us(args[0]))
    return TypedValue(PrimitiveColumn(
        (t // US_PER_HOUR).astype(jnp.int32), args[0].validity),
        DataType.INT32)


@register("minute", DataType.INT32)
def _minute(args, expr, batch, schema, ctx):
    t = _time_of_day_us(_ts_us(args[0]))
    return TypedValue(PrimitiveColumn(
        (t // US_PER_MIN % 60).astype(jnp.int32), args[0].validity),
        DataType.INT32)


@register("second", DataType.INT32)
def _second(args, expr, batch, schema, ctx):
    t = _time_of_day_us(_ts_us(args[0]))
    return TypedValue(PrimitiveColumn(
        (t // US_PER_SEC % 60).astype(jnp.int32), args[0].validity),
        DataType.INT32)


# ---------------------------------------------------------------------------
# date_format / from_unixtime / unix_timestamp / to_date
# ---------------------------------------------------------------------------

#: token → (digit count, extractor index) — extractors computed per batch
_TOKENS = ["yyyy", "yy", "MM", "dd", "HH", "hh", "mm", "ss", "SSS"]


def _tokenize(fmt: str):
    """Format string → list of ('tok', name) | ('lit', bytes)."""
    out, i = [], 0
    while i < len(fmt):
        for t in _TOKENS:
            if fmt.startswith(t, i):
                out.append(("tok", t))
                i += len(t)
                break
        else:
            if fmt[i] == "'":
                j = fmt.find("'", i + 1)
                j = len(fmt) if j < 0 else j
                out.append(("lit", fmt[i + 1:j].encode() or b"'"))
                i = j + 1
            else:
                out.append(("lit", fmt[i].encode()))
                i += 1
    return out


def _digits(x, ndig: int):
    """int array → uint8[?, ndig] ASCII digits, zero-padded."""
    cols = []
    for k in range(ndig - 1, -1, -1):
        cols.append((x // (10 ** k) % 10 + ord("0")).astype(jnp.uint8))
    return jnp.stack(cols, axis=1)


def format_timestamp(ts, fmt: str):
    """Device timestamp formatting → (chars, lens). Raises on tokens
    outside the supported set (callers fall back to host)."""
    days = jnp.floor_divide(ts, US_PER_DAY)
    tod = jnp.mod(ts, US_PER_DAY)
    y, mo, d = _civil_from_days(days)
    vals = {
        "yyyy": (y, 4), "yy": (jnp.mod(y, 100), 2),
        "MM": (mo, 2), "dd": (d, 2),
        "HH": ((tod // US_PER_HOUR).astype(jnp.int32), 2),
        "hh": ((jnp.mod(tod // US_PER_HOUR + 11, 12) + 1).astype(jnp.int32), 2),
        "mm": ((tod // US_PER_MIN % 60).astype(jnp.int32), 2),
        "ss": ((tod // US_PER_SEC % 60).astype(jnp.int32), 2),
        "SSS": ((tod // 1000 % 1000).astype(jnp.int32), 3),
    }
    segs = []
    n = ts.shape[0]
    for kind, tok in _tokenize(fmt):
        if kind == "lit":
            lit = np.frombuffer(tok, np.uint8)
            segs.append(jnp.broadcast_to(jnp.asarray(lit)[None, :],
                                         (n, len(lit))))
        else:
            x, nd = vals[tok]
            segs.append(_digits(x, nd))
    chars = jnp.concatenate(segs, axis=1) if segs else \
        jnp.zeros((n, 1), jnp.uint8)
    total = chars.shape[1]
    return chars, jnp.full(n, total, jnp.int32)


@register("date_format", _string_result)
def _date_format(args, expr, batch, schema, ctx):
    fmt = str(_lit(expr, 1, "yyyy-MM-dd HH:mm:ss"))
    ts = _ts_us(args[0])
    chars, lens = format_timestamp(ts, fmt)
    return TypedValue(StringColumn(chars, lens, args[0].validity),
                      DataType.STRING)


@register("from_unixtime", _string_result)
def _from_unixtime(args, expr, batch, schema, ctx):
    fmt = str(_lit(expr, 1, "yyyy-MM-dd HH:mm:ss"))
    secs = cast_value(args[0], DataType.INT64).data
    chars, lens = format_timestamp(secs * US_PER_SEC, fmt)
    return TypedValue(StringColumn(chars, lens, args[0].validity),
                      DataType.STRING)


def _java_to_strptime(fmt: str) -> str:
    for a, b in [("yyyy", "%Y"), ("yy", "%y"), ("MM", "%m"), ("dd", "%d"),
                 ("HH", "%H"), ("mm", "%M"), ("ss", "%S")]:
        fmt = fmt.replace(a, b)
    return fmt


def _host_parse_ts(col: StringColumn, validity, fmt: str):
    """string → epoch micros on host (strptime); invalid → null."""
    import datetime
    cap = col.capacity
    py_fmt = _java_to_strptime(fmt)

    def host(chars_np, lens_np, valid_np):
        out = np.zeros(cap, np.int64)
        ok = np.zeros(cap, bool)
        for i in range(cap):
            if not valid_np[i]:
                continue
            s = bytes(chars_np[i, : lens_np[i]]).decode("utf-8", "replace")
            try:
                dt = datetime.datetime.strptime(s.strip(), py_fmt)
            except ValueError:
                continue
            dt = dt.replace(tzinfo=datetime.timezone.utc)
            out[i] = int(dt.timestamp() * 1e6)
            ok[i] = True
        return out, ok

    return jax.pure_callback(
        host,
        (jax.ShapeDtypeStruct((cap,), jnp.int64),
         jax.ShapeDtypeStruct((cap,), jnp.bool_)),
        col.chars, col.lens, validity, vmap_method="sequential")


@register("unix_timestamp", DataType.INT64)
@register("to_unix_timestamp", DataType.INT64)
def _unix_timestamp(args, expr, batch, schema, ctx):
    v = args[0]
    if v.dtype == DataType.STRING:
        fmt = str(_lit(expr, 1, "yyyy-MM-dd HH:mm:ss"))
        us, ok = _host_parse_ts(v.col, v.validity, fmt)
        return TypedValue(PrimitiveColumn(us // US_PER_SEC, v.validity & ok),
                          DataType.INT64)
    secs = _ts_us(v) // US_PER_SEC
    return TypedValue(PrimitiveColumn(secs, v.validity), DataType.INT64)


@register("to_date", DataType.DATE32)
def _to_date(args, expr, batch, schema, ctx):
    v = args[0]
    if v.dtype != DataType.STRING:
        days = (_ts_us(v) // US_PER_DAY).astype(jnp.int32)
        return TypedValue(PrimitiveColumn(days, v.validity), DataType.DATE32)
    if len(expr.args) > 1:
        fmt = str(_lit(expr, 1))
        us, ok = _host_parse_ts(v.col, v.validity, fmt)
        return TypedValue(PrimitiveColumn(
            (us // US_PER_DAY).astype(jnp.int32), v.validity & ok),
            DataType.DATE32)
    return cast_value(v, DataType.DATE32)


# ---------------------------------------------------------------------------
# trunc / date_trunc / month math
# ---------------------------------------------------------------------------

@register("trunc", DataType.DATE32)
def _trunc(args, expr, batch, schema, ctx):
    """trunc(date, fmt): year/yyyy/yy → Jan 1; month/mon/mm → 1st; week →
    Monday; quarter → quarter start (Spark trunc)."""
    v = args[0]
    fmt = str(_lit(expr, 1, "")).lower()
    days = _days_arg(v)
    y, m, _d = _civil_from_days(days)
    one = jnp.ones_like(y)
    if fmt in ("year", "yyyy", "yy"):
        out = _days_from_civil(y, one, one)
    elif fmt in ("month", "mon", "mm"):
        out = _days_from_civil(y, m, one)
    elif fmt == "quarter":
        qm = ((m - 1) // 3) * 3 + 1
        out = _days_from_civil(y, qm, one)
    elif fmt == "week":
        # Monday of the week; 1970-01-01 was Thursday (dow0=Thu)
        dow_mon = jnp.mod(days + 3, 7)   # 0 = Monday
        out = days - dow_mon
    else:
        # unknown format → null (Spark returns null)
        return TypedValue(PrimitiveColumn(jnp.zeros_like(days),
                                          jnp.zeros_like(v.validity)),
                          DataType.DATE32)
    return TypedValue(PrimitiveColumn(out.astype(jnp.int32), v.validity),
                      DataType.DATE32)


@register("date_trunc", DataType.TIMESTAMP_US)
def _date_trunc(args, expr, batch, schema, ctx):
    """date_trunc(fmt, ts) → timestamp truncated to the unit."""
    fmt = str(_lit(expr, 0, "")).lower()
    v = args[1]
    ts = _ts_us(v)
    days = jnp.floor_divide(ts, US_PER_DAY)
    if fmt in ("year", "yyyy", "yy", "month", "mon", "mm", "quarter", "week"):
        y, m, _d = _civil_from_days(days)
        one = jnp.ones_like(y)
        if fmt in ("year", "yyyy", "yy"):
            d2 = _days_from_civil(y, one, one)
        elif fmt == "quarter":
            d2 = _days_from_civil(y, ((m - 1) // 3) * 3 + 1, one)
        elif fmt == "week":
            d2 = days - jnp.mod(days + 3, 7)
        else:
            d2 = _days_from_civil(y, m, one)
        out = d2.astype(jnp.int64) * US_PER_DAY
    elif fmt in ("day", "dd"):
        out = days * US_PER_DAY
    elif fmt == "hour":
        out = ts - jnp.mod(ts, US_PER_HOUR)
    elif fmt == "minute":
        out = ts - jnp.mod(ts, US_PER_MIN)
    elif fmt == "second":
        out = ts - jnp.mod(ts, US_PER_SEC)
    else:
        return TypedValue(PrimitiveColumn(jnp.zeros_like(ts),
                                          jnp.zeros_like(v.validity)),
                          DataType.TIMESTAMP_US)
    return TypedValue(PrimitiveColumn(out, v.validity), DataType.TIMESTAMP_US)


def _month_add(days, n):
    y, m, d = _civil_from_days(days)
    m0 = y * 12 + (m - 1) + n
    y2 = jnp.floor_divide(m0, 12)
    m2 = jnp.mod(m0, 12) + 1
    one = jnp.ones_like(y2)
    first = _days_from_civil(y2, m2, one)
    next_first = _days_from_civil(
        y2 + (m2 == 12), jnp.where(m2 == 12, 1, m2 + 1), one)
    dim = next_first - first               # days in target month
    d2 = jnp.minimum(d, dim)               # Spark clamps to last day
    return first + d2 - 1


@register("add_months", DataType.DATE32)
def _add_months(args, expr, batch, schema, ctx):
    days = _days_arg(args[0])
    n = cast_value(args[1], DataType.INT32).data
    out = _month_add(days, n)
    return TypedValue(PrimitiveColumn(out.astype(jnp.int32),
                                      args[0].validity & args[1].validity),
                      DataType.DATE32)


@register("last_day", DataType.DATE32)
def _last_day(args, expr, batch, schema, ctx):
    days = _days_arg(args[0])
    y, m, _d = _civil_from_days(days)
    one = jnp.ones_like(y)
    next_first = _days_from_civil(
        y + (m == 12), jnp.where(m == 12, 1, m + 1), one)
    return TypedValue(PrimitiveColumn((next_first - 1).astype(jnp.int32),
                                      args[0].validity), DataType.DATE32)


@register("months_between", DataType.FLOAT64)
def _months_between(args, expr, batch, schema, ctx):
    """Spark months_between: whole-month diff when both are the same day of
    month or both last days; otherwise 31-day-month fraction incl. time."""
    ts1, ts2 = _ts_us(args[0]), _ts_us(args[1])
    d1 = jnp.floor_divide(ts1, US_PER_DAY)
    d2 = jnp.floor_divide(ts2, US_PER_DAY)
    y1, m1, dd1 = _civil_from_days(d1)
    y2, m2, dd2 = _civil_from_days(d2)

    def last_dom(y, m, d):
        one = jnp.ones_like(y)
        nf = _days_from_civil(y + (m == 12), jnp.where(m == 12, 1, m + 1), one)
        f = _days_from_civil(y, m, one)
        return d == (nf - f)

    months = (y1 - y2) * 12 + (m1 - m2)
    both_last = last_dom(y1, m1, dd1) & last_dom(y2, m2, dd2)
    same_day = dd1 == dd2
    t1 = jnp.mod(ts1, US_PER_DAY).astype(jnp.float64)
    t2 = jnp.mod(ts2, US_PER_DAY).astype(jnp.float64)
    day_frac = ((dd1 - dd2).astype(jnp.float64) * US_PER_DAY + (t1 - t2)) \
        / (31.0 * US_PER_DAY)
    # Spark short-circuits to the whole-month diff whenever the days of
    # month match (time of day ignored) or both are month-ends
    frac = jnp.where(both_last | same_day, 0.0, day_frac)
    out = months.astype(jnp.float64) + frac
    roundoff = _lit(expr, 2, True) if len(expr.args) > 2 else True
    if roundoff:
        out = jnp.round(out * 1e8) / 1e8
    return TypedValue(PrimitiveColumn(out, args[0].validity & args[1].validity),
                      DataType.FLOAT64)


@register("weekofyear", DataType.INT32)
def _weekofyear(args, expr, batch, schema, ctx):
    """ISO-8601 week number, fully vectorized."""
    days = _days_arg(args[0])
    y, _m, _d = _civil_from_days(days)

    def iso_week(days, y):
        jan1 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
        ordinal = days - jan1 + 1                       # 1-based day of year
        wd = jnp.mod(days + 3, 7) + 1                   # ISO weekday 1=Mon
        return jnp.floor_divide(ordinal - wd + 10, 7)

    w0 = iso_week(days, y)
    # w0 == 0 → last week of previous year; own-year w0 == 53 rolls to
    # week 1 when the year has no week 53. Dec 28 is ALWAYS in the year's
    # last ISO week, so its week number IS the year's week count (Dec 31
    # itself overcounts in exactly the years that roll).
    w_prev = iso_week(days, y - 1)
    dec28 = _days_from_civil(y, jnp.full_like(y, 12), jnp.full_like(y, 28))
    weeks_in_year = iso_week(dec28, y)
    roll = w0 > weeks_in_year
    w = jnp.where(w0 < 1, w_prev, jnp.where(roll, 1, w0))
    return TypedValue(PrimitiveColumn(w.astype(jnp.int32), args[0].validity),
                      DataType.INT32)


_DOW = {"mo": 0, "tu": 1, "we": 2, "th": 3, "fr": 4, "sa": 5, "su": 6}


@register("next_day", DataType.DATE32)
def _next_day(args, expr, batch, schema, ctx):
    days = _days_arg(args[0])
    dow_s = str(_lit(expr, 1, "")).strip().lower()[:2]
    if dow_s not in _DOW:
        return TypedValue(PrimitiveColumn(jnp.zeros_like(days),
                                          jnp.zeros_like(args[0].validity)),
                          DataType.DATE32)
    target = _DOW[dow_s]
    cur = jnp.mod(days + 3, 7)                # 0 = Monday
    delta = jnp.mod(target - cur + 6, 7) + 1  # strictly after
    return TypedValue(PrimitiveColumn((days + delta).astype(jnp.int32),
                                      args[0].validity), DataType.DATE32)


@register("make_date", DataType.DATE32)
def _make_date(args, expr, batch, schema, ctx):
    y = cast_value(args[0], DataType.INT32).data
    m = cast_value(args[1], DataType.INT32).data
    d = cast_value(args[2], DataType.INT32).data
    # Spark nulls invalid dates (make_date(2019,2,29) → NULL), it never
    # rolls them over into the next month
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    month_len = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30,
                             31], jnp.int32)[jnp.clip(m, 1, 12) - 1]
    month_len = month_len + (leap & (m == 2)).astype(jnp.int32)
    ok = (m >= 1) & (m <= 12) & (d >= 1) & (d <= month_len)
    out = _days_from_civil(y, jnp.clip(m, 1, 12), jnp.clip(d, 1, 31))
    valid = args[0].validity & args[1].validity & args[2].validity & ok
    return TypedValue(PrimitiveColumn(out.astype(jnp.int32), valid),
                      DataType.DATE32)
