"""Hash / encoding functions — MD5 and SHA-256 as fully vectorized device
kernels.

The reference calls the md5/sha crates per row (reference:
datafusion-ext-functions/src/spark_crypto.rs). Block ciphers look hostile
to SIMD-per-row execution, but with the fixed-width string layout the whole
column can run one block schedule in lockstep: every row processes the
same static number of blocks, and rows whose message ended earlier simply
stop updating their lanes (per-row active masking after each block). All
arithmetic is uint32 adds/rotates — pure VPU work, no host round-trip.

sha1/sha2(224/384/512) fall back to host hashlib (rare in plans); base64 /
hex / crc32 are device kernels.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from auron_tpu.columnar.batch import PrimitiveColumn, StringColumn
from auron_tpu.columnar.schema import DataType
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import TypedValue
from auron_tpu.exprs.functions import register

U32 = jnp.uint32


def _string_result(expr, schema):
    return DataType.STRING, 0, 0


def _lit(expr, k, default=None):
    if k >= len(expr.args):
        return default
    a = expr.args[k]
    if not isinstance(a, ir.Literal):
        raise NotImplementedError(f"{expr.name}: arg {k} must be a literal")
    return a.value


def _rotl(x, s):
    return (x << U32(s)) | (x >> U32(32 - s))


def _message_blocks(chars, lens, big_endian_len: bool):
    """Merkle–Damgård padding for the whole column: returns
    (words uint32[n, B, 16], n_blocks int32[n], B)."""
    n, w = chars.shape
    B = (w + 9 + 63) // 64
    total = B * 64
    pos = jnp.arange(total, dtype=jnp.int32)[None, :]
    src = jnp.pad(chars, ((0, 0), (0, total - w)))
    lens_c = lens[:, None]
    base = jnp.where(pos < lens_c, src,
                     jnp.where(pos == lens_c, 0x80, 0)).astype(jnp.uint8)
    nb = (lens + 9 + 63) // 64                       # blocks per row
    lfield = nb[:, None] * 64 - 8                    # length-field start
    in_len = (pos >= lfield) & (pos < lfield + 8)
    bitlen = (lens.astype(jnp.uint64) * 8)[:, None]
    if big_endian_len:
        shift = (7 - (pos - lfield)).astype(jnp.uint64) * 8
    else:
        shift = (pos - lfield).astype(jnp.uint64) * 8
    lbyte = ((bitlen >> jnp.where(in_len, shift, 0)) & 0xFF).astype(jnp.uint8)
    msg = jnp.where(in_len, lbyte, base)
    u = msg.astype(U32).reshape(n, B, 16, 4)
    if big_endian_len:   # SHA: big-endian words
        words = (u[..., 0] << 24) | (u[..., 1] << 16) | (u[..., 2] << 8) | u[..., 3]
    else:                # MD5: little-endian words
        words = (u[..., 3] << 24) | (u[..., 2] << 16) | (u[..., 1] << 8) | u[..., 0]
    return words, nb, B


_MD5_K = [int(abs(np.sin(i + 1)) * 2 ** 32) & 0xFFFFFFFF for i in range(64)]
_MD5_S = [7, 12, 17, 22] * 4 + [5, 9, 14, 20] * 4 + \
    [4, 11, 16, 23] * 4 + [6, 10, 15, 21] * 4


def md5_digest(chars, lens):
    """uint32[n, 4] little-endian MD5 state over the column."""
    words, nb, B = _message_blocks(chars, lens, big_endian_len=False)
    n = chars.shape[0]
    a0 = jnp.full(n, 0x67452301, U32)
    b0 = jnp.full(n, 0xEFCDAB89, U32)
    c0 = jnp.full(n, 0x98BADCFE, U32)
    d0 = jnp.full(n, 0x10325476, U32)
    for blk in range(B):
        M = words[:, blk, :]
        a, b, c, d = a0, b0, c0, d0
        for i in range(64):
            if i < 16:
                f = (b & c) | (~b & d); g = i
            elif i < 32:
                f = (d & b) | (~d & c); g = (5 * i + 1) % 16
            elif i < 48:
                f = b ^ c ^ d; g = (3 * i + 5) % 16
            else:
                f = c ^ (b | ~d); g = (7 * i) % 16
            f = f + a + U32(_MD5_K[i]) + M[:, g]
            a, d, c = d, c, b
            b = b + _rotl(f, _MD5_S[i])
            # note: b computed from pre-rotation c (old b) — order above
            # keeps the classic (a,b,c,d) rotation correct
        active = (blk < nb)
        a0 = jnp.where(active, a0 + a, a0)
        b0 = jnp.where(active, b0 + b, b0)
        c0 = jnp.where(active, c0 + c, c0)
        d0 = jnp.where(active, d0 + d, d0)
    return jnp.stack([a0, b0, c0, d0], axis=1)


_SHA256_K = [
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
    0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
    0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
    0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
    0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
    0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2]


def sha256_digest(chars, lens):
    """uint32[n, 8] big-endian SHA-256 state over the column. Message
    schedule and compression run as lax.fori_loop (a fully unrolled 112-step
    round function per block blows up XLA's optimization passes)."""
    from jax import lax
    words, nb, B = _message_blocks(chars, lens, big_endian_len=True)
    n = chars.shape[0]
    K = jnp.asarray(_SHA256_K, U32)
    H = tuple(jnp.full(n, h, U32) for h in
              (0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
               0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19))
    for blk in range(B):
        W0 = jnp.zeros((64, n), U32).at[:16].set(words[:, blk, :].T)

        def extend(t, W):
            w15, w2 = W[t - 15], W[t - 2]
            s0 = _rotl(w15, 25) ^ _rotl(w15, 14) ^ (w15 >> U32(3))
            s1 = _rotl(w2, 15) ^ _rotl(w2, 13) ^ (w2 >> U32(10))
            return W.at[t].set(W[t - 16] + s0 + W[t - 7] + s1)

        W = lax.fori_loop(16, 64, extend, W0)

        def rnd(t, st):
            a, b, c, d, e, f, g, h = st
            S1 = _rotl(e, 26) ^ _rotl(e, 21) ^ _rotl(e, 7)
            ch = (e & f) ^ (~e & g)
            t1 = h + S1 + ch + K[t] + W[t]
            S0 = _rotl(a, 30) ^ _rotl(a, 19) ^ _rotl(a, 10)
            maj = (a & b) ^ (a & c) ^ (b & c)
            return (t1 + S0 + maj, a, b, c, d + t1, e, f, g)

        out = lax.fori_loop(0, 64, rnd, H)
        active = (blk < nb)
        H = tuple(jnp.where(active, h0 + hn, h0)
                  for h0, hn in zip(H, out))
    return jnp.stack(H, axis=1)


def _state_to_hex(state, little_endian: bool) -> tuple[jax.Array, int]:
    """uint32[n, k] → lowercase hex chars uint8[n, k*8]."""
    n, k = state.shape
    shifts = [0, 8, 16, 24] if little_endian else [24, 16, 8, 0]
    by = jnp.stack([(state >> U32(s)) & U32(0xFF) for s in shifts],
                   axis=2).reshape(n, k * 4)
    hi, lo = by >> U32(4), by & U32(0xF)

    def hexc(x):
        return jnp.where(x < 10, x + ord("0"), x - 10 + ord("a"))

    out = jnp.stack([hexc(hi), hexc(lo)], axis=2).reshape(n, k * 8)
    return out.astype(jnp.uint8), k * 8


@register("md5", _string_result)
def _md5(args, expr, batch, schema, ctx):
    v = args[0]
    state = md5_digest(v.col.chars, v.col.lens)
    chars, w = _state_to_hex(state, little_endian=True)
    return TypedValue(StringColumn(chars, jnp.full(v.col.capacity, w,
                                                   jnp.int32), v.validity),
                      DataType.STRING)


@register("sha1", _string_result)
def _sha1(args, expr, batch, schema, ctx):
    return _host_hash(args[0], "sha1")


@register("sha2", _string_result)
def _sha2(args, expr, batch, schema, ctx):
    bits = int(_lit(expr, 1, 256) or 256)
    v = args[0]
    if bits in (0, 256):
        state = sha256_digest(v.col.chars, v.col.lens)
        chars, w = _state_to_hex(state, little_endian=False)
        return TypedValue(StringColumn(
            chars, jnp.full(v.col.capacity, w, jnp.int32), v.validity),
            DataType.STRING)
    if bits not in (224, 384, 512):
        n = v.col.capacity
        return TypedValue(StringColumn(jnp.zeros((n, 8), jnp.uint8),
                                       jnp.zeros(n, jnp.int32),
                                       jnp.zeros(n, bool)), DataType.STRING)
    return _host_hash(v, f"sha{bits}")


def _host_hash(v: TypedValue, algo: str) -> TypedValue:
    import hashlib
    col: StringColumn = v.col
    cap = col.capacity
    out_w = hashlib.new(algo).digest_size * 2

    def host(chars_np, lens_np):
        out = np.zeros((cap, out_w), np.uint8)
        for i in range(cap):
            h = hashlib.new(algo, bytes(chars_np[i, : lens_np[i]])).hexdigest()
            out[i] = np.frombuffer(h.encode(), np.uint8)
        return out

    chars = jax.pure_callback(
        host, jax.ShapeDtypeStruct((cap, out_w), jnp.uint8),
        col.chars, col.lens, vmap_method="sequential")
    return TypedValue(StringColumn(chars, jnp.full(cap, out_w, jnp.int32),
                                   v.validity), DataType.STRING)


@register("crc32", DataType.INT64)
def _crc32(args, expr, batch, schema, ctx):
    from jax import lax
    v = args[0]
    chars, lens = v.col.chars, v.col.lens
    n, w = chars.shape
    poly = U32(0xEDB88320)
    byte_cols = chars.T.astype(U32)    # [w, n] for per-step dynamic indexing

    def step(j, crc):
        c = crc ^ byte_cols[j]

        def bit(_, c):
            return (c >> U32(1)) ^ jnp.where((c & U32(1)) != 0, poly, U32(0))

        c = lax.fori_loop(0, 8, bit, c)
        return jnp.where(j < lens, c, crc)

    crc = lax.fori_loop(0, w, step, jnp.full(n, 0xFFFFFFFF, U32))
    out = (crc ^ U32(0xFFFFFFFF)).astype(jnp.int64) & 0xFFFFFFFF
    return TypedValue(PrimitiveColumn(out, v.validity), DataType.INT64)


_B64 = "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/"


@register("base64", _string_result)
def _base64(args, expr, batch, schema, ctx):
    v = args[0]
    chars, lens = v.col.chars, v.col.lens
    n, w = chars.shape
    groups = (w + 2) // 3
    out_w = groups * 4
    lut = jnp.asarray(np.frombuffer(_B64.encode(), np.uint8))
    src = jnp.pad(chars, ((0, 0), (0, groups * 3 - w))).astype(U32)
    b0 = src[:, 0::3]; b1 = src[:, 1::3]; b2 = src[:, 2::3]
    s0 = b0 >> U32(2)
    s1 = ((b0 & U32(3)) << U32(4)) | (b1 >> U32(4))
    s2 = ((b1 & U32(15)) << U32(2)) | (b2 >> U32(6))
    s3 = b2 & U32(63)
    sx = jnp.stack([s0, s1, s2, s3], axis=2).reshape(n, out_w)
    enc = lut[sx.astype(jnp.int32)]
    # '=' padding: slot index within its group vs bytes available
    pos = jnp.arange(out_w, dtype=jnp.int32)[None, :]
    gidx = pos // 4
    slot = pos % 4
    avail = jnp.clip(lens[:, None] - gidx * 3, 0, 3)
    is_pad = ((slot == 2) & (avail < 2)) | ((slot == 3) & (avail < 3))
    out_len = ((lens + 2) // 3) * 4
    in_out = pos < out_len[:, None]
    out = jnp.where(in_out, jnp.where(is_pad, ord("="), enc), 0)
    return TypedValue(StringColumn(out.astype(jnp.uint8),
                                   out_len.astype(jnp.int32), v.validity),
                      DataType.STRING)


@register("unbase64", _string_result)
def _unbase64(args, expr, batch, schema, ctx):
    v = args[0]
    chars, lens = v.col.chars, v.col.lens
    n, w = chars.shape
    rev = np.full(256, 0, np.uint8)
    bad = np.ones(256, bool)
    for i, ch in enumerate(_B64.encode()):
        rev[ch] = i
        bad[ch] = False
    bad[ord("=")] = False
    groups = (w + 3) // 4
    src = jnp.pad(chars, ((0, 0), (0, groups * 4 - w)))
    sext = jnp.asarray(rev)[src.astype(jnp.int32)].astype(U32)
    invalid = jnp.any(jnp.asarray(bad)[src.astype(jnp.int32)]
                      & (jnp.arange(groups * 4)[None, :] < lens[:, None]),
                      axis=1)
    c0 = sext[:, 0::4]; c1 = sext[:, 1::4]; c2 = sext[:, 2::4]; c3 = sext[:, 3::4]
    o0 = (c0 << U32(2)) | (c1 >> U32(4))
    o1 = ((c1 & U32(15)) << U32(4)) | (c2 >> U32(2))
    o2 = ((c2 & U32(3)) << U32(6)) | c3
    out = jnp.stack([o0, o1, o2], axis=2).reshape(n, groups * 3)
    pads = (jnp.take_along_axis(
        chars, jnp.clip(lens - 1, 0, w - 1)[:, None], axis=1)[:, 0]
        == ord("=")).astype(jnp.int32) + \
        (jnp.take_along_axis(
            chars, jnp.clip(lens - 2, 0, w - 1)[:, None], axis=1)[:, 0]
         == ord("=")).astype(jnp.int32)
    out_len = jnp.maximum(lens // 4 * 3 - pads, 0)
    mask = jnp.arange(groups * 3, dtype=jnp.int32)[None, :] < out_len[:, None]
    return TypedValue(StringColumn(
        jnp.where(mask, out, 0).astype(jnp.uint8), out_len.astype(jnp.int32),
        v.validity & ~invalid), DataType.STRING)


@register("hex", _string_result)
def _hex(args, expr, batch, schema, ctx):
    v = args[0]
    if isinstance(v.col, StringColumn):
        chars, lens = v.col.chars, v.col.lens
        n, w = chars.shape
        hi, lo = chars >> 4, chars & 15

        def hexc(x):
            return jnp.where(x < 10, x + ord("0"), x - 10 + ord("A"))

        out = jnp.stack([hexc(hi.astype(jnp.int32)),
                         hexc(lo.astype(jnp.int32))], axis=2).reshape(n, 2 * w)
        out_len = lens * 2
        mask = jnp.arange(2 * w, dtype=jnp.int32)[None, :] < out_len[:, None]
        return TypedValue(StringColumn(
            jnp.where(mask, out, 0).astype(jnp.uint8), out_len, v.validity),
            DataType.STRING)
    # bigint → uppercase hex without leading zeros
    x = v.data.astype(jnp.int64).view(jnp.uint64)
    n = v.col.capacity
    nibs = jnp.stack([(x >> jnp.uint64(4 * (15 - k))) & jnp.uint64(15)
                      for k in range(16)], axis=1).astype(jnp.int32)
    nz = nibs != 0
    first = jnp.argmax(nz, axis=1)
    all_zero = ~jnp.any(nz, axis=1)
    start = jnp.where(all_zero, 15, first)
    idx = start[:, None] + jnp.arange(16)[None, :]
    g = jnp.take_along_axis(nibs, jnp.clip(idx, 0, 15), axis=1)
    chars = jnp.where(g < 10, g + ord("0"), g - 10 + ord("A"))
    out_len = (16 - start).astype(jnp.int32)
    mask = jnp.arange(16)[None, :] < out_len[:, None]
    return TypedValue(StringColumn(
        jnp.where(mask, chars, 0).astype(jnp.uint8), out_len, v.validity),
        DataType.STRING)


@register("unhex", _string_result)
def _unhex(args, expr, batch, schema, ctx):
    v = args[0]
    chars, lens = v.col.chars, v.col.lens
    n, w = chars.shape
    val = np.full(256, 255, np.uint8)
    for i, ch in enumerate(b"0123456789"):
        val[ch] = i
    for i, ch in enumerate(b"abcdef"):
        val[ch] = 10 + i
    for i, ch in enumerate(b"ABCDEF"):
        val[ch] = 10 + i
    lut = jnp.asarray(val)
    # odd length → implicit leading zero (Spark pads on the left)
    odd = (lens % 2) == 1
    shifted = jnp.concatenate(
        [jnp.zeros((n, 1), jnp.uint8), chars[:, :-1]], axis=1) if w > 0 else chars
    eff = jnp.where(odd[:, None], shifted, chars)
    eff = eff.at[:, 0].set(jnp.where(odd, ord("0"), eff[:, 0]))
    eff_len = lens + odd.astype(jnp.int32)
    pairs = (w + 1) // 2
    src = jnp.pad(eff, ((0, 0), (0, pairs * 2 - w)))
    nib = lut[src.astype(jnp.int32)]
    in_str = jnp.arange(pairs * 2)[None, :] < eff_len[:, None]
    invalid = jnp.any((nib == 255) & in_str, axis=1)
    hi = nib[:, 0::2].astype(jnp.int32)
    lo = nib[:, 1::2].astype(jnp.int32)
    out = ((hi << 4) | lo).astype(jnp.uint8)
    out_len = eff_len // 2
    mask = jnp.arange(pairs, dtype=jnp.int32)[None, :] < out_len[:, None]
    return TypedValue(StringColumn(jnp.where(mask, out, 0).astype(jnp.uint8),
                                   out_len, v.validity & ~invalid),
                      DataType.STRING)
