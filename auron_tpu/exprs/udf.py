"""Host UDF / UDAF / UDTF registry.

Serialized plans carry only a registry name (ir/auron.proto HostUDFE) — the
callable is resolved host-side at plan-parse time. This mirrors the
reference's design where the serialized Spark expression travels in the proto
and the JVM materializes the evaluator on first use (reference:
datafusion-ext-exprs/src/spark_udf_wrapper.rs:43-97), minus the code
shipping: in a multi-host deployment every host registers the same UDFs at
startup.
"""

from __future__ import annotations

from typing import Any, Callable

from auron_tpu.columnar.schema import DataType

_UDFS: dict[str, tuple[Callable, DataType, int, int]] = {}
_UDTFS: dict[str, Any] = {}
_UDAFS: dict[str, Any] = {}


def register_udf(name: str, fn: Callable, dtype: DataType,
                 precision: int = 0, scale: int = 0) -> None:
    """fn: list[pyarrow.Array] -> pyarrow.Array (vectorized over the batch)."""
    _UDFS[name] = (fn, dtype, precision, scale)


def lookup_udf(name: str) -> tuple[Callable, DataType, int, int]:
    if name not in _UDFS:
        raise KeyError(f"host UDF '{name}' is not registered on this host")
    return _UDFS[name]


def register_udtf(name: str, fn: Any) -> None:
    """fn: callable(row tuple) -> iterable of output row tuples, with an
    ``output_fields`` attribute: list[(name, DataType)] (generator fallback,
    reference: generate/spark_udtf_wrapper.rs)."""
    _UDTFS[name] = fn


def lookup_udtf(name: str) -> Any:
    if name not in _UDTFS:
        raise KeyError(f"host UDTF '{name}' is not registered on this host")
    return _UDTFS[name]


def register_udaf(name: str, udaf: Any) -> None:
    """udaf: object with zero()/update(buf, row)/merge(a, b)/eval(buf)
    (reference: SparkUDAFWrapperContext.scala:100-235)."""
    _UDAFS[name] = udaf


def lookup_udaf(name: str) -> Any:
    if name not in _UDAFS:
        raise KeyError(f"host UDAF '{name}' is not registered on this host")
    return _UDAFS[name]
