"""Struct constructors/accessors.

reference: datafusion-ext-exprs/src/named_struct.rs (NamedStructExpr
builds a StructArray from child expressions) and get_indexed_field.rs
(struct field access). Here a struct is the engine's StructColumn — the
child columns themselves plus a row validity — so construction is free
(tuple packing) and field access is a tuple index.
"""

from __future__ import annotations

import jax.numpy as jnp

from auron_tpu.columnar.batch import StructColumn
from auron_tpu.columnar.schema import DataType, Field
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import TypedValue, infer_field
from auron_tpu.exprs.functions import register


def _literal_name(e: ir.Expr, default: str) -> str:
    if isinstance(e, ir.Literal) and isinstance(e.value, str):
        return e.value
    return default


def _named_struct_field(expr, schema):
    kids = []
    for i in range(0, len(expr.args), 2):
        nm = _literal_name(expr.args[i], f"col{i // 2}")
        kids.append(infer_field(expr.args[i + 1], schema, name=nm))
    return Field("c", DataType.STRUCT, True, children=tuple(kids))


def _struct_field(expr, schema):
    kids = []
    for i, a in enumerate(expr.args):
        # Spark naming: source column name for plain refs, else colN
        name = schema[a.index].name if isinstance(a, ir.ColumnRef) \
            else f"col{i + 1}"
        kids.append(infer_field(a, schema, name=name))
    return Field("c", DataType.STRUCT, True, children=tuple(kids))


def _struct_result(expr, schema):
    return DataType.STRUCT, 0, 0


@register("named_struct", _struct_result, result_field=_named_struct_field)
def _named_struct(args, expr, batch, schema, ctx):
    """named_struct(name1, val1, name2, val2, ...) — names are string
    literals consumed at plan time; only the value args contribute
    columns (reference: named_struct.rs:eval)."""
    assert len(args) % 2 == 0 and args, "named_struct needs name/value pairs"
    kids = tuple(args[i].col for i in range(1, len(args), 2))
    cap = batch.capacity
    return TypedValue(StructColumn(kids, jnp.ones(cap, bool)),
                      DataType.STRUCT)


@register("struct", _struct_result, result_field=_struct_field)
def _struct(args, expr, batch, schema, ctx):
    kids = tuple(a.col for a in args)
    cap = batch.capacity
    return TypedValue(StructColumn(kids, jnp.ones(cap, bool)),
                      DataType.STRUCT)


def _get_struct_field_result(expr, schema):
    f = _resolve_child(expr, schema)
    return f.dtype, f.precision, f.scale


def _get_struct_field_field(expr, schema):
    return _resolve_child(expr, schema)


def _resolve_child(expr, schema) -> Field:
    sf = infer_field(expr.args[0], schema)
    sel = expr.args[1]
    if isinstance(sel, ir.Literal) and isinstance(sel.value, str):
        for cf in sf.children:
            if cf.name == sel.value:
                return cf
        raise KeyError(f"struct has no field {sel.value!r}")
    idx = int(sel.value)
    return sf.children[idx]


@register("get_struct_field", _get_struct_field_result,
          result_field=_get_struct_field_field)
def _get_struct_field(args, expr, batch, schema, ctx):
    """get_struct_field(struct, name_or_ordinal) — the functional form of
    the GetStructField expression node."""
    v = args[0]
    assert isinstance(v.col, StructColumn), "get_struct_field needs struct"
    sf = infer_field(expr.args[0], schema)
    sel = expr.args[1]
    if isinstance(sel, ir.Literal) and isinstance(sel.value, str):
        idx = [cf.name for cf in sf.children].index(sel.value)
    else:
        idx = int(sel.value)
    cf = sf.children[idx]
    child = v.col.children[idx]
    return TypedValue(child.with_validity(child.validity & v.validity),
                      cf.dtype, cf.precision, cf.scale)
