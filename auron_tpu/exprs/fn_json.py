"""JSON path extraction and regular expressions.

Data-dependent parsing (JSON trees, regex NFAs) has no mapping onto the
MXU/VPU — the reference runs these as native Rust row loops
(datafusion-ext-functions/src/spark_get_json_object.rs, 867 LoC). Here
they run as host callbacks over the (chars, lens) wire — the same escape
hatch the engine uses for Spark UDFs (SURVEY.md §3.5) — with patterns
compiled once per plan, not per batch.

Spark semantics notes:
- get_json_object returns NULL for missing paths, the raw string for JSON
  strings (no quotes), and compact JSON for objects/arrays.
- regexp_extract returns "" (not NULL) when the pattern misses.
- regexp_replace uses Java's $1 group references.
"""

from __future__ import annotations

import json
import re

import numpy as np

import jax
import jax.numpy as jnp

from auron_tpu.columnar.batch import PrimitiveColumn, StringColumn
from auron_tpu.columnar.schema import DataType
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import TypedValue
from auron_tpu.exprs.functions import register
from auron_tpu.utils.shapes import bucket_string_width


def _string_result(expr, schema):
    return DataType.STRING, 0, 0


def _lit(expr, k, default=None):
    if k >= len(expr.args):
        return default
    a = expr.args[k]
    if not isinstance(a, ir.Literal):
        raise NotImplementedError(f"{expr.name}: arg {k} must be a literal")
    return a.value


def host_string_fn(v: TypedValue, out_w: int, rowfn) -> TypedValue:
    """Run ``rowfn(str) -> Optional[str]`` over a string column on host;
    None → null."""
    col: StringColumn = v.col
    cap = col.capacity

    def host(chars_np, lens_np, valid_np):
        chars = np.zeros((cap, out_w), np.uint8)
        lens = np.zeros(cap, np.int32)
        ok = np.zeros(cap, bool)
        for i in range(cap):
            if not valid_np[i]:
                continue
            s = bytes(chars_np[i, : lens_np[i]]).decode("utf-8", "replace")
            r = rowfn(s)
            if r is None:
                continue
            b = r.encode()[:out_w]
            chars[i, : len(b)] = np.frombuffer(b, np.uint8)
            lens[i] = len(b)
            ok[i] = True
        return chars, lens, ok

    chars, lens, ok = jax.pure_callback(
        host,
        (jax.ShapeDtypeStruct((cap, out_w), jnp.uint8),
         jax.ShapeDtypeStruct((cap,), jnp.int32),
         jax.ShapeDtypeStruct((cap,), jnp.bool_)),
        col.chars, col.lens, v.validity, vmap_method="sequential")
    return TypedValue(StringColumn(chars, lens, ok), DataType.STRING)


# ---------------------------------------------------------------------------
# get_json_object
# ---------------------------------------------------------------------------

_PATH_STEP = re.compile(r"\.([^.\[]+)|\[(\d+)\]|\['([^']+)'\]")


def _compile_path(path: str):
    """'$.a.b[2]' → list of dict-key / list-index steps; None if invalid."""
    if not path.startswith("$"):
        return None
    steps, pos = [], 1
    while pos < len(path):
        m = _PATH_STEP.match(path, pos)
        if not m:
            return None
        if m.group(1) is not None:
            steps.append(m.group(1))
        elif m.group(2) is not None:
            steps.append(int(m.group(2)))
        else:
            steps.append(m.group(3))
        pos = m.end()
    return steps


def _json_to_spark_string(v):
    if v is None:
        return None
    if isinstance(v, str):
        return v
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (dict, list)):
        return json.dumps(v, separators=(",", ":"))
    if isinstance(v, float) and v.is_integer():
        return json.dumps(v)
    return str(v)


@register("get_json_object", _string_result)
def _get_json_object(args, expr, batch, schema, ctx):
    path = _compile_path(str(_lit(expr, 1, "")))
    v = args[0]
    out_w = v.col.width  # the value is a substring of the document

    def rowfn(s):
        if path is None:
            return None
        try:
            node = json.loads(s)
        except (ValueError, RecursionError):
            return None
        for step in path:
            if isinstance(step, int):
                if not isinstance(node, list) or step >= len(node):
                    return None
                node = node[step]
            else:
                if not isinstance(node, dict) or step not in node:
                    return None
                node = node[step]
        return _json_to_spark_string(node)

    return host_string_fn(v, out_w, rowfn)


@register("json_array_length", DataType.INT32)
def _json_array_length(args, expr, batch, schema, ctx):
    v = args[0]
    col: StringColumn = v.col
    cap = col.capacity

    def host(chars_np, lens_np, valid_np):
        out = np.zeros(cap, np.int32)
        ok = np.zeros(cap, bool)
        for i in range(cap):
            if not valid_np[i]:
                continue
            try:
                node = json.loads(
                    bytes(chars_np[i, : lens_np[i]]).decode("utf-8", "replace"))
            except ValueError:
                continue
            if isinstance(node, list):
                out[i] = len(node)
                ok[i] = True
        return out, ok

    data, ok = jax.pure_callback(
        host, (jax.ShapeDtypeStruct((cap,), jnp.int32),
               jax.ShapeDtypeStruct((cap,), jnp.bool_)),
        col.chars, col.lens, v.validity, vmap_method="sequential")
    return TypedValue(PrimitiveColumn(data, ok), DataType.INT32)


# ---------------------------------------------------------------------------
# regex family
# ---------------------------------------------------------------------------

def _java_replacement_to_python(rep: str) -> str:
    # Java "$1" group refs → Python "\1"; escaped "\$" stays literal
    out, i = [], 0
    while i < len(rep):
        c = rep[i]
        if c == "\\" and i + 1 < len(rep):
            out.append(rep[i + 1] if rep[i + 1] in "$\\" else rep[i:i + 2])
            i += 2
        elif c == "$" and i + 1 < len(rep) and rep[i + 1].isdigit():
            out.append("\\" + rep[i + 1])
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


@register("regexp_extract", _string_result)
def _regexp_extract(args, expr, batch, schema, ctx):
    rx = re.compile(str(_lit(expr, 1, "")))
    idx = int(_lit(expr, 2, 1) or 0) if len(expr.args) > 2 else 1
    v = args[0]

    def rowfn(s):
        m = rx.search(s)
        if m is None:
            return ""          # Spark: empty string on no match
        if idx > (m.re.groups or 0) and idx != 0:
            return None
        g = m.group(idx)
        return g if g is not None else ""

    return host_string_fn(v, v.col.width, rowfn)


@register("regexp_replace", _string_result)
def _regexp_replace(args, expr, batch, schema, ctx):
    rx = re.compile(str(_lit(expr, 1, "")))
    rep = _java_replacement_to_python(str(_lit(expr, 2, "")))
    v = args[0]
    out_w = bucket_string_width(max(v.col.width * 2, 64))
    return host_string_fn(v, out_w, lambda s: rx.sub(rep, s))


@register("rlike", DataType.BOOL)
@register("regexp_like", DataType.BOOL)
@register("regexp", DataType.BOOL)
def _rlike(args, expr, batch, schema, ctx):
    rx = re.compile(str(_lit(expr, 1, "")))
    v = args[0]
    col: StringColumn = v.col
    cap = col.capacity

    def host(chars_np, lens_np):
        out = np.zeros(cap, bool)
        for i in range(cap):
            s = bytes(chars_np[i, : lens_np[i]]).decode("utf-8", "replace")
            out[i] = rx.search(s) is not None
        return out

    hit = jax.pure_callback(
        host, jax.ShapeDtypeStruct((cap,), jnp.bool_),
        col.chars, col.lens, vmap_method="sequential")
    return TypedValue(PrimitiveColumn(hit, v.validity), DataType.BOOL)
