"""Device-resident columnar batches.

The TPU analogue of an Arrow RecordBatch (which is what flows between the
reference's operators, reference: native-engine/auron/src/rt.rs:149-205):

- every column is padded to a static ``capacity`` so kernels compile once per
  shape bucket; the true row count is a device scalar (``num_rows``),
- validity is a dense bool mask (Arrow's validity bitmap, unpacked — TPU has
  no cheap bit addressing and the VPU is happiest on bool/int8 lanes),
- strings are fixed-width byte matrices ``[capacity, width]`` plus a length
  column. Variable-length offsets+bytes (Arrow's native layout) are hostile
  to a static-shape compiler; padded widths are bucketed (8..256) so the
  overwhelmingly short SQL strings stay cheap and every string kernel
  (compare / hash / substr) is a dense vector op.

Batches are pytrees, so they pass straight through jit / shard_map / scan.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from auron_tpu.columnar.decimal128 import Decimal128Column
from typing import Sequence, Union

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class PrimitiveColumn:
    """Fixed-width column: data[capacity] + validity[capacity]."""

    data: jax.Array
    validity: jax.Array  # bool[capacity]

    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    def with_validity(self, validity: jax.Array) -> "PrimitiveColumn":
        return replace(self, validity=validity)


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class StringColumn:
    """Fixed-width string column: chars[capacity, width] (zero padded),
    lens[capacity], validity[capacity]."""

    chars: jax.Array  # uint8[capacity, width]
    lens: jax.Array   # int32[capacity]
    validity: jax.Array  # bool[capacity]

    @property
    def capacity(self) -> int:
        return self.chars.shape[0]

    @property
    def width(self) -> int:
        return self.chars.shape[1]

    def with_validity(self, validity: jax.Array) -> "StringColumn":
        return replace(self, validity=validity)


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class ListColumn:
    """Padded list-of-primitive column: values[capacity, max_elems] +
    per-element validity + list lengths + row validity.

    The device layout for Arrow list arrays (the reference's explode /
    UserDefinedArray paths, reference: datafusion-ext-plans/src/generate/,
    datafusion-ext-commons/src/uda.rs): offsets+child become a dense padded
    matrix so explode is one mask+compact kernel."""

    values: jax.Array      # [capacity, max_elems] primitive payload
    elem_valid: jax.Array  # bool[capacity, max_elems]
    lens: jax.Array        # int32[capacity]
    validity: jax.Array    # bool[capacity]  (row null = whole list null)

    @property
    def capacity(self) -> int:
        return self.values.shape[0]

    @property
    def max_elems(self) -> int:
        return self.values.shape[1]

    def with_validity(self, validity: jax.Array) -> "ListColumn":
        return replace(self, validity=validity)


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class StringListColumn:
    """Padded list-of-STRING column: a [capacity, max_elems, width] char
    tensor + per-element byte lengths. The string analogue of ListColumn
    (reference: Arrow ListArray over a StringArray child — offsets over
    offsets; here both levels become dense padded matrices so explode /
    element_at are one gather)."""

    chars: jax.Array       # uint8[capacity, max_elems, width]
    slens: jax.Array       # int32[capacity, max_elems] per-element bytes
    elem_valid: jax.Array  # bool[capacity, max_elems]
    lens: jax.Array        # int32[capacity]
    validity: jax.Array    # bool[capacity]  (row null = whole list null)

    @property
    def capacity(self) -> int:
        return self.chars.shape[0]

    @property
    def max_elems(self) -> int:
        return self.chars.shape[1]

    @property
    def width(self) -> int:
        return self.chars.shape[2]

    def with_validity(self, validity: jax.Array) -> "StringListColumn":
        return replace(self, validity=validity)


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class StringMapColumn:
    """Padded map<string,string> column: parallel key/value CHAR tensors
    sharing one length column (reference: spark_map.rs str_to_map builds
    Arrow MapArray over utf8 children). Spark map keys cannot be null,
    so keys carry no element validity; values can be null per entry."""

    kchars: jax.Array      # uint8[capacity, max_elems, kwidth]
    kslens: jax.Array      # int32[capacity, max_elems]
    vchars: jax.Array      # uint8[capacity, max_elems, vwidth]
    vslens: jax.Array      # int32[capacity, max_elems]
    val_valid: jax.Array   # bool[capacity, max_elems]
    lens: jax.Array        # int32[capacity]
    validity: jax.Array    # bool[capacity]

    @property
    def capacity(self) -> int:
        return self.kchars.shape[0]

    @property
    def max_elems(self) -> int:
        return self.kchars.shape[1]

    def with_validity(self, validity: jax.Array) -> "StringMapColumn":
        return replace(self, validity=validity)


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class MapColumn:
    """Padded map column: parallel key/value matrices sharing one length
    column (reference stores these as Arrow MapArray — offsets over a
    struct<key,value> child, datafusion-ext-functions/src/spark_map.rs;
    here the offsets+child become dense padded matrices like ListColumn).
    Keys and values are primitive payloads; Spark map keys cannot be null
    so keys carry no element validity."""

    keys: jax.Array        # [capacity, max_elems] primitive key payload
    values: jax.Array      # [capacity, max_elems] primitive value payload
    val_valid: jax.Array   # bool[capacity, max_elems]
    lens: jax.Array        # int32[capacity]  entry count per row
    validity: jax.Array    # bool[capacity]   (row null = whole map null)

    @property
    def capacity(self) -> int:
        return self.keys.shape[0]

    @property
    def max_elems(self) -> int:
        return self.keys.shape[1]

    def with_validity(self, validity: jax.Array) -> "MapColumn":
        return replace(self, validity=validity)


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class StructColumn:
    """Struct column: per-field child columns + row validity (Arrow
    StructArray, reference: datafusion-ext-exprs/src/named_struct.rs /
    get_indexed_field.rs). Field names/types live in the schema's Field
    children, never on the device."""

    children: tuple        # tuple[Column, ...] (no nested struct/map yet)
    validity: jax.Array    # bool[capacity]

    @property
    def capacity(self) -> int:
        return self.validity.shape[0]

    def with_validity(self, validity: jax.Array) -> "StructColumn":
        return replace(self, validity=validity)


Column = Union[PrimitiveColumn, StringColumn, ListColumn,
               StringListColumn, Decimal128Column, MapColumn,
               StringMapColumn, StructColumn]


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class DeviceBatch:
    """A bundle of equal-capacity columns plus the dynamic row count."""

    columns: tuple[Column, ...]
    num_rows: jax.Array  # int32 scalar, <= capacity

    @property
    def capacity(self) -> int:
        if not self.columns:
            return 0
        return self.columns[0].capacity

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def row_mask(self) -> jax.Array:
        """bool[capacity]: True for live rows."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.num_rows

    def column(self, i: int) -> Column:
        return self.columns[i]

    def with_columns(self, columns) -> "DeviceBatch":
        return DeviceBatch(tuple(columns), self.num_rows)

    def select(self, indices) -> "DeviceBatch":
        return DeviceBatch(tuple(self.columns[i] for i in indices), self.num_rows)


def column_nbytes(col: Column) -> int:
    """Device bytes held by one column (at capacity, incl. padding)."""
    if isinstance(col, StringColumn):
        return col.chars.nbytes + col.lens.nbytes + col.validity.nbytes
    if isinstance(col, ListColumn):
        return (col.values.nbytes + col.elem_valid.nbytes
                + col.lens.nbytes + col.validity.nbytes)
    if isinstance(col, StringListColumn):
        return (col.chars.nbytes + col.slens.nbytes
                + col.elem_valid.nbytes + col.lens.nbytes
                + col.validity.nbytes)
    if isinstance(col, Decimal128Column):
        return col.hi.nbytes + col.lo.nbytes + col.validity.nbytes
    if isinstance(col, MapColumn):
        return (col.keys.nbytes + col.values.nbytes + col.val_valid.nbytes
                + col.lens.nbytes + col.validity.nbytes)
    if isinstance(col, StringMapColumn):
        return (col.kchars.nbytes + col.kslens.nbytes + col.vchars.nbytes
                + col.vslens.nbytes + col.val_valid.nbytes
                + col.lens.nbytes + col.validity.nbytes)
    if isinstance(col, StructColumn):
        return (sum(column_nbytes(c) for c in col.children)
                + col.validity.nbytes)
    return col.data.nbytes + col.validity.nbytes


def batch_nbytes(batch: DeviceBatch) -> int:
    """Device bytes held by the batch (at capacity, incl. padding)."""
    return sum(column_nbytes(c) for c in batch.columns)


def mask_validity(batch: DeviceBatch) -> DeviceBatch:
    """Force validity False on padding rows (defensive normalization)."""
    mask = batch.row_mask()
    return batch.with_columns(
        c.with_validity(c.validity & mask) for c in batch.columns
    )


def gather_column(col: Column, indices: jax.Array, valid: jax.Array) -> Column:
    """Take rows ``indices`` from ``col``; rows where ``valid`` is False become
    null. Core primitive behind filter compaction, sort reordering and join
    probing (the reference does the same with Arrow take kernels, reference:
    native-engine/datafusion-ext-commons/src/arrow/selection.rs)."""
    if isinstance(col, StringColumn):
        return StringColumn(
            chars=col.chars[indices],
            lens=jnp.where(valid, col.lens[indices], 0),
            validity=col.validity[indices] & valid,
        )
    if isinstance(col, ListColumn):
        return ListColumn(
            values=col.values[indices],
            elem_valid=col.elem_valid[indices] & valid[:, None],
            lens=jnp.where(valid, col.lens[indices], 0),
            validity=col.validity[indices] & valid,
        )
    if isinstance(col, StringListColumn):
        return StringListColumn(
            chars=col.chars[indices],
            slens=col.slens[indices],
            elem_valid=col.elem_valid[indices] & valid[:, None],
            lens=jnp.where(valid, col.lens[indices], 0),
            validity=col.validity[indices] & valid,
        )
    if isinstance(col, Decimal128Column):
        return Decimal128Column(
            hi=col.hi[indices], lo=col.lo[indices],
            validity=col.validity[indices] & valid,
        )
    if isinstance(col, MapColumn):
        return MapColumn(
            keys=col.keys[indices],
            values=col.values[indices],
            val_valid=col.val_valid[indices] & valid[:, None],
            lens=jnp.where(valid, col.lens[indices], 0),
            validity=col.validity[indices] & valid,
        )
    if isinstance(col, StringMapColumn):
        return StringMapColumn(
            kchars=col.kchars[indices], kslens=col.kslens[indices],
            vchars=col.vchars[indices], vslens=col.vslens[indices],
            val_valid=col.val_valid[indices] & valid[:, None],
            lens=jnp.where(valid, col.lens[indices], 0),
            validity=col.validity[indices] & valid,
        )
    if isinstance(col, StructColumn):
        return StructColumn(
            children=tuple(gather_column(c, indices, valid)
                           for c in col.children),
            validity=col.validity[indices] & valid,
        )
    return PrimitiveColumn(
        data=col.data[indices],
        validity=col.validity[indices] & valid,
    )


def gather_batch(batch: DeviceBatch, indices: jax.Array, num_rows: jax.Array) -> DeviceBatch:
    """Take ``indices`` (shape [new_capacity]) from every column; entries with
    position >= num_rows are padding."""
    new_cap = indices.shape[0]
    valid = jnp.arange(new_cap, dtype=jnp.int32) < num_rows
    return DeviceBatch(
        tuple(gather_column(c, indices, valid) for c in batch.columns),
        jnp.asarray(num_rows, jnp.int32),
    )


def pad_string_width(col: StringColumn, width: int) -> StringColumn:
    """Zero-pad a string column's char matrix out to `width` bytes/slot."""
    if col.width >= width:
        return col
    return StringColumn(
        jnp.pad(col.chars, ((0, 0), (0, width - col.width))),
        col.lens, col.validity)


def pad_list_elems(col: ListColumn, max_elems: int) -> ListColumn:
    """Pad a list column's element axis out to `max_elems` slots."""
    if col.max_elems >= max_elems:
        return col
    pad = max_elems - col.max_elems
    return ListColumn(
        jnp.pad(col.values, ((0, 0), (0, pad))),
        jnp.pad(col.elem_valid, ((0, 0), (0, pad))),
        col.lens, col.validity)


def pad_map_elems(col: "MapColumn", max_elems: int) -> "MapColumn":
    """Pad a map column's entry axis out to `max_elems` slots."""
    if col.max_elems >= max_elems:
        return col
    pad = max_elems - col.max_elems
    return MapColumn(
        jnp.pad(col.keys, ((0, 0), (0, pad))),
        jnp.pad(col.values, ((0, 0), (0, pad))),
        jnp.pad(col.val_valid, ((0, 0), (0, pad))),
        col.lens, col.validity)


def unify_column_widths(cols: Sequence[Column]) -> list[Column]:
    """Pad string widths / list element counts to the max across `cols` so
    they can be concatenated (capacities may differ; widths must not)."""
    if isinstance(cols[0], StringColumn):
        w = max(c.width for c in cols)
        return [pad_string_width(c, w) for c in cols]
    if isinstance(cols[0], ListColumn):
        m = max(c.max_elems for c in cols)
        return [pad_list_elems(c, m) for c in cols]
    if isinstance(cols[0], StringListColumn):
        m = max(c.max_elems for c in cols)
        w = max(c.width for c in cols)
        out = []
        for c in cols:
            pe, pw = m - c.max_elems, w - c.width
            out.append(StringListColumn(
                jnp.pad(c.chars, ((0, 0), (0, pe), (0, pw))),
                jnp.pad(c.slens, ((0, 0), (0, pe))),
                jnp.pad(c.elem_valid, ((0, 0), (0, pe))),
                c.lens, c.validity))
        return out
    if isinstance(cols[0], MapColumn):
        m = max(c.max_elems for c in cols)
        return [pad_map_elems(c, m) for c in cols]
    if isinstance(cols[0], StringMapColumn):
        m = max(c.max_elems for c in cols)
        kw = max(c.kchars.shape[2] for c in cols)
        vw = max(c.vchars.shape[2] for c in cols)
        out = []
        for c in cols:
            pe = m - c.max_elems
            out.append(StringMapColumn(
                jnp.pad(c.kchars, ((0, 0), (0, pe),
                                   (0, kw - c.kchars.shape[2]))),
                jnp.pad(c.kslens, ((0, 0), (0, pe))),
                jnp.pad(c.vchars, ((0, 0), (0, pe),
                                   (0, vw - c.vchars.shape[2]))),
                jnp.pad(c.vslens, ((0, 0), (0, pe))),
                jnp.pad(c.val_valid, ((0, 0), (0, pe))),
                c.lens, c.validity))
        return out
    if isinstance(cols[0], StructColumn):
        per_child = [unify_column_widths([c.children[i] for c in cols])
                     for i in range(len(cols[0].children))]
        return [StructColumn(tuple(per_child[i][j]
                                   for i in range(len(per_child))),
                             c.validity)
                for j, c in enumerate(cols)]
    return list(cols)


def concat_columns(a: Column, b: Column) -> Column:
    """Stack two columns (capacities add). String widths / list elem counts
    must match — callers re-bucket beforehand."""
    if isinstance(a, StringColumn):
        assert isinstance(b, StringColumn) and a.width == b.width
        return StringColumn(
            chars=jnp.concatenate([a.chars, b.chars], axis=0),
            lens=jnp.concatenate([a.lens, b.lens]),
            validity=jnp.concatenate([a.validity, b.validity]),
        )
    if isinstance(a, ListColumn):
        assert isinstance(b, ListColumn) and a.max_elems == b.max_elems
        return ListColumn(
            values=jnp.concatenate([a.values, b.values], axis=0),
            elem_valid=jnp.concatenate([a.elem_valid, b.elem_valid], axis=0),
            lens=jnp.concatenate([a.lens, b.lens]),
            validity=jnp.concatenate([a.validity, b.validity]),
        )
    if isinstance(a, StringListColumn):
        assert isinstance(b, StringListColumn) \
            and a.max_elems == b.max_elems and a.width == b.width
        return StringListColumn(
            chars=jnp.concatenate([a.chars, b.chars], axis=0),
            slens=jnp.concatenate([a.slens, b.slens], axis=0),
            elem_valid=jnp.concatenate([a.elem_valid, b.elem_valid], axis=0),
            lens=jnp.concatenate([a.lens, b.lens]),
            validity=jnp.concatenate([a.validity, b.validity]),
        )
    if isinstance(a, Decimal128Column):
        assert isinstance(b, Decimal128Column)
        return Decimal128Column(
            hi=jnp.concatenate([a.hi, b.hi]),
            lo=jnp.concatenate([a.lo, b.lo]),
            validity=jnp.concatenate([a.validity, b.validity]),
        )
    if isinstance(a, MapColumn):
        assert isinstance(b, MapColumn) and a.max_elems == b.max_elems
        return MapColumn(
            keys=jnp.concatenate([a.keys, b.keys], axis=0),
            values=jnp.concatenate([a.values, b.values], axis=0),
            val_valid=jnp.concatenate([a.val_valid, b.val_valid], axis=0),
            lens=jnp.concatenate([a.lens, b.lens]),
            validity=jnp.concatenate([a.validity, b.validity]),
        )
    if isinstance(a, StringMapColumn):
        assert isinstance(b, StringMapColumn) \
            and a.max_elems == b.max_elems \
            and a.kchars.shape[2] == b.kchars.shape[2] \
            and a.vchars.shape[2] == b.vchars.shape[2]
        return StringMapColumn(
            kchars=jnp.concatenate([a.kchars, b.kchars], axis=0),
            kslens=jnp.concatenate([a.kslens, b.kslens], axis=0),
            vchars=jnp.concatenate([a.vchars, b.vchars], axis=0),
            vslens=jnp.concatenate([a.vslens, b.vslens], axis=0),
            val_valid=jnp.concatenate([a.val_valid, b.val_valid], axis=0),
            lens=jnp.concatenate([a.lens, b.lens]),
            validity=jnp.concatenate([a.validity, b.validity]),
        )
    if isinstance(a, StructColumn):
        assert isinstance(b, StructColumn)
        return StructColumn(
            children=tuple(concat_columns(ca, cb)
                           for ca, cb in zip(a.children, b.children)),
            validity=jnp.concatenate([a.validity, b.validity]),
        )
    assert isinstance(b, PrimitiveColumn)
    return PrimitiveColumn(
        data=jnp.concatenate([a.data, b.data]),
        validity=jnp.concatenate([a.validity, b.validity]),
    )


def compact(batch: DeviceBatch, keep: jax.Array) -> DeviceBatch:
    """Stable-compact live rows where ``keep`` is True to the front.

    ``keep`` is bool[capacity]; padding rows must already be False. The
    output batch has the same capacity with num_rows = sum(keep). This is the
    device analogue of Arrow's filter kernel used by FilterExec (reference:
    native-engine/datafusion-ext-plans/src/filter_exec.rs).
    """
    keep = keep & batch.row_mask()
    cap = batch.capacity
    n_keep = jnp.sum(keep.astype(jnp.int32))
    # Stable partition: keys = position for kept rows, capacity+position for
    # dropped ones; argsort is ascending and stable on ties.
    order_keys = jnp.where(keep, 0, cap) + jnp.arange(cap, dtype=jnp.int32)
    indices = jnp.argsort(order_keys)
    return gather_batch(batch, indices, n_keep)


def resize(batch: DeviceBatch, new_capacity: int) -> DeviceBatch:
    """Grow or shrink capacity (shrink drops padding only if num_rows fits —
    caller's responsibility)."""
    cap = batch.capacity
    if new_capacity == cap:
        return batch

    def resize_col(c: Column) -> Column:
        if isinstance(c, StructColumn):
            return StructColumn(
                children=tuple(resize_col(ch) for ch in c.children),
                validity=(jnp.pad(c.validity, (0, new_capacity - cap))
                          if new_capacity > cap
                          else c.validity[:new_capacity]))
        if isinstance(c, MapColumn):
            if new_capacity > cap:
                pad = new_capacity - cap
                return MapColumn(
                    keys=jnp.pad(c.keys, ((0, pad), (0, 0))),
                    values=jnp.pad(c.values, ((0, pad), (0, 0))),
                    val_valid=jnp.pad(c.val_valid, ((0, pad), (0, 0))),
                    lens=jnp.pad(c.lens, (0, pad)),
                    validity=jnp.pad(c.validity, (0, pad)))
            return MapColumn(
                keys=c.keys[:new_capacity], values=c.values[:new_capacity],
                val_valid=c.val_valid[:new_capacity],
                lens=c.lens[:new_capacity],
                validity=c.validity[:new_capacity])
        if new_capacity > cap:
            pad = new_capacity - cap
            if isinstance(c, StringColumn):
                return StringColumn(
                    chars=jnp.pad(c.chars, ((0, pad), (0, 0))),
                    lens=jnp.pad(c.lens, (0, pad)),
                    validity=jnp.pad(c.validity, (0, pad)),
                )
            if isinstance(c, ListColumn):
                return ListColumn(
                    values=jnp.pad(c.values, ((0, pad), (0, 0))),
                    elem_valid=jnp.pad(c.elem_valid, ((0, pad), (0, 0))),
                    lens=jnp.pad(c.lens, (0, pad)),
                    validity=jnp.pad(c.validity, (0, pad)),
                )
            if isinstance(c, StringListColumn):
                return StringListColumn(
                    chars=jnp.pad(c.chars, ((0, pad), (0, 0), (0, 0))),
                    slens=jnp.pad(c.slens, ((0, pad), (0, 0))),
                    elem_valid=jnp.pad(c.elem_valid, ((0, pad), (0, 0))),
                    lens=jnp.pad(c.lens, (0, pad)),
                    validity=jnp.pad(c.validity, (0, pad)),
                )
            if isinstance(c, StringMapColumn):
                return StringMapColumn(
                    kchars=jnp.pad(c.kchars, ((0, pad), (0, 0), (0, 0))),
                    kslens=jnp.pad(c.kslens, ((0, pad), (0, 0))),
                    vchars=jnp.pad(c.vchars, ((0, pad), (0, 0), (0, 0))),
                    vslens=jnp.pad(c.vslens, ((0, pad), (0, 0))),
                    val_valid=jnp.pad(c.val_valid, ((0, pad), (0, 0))),
                    lens=jnp.pad(c.lens, (0, pad)),
                    validity=jnp.pad(c.validity, (0, pad)),
                )
            if isinstance(c, Decimal128Column):
                return Decimal128Column(
                    hi=jnp.pad(c.hi, (0, pad)),
                    lo=jnp.pad(c.lo, (0, pad)),
                    validity=jnp.pad(c.validity, (0, pad)),
                )
            return PrimitiveColumn(
                data=jnp.pad(c.data, (0, pad)),
                validity=jnp.pad(c.validity, (0, pad)),
            )
        if isinstance(c, StringColumn):
            return StringColumn(
                chars=c.chars[:new_capacity],
                lens=c.lens[:new_capacity],
                validity=c.validity[:new_capacity],
            )
        if isinstance(c, ListColumn):
            return ListColumn(
                values=c.values[:new_capacity],
                elem_valid=c.elem_valid[:new_capacity],
                lens=c.lens[:new_capacity],
                validity=c.validity[:new_capacity],
            )
        if isinstance(c, StringListColumn):
            return StringListColumn(
                chars=c.chars[:new_capacity], slens=c.slens[:new_capacity],
                elem_valid=c.elem_valid[:new_capacity],
                lens=c.lens[:new_capacity],
                validity=c.validity[:new_capacity])
        if isinstance(c, StringMapColumn):
            return StringMapColumn(
                kchars=c.kchars[:new_capacity],
                kslens=c.kslens[:new_capacity],
                vchars=c.vchars[:new_capacity],
                vslens=c.vslens[:new_capacity],
                val_valid=c.val_valid[:new_capacity],
                lens=c.lens[:new_capacity],
                validity=c.validity[:new_capacity])
        if isinstance(c, Decimal128Column):
            return Decimal128Column(hi=c.hi[:new_capacity],
                                    lo=c.lo[:new_capacity],
                                    validity=c.validity[:new_capacity])
        return PrimitiveColumn(data=c.data[:new_capacity], validity=c.validity[:new_capacity])

    return DeviceBatch(tuple(resize_col(c) for c in batch.columns), batch.num_rows)


def concat_batches(a: DeviceBatch, b: DeviceBatch) -> DeviceBatch:
    """Concatenate b's live rows after a's live rows.

    Implemented as stacked-capacity concat + compaction of live rows, keeping
    everything static-shape: result capacity = a.capacity + b.capacity.
    """
    stacked = DeviceBatch(
        tuple(concat_columns(ca, cb) for ca, cb in zip(a.columns, b.columns)),
        a.num_rows + b.num_rows,
    )
    keep = jnp.concatenate([a.row_mask(), b.row_mask()])
    return compact(replace(stacked, num_rows=jnp.asarray(a.capacity + b.capacity, jnp.int32)), keep)
