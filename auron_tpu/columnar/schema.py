"""Logical schema, kept host-side.

Device batches are bare pytrees of arrays; the logical types (the analogue of
the Arrow schema that travels with every RecordBatch in the reference,
reference: native-engine/datafusion-ext-commons/src/io/batch_serde.rs) live
here and are threaded through the planner, never onto the device.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

import numpy as np


class DataType(enum.Enum):
    BOOL = "bool"
    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    DATE32 = "date32"          # days since epoch, int32 payload
    TIMESTAMP_US = "timestamp_us"  # microseconds since epoch, int64 payload
    DECIMAL = "decimal"        # precision<=18 stored as scaled int64
    STRING = "string"
    LIST = "list"              # list of primitives; element type in Field.elem
    MAP = "map"                # primitive keys/values; types in Field.key/elem
    STRUCT = "struct"          # child fields in Field.children
    NULL = "null"

    # ---- classification helpers -------------------------------------------
    @property
    def is_numeric(self) -> bool:
        return self in _NUMERIC

    @property
    def is_integer(self) -> bool:
        return self in _INTEGER

    @property
    def is_floating(self) -> bool:
        return self in (DataType.FLOAT32, DataType.FLOAT64)

    def to_np(self) -> np.dtype:
        return np.dtype(_NP[self])


_NUMERIC = {
    DataType.INT8, DataType.INT16, DataType.INT32, DataType.INT64,
    DataType.FLOAT32, DataType.FLOAT64, DataType.DECIMAL,
}
_INTEGER = {DataType.INT8, DataType.INT16, DataType.INT32, DataType.INT64}

# Physical numpy payload for each logical type (strings handled separately).
_NP = {
    DataType.BOOL: "bool",
    DataType.INT8: "int8",
    DataType.INT16: "int16",
    DataType.INT32: "int32",
    DataType.INT64: "int64",
    DataType.FLOAT32: "float32",
    DataType.FLOAT64: "float64",
    DataType.DATE32: "int32",
    DataType.TIMESTAMP_US: "int64",
    DataType.DECIMAL: "int64",
    DataType.NULL: "bool",
}


@dataclass(frozen=True)
class Field:
    name: str
    dtype: DataType
    nullable: bool = True
    # decimal only
    precision: int = 0
    scale: int = 0
    # list element / map VALUE type (dtype in (LIST, MAP))
    elem: "DataType" = None
    # map KEY type (dtype == MAP; Spark map keys are non-null primitives)
    key: "DataType" = None
    # struct child fields (dtype == STRUCT)
    children: tuple = ()

    def with_name(self, name: str) -> "Field":
        return Field(name, self.dtype, self.nullable, self.precision,
                     self.scale, self.elem, self.key, self.children)


@dataclass(frozen=True)
class Schema:
    fields: tuple[Field, ...] = field(default_factory=tuple)

    def __post_init__(self):
        object.__setattr__(self, "fields", tuple(self.fields))

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __getitem__(self, i) -> Field:
        return self.fields[i]

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(f"no field named {name!r} in {self.names}")

    @property
    def names(self) -> list[str]:
        return [f.name for f in self.fields]

    @property
    def dtypes(self) -> list[DataType]:
        return [f.dtype for f in self.fields]
