"""Batch ↔ bytes serde for spill and shuffle payloads.

The analogue of the reference's length-prefixed columnar wire format +
block-compressed IPC framing (reference:
datafusion-ext-commons/src/io/batch_serde.rs:68-149,
io/ipc_compression.rs:35-280). Layout per frame:

    magic 'ATB1' | u8 codec | u32 body_len | body (maybe compressed)

body:
    u32 num_rows | u16 num_cols | u16 num_extras
    per column:   u8 kind (0 prim / 1 string) | dtype tag | buffers
    per extra:    name | uint64 word matrix   (sort-key words for merge)

Buffers are raw little-endian numpy bytes, each u32-length-prefixed. Only
live rows travel — capacity padding is re-applied on load. Compression is
zstd level 1 (the codec baked into this image; the reference defaults to
lz4 with zstd as option, conf.rs SPILL_COMPRESSION_CODEC).
"""

from __future__ import annotations

import io
import struct
from dataclasses import dataclass
from typing import Optional, Union

import numpy as np
import zstandard

from auron_tpu.columnar.batch import (DeviceBatch, ListColumn,
                                      PrimitiveColumn, StringColumn)

MAGIC = b"ATB1"
CODEC_NONE = 0
CODEC_ZSTD = 1

# zstd contexts are not safe for concurrent use; spills may run from
# multiple threads, so keep one per thread
import threading

_tls = threading.local()


def _compressor(level: int = None) -> zstandard.ZstdCompressor:
    if level is None:
        from auron_tpu import config as cfg
        level = cfg.get_config().get(cfg.SPILL_CODEC_LEVEL)
    if getattr(_tls, "level", None) != level:
        _tls.c = zstandard.ZstdCompressor(level=level)
        _tls.level = level
    return _tls.c


def _decompressor() -> zstandard.ZstdDecompressor:
    if not hasattr(_tls, "d"):
        _tls.d = zstandard.ZstdDecompressor()
    return _tls.d


# ---------------------------------------------------------------------------
# host-side batch representation
# ---------------------------------------------------------------------------

@dataclass
class HostPrimitive:
    data: np.ndarray       # [n]
    validity: np.ndarray   # bool[n]


@dataclass
class HostString:
    chars: np.ndarray      # uint8[n, width]
    lens: np.ndarray       # int32[n]
    validity: np.ndarray   # bool[n]


@dataclass
class HostList:
    values: np.ndarray     # [n, max_elems]
    elem_valid: np.ndarray  # bool[n, max_elems]
    lens: np.ndarray       # int32[n]
    validity: np.ndarray   # bool[n]


@dataclass
class HostDecimal128:
    hi: np.ndarray         # int64[n]
    lo: np.ndarray         # int64[n] (unsigned bit pattern)
    validity: np.ndarray   # bool[n]


HostColumn = Union[HostPrimitive, HostString, HostList, HostDecimal128]


@dataclass
class HostBatch:
    columns: list
    num_rows: int

    @property
    def nbytes(self) -> int:
        total = 0
        for c in self.columns:
            if isinstance(c, HostString):
                total += c.chars.nbytes + c.lens.nbytes + c.validity.nbytes
            elif isinstance(c, HostList):
                total += (c.values.nbytes + c.elem_valid.nbytes
                          + c.lens.nbytes + c.validity.nbytes)
            elif isinstance(c, HostDecimal128):
                total += c.hi.nbytes + c.lo.nbytes + c.validity.nbytes
            else:
                total += c.data.nbytes + c.validity.nbytes
        return total


def slice_host_batch(host: HostBatch, lo: int, hi: int) -> HostBatch:
    """Row-range view [lo, hi) over every column."""
    cols: list[HostColumn] = []
    for c in host.columns:
        if isinstance(c, HostString):
            cols.append(HostString(c.chars[lo:hi], c.lens[lo:hi],
                                   c.validity[lo:hi]))
        elif isinstance(c, HostList):
            cols.append(HostList(c.values[lo:hi], c.elem_valid[lo:hi],
                                 c.lens[lo:hi], c.validity[lo:hi]))
        elif isinstance(c, HostDecimal128):
            cols.append(HostDecimal128(c.hi[lo:hi], c.lo[lo:hi],
                                       c.validity[lo:hi]))
        else:
            cols.append(HostPrimitive(c.data[lo:hi], c.validity[lo:hi]))
    return HostBatch(cols, hi - lo)


def fetch_leaves(leaves: list) -> list[np.ndarray]:
    """Fetch many device arrays in ONE batched round trip.

    On tunneled accelerators a blocking per-array fetch costs ~70 ms of
    fixed latency regardless of size, so `np.asarray` per buffer (10+ per
    batch) dominates everything; `jax.device_get` on the whole list issues
    the transfers together and awaits them once (measured 7x faster for a
    10-array batch on v5e-over-tunnel)."""
    import jax
    return list(jax.device_get(list(leaves)))


def fetch_batch_numpy(batch: DeviceBatch) -> tuple[list[list[np.ndarray]], int]:
    """All column arrays of a batch (full capacity) + the row count, in a
    single device→host transfer. Returns (per-column array lists, n)."""
    leaves: list = []
    counts: list[int] = []
    from auron_tpu.columnar.decimal128 import Decimal128Column
    for c in batch.columns:
        if isinstance(c, StringColumn):
            arrs = [c.chars, c.lens, c.validity]
        elif isinstance(c, ListColumn):
            arrs = [c.values, c.elem_valid, c.lens, c.validity]
        elif isinstance(c, Decimal128Column):
            arrs = [c.hi, c.lo, c.validity]
        else:
            arrs = [c.data, c.validity]
        counts.append(len(arrs))
        leaves.extend(arrs)
    import jax.numpy as jnp
    leaves.append(jnp.asarray(batch.num_rows, jnp.int32).reshape(1))
    fetched = fetch_leaves(leaves)
    n = int(fetched[-1][0])
    cols = []
    pos = 0
    for k in counts:
        cols.append(fetched[pos:pos + k])
        pos += k
    return cols, n


def batch_to_host(batch: DeviceBatch,
                  num_rows: Optional[int] = None) -> HostBatch:
    """Device → host, keeping only live rows — ONE batched transfer for
    the whole batch (fetch_leaves). When the caller knows ``num_rows``
    (every spill path does), only the live row prefix is transferred —
    spills run exactly when memory is tight, so shipping capacity padding
    there would be self-defeating."""
    if num_rows is not None:
        n = num_rows
        leaves: list = []
        counts: list[int] = []
        from auron_tpu.columnar.decimal128 import Decimal128Column
        for c in batch.columns:
            if isinstance(c, StringColumn):
                arrs = [c.chars[:n], c.lens[:n], c.validity[:n]]
            elif isinstance(c, ListColumn):
                arrs = [c.values[:n], c.elem_valid[:n], c.lens[:n],
                        c.validity[:n]]
            elif isinstance(c, Decimal128Column):
                arrs = [c.hi[:n], c.lo[:n], c.validity[:n]]
            else:
                arrs = [c.data[:n], c.validity[:n]]
            counts.append(len(arrs))
            leaves.extend(arrs)
        flat = fetch_leaves(leaves)
        fetched = []
        pos = 0
        for k in counts:
            fetched.append(flat[pos:pos + k])
            pos += k
    else:
        fetched, n = fetch_batch_numpy(batch)
        fetched = [[a[:n] for a in arrs] for arrs in fetched]
    from auron_tpu.columnar.decimal128 import Decimal128Column
    cols: list[HostColumn] = []
    for c, arrs in zip(batch.columns, fetched):
        if isinstance(c, StringColumn):
            cols.append(HostString(*[np.ascontiguousarray(a)
                                     for a in arrs]))
        elif isinstance(c, ListColumn):
            cols.append(HostList(*[np.ascontiguousarray(a) for a in arrs]))
        elif isinstance(c, Decimal128Column):
            cols.append(HostDecimal128(*[np.ascontiguousarray(a)
                                         for a in arrs]))
        else:
            cols.append(HostPrimitive(*[np.ascontiguousarray(a)
                                        for a in arrs]))
    return HostBatch(cols, n)


def host_to_batch(host: HostBatch, capacity: Optional[int] = None) -> DeviceBatch:
    """Host → device with padding back to ``capacity`` (>= num_rows)."""
    import jax.numpy as jnp
    n = host.num_rows
    cap = capacity or n
    assert cap >= n, (cap, n)
    pad = cap - n
    cols = []
    for c in host.columns:
        if isinstance(c, HostString):
            chars = np.pad(c.chars, ((0, pad), (0, 0))) if pad else c.chars
            lens = np.pad(c.lens, (0, pad)) if pad else c.lens
            val = np.pad(c.validity, (0, pad)) if pad else c.validity
            cols.append(StringColumn(jnp.asarray(chars), jnp.asarray(lens),
                                     jnp.asarray(val)))
        elif isinstance(c, HostList):
            values = np.pad(c.values, ((0, pad), (0, 0))) if pad else c.values
            ev = np.pad(c.elem_valid, ((0, pad), (0, 0))) if pad else c.elem_valid
            lens = np.pad(c.lens, (0, pad)) if pad else c.lens
            val = np.pad(c.validity, (0, pad)) if pad else c.validity
            cols.append(ListColumn(jnp.asarray(values), jnp.asarray(ev),
                                   jnp.asarray(lens), jnp.asarray(val)))
        elif isinstance(c, HostDecimal128):
            from auron_tpu.columnar.decimal128 import Decimal128Column
            hi = np.pad(c.hi, (0, pad)) if pad else c.hi
            lo = np.pad(c.lo, (0, pad)) if pad else c.lo
            val = np.pad(c.validity, (0, pad)) if pad else c.validity
            cols.append(Decimal128Column(jnp.asarray(hi), jnp.asarray(lo),
                                         jnp.asarray(val)))
        else:
            data = np.pad(c.data, (0, pad)) if pad else c.data
            val = np.pad(c.validity, (0, pad)) if pad else c.validity
            cols.append(PrimitiveColumn(jnp.asarray(data), jnp.asarray(val)))
    return DeviceBatch(tuple(cols), jnp.asarray(n, jnp.int32))


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------

def _put_buf(out: io.BytesIO, arr: np.ndarray) -> None:
    b = np.ascontiguousarray(arr).tobytes()
    out.write(struct.pack("<I", len(b)))
    out.write(b)


def _get_buf(src: io.BytesIO, dtype, shape) -> np.ndarray:
    (ln,) = struct.unpack("<I", src.read(4))
    return np.frombuffer(src.read(ln), dtype=dtype).reshape(shape).copy()


def serialize_host_batch(host: HostBatch,
                         extras: Optional[dict[str, np.ndarray]] = None,
                         codec: str = "zstd",
                         codec_level: Optional[int] = None) -> bytes:
    extras = extras or {}
    body = io.BytesIO()
    body.write(struct.pack("<IHH", host.num_rows, len(host.columns),
                           len(extras)))
    for c in host.columns:
        if isinstance(c, HostString):
            body.write(struct.pack("<BH", 1, c.chars.shape[1]))
            _put_buf(body, c.chars)
            _put_buf(body, c.lens.astype(np.int32))
            _put_buf(body, c.validity.astype(np.bool_))
        elif isinstance(c, HostList):
            tag = c.values.dtype.str.encode()
            body.write(struct.pack("<BHB", 2, c.values.shape[1], len(tag)))
            body.write(tag)
            _put_buf(body, c.values)
            _put_buf(body, c.elem_valid.astype(np.bool_))
            _put_buf(body, c.lens.astype(np.int32))
            _put_buf(body, c.validity.astype(np.bool_))
        elif isinstance(c, HostDecimal128):
            body.write(struct.pack("<B", 3))
            _put_buf(body, c.hi.astype(np.int64))
            _put_buf(body, c.lo.astype(np.int64))
            _put_buf(body, c.validity.astype(np.bool_))
        else:
            tag = c.data.dtype.str.encode()
            body.write(struct.pack("<BB", 0, len(tag)))
            body.write(tag)
            _put_buf(body, c.data)
            _put_buf(body, c.validity.astype(np.bool_))
    for name, arr in extras.items():
        nb = name.encode()
        assert arr.ndim == 2 and arr.dtype == np.uint64, name
        body.write(struct.pack("<BIH", len(nb), arr.shape[0], arr.shape[1]))
        body.write(nb)
        _put_buf(body, arr)

    raw = body.getvalue()
    if codec == "zstd":
        payload = _compressor(codec_level).compress(raw)
        code = CODEC_ZSTD
    else:
        payload, code = raw, CODEC_NONE
    return MAGIC + struct.pack("<BI", code, len(payload)) + payload


def deserialize_host_batch(data: bytes) -> tuple[HostBatch, dict[str, np.ndarray]]:
    if data[:4] != MAGIC:
        raise ValueError("bad batch frame magic")
    code, body_len = struct.unpack("<BI", data[4:9])
    payload = data[9:9 + body_len]
    raw = _decompressor().decompress(payload) if code == CODEC_ZSTD else payload
    src = io.BytesIO(raw)
    num_rows, num_cols, num_extras = struct.unpack("<IHH", src.read(8))
    cols: list[HostColumn] = []
    for _ in range(num_cols):
        kind = struct.unpack("<B", src.read(1))[0]
        if kind == 1:
            (width,) = struct.unpack("<H", src.read(2))
            chars = _get_buf(src, np.uint8, (num_rows, width))
            lens = _get_buf(src, np.int32, (num_rows,))
            val = _get_buf(src, np.bool_, (num_rows,))
            cols.append(HostString(chars, lens, val))
        elif kind == 2:
            m, tag_len = struct.unpack("<HB", src.read(3))
            dt = np.dtype(src.read(tag_len).decode())
            values = _get_buf(src, dt, (num_rows, m))
            ev = _get_buf(src, np.bool_, (num_rows, m))
            lens = _get_buf(src, np.int32, (num_rows,))
            val = _get_buf(src, np.bool_, (num_rows,))
            cols.append(HostList(values, ev, lens, val))
        elif kind == 3:
            hi = _get_buf(src, np.int64, (num_rows,))
            lo = _get_buf(src, np.int64, (num_rows,))
            val = _get_buf(src, np.bool_, (num_rows,))
            cols.append(HostDecimal128(hi, lo, val))
        else:
            (tag_len,) = struct.unpack("<B", src.read(1))
            dt = np.dtype(src.read(tag_len).decode())
            data_arr = _get_buf(src, dt, (num_rows,))
            val = _get_buf(src, np.bool_, (num_rows,))
            cols.append(HostPrimitive(data_arr, val))
    extras: dict[str, np.ndarray] = {}
    for _ in range(num_extras):
        name_len, rows, words = struct.unpack("<BIH", src.read(7))
        name = src.read(name_len).decode()
        extras[name] = _get_buf(src, np.uint64, (rows, words))
    return HostBatch(cols, num_rows), extras


def serialize_batch(batch: DeviceBatch, codec: str = "zstd",
                    codec_level: Optional[int] = None) -> bytes:
    return serialize_host_batch(batch_to_host(batch), codec=codec,
                                codec_level=codec_level)


def deserialize_batch(data: bytes,
                      capacity: Optional[int] = None) -> DeviceBatch:
    host, _ = deserialize_host_batch(data)
    return host_to_batch(host, capacity)
