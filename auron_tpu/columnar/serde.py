"""Batch ↔ bytes serde for spill and shuffle payloads.

The analogue of the reference's length-prefixed columnar wire format +
block-compressed IPC framing (reference:
datafusion-ext-commons/src/io/batch_serde.rs:68-149,
io/ipc_compression.rs:35-280). Layout per frame:

    magic 'ATB1' | u8 codec | u32 body_len | body (maybe compressed)

body:
    u32 num_rows | u16 num_cols | u16 num_extras
    per column:   u8 kind (0 prim / 1 string) | dtype tag | buffers
    per extra:    name | uint64 word matrix   (sort-key words for merge)

Buffers are raw little-endian numpy bytes, each u32-length-prefixed. Only
live rows travel — capacity padding is re-applied on load. Compression is
zstd level 1 (the codec baked into this image; the reference defaults to
lz4 with zstd as option, conf.rs SPILL_COMPRESSION_CODEC).
"""

from __future__ import annotations

import io
import struct
from dataclasses import dataclass
from typing import Optional, Union

import numpy as np

try:
    import zstandard
except ImportError:    # gated dependency: fall back to CODEC_NONE frames
    zstandard = None

from auron_tpu.columnar.batch import (DeviceBatch, ListColumn,
                                      PrimitiveColumn, StringColumn)

MAGIC = b"ATB1"
CODEC_NONE = 0
CODEC_ZSTD = 1

# zstd contexts are not safe for concurrent use; spills may run from
# multiple threads, so keep one per thread
import threading

_tls = threading.local()


def _compressor(level: int = None):
    if level is None:
        from auron_tpu import config as cfg
        level = cfg.get_config().get(cfg.SPILL_CODEC_LEVEL)
    if getattr(_tls, "level", None) != level:
        _tls.c = zstandard.ZstdCompressor(level=level)
        _tls.level = level
    return _tls.c


def _decompressor():
    if zstandard is None:
        raise RuntimeError(
            "frame was written with the zstd codec but the zstandard "
            "module is not installed in this environment")
    if not hasattr(_tls, "d"):
        _tls.d = zstandard.ZstdDecompressor()
    return _tls.d


# ---------------------------------------------------------------------------
# host-side batch representation
# ---------------------------------------------------------------------------

@dataclass
class HostPrimitive:
    data: np.ndarray       # [n]
    validity: np.ndarray   # bool[n]


@dataclass
class HostString:
    chars: np.ndarray      # uint8[n, width]
    lens: np.ndarray       # int32[n]
    validity: np.ndarray   # bool[n]


@dataclass
class HostList:
    values: np.ndarray     # [n, max_elems]
    elem_valid: np.ndarray  # bool[n, max_elems]
    lens: np.ndarray       # int32[n]
    validity: np.ndarray   # bool[n]


@dataclass
class HostStringList:
    chars: np.ndarray       # uint8[n, max_elems, width]
    slens: np.ndarray       # int32[n, max_elems]
    elem_valid: np.ndarray  # bool[n, max_elems]
    lens: np.ndarray        # int32[n]
    validity: np.ndarray    # bool[n]


@dataclass
class HostDecimal128:
    hi: np.ndarray         # int64[n]
    lo: np.ndarray         # int64[n] (unsigned bit pattern)
    validity: np.ndarray   # bool[n]


@dataclass
class HostMap:
    keys: np.ndarray       # [n, max_elems]
    values: np.ndarray     # [n, max_elems]
    val_valid: np.ndarray  # bool[n, max_elems]
    lens: np.ndarray       # int32[n]
    validity: np.ndarray   # bool[n]


@dataclass
class HostStringMap:
    kchars: np.ndarray      # uint8[n, max_elems, kw]
    kslens: np.ndarray      # int32[n, max_elems]
    vchars: np.ndarray      # uint8[n, max_elems, vw]
    vslens: np.ndarray      # int32[n, max_elems]
    val_valid: np.ndarray   # bool[n, max_elems]
    lens: np.ndarray        # int32[n]
    validity: np.ndarray    # bool[n]


@dataclass
class HostStruct:
    children: list         # list[HostColumn]
    validity: np.ndarray   # bool[n]


HostColumn = Union[HostPrimitive, HostString, HostList, HostStringList,
                   HostDecimal128, HostMap, HostStringMap, HostStruct]


def _host_col_nbytes(c: HostColumn) -> int:
    if isinstance(c, HostString):
        return c.chars.nbytes + c.lens.nbytes + c.validity.nbytes
    if isinstance(c, HostList):
        return (c.values.nbytes + c.elem_valid.nbytes
                + c.lens.nbytes + c.validity.nbytes)
    if isinstance(c, HostStringList):
        return (c.chars.nbytes + c.slens.nbytes + c.elem_valid.nbytes
                + c.lens.nbytes + c.validity.nbytes)
    if isinstance(c, HostDecimal128):
        return c.hi.nbytes + c.lo.nbytes + c.validity.nbytes
    if isinstance(c, HostMap):
        return (c.keys.nbytes + c.values.nbytes + c.val_valid.nbytes
                + c.lens.nbytes + c.validity.nbytes)
    if isinstance(c, HostStringMap):
        return (c.kchars.nbytes + c.kslens.nbytes + c.vchars.nbytes
                + c.vslens.nbytes + c.val_valid.nbytes + c.lens.nbytes
                + c.validity.nbytes)
    if isinstance(c, HostStruct):
        return sum(_host_col_nbytes(ch) for ch in c.children) \
            + c.validity.nbytes
    return c.data.nbytes + c.validity.nbytes


@dataclass
class HostBatch:
    columns: list
    num_rows: int

    @property
    def nbytes(self) -> int:
        return sum(_host_col_nbytes(c) for c in self.columns)


def _slice_host_col(c: HostColumn, lo: int, hi: int) -> HostColumn:
    if isinstance(c, HostString):
        return HostString(c.chars[lo:hi], c.lens[lo:hi], c.validity[lo:hi])
    if isinstance(c, HostList):
        return HostList(c.values[lo:hi], c.elem_valid[lo:hi],
                        c.lens[lo:hi], c.validity[lo:hi])
    if isinstance(c, HostStringList):
        return HostStringList(c.chars[lo:hi], c.slens[lo:hi],
                              c.elem_valid[lo:hi], c.lens[lo:hi],
                              c.validity[lo:hi])
    if isinstance(c, HostDecimal128):
        return HostDecimal128(c.hi[lo:hi], c.lo[lo:hi], c.validity[lo:hi])
    if isinstance(c, HostMap):
        return HostMap(c.keys[lo:hi], c.values[lo:hi], c.val_valid[lo:hi],
                       c.lens[lo:hi], c.validity[lo:hi])
    if isinstance(c, HostStringMap):
        return HostStringMap(c.kchars[lo:hi], c.kslens[lo:hi],
                             c.vchars[lo:hi], c.vslens[lo:hi],
                             c.val_valid[lo:hi], c.lens[lo:hi],
                             c.validity[lo:hi])
    if isinstance(c, HostStruct):
        return HostStruct([_slice_host_col(ch, lo, hi) for ch in c.children],
                          c.validity[lo:hi])
    return HostPrimitive(c.data[lo:hi], c.validity[lo:hi])


def slice_host_batch(host: HostBatch, lo: int, hi: int) -> HostBatch:
    """Row-range view [lo, hi) over every column."""
    return HostBatch([_slice_host_col(c, lo, hi) for c in host.columns],
                     hi - lo)


def fetch_leaves(leaves: list) -> list[np.ndarray]:
    """Fetch many device arrays in ONE batched round trip.

    On tunneled accelerators a blocking per-array fetch costs ~70 ms of
    fixed latency regardless of size, so `np.asarray` per buffer (10+ per
    batch) dominates everything; `jax.device_get` on the whole list issues
    the transfers together and awaits them once (measured 7x faster for a
    10-array batch on v5e-over-tunnel)."""
    import jax
    return list(jax.device_get(list(leaves)))


def host_col_from_device(c, it) -> HostColumn:
    """Rebuild one host column from a device column TEMPLATE plus an
    iterator over its fetched numpy leaves (depth-first dataclass field
    order — the jax pytree flattening order of the registered column
    dataclasses)."""
    from auron_tpu.columnar.batch import MapColumn, StructColumn
    from auron_tpu.columnar.decimal128 import Decimal128Column
    if isinstance(c, StringColumn):
        return HostString(next(it), next(it), next(it))
    if isinstance(c, ListColumn):
        return HostList(next(it), next(it), next(it), next(it))
    from auron_tpu.columnar.batch import StringListColumn
    if isinstance(c, StringListColumn):
        return HostStringList(next(it), next(it), next(it), next(it),
                              next(it))
    if isinstance(c, Decimal128Column):
        return HostDecimal128(next(it), next(it), next(it))
    if isinstance(c, MapColumn):
        return HostMap(next(it), next(it), next(it), next(it), next(it))
    from auron_tpu.columnar.batch import StringMapColumn
    if isinstance(c, StringMapColumn):
        return HostStringMap(next(it), next(it), next(it), next(it),
                             next(it), next(it), next(it))
    if isinstance(c, StructColumn):
        kids = [host_col_from_device(ch, it) for ch in c.children]
        return HostStruct(kids, next(it))
    return HostPrimitive(next(it), next(it))


def fetch_batch_numpy(batch: DeviceBatch) -> tuple[list[list[np.ndarray]], int]:
    """All column arrays of a batch (full capacity) + the row count, in a
    single device→host transfer. Returns (per-column leaf lists in pytree
    order — see host_col_from_device — and n)."""
    import jax
    per_col = [jax.tree_util.tree_leaves(c) for c in batch.columns]
    leaves = [a for arrs in per_col for a in arrs]
    import jax.numpy as jnp
    leaves.append(jnp.asarray(batch.num_rows, jnp.int32).reshape(1))
    fetched = fetch_leaves(leaves)
    n = int(fetched[-1][0])
    cols = []
    pos = 0
    for arrs in per_col:
        cols.append(fetched[pos:pos + len(arrs)])
        pos += len(arrs)
    return cols, n


def batch_to_host(batch: DeviceBatch,
                  num_rows: Optional[int] = None) -> HostBatch:
    """Device → host, keeping only live rows — ONE batched transfer for
    the whole batch (fetch_leaves). When the caller knows ``num_rows``
    (every spill path does), only the live row prefix is transferred —
    spills run exactly when memory is tight, so shipping capacity padding
    there would be self-defeating. Every column leaf is row-major with
    capacity on axis 0, so the prefix slice is uniform."""
    import jax
    if num_rows is not None:
        n = num_rows
        per_col = [[a[:n] for a in jax.tree_util.tree_leaves(c)]
                   for c in batch.columns]
        flat = fetch_leaves([a for arrs in per_col for a in arrs])
        fetched = []
        pos = 0
        for arrs in per_col:
            fetched.append(flat[pos:pos + len(arrs)])
            pos += len(arrs)
    else:
        fetched, n = fetch_batch_numpy(batch)
        fetched = [[a[:n] for a in arrs] for arrs in fetched]
    cols: list[HostColumn] = []
    for c, arrs in zip(batch.columns, fetched):
        cols.append(host_col_from_device(
            c, iter([np.ascontiguousarray(a) for a in arrs])))
    return HostBatch(cols, n)


def _host_col_to_device(c: HostColumn, pad: int):
    import jax.numpy as jnp
    from auron_tpu.columnar.batch import MapColumn, StructColumn

    def p1(a):
        return np.pad(a, (0, pad)) if pad else a

    def p2(a):
        return np.pad(a, ((0, pad), (0, 0))) if pad else a

    if isinstance(c, HostMap):
        return MapColumn(jnp.asarray(p2(c.keys)), jnp.asarray(p2(c.values)),
                         jnp.asarray(p2(c.val_valid)),
                         jnp.asarray(p1(c.lens)), jnp.asarray(p1(c.validity)))
    if isinstance(c, HostStringMap):
        from auron_tpu.columnar.batch import StringMapColumn

        def p3m(a):
            return np.pad(a, ((0, pad), (0, 0), (0, 0))) if pad else a

        return StringMapColumn(
            jnp.asarray(p3m(c.kchars)), jnp.asarray(p2(c.kslens)),
            jnp.asarray(p3m(c.vchars)), jnp.asarray(p2(c.vslens)),
            jnp.asarray(p2(c.val_valid)), jnp.asarray(p1(c.lens)),
            jnp.asarray(p1(c.validity)))
    if isinstance(c, HostStruct):
        return StructColumn(tuple(_host_col_to_device(ch, pad)
                                  for ch in c.children),
                            jnp.asarray(p1(c.validity)))
    if isinstance(c, HostString):
        return StringColumn(jnp.asarray(p2(c.chars)), jnp.asarray(p1(c.lens)),
                            jnp.asarray(p1(c.validity)))
    if isinstance(c, HostList):
        return ListColumn(jnp.asarray(p2(c.values)),
                          jnp.asarray(p2(c.elem_valid)),
                          jnp.asarray(p1(c.lens)), jnp.asarray(p1(c.validity)))
    if isinstance(c, HostStringList):
        from auron_tpu.columnar.batch import StringListColumn

        def p3(a):
            return np.pad(a, ((0, pad), (0, 0), (0, 0))) if pad else a

        return StringListColumn(jnp.asarray(p3(c.chars)),
                                jnp.asarray(p2(c.slens)),
                                jnp.asarray(p2(c.elem_valid)),
                                jnp.asarray(p1(c.lens)),
                                jnp.asarray(p1(c.validity)))
    if isinstance(c, HostDecimal128):
        from auron_tpu.columnar.decimal128 import Decimal128Column
        return Decimal128Column(jnp.asarray(p1(c.hi)), jnp.asarray(p1(c.lo)),
                                jnp.asarray(p1(c.validity)))
    return PrimitiveColumn(jnp.asarray(p1(c.data)),
                           jnp.asarray(p1(c.validity)))


def host_to_batch(host: HostBatch, capacity: Optional[int] = None) -> DeviceBatch:
    """Host → device with padding back to ``capacity`` (>= num_rows)."""
    import jax.numpy as jnp
    n = host.num_rows
    cap = capacity or n
    assert cap >= n, (cap, n)
    pad = cap - n
    cols = []
    for c in host.columns:
        if isinstance(c, (HostMap, HostStruct, HostStringMap,
                          HostStringList)):
            cols.append(_host_col_to_device(c, pad))
        elif isinstance(c, HostString):
            chars = np.pad(c.chars, ((0, pad), (0, 0))) if pad else c.chars
            lens = np.pad(c.lens, (0, pad)) if pad else c.lens
            val = np.pad(c.validity, (0, pad)) if pad else c.validity
            cols.append(StringColumn(jnp.asarray(chars), jnp.asarray(lens),
                                     jnp.asarray(val)))
        elif isinstance(c, HostList):
            values = np.pad(c.values, ((0, pad), (0, 0))) if pad else c.values
            ev = np.pad(c.elem_valid, ((0, pad), (0, 0))) if pad else c.elem_valid
            lens = np.pad(c.lens, (0, pad)) if pad else c.lens
            val = np.pad(c.validity, (0, pad)) if pad else c.validity
            cols.append(ListColumn(jnp.asarray(values), jnp.asarray(ev),
                                   jnp.asarray(lens), jnp.asarray(val)))
        elif isinstance(c, HostDecimal128):
            from auron_tpu.columnar.decimal128 import Decimal128Column
            hi = np.pad(c.hi, (0, pad)) if pad else c.hi
            lo = np.pad(c.lo, (0, pad)) if pad else c.lo
            val = np.pad(c.validity, (0, pad)) if pad else c.validity
            cols.append(Decimal128Column(jnp.asarray(hi), jnp.asarray(lo),
                                         jnp.asarray(val)))
        else:
            data = np.pad(c.data, (0, pad)) if pad else c.data
            val = np.pad(c.validity, (0, pad)) if pad else c.validity
            cols.append(PrimitiveColumn(jnp.asarray(data), jnp.asarray(val)))
    return DeviceBatch(tuple(cols), jnp.asarray(n, jnp.int32))


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------

def _put_buf(out: io.BytesIO, arr: np.ndarray) -> None:
    b = np.ascontiguousarray(arr).tobytes()
    out.write(struct.pack("<I", len(b)))
    out.write(b)


def _get_buf(src: io.BytesIO, dtype, shape) -> np.ndarray:
    (ln,) = struct.unpack("<I", src.read(4))
    return np.frombuffer(src.read(ln), dtype=dtype).reshape(shape).copy()


def _write_host_col(body: io.BytesIO, c: HostColumn) -> None:
    if isinstance(c, HostString):
        body.write(struct.pack("<BH", 1, c.chars.shape[1]))
        _put_buf(body, c.chars)
        _put_buf(body, c.lens.astype(np.int32))
        _put_buf(body, c.validity.astype(np.bool_))
    elif isinstance(c, HostList):
        tag = c.values.dtype.str.encode()
        body.write(struct.pack("<BHB", 2, c.values.shape[1], len(tag)))
        body.write(tag)
        _put_buf(body, c.values)
        _put_buf(body, c.elem_valid.astype(np.bool_))
        _put_buf(body, c.lens.astype(np.int32))
        _put_buf(body, c.validity.astype(np.bool_))
    elif isinstance(c, HostStringList):
        body.write(struct.pack("<BHH", 6, c.chars.shape[1],
                               c.chars.shape[2]))
        _put_buf(body, c.chars)
        _put_buf(body, c.slens.astype(np.int32))
        _put_buf(body, c.elem_valid.astype(np.bool_))
        _put_buf(body, c.lens.astype(np.int32))
        _put_buf(body, c.validity.astype(np.bool_))
    elif isinstance(c, HostDecimal128):
        body.write(struct.pack("<B", 3))
        _put_buf(body, c.hi.astype(np.int64))
        _put_buf(body, c.lo.astype(np.int64))
        _put_buf(body, c.validity.astype(np.bool_))
    elif isinstance(c, HostMap):
        ktag = c.keys.dtype.str.encode()
        vtag = c.values.dtype.str.encode()
        body.write(struct.pack("<BHBB", 4, c.keys.shape[1],
                               len(ktag), len(vtag)))
        body.write(ktag)
        body.write(vtag)
        _put_buf(body, c.keys)
        _put_buf(body, c.values)
        _put_buf(body, c.val_valid.astype(np.bool_))
        _put_buf(body, c.lens.astype(np.int32))
        _put_buf(body, c.validity.astype(np.bool_))
    elif isinstance(c, HostStringMap):
        body.write(struct.pack("<BHHH", 7, c.kchars.shape[1],
                               c.kchars.shape[2], c.vchars.shape[2]))
        _put_buf(body, c.kchars)
        _put_buf(body, c.kslens.astype(np.int32))
        _put_buf(body, c.vchars)
        _put_buf(body, c.vslens.astype(np.int32))
        _put_buf(body, c.val_valid.astype(np.bool_))
        _put_buf(body, c.lens.astype(np.int32))
        _put_buf(body, c.validity.astype(np.bool_))
    elif isinstance(c, HostStruct):
        body.write(struct.pack("<BB", 5, len(c.children)))
        for ch in c.children:
            _write_host_col(body, ch)
        _put_buf(body, c.validity.astype(np.bool_))
    else:
        tag = c.data.dtype.str.encode()
        body.write(struct.pack("<BB", 0, len(tag)))
        body.write(tag)
        _put_buf(body, c.data)
        _put_buf(body, c.validity.astype(np.bool_))


def serialize_host_batch(host: HostBatch,
                         extras: Optional[dict[str, np.ndarray]] = None,
                         codec: str = "zstd",
                         codec_level: Optional[int] = None) -> bytes:
    extras = extras or {}
    body = io.BytesIO()
    body.write(struct.pack("<IHH", host.num_rows, len(host.columns),
                           len(extras)))
    for c in host.columns:
        _write_host_col(body, c)
    for name, arr in extras.items():
        nb = name.encode()
        assert arr.ndim == 2 and arr.dtype == np.uint64, name
        body.write(struct.pack("<BIH", len(nb), arr.shape[0], arr.shape[1]))
        body.write(nb)
        _put_buf(body, arr)

    raw = body.getvalue()
    if codec == "zstd" and zstandard is not None:
        payload = _compressor(codec_level).compress(raw)
        code = CODEC_ZSTD
    else:
        # zstandard absent: uncompressed frames keep serde functional
        # (the codec byte makes readers self-describing either way)
        payload, code = raw, CODEC_NONE
    return MAGIC + struct.pack("<BI", code, len(payload)) + payload


def _read_host_col(src: io.BytesIO, num_rows: int) -> HostColumn:
    kind = struct.unpack("<B", src.read(1))[0]
    if kind == 1:
        (width,) = struct.unpack("<H", src.read(2))
        chars = _get_buf(src, np.uint8, (num_rows, width))
        lens = _get_buf(src, np.int32, (num_rows,))
        val = _get_buf(src, np.bool_, (num_rows,))
        return HostString(chars, lens, val)
    if kind == 2:
        m, tag_len = struct.unpack("<HB", src.read(3))
        dt = np.dtype(src.read(tag_len).decode())
        values = _get_buf(src, dt, (num_rows, m))
        ev = _get_buf(src, np.bool_, (num_rows, m))
        lens = _get_buf(src, np.int32, (num_rows,))
        val = _get_buf(src, np.bool_, (num_rows,))
        return HostList(values, ev, lens, val)
    if kind == 3:
        hi = _get_buf(src, np.int64, (num_rows,))
        lo = _get_buf(src, np.int64, (num_rows,))
        val = _get_buf(src, np.bool_, (num_rows,))
        return HostDecimal128(hi, lo, val)
    if kind == 4:
        m, ktag_len, vtag_len = struct.unpack("<HBB", src.read(4))
        kdt = np.dtype(src.read(ktag_len).decode())
        vdt = np.dtype(src.read(vtag_len).decode())
        keys = _get_buf(src, kdt, (num_rows, m))
        values = _get_buf(src, vdt, (num_rows, m))
        vv = _get_buf(src, np.bool_, (num_rows, m))
        lens = _get_buf(src, np.int32, (num_rows,))
        val = _get_buf(src, np.bool_, (num_rows,))
        return HostMap(keys, values, vv, lens, val)
    if kind == 7:
        m, kw, vw = struct.unpack("<HHH", src.read(6))
        kchars = _get_buf(src, np.uint8, (num_rows, m, kw))
        kslens = _get_buf(src, np.int32, (num_rows, m))
        vchars = _get_buf(src, np.uint8, (num_rows, m, vw))
        vslens = _get_buf(src, np.int32, (num_rows, m))
        vv = _get_buf(src, np.bool_, (num_rows, m))
        lens = _get_buf(src, np.int32, (num_rows,))
        val = _get_buf(src, np.bool_, (num_rows,))
        return HostStringMap(kchars, kslens, vchars, vslens, vv, lens, val)
    if kind == 6:
        m, width = struct.unpack("<HH", src.read(4))
        chars = _get_buf(src, np.uint8, (num_rows, m, width))
        slens = _get_buf(src, np.int32, (num_rows, m))
        ev = _get_buf(src, np.bool_, (num_rows, m))
        lens = _get_buf(src, np.int32, (num_rows,))
        val = _get_buf(src, np.bool_, (num_rows,))
        return HostStringList(chars, slens, ev, lens, val)
    if kind == 5:
        (n_children,) = struct.unpack("<B", src.read(1))
        kids = [_read_host_col(src, num_rows) for _ in range(n_children)]
        val = _get_buf(src, np.bool_, (num_rows,))
        return HostStruct(kids, val)
    (tag_len,) = struct.unpack("<B", src.read(1))
    dt = np.dtype(src.read(tag_len).decode())
    data_arr = _get_buf(src, dt, (num_rows,))
    val = _get_buf(src, np.bool_, (num_rows,))
    return HostPrimitive(data_arr, val)


def deserialize_host_batch(data: bytes) -> tuple[HostBatch, dict[str, np.ndarray]]:
    if data[:4] != MAGIC:
        raise ValueError("bad batch frame magic")
    code, body_len = struct.unpack("<BI", data[4:9])
    payload = data[9:9 + body_len]
    raw = _decompressor().decompress(payload) if code == CODEC_ZSTD else payload
    src = io.BytesIO(raw)
    num_rows, num_cols, num_extras = struct.unpack("<IHH", src.read(8))
    cols = [_read_host_col(src, num_rows) for _ in range(num_cols)]
    extras: dict[str, np.ndarray] = {}
    for _ in range(num_extras):
        name_len, rows, words = struct.unpack("<BIH", src.read(7))
        name = src.read(name_len).decode()
        extras[name] = _get_buf(src, np.uint64, (rows, words))
    return HostBatch(cols, num_rows), extras


def serialize_batch(batch: DeviceBatch, codec: str = "zstd",
                    codec_level: Optional[int] = None) -> bytes:
    return serialize_host_batch(batch_to_host(batch), codec=codec,
                                codec_level=codec_level)


def deserialize_batch(data: bytes,
                      capacity: Optional[int] = None) -> DeviceBatch:
    host, _ = deserialize_host_batch(data)
    return host_to_batch(host, capacity)
